"""On-demand device profiling: programmatic jax.profiler capture.

The span collector (obs/trace.py) answers "where did this request's
milliseconds go" at host granularity; this module answers the next
question — "what was the DEVICE doing" — with a real `jax.profiler`
capture (device + host timeline, Perfetto-loadable `*.trace.json.gz`
under the capture dir) taken from a RUNNING server:

  * `POST /profilez?ms=N` on the obs HTTP endpoint (obs/http.py)
    captures N milliseconds into a bounded spool directory and returns
    the capture path — no restart, no TensorBoard session;
  * `POST /profilez?auto=1&threshold_ms=T[&ms=N]` ARMS the auto
    trigger: the LM batcher worker captures the next decode step after
    one exceeds T milliseconds (the p99-breach post-mortem: you never
    have to be watching when the slow step happens);
  * `annotation(name)` / `step_annotation(step)` are the obs-gated host
    span annotations (jax.profiler.TraceAnnotation) that make captures
    readable — the serving runtime wraps decode steps, prefill chunks
    and relay stage hops in them, and the models thread
    `jax.named_scope` through their blocks so TPU timelines name layers
    too. utils/tracing.py re-exports these (its original span API
    predates the obs gate and is deprecated).

Capture locking: jax.profiler supports ONE trace at a time per process;
concurrent `capture()` calls (two curls racing, or a curl racing the
auto trigger) serialize on a module lock, with the loser failing fast
(`ProfilerBusy`) rather than corrupting the winner's capture.

The spool is bounded (default 8 captures): oldest captures are deleted
as new ones land, so a long-lived daemon with a trigger-happy operator
cannot fill the disk.

Every obs-driven capture writes a sidecar `meta.json` at the capture
root — monotonic (perf_counter) begin/end, wall-clock bounds, the
StepClock step-counter range, and the backend — so
`obs/timeline.analyze()` can place the capture on the decode-step axis
(which steps the window covers, and how much of each the device was
busy for).
"""

from __future__ import annotations

import contextlib
import glob
import json
import os
import shutil
import threading
import time
from typing import Iterator, Optional

__all__ = ["ProfilerBusy", "capture", "capture_step", "spool_dir",
           "list_captures", "annotation", "annotation_ctx",
           "step_annotation", "Profiler"]


class ProfilerBusy(RuntimeError):
    """A capture is already in flight (jax.profiler is single-trace)."""


_capture_lock = threading.Lock()


def spool_dir() -> str:
    """$DNN_TPU_OBS_DIR/profiles (obs/flight.default_dump_dir anchors
    the shared obs artifact root)."""
    from dnn_tpu.obs.flight import default_dump_dir

    return os.path.join(default_dump_dir(), "profiles")


def list_captures(root: Optional[str] = None) -> list:
    """Capture dirs in the spool, oldest first."""
    root = root or spool_dir()
    if not os.path.isdir(root):
        return []
    out = [os.path.join(root, d) for d in os.listdir(root)
           if d.startswith("capture-")]
    return sorted(out)


def _prune(root: str, keep: int):
    for old in list_captures(root)[:-keep] if keep > 0 else []:
        shutil.rmtree(old, ignore_errors=True)


def trace_files(capture_dir: str) -> list:
    """The Perfetto-loadable artifacts inside one capture dir."""
    return sorted(glob.glob(os.path.join(
        capture_dir, "plugins", "profile", "*", "*.trace.json.gz")))


_capturing = False  # read by annotation_ctx: annotations only pay their
# TraceAnnotation cost while a capture is actually recording


def capturing() -> bool:
    return _capturing


@contextlib.contextmanager
def mark_recording() -> Iterator[None]:
    """Mark an EXTERNALLY-driven capture (bare jax.profiler.start_trace,
    a TensorBoard attach) as recording so annotation_ctx emits during
    it. obs-driven captures (_traced) set the flag themselves; this is
    the compatibility hook utils/tracing.trace_to wraps its body in so
    the legacy trace_to + span pattern still yields annotated captures."""
    global _capturing
    prev = _capturing
    _capturing = True
    try:
        yield
    finally:
        _capturing = prev


def _step_counter() -> Optional[int]:
    """The active StepClock's step counter (obs/timeline.py), or None
    when no clock is installed — guarded so a broken clock can never
    cost a capture."""
    try:
        from dnn_tpu.obs.timeline import active_clock

        clk = active_clock()
        return None if clk is None else int(clk.steps_total)
    except Exception:  # noqa: BLE001 — meta is best-effort
        return None


def _write_meta(path: str, meta: dict):
    """Sidecar `meta.json` at the capture root: monotonic begin/end
    (perf_counter — the clock StepClock records on), wall-clock
    bounds, the step-counter range, and the backend. This is what lets
    `timeline.analyze()` place a spooled capture on the step axis —
    without it a capture floats free of the step stream entirely.
    Best-effort: an unwritable spool loses the meta, never the trace."""
    try:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
    except OSError:
        pass


@contextlib.contextmanager
def _traced(capture_root: Optional[str], keep: int) -> Iterator[str]:
    """Exclusive start_trace/stop_trace around the body; yields the
    capture dir. Raises ProfilerBusy instead of queueing — a capture
    request against a busy profiler wants a fast 409, not a pile-up."""
    global _capturing
    import jax

    if not _capture_lock.acquire(blocking=False):
        raise ProfilerBusy("a profiler capture is already in flight")
    try:
        root = capture_root or spool_dir()
        path = os.path.join(root, f"capture-{int(time.time() * 1e3):x}")
        os.makedirs(path, exist_ok=True)
        try:
            backend = jax.default_backend()
        except Exception:  # noqa: BLE001 — a wedged backend still traces
            backend = None
        jax.profiler.start_trace(path)
        # perf_begin lands right after start_trace returns: the trace's
        # ts axis starts ~here, so (perf_counter - perf_begin) maps a
        # StepClock timestamp onto the capture's microsecond axis
        meta = {"perf_begin": time.perf_counter(),
                "t_begin_unix": time.time(),
                "step_begin": _step_counter(),
                "backend": backend}
        _capturing = True
        try:
            yield path
        finally:
            _capturing = False
            meta["perf_end"] = time.perf_counter()
            meta["t_end_unix"] = time.time()
            meta["step_end"] = _step_counter()
            jax.profiler.stop_trace()
            _write_meta(path, meta)
            try:
                keep_n = int(os.environ["DNN_TPU_OBS_PROFILE_KEEP"])
            except (KeyError, ValueError):
                keep_n = keep
            _prune(root, keep_n)
    finally:
        _capture_lock.release()


def capture(duration_ms: float = 1000.0, *,
            capture_root: Optional[str] = None, keep: int = 8) -> str:
    """Capture `duration_ms` of whatever the process is doing (the
    serving worker keeps stepping; this thread just sleeps inside the
    trace). Returns the capture dir; flight-records the capture."""
    from dnn_tpu.obs import flight

    with _traced(capture_root, keep) as path:
        time.sleep(max(0.0, float(duration_ms)) / 1e3)
    flight.record("profile_capture", path=path, ms=float(duration_ms))
    return path


def capture_step(fn, *, capture_root: Optional[str] = None,
                 keep: int = 8, extra_s: float = 0.0):
    """Capture exactly one call of `fn` (the auto-trigger's "next decode
    step") instead of a wall-clock window; `extra_s` extends the trace
    past the call. Returns (capture_dir, fn's result).

    NOTE the capture wall time is dominated by profiler init + trace
    EXPORT (stop_trace writes the json.gz + xplane.pb — measured ~10 s
    for a first capture on this host), during which the calling thread
    (the batcher worker, for the auto trigger) is stalled: requests
    queue behind an auto capture. That is the accepted cost of an
    operator-armed post-mortem, not a steady-state tax.

    Failure contract: ProfilerBusy and `fn`'s OWN exceptions propagate
    (the caller decides what a failed step means — for the batcher
    worker it is fatal). Any OTHER profiler-machinery failure — a trace
    conflict with a bare jax.profiler.start_trace, an unwritable spool,
    an export error inside stop_trace — must never cost the step: the
    step runs uninstrumented (setup failure) or its already-computed
    result is returned (export failure), with (None, result) and a
    `profile_capture_failed` flight event recording the miss. An armed
    auto-capture is an observer; it is not allowed to kill the serving
    loop it observes."""
    from dnn_tpu.obs import flight

    t0 = time.perf_counter()
    ran, out, step_err, step_ms, path = False, None, None, None, None
    try:
        with _traced(capture_root, keep) as path:
            t1 = time.perf_counter()
            try:
                out = fn()
                ran = True
            except Exception as e:
                step_err = e
                raise
            step_ms = round((time.perf_counter() - t1) * 1e3, 3)
            if extra_s > 0:
                time.sleep(extra_s)
    except ProfilerBusy:
        raise
    except Exception as e:
        if step_err is not None:
            raise  # the step's own failure is the caller's business
        flight.record("profile_capture_failed", error=str(e)[:200])
        if not ran:
            out = fn()
        return None, out
    flight.record("profile_capture", path=path, trigger="auto",
                  step_ms=step_ms,
                  capture_ms=round((time.perf_counter() - t0) * 1e3, 3))
    return path, out


# ----------------------------------------------------------------------
# host annotations (the obs-gated successor of utils/tracing.span)
# ----------------------------------------------------------------------

_NULL_CTX = contextlib.nullcontext()
_trace_annotation = False  # unresolved; None = profiler unavailable


def annotation_ctx(name: str):
    """HOT-PATH form: returns a jax.profiler.TraceAnnotation (obs on AND
    an obs-driven capture recording) or a shared nullcontext — a plain
    call + two checks, no generator. Two measured costs forced this
    shape: the @contextmanager `annotation` below costs ~30 µs around a
    jit dispatch (generator machinery + per-call imports), and even a
    bare TraceAnnotation costs ~6 µs there — both real money against a
    ms-scale decode step, paid EVERY step for annotations nobody is
    recording. Gating on `capturing()` (set by _traced during POST
    /profilez and the auto-trigger) makes the steady state ~0.3 µs; a
    capture driven outside obs.profile (bare jax.profiler.start_trace)
    won't see these annotations unless it wraps its body in
    `mark_recording` (utils/tracing.trace_to does) — prefer
    obs.profile.capture. The
    TraceAnnotation class is resolved once, lazily — importing this
    module still never touches jax."""
    global _trace_annotation
    from dnn_tpu import obs

    if not _capturing or not obs.enabled():
        return _NULL_CTX
    if _trace_annotation is False:
        try:
            from jax.profiler import TraceAnnotation

            _trace_annotation = TraceAnnotation
        except Exception:  # pragma: no cover - profiler unavailable
            _trace_annotation = None
    if _trace_annotation is None:
        return _NULL_CTX
    return _trace_annotation(name)


@contextlib.contextmanager
def annotation(name: str) -> Iterator[None]:
    """Named host-side span, visible in captured profiles. Degrades to
    nothing when observability is off or the profiler is unavailable —
    library code annotates unconditionally. Convenient for ms-scale
    paths (relay stage hops, prefill chunks); per-decode-step code uses
    `annotation_ctx`."""
    with annotation_ctx(name):
        yield


@contextlib.contextmanager
def step_annotation(step: int, name: str = "step") -> Iterator[None]:
    """Mark one pipeline/training step; XLA profilers group device ops
    under it. Obs-gated like `annotation`."""
    from dnn_tpu import obs

    if not obs.enabled():
        yield
        return
    try:
        import jax

        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:  # pragma: no cover
        ctx = contextlib.nullcontext()
    with ctx:
        yield


# ----------------------------------------------------------------------
# server-side handle (what obs/http.py drives)
# ----------------------------------------------------------------------

class Profiler:
    """The /profilez backend: on-demand capture plus (optionally) the
    auto-trigger arm. `arm_target` is any object with a writable
    `auto_profile` attribute — the LM batcher worker reads it once per
    step (one None check) and, when armed, captures the step after the
    first one that exceeds the threshold."""

    def __init__(self, *, capture_root: Optional[str] = None,
                 arm_target=None, keep: int = 8):
        self.capture_root = capture_root or spool_dir()
        self.keep = keep
        self._arm_target = arm_target

    def capture(self, duration_ms: float) -> str:
        return capture(duration_ms, capture_root=self.capture_root,
                       keep=self.keep)

    @property
    def can_arm(self) -> bool:
        return self._arm_target is not None

    def arm_auto(self, threshold_ms: float, duration_ms: float = 0.0):
        """Arm the next-slow-step auto capture. duration_ms > 0 extends
        the capture past the triggering step by that wall window (0 =
        exactly one step)."""
        if self._arm_target is None:
            raise ValueError("this endpoint has no step loop to arm "
                             "(stage servers capture on demand only)")
        self._arm_target.auto_profile = {
            "threshold_s": float(threshold_ms) / 1e3,
            "extra_s": max(0.0, float(duration_ms)) / 1e3,
            "capture_root": self.capture_root, "keep": self.keep,
        }

    def disarm(self):
        if self._arm_target is not None:
            self._arm_target.auto_profile = None

    def status(self) -> dict:
        armed = getattr(self._arm_target, "auto_profile", None) \
            if self._arm_target is not None else None
        return {
            "captures": list_captures(self.capture_root),
            "armed": None if armed is None else {
                "threshold_ms": armed["threshold_s"] * 1e3,
                "extra_ms": armed["extra_s"] * 1e3},
        }
