"""SLO verdict engine + automatic breach forensics.

obs/goodput.py tracks LIVE burn rates (the paging signal); this module
is the after-the-fact judge: a scenario (dnn_tpu/workloads) hands it
the per-request records it collected plus the scenario's declared SLO,
and gets back a per-objective report with one ok/breach VERDICT — the
per-scenario goodput-under-SLO accounting the Gemma-on-TPU serving
comparison (PAPERS.md 2605.25645) reports, as an asserted artifact
instead of a table in a paper.

Record schema (one dict per request; the workloads runner produces
these, but anything shaped like this evaluates):

    {"i": int, "t": sched offset s, "outcome": "ok"|"rejected"|None,
     "tokens": int, "ttft_s": float|None, "itl_s": [float, ...],
     "t_done": float|None}

`outcome=None` means SILENTLY LOST — the one thing no SLO tolerates;
it fails availability unconditionally.

On breach, `write_incident_bundle` snapshots the process's forensic
surfaces — the flight ring filtered to the breach window (/debugz),
the step clock (/stepz), the fleet view (/fleetz) — into one on-disk
directory, and `python -m dnn_tpu.obs incident PATH` renders the
event-by-event timeline back out of it. That is the "reconstructable
from the flight recorder" promise (ROADMAP item 5) automated: the
breach scenario's test asserts by READING THE BUNDLE BACK, never from
in-memory state. No jax import anywhere on these paths.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import List, Optional

__all__ = ["SLOSpec", "SLOReport", "evaluate", "write_incident_bundle",
           "load_incident", "render_incident"]


# nearest-rank percentile — the registry's convention, shared so the
# SLO verdicts can never diverge from the /metrics reservoir quantiles
# (utils.metrics is stdlib-only, safe on the no-jax CLI path)
from dnn_tpu.utils.metrics import percentile as _percentile  # noqa: E402


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """A scenario's declared objectives. Latency objectives are
    (percentile, threshold) pairs — `ttft_p=95, ttft_s=0.5` reads "the
    95th-percentile time-to-first-token stays under 500 ms".
    `availability` is the COMPLETED fraction of submitted requests —
    stricter than the chaos probe's completed-or-rejected accounting,
    because a scenario declares the demand it expects SERVED: a shed
    request is a served-SLO failure even when it is a correct admission
    decision. Silently-lost requests additionally fail the always-on
    `lost` objective, which tolerates ZERO. `goodput_floor_tps` is the
    delivered-tokens/sec floor over the measured window — the "goodput
    under SLO" column."""

    ttft_s: Optional[float] = None
    ttft_p: float = 95.0
    itl_s: Optional[float] = None
    itl_p: float = 95.0
    availability: Optional[float] = None
    goodput_floor_tps: Optional[float] = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items()
                if v is not None}


@dataclasses.dataclass
class SLOReport:
    """The verdict: `ok` is the AND over objectives; `objectives` holds
    one row per declared objective (name, measured, threshold, ok);
    `breach_window` is the [first, last] wall-clock epoch-second span
    of the bad samples that tripped it (None when ok) — the window the
    incident bundle filters the flight ring to."""

    scenario: str
    ok: bool
    objectives: List[dict]
    requests: int
    completed: int
    rejected: int
    lost: int
    goodput_tps: float
    wall_s: float
    breach_window: Optional[tuple] = None
    burn_rates: Optional[dict] = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if self.breach_window is not None:
            d["breach_window"] = list(self.breach_window)
        return d


def evaluate(scenario: str, records: List[dict], spec: SLOSpec, *,
             wall_s: float, t0_epoch: Optional[float] = None,
             burn_rates: Optional[dict] = None) -> SLOReport:
    """Judge `records` against `spec`. `wall_s` is the measured window
    (the goodput denominator — the runner's, never inferred from the
    records, which would under-count an idle tail). `t0_epoch` maps the
    records' relative `t` offsets onto wall-clock epoch seconds so the
    breach window can address the flight ring; omitted, the window is
    reported in relative offsets. `burn_rates` (obs/goodput
    GoodputTracker.burn_rates()) rides the report verbatim — the live
    gauges' view next to the post-hoc arithmetic."""
    if wall_s <= 0:
        raise ValueError(f"wall_s must be > 0, got {wall_s}")
    n = len(records)
    completed = [r for r in records if r.get("outcome") == "ok"]
    rejected = [r for r in records if r.get("outcome") == "rejected"]
    lost = [r for r in records if r.get("outcome") is None]
    goodput = sum(int(r.get("tokens") or 0) for r in completed) / wall_s

    def _epoch(rel: float) -> float:
        return rel if t0_epoch is None else t0_epoch + rel

    objectives: List[dict] = []
    bad_ts: List[float] = []

    def obj(name, measured, threshold, ok, *, bad_records=()):
        objectives.append({
            "name": name,
            "measured": (None if measured is None
                         else round(float(measured), 6)),
            "threshold": threshold, "ok": bool(ok)})
        if not ok:
            for r in bad_records:
                # a lost record carries t_done=None (the key exists) —
                # its scheduled time still anchors the breach window
                t = r.get("t_done")
                if t is None:
                    t = r.get("t")
                if t is not None:
                    bad_ts.append(_epoch(float(t)))

    if spec.ttft_s is not None:
        samples = [(r["ttft_s"], r) for r in completed
                   if r.get("ttft_s") is not None]
        if samples:
            p = _percentile([s for s, _ in samples], spec.ttft_p)
            bad = [r for s, r in samples if s > spec.ttft_s]
            obj(f"ttft_p{spec.ttft_p:g}", p, spec.ttft_s,
                p <= spec.ttft_s, bad_records=bad)
        else:
            # an SLO over zero samples is vacuous only when nothing
            # completed AND availability judges that; a declared TTFT
            # objective with no completions is a failure, not a pass
            obj(f"ttft_p{spec.ttft_p:g}", None, spec.ttft_s,
                not records, bad_records=records)
    if spec.itl_s is not None:
        samples = [s for r in completed for s in (r.get("itl_s") or ())]
        if samples:
            p = _percentile(samples, spec.itl_p)
            bad = [r for r in completed
                   if any(s > spec.itl_s for s in (r.get("itl_s") or ()))]
            obj(f"itl_p{spec.itl_p:g}", p, spec.itl_s, p <= spec.itl_s,
                bad_records=bad)
        # no samples at all (all requests emitted <= 1 token): vacuous
        # by construction, skip rather than fail — the objective had no
        # events to judge and availability covers the did-anything-run
        # question
    if spec.availability is not None:
        avail = len(completed) / n if n else 0.0
        obj("availability", avail, spec.availability,
            avail >= spec.availability and not lost,
            bad_records=rejected + lost)
    # silent loss is unconditionally asserted — a record without an
    # outcome is the failure mode every probe in this repo exists to
    # make impossible
    obj("lost", len(lost), 0, not lost, bad_records=lost)
    if spec.goodput_floor_tps is not None:
        obj("goodput_tps", goodput, spec.goodput_floor_tps,
            goodput >= spec.goodput_floor_tps)

    ok = all(o["ok"] for o in objectives)
    window = None
    if not ok and bad_ts:
        window = (min(bad_ts), max(bad_ts))
    return SLOReport(
        scenario=scenario, ok=ok, objectives=objectives, requests=n,
        completed=len(completed), rejected=len(rejected),
        lost=len(lost), goodput_tps=round(goodput, 3),
        wall_s=round(wall_s, 3), breach_window=window,
        burn_rates=burn_rates)


# ----------------------------------------------------------------------
# incident bundles: the breach's forensic snapshot, on disk
# ----------------------------------------------------------------------

MANIFEST = "manifest.json"
FLIGHT = "flight.jsonl"
STEPZ = "stepz.json"
FLEETZ = "fleetz.json"


def write_incident_bundle(dir_path: str, report: SLOReport, *,
                          flight=None, stepclock=None, fleet=None,
                          url: Optional[str] = None,
                          records: Optional[List[dict]] = None,
                          window_pad_s: float = 30.0) -> str:
    """Snapshot the forensic surfaces into `dir_path` (created):

      manifest.json   the SLO report + what was captured and why a
                      surface is absent (honest nulls, never silence)
      flight.jsonl    the flight ring, filtered to the breach window
                      (± window_pad_s) when the report has one, whole
                      ring otherwise — /debugz's content
      stepz.json      StepClock.summary() — /stepz's content
      fleetz.json     FleetCollector.fleetz() — /fleetz's content

    Sources are either in-process objects (`flight` a FlightRecorder —
    default the shared ring, `stepclock`, `fleet`) or a live server's
    obs endpoint (`url`), in which case the three surfaces are fetched
    over HTTP exactly as an operator would. Returns `dir_path`."""
    os.makedirs(dir_path, exist_ok=True)
    captured: dict = {}

    if url is not None:
        from urllib.request import urlopen

        base = url.rstrip("/")
        for name, path, fname in (("flight", "/debugz", FLIGHT),
                                  ("stepz", "/stepz", STEPZ),
                                  ("fleetz", "/fleetz", FLEETZ)):
            try:
                body = urlopen(base + path, timeout=10).read().decode()
                with open(os.path.join(dir_path, fname), "w") as f:
                    f.write(body)
                captured[name] = fname
            except Exception as e:  # noqa: BLE001 — a server without the
                # surface (404) or mid-crash must not lose the bundle
                captured[name] = f"unavailable: {str(e)[:120]}"
    else:
        if flight is None:
            from dnn_tpu.obs import flight as _flight

            flight = _flight.recorder()
        events = flight.events()
        if report.breach_window is not None:
            lo = report.breach_window[0] - window_pad_s
            hi = report.breach_window[1] + window_pad_s
            events = [e for e in events if lo <= e["ts"] <= hi]
        with open(os.path.join(dir_path, FLIGHT), "w") as f:
            for e in events:
                f.write(json.dumps(e, sort_keys=True, default=str) + "\n")
        captured["flight"] = f"{FLIGHT} ({len(events)} events)"
        if stepclock is not None and getattr(stepclock, "steps_total", 0):
            with open(os.path.join(dir_path, STEPZ), "w") as f:
                json.dump(stepclock.summary(), f, default=str)
            captured["stepz"] = STEPZ
        else:
            captured["stepz"] = "unavailable: no step clock attached"
        if fleet is not None:
            with open(os.path.join(dir_path, FLEETZ), "w") as f:
                json.dump(fleet.fleetz(), f, default=str)
            captured["fleetz"] = FLEETZ
        else:
            captured["fleetz"] = ("unavailable: single process, no "
                                  "fleet collector")

    with open(os.path.join(dir_path, MANIFEST), "w") as f:
        json.dump({"kind": "dnn_tpu_incident", "version": 1,
                   "written_at": time.time(), "report": report.to_dict(),
                   "captured": captured,
                   "records": records if records is not None else None},
                  f, indent=2, default=str)
    from dnn_tpu.obs import flight as _fl

    _fl.record("incident_bundle", scenario=report.scenario,
               path=dir_path)
    return dir_path


def load_incident(path: str) -> dict:
    """Read a bundle back: {"manifest", "flight" (event list),
    "stepz"|None, "fleetz"|None}. Fails loud on a directory without a
    manifest — half a bundle must not render as a clean incident."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise ValueError(
            f"{path!r} is not an incident bundle (no {MANIFEST})")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("kind") != "dnn_tpu_incident":
        raise ValueError(
            f"{mpath} is not an incident manifest "
            f"(kind={manifest.get('kind')!r})")
    out = {"manifest": manifest, "flight": [], "stepz": None,
           "fleetz": None}
    fpath = os.path.join(path, FLIGHT)
    if os.path.isfile(fpath):
        with open(fpath) as f:
            for line in f:
                line = line.strip()
                if line:
                    out["flight"].append(json.loads(line))
    for key, fname in (("stepz", STEPZ), ("fleetz", FLEETZ)):
        p = os.path.join(path, fname)
        if os.path.isfile(p):
            with open(p) as f:
                out[key] = json.load(f)
    return out


def render_incident(bundle: dict) -> str:
    """The event-by-event timeline, human-first: the verdict header,
    each failed objective, then every flight event in seq order with
    its offset from the breach window's start — the post-mortem a
    responder reads top to bottom."""
    man = bundle["manifest"]
    rep = man["report"]
    lines = [f"incident: scenario {rep['scenario']!r} — "
             + ("OK (no breach)" if rep["ok"] else "SLO BREACH"),
             f"  requests {rep['requests']}  completed "
             f"{rep['completed']}  rejected {rep['rejected']}  lost "
             f"{rep['lost']}  goodput {rep['goodput_tps']} tok/s over "
             f"{rep['wall_s']} s"]
    for o in rep["objectives"]:
        mark = "ok " if o["ok"] else "FAIL"
        lines.append(f"  [{mark}] {o['name']}: measured "
                     f"{o['measured']} vs threshold {o['threshold']}")
    if rep.get("burn_rates"):
        lines.append("  live burn rates at verdict: " + ", ".join(
            f"{k}={v:.2f}" for k, v in rep["burn_rates"].items()))
    win = rep.get("breach_window")
    if win:
        lines.append(f"  breach window: {win[0]:.3f} .. {win[1]:.3f} "
                     f"({win[1] - win[0]:.3f} s)")
    events = bundle["flight"]
    lines.append(f"timeline ({len(events)} flight events):")
    t_anchor = win[0] if win else (events[0]["ts"] if events else 0.0)
    for e in events:
        extra = {k: v for k, v in e.items()
                 if k not in ("seq", "ts", "kind")}
        detail = " ".join(f"{k}={v}" for k, v in extra.items())
        lines.append(f"  {e['ts'] - t_anchor:+9.3f}s  #{e['seq']:<5d} "
                     f"{e['kind']:<24s} {detail}".rstrip())
    sz = bundle.get("stepz")
    if sz:
        lines.append(
            f"step clock: {sz.get('steps_total')} steps, host fraction "
            f"{sz.get('host_fraction', 0):.1%}, "
            f"{sz.get('steps_per_sec', 0):.1f} steps/s")
    fz = bundle.get("fleetz")
    if fz:
        lines.append(f"fleet: state {fz.get('state')!r}, "
                     f"{len(fz.get('stages', {}))} stages")
    return "\n".join(lines)
