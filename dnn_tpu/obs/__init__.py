"""dnn_tpu.obs — observability for the serving stack.

The reference's only observability is ad-hoc stdout prints (SURVEY §5:
"Tracing/profiling: ABSENT"); PRs 1-2 built the perf and correctness
legs, this package builds the eyes. Three coordinated layers share one
registry and one span collector:

  * request tracing (obs/trace.py): per-request span trees — queue wait,
    admission, prefill, per-bucket decode, detokenize, per-hop RPC —
    propagated across the wire on the existing `request_id` field and
    exportable as JSONL / Chrome-trace JSON (`python -m dnn_tpu.obs
    trace`, or GET /trace on the metrics endpoint);
  * metrics (utils/metrics.py grown for this layer): counters, gauges,
    quantile summaries and histograms, rendered in Prometheus text
    format and served from a stdlib-HTTP `/metrics` endpoint
    (obs/http.py) attached to the LM daemon and the stage servers;
  * compile telemetry (obs/compile_watch.py): a jax.monitoring listener
    counting XLA compilations and compile-seconds into the same registry
    — the RUNTIME cross-check of the static recompile census (PRG004,
    dnn_tpu/analysis): a live recompile storm is a counter, not a stall.

v2 adds the failure-facing layer on the same substrate:

  * flight recorder (obs/flight.py): a bounded ring of structured
    events (admissions, evictions, retries, deadline misses, compiles,
    errors, watchdog firings) — dumped via GET /debugz, `python -m
    dnn_tpu.obs flight`, and automatically on unhandled crash;
  * on-demand device profiling (obs/profile.py): POST /profilez drives
    a programmatic jax.profiler capture into a bounded spool, with an
    arm-the-next-slow-step auto trigger; host annotations + model
    named_scopes make the timelines name layers and stages;
  * memory observability (obs/mem.py): per-device memory_stats, host
    RSS, and pool watermark gauges through the same registry;
  * hung-device watchdog (obs/watchdog.py): subprocess-bounded device
    probes + decode heartbeat staleness -> ok|degraded|wedged on
    /statusz, with /healthz degrading accordingly.

v3 adds the CROSS-PROCESS layer — the first obs subsystem that sees the
whole pipeline instead of one process:

  * fleet collector (obs/fleet.py): polls every stage's /metrics +
    /statusz + /trace.jsonl, serves the merged view on /fleetz
    (worst-of health, per-stage percentile tables, fleet totals),
    estimates per-stage clock offsets NTP-style from the existing RPC
    spans, and stitches per-hop span trees from different hosts into
    ONE Perfetto timeline with per-request critical-path and bubble-
    fraction attribution (`python -m dnn_tpu.obs fleet`);
  * goodput accounting (obs/goodput.py): live MFU / MBU / goodput
    tokens-per-sec scrape-time gauges from the decode/prefill step
    stream + utils/flops.py serving-shape estimates, plus SLO
    error-budget burn-rate tracking (TTFT / inter-token /
    availability) with flight events on breach.

v4 adds the INTRA-STEP layer — the instrument for the overlap/fusion
arc (ROADMAP item 4):

  * step-timeline attribution (obs/timeline.py): a per-phase decode-
    step clock on the serving pool (admit / host / dispatch / wait /
    commit / obs) with dispatch-slack, sync-tax and host-fraction
    series on /stepz (+ a Perfetto host-track export), capture
    analysis over the profiler's spooled artifacts (device busy/idle,
    host-gap histogram, top ops) aligned to the step axis through
    profile.py's sidecar meta, and an asserted phase-accounting
    baseline (benchmarks/step_timeline_probe.py) whose measured
    host-serialization fraction is the item-4 ratchet (BASELINE.md).

v5 adds the JUDGMENT layer — the workload suite's verdict machinery
(ISSUE 14):

  * SLO verdicts + incident bundles (obs/slo.py): a scenario's
    per-request records judged against its declared SLOSpec into one
    ok/breach report, and — on breach — an on-disk incident bundle
    (flight ring over the breach window, /stepz, /fleetz) that
    `python -m dnn_tpu.obs incident PATH` renders back as the
    event-by-event post-mortem (dnn_tpu/workloads drives it).

v6 adds the MEMORY-ECONOMY layer — the sizing instrument for the KV
capacity hierarchy (ROADMAP item 4) and the autoscaler's
capacity-vs-compute question (item 3):

  * kvlens (obs/kvlens.py): SHARDS-style sampled reuse-distance
    tracking over the radix KV tier's admission stream (deterministic
    blake2s spatial sampling — zero wall-clock randomness), miss-ratio
    curves predicting the block-hit ratio at 0.5x..8x of the
    configured pool on /kvz (+ weak scrape gauges, /fleetz rollup
    columns, `python -m dnn_tpu.obs kvlens`), a bounded per-block
    lifecycle ledger (birth/share/COW/evict/migrate/refetch with
    cause attribution), and a thrash detector pricing
    evict→refetch-within-window churn in re-prefill chunk-seconds and
    migrated bytes; benchmarks/kv_economy_probe.py asserts the curve
    against ground truth (|predicted − measured| ≤ 0.10 at an
    untested pool size).

v7 adds the TRAINING layer — the observatory for the one ROADMAP
pillar that had none (built before the training-at-scale PR it
judges, the instrument-first pattern):

  * trainlens (obs/trainlens.py): a per-step TRAINING clock in the
    StepClock idiom — train.fit splits every iteration into
    data/dispatch/wait/ckpt/eval/obs phases with a derived
    `data_stall_fraction` and step-time MFU/tokens-per-sec priced by
    the utils/flops.py training helpers against the same
    device_peak_flops rooflines goodput uses (weak gauges
    dnn_tpu_train_mfu / _tokens_per_sec / _data_stall; /trainz
    JSON|prom|trace; `python -m dnn_tpu.obs trainlens`) — plus
    gradient-health sentinels over the train steps' opt-in on-device
    stats leg (grad_spike / loss_nan / train_stall flight events, an
    incident bundle on divergence) and checkpoint observability
    (save/restore histograms, dnn_tpu_ckpt_last_good_step /
    staleness gauges, ckpt_saved/ckpt_restored events);
    benchmarks/train_goodput_probe.py asserts phase coverage, the
    MFU floor, stall attribution, sentinel latency, and the <2%
    overhead budget.

Gate: DNN_TPU_OBS=off (or 0/false) disables everything — producers see
`metrics()` return None, `start_span` return the free NULL_SPAN, and
`flight.record` short-circuit on one boolean. The gate is re-checked
per call, so benchmarks can flip it at runtime (`set_enabled`) to
measure the instrumentation tax (benchmarks/obs_overhead_probe.py pins
it < 2% of a decode step, flight + watchdog included).

Import cost: this package imports stdlib + utils.metrics only; jax is
touched lazily inside install_compile_telemetry() and obs/profile.
"""

from __future__ import annotations

import os
import threading

from dnn_tpu.obs.trace import (  # noqa: F401 — the package's public API
    NULL_SPAN,
    Span,
    TraceCollector,
    collector,
    continue_or_start,
    current_span,
    new_trace_id,
    parse_wire_tag,
    record_span,
    span,
    spans_to_chrome,
    start_span,
    strip_wire_tag,
    tag_request_id,
)

from dnn_tpu.obs import flight  # noqa: F401 — obs.flight.record(...)

__all__ = [
    "enabled", "set_enabled", "metrics", "collector", "span",
    "start_span", "record_span", "current_span", "continue_or_start",
    "tag_request_id", "parse_wire_tag", "strip_wire_tag", "new_trace_id",
    "NULL_SPAN", "Span", "TraceCollector", "spans_to_chrome",
    "install_compile_telemetry", "serve_metrics", "flight",
]

_enabled = os.environ.get("DNN_TPU_OBS", "on").lower() not in (
    "off", "0", "false", "no")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool):
    """Runtime toggle (benchmarks, tests). Producers re-check per call,
    so flipping takes effect immediately — no reconstruction needed."""
    global _enabled
    _enabled = bool(on)


_default_metrics = None  # resolved lazily once: metrics() is on every
# per-step hot path, and a per-call submodule import is measurable there


def metrics():
    """The shared registry (utils.metrics.default_metrics) when
    observability is on, else None — hot paths guard with one `is not
    None` check and skip all bookkeeping when off."""
    if not _enabled:
        return None
    global _default_metrics
    if _default_metrics is None:
        from dnn_tpu.utils.metrics import default_metrics

        _default_metrics = default_metrics
    return _default_metrics


_install_lock = threading.Lock()
_compile_installed = False


def install_compile_telemetry() -> bool:
    """Install the jax.monitoring compile listener once per process
    (idempotent — every engine/server constructor calls this). Returns
    True when the listener is active. See obs/compile_watch.py."""
    global _compile_installed
    with _install_lock:
        if _compile_installed:
            return True
        from dnn_tpu.obs.compile_watch import _install

        _compile_installed = _install()
        return _compile_installed


def serve_metrics(port: int = 0, host: str = "127.0.0.1", *,
                  healthy=None, status=None, profiler=None, fleet=None,
                  drain=None, stepclock=None, kvlens=None,
                  trainlens=None, caplens=None):
    """Start the observability HTTP endpoint on a daemon thread; returns
    the MetricsHTTPServer (`.port` for port=0 ephemeral binds,
    `.close()` to stop; loopback by default — pass host="0.0.0.0" to
    expose to a scrape fleet). Serves the full surface — GET /metrics
    /trace /debugz /statusz /healthz, POST /profilez — and installs the
    device/host memory gauges (obs/mem.py; no-op with observability
    off). This is THE construction path: LMServer and comm.serve_stage
    both go through it, so the public helper cannot drift behind the
    endpoints the real servers expose. `healthy`/`status` as on
    MetricsHTTPServer; `profiler` defaults to a fresh
    obs.profile.Profiler (pass one to enable auto-trigger arming, or
    False to disable /profilez). `fleet` (an obs.fleet.FleetCollector)
    additionally serves the merged fleet view on /fleetz (JSON;
    ?format=prom|trace|report). `drain` (callable -> dict) enables
    POST /drainz — connection draining (runtime/lm_server.LMServer
    passes its handler). `stepclock` (an obs.timeline.StepClock)
    additionally serves the step-timeline attribution on /stepz (JSON;
    ?format=prom|trace). `kvlens` (an obs.kvlens.KVLens) additionally
    serves the memory-economy observatory on /kvz (JSON;
    ?format=prom) — LMServer attaches its batcher's lens after
    construction by assigning `server._kvlens` (the batcher is built
    after the endpoint comes up). `trainlens` (an
    obs.trainlens.TrainClock) additionally serves the training-step
    observatory on /trainz (JSON; ?format=prom|trace) — the training
    counterpart of /stepz. `caplens` (an obs.caplens.CapLens)
    additionally serves the capacity observatory on /capz (JSON;
    ?format=prom) — serve_router passes its router's lens. See
    obs/http.py."""
    from dnn_tpu.obs.http import MetricsHTTPServer
    from dnn_tpu.obs.mem import install_memory_gauges

    install_memory_gauges()
    if profiler is None:
        from dnn_tpu.obs.profile import Profiler

        profiler = Profiler()
    return MetricsHTTPServer(port=port, host=host, healthy=healthy,
                             status=status, profiler=profiler or None,
                             fleet=fleet, drain=drain,
                             stepclock=stepclock, kvlens=kvlens,
                             trainlens=trainlens, caplens=caplens)
