"""kvlens: the memory-economy observatory for the radix KV tier.

The PR 14 pool answers "what is resident"; nothing answered "what
WOULD be resident at a different size". When the pool fills, leaf-LRU
discards blocks and the only visible signal is the hit-ratio gauge at
the ONE capacity actually configured — useless for sizing a host tier
(ROADMAP item 4) or for an autoscaler deciding whether capacity, not
compute, is the scarce resource (item 3). This module is the sizing
oracle, three instruments in one object:

  1. **Sampled reuse-distance tracker.** Every admission lookup feeds
     the full-chunk keys of the arriving prompt through SHARDS-style
     spatial hash sampling: a chunk is tracked iff the low 64 bits of
     its deterministic blake2s path digest fall under `rate` (the
     chaos-planner idiom — zero wall-clock randomness, so the same
     trace + seed reproduces the same curve bit-for-bit). Tracked keys
     live in a bounded LRU stack; a re-access at stack depth d among
     sampled keys estimates a TRUE stack distance of d/rate distinct
     blocks — the classic SHARDS scaling.

  2. **Miss-ratio curves.** Each sampled re-access scores a hit at
     every hypothetical capacity its scaled distance fits under:
     0.5x/1x/2x/4x/8x of the configured pool. `curve()` is the
     predicted block-hit ratio vs capacity; exported as weak
     scrape-time gauges (`prom_gauges()`), as `/kvz` on the obs HTTP
     server (JSON | `?format=prom`), as `/fleetz` rollup columns, and
     via `python -m dnn_tpu.obs kvlens [--url|PATH|--selftest]`.
     `benchmarks/kv_economy_probe.py` proves the instrument against
     ground truth: the curve's prediction for an untested pool size
     must land within 0.10 absolute of the ratio measured there.

  3. **Block-lifetime forensics + thrash detector.** A bounded
     per-block lifecycle ledger (its own FlightRecorder ring, so the
     process crash ring stays clean) records birth/share/COW/evict/
     migrate/refetch events with cause attribution. An evicted key
     re-inserted within `thrash_window_s` is a REFETCH — capacity
     churn that re-ran prefill for work the pool already held — priced
     in re-prefill chunk-seconds (an EMA fed by the serving prefill
     timer) and migrated bytes (adopted-origin refetches paid the
     wire again).

Overhead contract: every producer method opens with the obs gate
check (one boolean when DNN_TPU_OBS is off) and the hook sites in
kvtier/store.py guard with one `lens is not None` test — the
`obs_overhead_probe --kvlens` leg holds the admission path under the
repo-wide <2% tax with the tracker live.

Threading: producer methods run on the pool's single worker thread
(the PrefixStore contract); scrape-side readers (`curve`, `summary`,
`render_prom`, the gauge closures) only load ints/floats and copy
bounded structures, the same tolerance every serving gauge lives with.
"""

from __future__ import annotations

import hashlib
import time
import weakref
from collections import OrderedDict
from typing import List, Optional, Sequence

import numpy as np

from dnn_tpu.obs.flight import FlightRecorder
from dnn_tpu.utils.metrics import labeled

__all__ = ["KVLens", "DEFAULT_MULTS", "DEFAULT_RATE"]

DEFAULT_MULTS = (0.5, 1.0, 2.0, 4.0, 8.0)
DEFAULT_RATE = 0.25

_obs = None  # lazy: breaks the obs<->kvlens import cycle (flight idiom)


def _enabled() -> bool:
    global _obs
    if _obs is None:
        from dnn_tpu import obs as _o

        _obs = _o
    return _obs.enabled()


def _mult_label(m: float) -> str:
    return f"{m:g}x"


class KVLens:
    """One lens per PrefixStore. See module docstring."""

    def __init__(self, pool_blocks: int, block_len: int, *, seed: int = 0,
                 rate: float = DEFAULT_RATE,
                 mults: Sequence[float] = DEFAULT_MULTS,
                 thrash_window_s: float = 30.0,
                 ledger_cap: int = 512,
                 bytes_per_block: int = 0,
                 now=time.monotonic):
        if not (0.0 < rate <= 1.0):
            raise ValueError(f"rate must be in (0, 1], got {rate}")
        self.pool_blocks = int(pool_blocks)
        self.block_len = int(block_len)
        self.seed = int(seed)
        self.rate = float(rate)
        self.mults = tuple(float(m) for m in mults)
        self.thrash_window_s = float(thrash_window_s)
        self.bytes_per_block = int(bytes_per_block)
        self._now = now
        # the digest prefix pins the sample set to the seed: a different
        # seed picks a different (deterministic) 'rate' slice of keys
        self._prefix = f"kvlens:{self.seed}:".encode()
        # hypothetical capacities, in blocks, evaluated per re-access
        self._caps = [max(1, int(round(m * self.pool_blocks)))
                      for m in self.mults]
        # sampled-key LRU stack: only needs to resolve distances up to
        # the LARGEST evaluated capacity — beyond it every capacity
        # already scored a miss, so overflowed keys degrade to "cold"
        # (a miss everywhere), never to a wrong hit
        self._stack_cap = max(64, int(max(self._caps) * self.rate) + 16)
        self._stack: "OrderedDict[bytes, None]" = OrderedDict()
        # curve accumulators (ints only: scrape readers load atomically)
        self.accesses = 0            # full-chunk accesses, unsampled
        self.sampled = 0             # ... that fell under the hash rate
        self.sampled_cold = 0        # sampled first-touches (miss at all)
        self._hits = [0] * len(self._caps)   # per-capacity sampled hits
        self.stack_drops = 0         # keys aged past the bounded stack
        # exact measured tally at the REAL capacity (prediction's anchor)
        self.measured_accesses = 0
        self.measured_hits = 0
        # lifecycle counts + the bounded per-block ledger ring
        self.ledger = FlightRecorder(ledger_cap)
        self.births = 0
        self.shares = 0
        self.remote_shares = 0
        self.cows = 0
        self.migrations = 0
        self.migrated_bytes = 0
        self.evictions_by_cause: dict = {}
        # thrash detector: evicted key -> (monotonic ts, cause)
        self._evicted: "OrderedDict[bytes, tuple]" = OrderedDict()
        self._evicted_cap = 4096
        self.refetch_blocks = 0
        self.thrash_chunk_seconds = 0.0
        self.thrash_migrated_bytes = 0
        self._chunk_s_ema: Optional[float] = None

    # -- keys ----------------------------------------------------------

    def chunk_keys(self, tokens, n_chunks: Optional[int] = None
                   ) -> List[bytes]:
        """Path digests for the full chunks of `tokens`: incremental
        blake2s over the int32 token bytes, one `.copy().digest()` per
        chunk boundary — O(len) total for the whole path, matching the
        radix trie's own `chunk_key` framing (prefix-closed: the key
        of chunk i commits to every token before it)."""
        arr = np.asarray(tokens).astype(np.int32, copy=False).ravel()
        bp = self.block_len
        n = arr.size // bp if n_chunks is None else min(
            int(n_chunks), arr.size // bp)
        if n <= 0:
            return []
        h = hashlib.blake2s(self._prefix, digest_size=16)
        out = []
        for i in range(n):
            h.update(arr[i * bp:(i + 1) * bp].tobytes())
            out.append(h.copy().digest())
        return out

    # -- producers (pool worker thread) --------------------------------

    def on_access(self, tokens, n_resident: int = 0):
        """One admission lookup: every full chunk of the prompt is one
        block access. `n_resident` = blocks the real store matched
        (the exact measured tally the curve is validated against)."""
        if not _enabled():
            return
        keys = self.chunk_keys(tokens)
        if not keys:
            return
        n = len(keys)
        self.accesses += n
        self.measured_accesses += n
        self.measured_hits += min(int(n_resident), n)
        rate = self.rate
        stack = self._stack
        for k in keys:
            if int.from_bytes(k[:8], "big") / 2.0 ** 64 >= rate:
                continue
            self.sampled += 1
            if k in stack:
                d = 0  # sampled keys more recent than k
                for kk in reversed(stack):
                    if kk == k:
                        break
                    d += 1
                scaled = d / rate
                for i, cap in enumerate(self._caps):
                    if scaled < cap:
                        self._hits[i] += 1
                stack.move_to_end(k)
            else:
                self.sampled_cold += 1
                stack[k] = None
                if len(stack) > self._stack_cap:
                    stack.popitem(last=False)
                    self.stack_drops += 1

    def on_insert(self, tokens, created, *, origin: str = "local",
                  now: Optional[float] = None):
        """Blocks became resident: stamp each created node's path
        digest (read back at evict time, after the trie detaches it),
        ledger a birth, and check the thrash window — a key evicted
        less than `thrash_window_s` ago is a REFETCH the pool's size
        forced us to re-prefill."""
        if not _enabled() or not created:
            return
        keys = self.chunk_keys(tokens)
        t = self._now() if now is None else now
        for node in created:
            depth = getattr(node, "depth", 0)
            key = keys[depth - 1] if 0 < depth <= len(keys) else None
            if key is not None:
                try:
                    node.obskey = key
                except AttributeError:
                    pass  # foreign node type: forensics degrade, counts hold
            self.births += 1
            self.ledger.record("birth", key=key.hex()[:12] if key else None,
                               depth=depth, origin=origin)
            if key is None:
                continue
            ev = self._evicted.pop(key, None)
            if ev is not None and t - ev[0] <= self.thrash_window_s:
                self.refetch_blocks += 1
                if self._chunk_s_ema is not None:
                    self.thrash_chunk_seconds += self._chunk_s_ema
                if origin == "adopted":
                    self.thrash_migrated_bytes += self.bytes_per_block
                self.ledger.record("refetch", key=key.hex()[:12],
                                   cause=ev[1], origin=origin,
                                   age_s=round(t - ev[0], 3))

    def on_evict(self, keys: Sequence[Optional[bytes]],
                 cause: str = "capacity", now: Optional[float] = None):
        """Blocks left residency. `keys` are the victims' stamped path
        digests (None for nodes born before the lens attached — the
        cause still counts, the refetch correlation is just lost)."""
        if not _enabled() or not keys:
            return
        t = self._now() if now is None else now
        self.evictions_by_cause[cause] = (
            self.evictions_by_cause.get(cause, 0) + len(keys))
        for key in keys:
            self.ledger.record(
                "evict", key=key.hex()[:12] if key else None, cause=cause)
            if key is None:
                continue
            self._evicted[key] = (t, cause)
            if len(self._evicted) > self._evicted_cap:
                self._evicted.popitem(last=False)

    def on_share(self, n_blocks: int, n_remote: int = 0,
                 cow: bool = False):
        """Admission actually reused `n_blocks` resident blocks (the
        note_reuse passthrough); `cow` marks a boundary copy-on-write
        alongside. One aggregate ledger event per admission, not per
        block — the ring stays bounded by admissions, not blocks."""
        if not _enabled() or (n_blocks <= 0 and not cow):
            return
        self.shares += max(0, int(n_blocks))
        self.remote_shares += max(0, int(n_remote))
        if cow:
            self.cows += 1
            self.ledger.record("cow", shared=int(n_blocks),
                               remote=int(n_remote))
        elif n_blocks > 0:
            self.ledger.record("share", shared=int(n_blocks),
                               remote=int(n_remote))

    def on_migrate(self, n_blocks: int, nbytes: int = 0):
        """Blocks adopted from a sibling replica over the wire."""
        if not _enabled() or n_blocks <= 0:
            return
        self.migrations += int(n_blocks)
        self.migrated_bytes += max(0, int(nbytes))
        self.ledger.record("migrate", blocks=int(n_blocks),
                           bytes=int(nbytes))

    def note_prefill(self, n_chunks: int, seconds: float):
        """Prefill cost signal: EMA of seconds per chunk, the price a
        refetch is billed at (re-prefill chunk-seconds)."""
        if not _enabled() or n_chunks <= 0 or seconds < 0:
            return
        per = float(seconds) / float(n_chunks)
        self._chunk_s_ema = per if self._chunk_s_ema is None else (
            0.2 * per + 0.8 * self._chunk_s_ema)

    # -- scrape side ---------------------------------------------------

    def predicted_hit_ratio(self, mult: float) -> Optional[float]:
        """Curve value at `mult` x pool (None until anything sampled)."""
        if self.sampled <= 0:
            return None
        for i, m in enumerate(self.mults):
            if m == mult:
                return self._hits[i] / self.sampled
        return None

    def curve(self) -> List[dict]:
        s = self.sampled
        return [{"mult": _mult_label(m),
                 "capacity_blocks": self._caps[i],
                 "predicted_hit_ratio":
                     (self._hits[i] / s) if s else None}
                for i, m in enumerate(self.mults)]

    def measured_hit_ratio(self) -> Optional[float]:
        if self.measured_accesses <= 0:
            return None
        return self.measured_hits / self.measured_accesses

    def thrash(self) -> dict:
        return {"window_s": self.thrash_window_s,
                "refetch_blocks": self.refetch_blocks,
                "chunk_seconds": round(self.thrash_chunk_seconds, 6),
                "migrated_bytes": self.thrash_migrated_bytes,
                "chunk_s_ema": self._chunk_s_ema}

    def summary(self) -> dict:
        """The /kvz JSON body."""
        return {
            "config": {"pool_blocks": self.pool_blocks,
                       "block_len": self.block_len,
                       "seed": self.seed, "rate": self.rate,
                       "mults": [_mult_label(m) for m in self.mults]},
            "samples": {"accesses": self.accesses,
                        "sampled": self.sampled,
                        "cold": self.sampled_cold,
                        "stack_len": len(self._stack),
                        "stack_cap": self._stack_cap,
                        "stack_drops": self.stack_drops},
            "curve": self.curve(),
            "measured": {"accesses": self.measured_accesses,
                         "hits": self.measured_hits,
                         "hit_ratio": self.measured_hit_ratio()},
            "lifecycle": {"births": self.births,
                          "shares": self.shares,
                          "remote_shares": self.remote_shares,
                          "cows": self.cows,
                          "migrations": self.migrations,
                          "migrated_bytes": self.migrated_bytes,
                          "evictions_by_cause":
                              dict(self.evictions_by_cause)},
            "thrash": self.thrash(),
            "ledger": self.ledger.events(last=64),
        }

    def render_prom(self) -> str:
        """Prometheus text for `/kvz?format=prom` (self-contained: the
        lens's own families, not the shared registry)."""
        lines = [
            "# HELP dnn_tpu_kvlens_pred_hit_ratio predicted block-hit "
            "ratio at a hypothetical pool capacity (SHARDS-sampled MRC)",
            "# TYPE dnn_tpu_kvlens_pred_hit_ratio gauge",
        ]
        s = self.sampled
        for i, m in enumerate(self.mults):
            v = (self._hits[i] / s) if s else 0.0
            lines.append(
                f'dnn_tpu_kvlens_pred_hit_ratio{{mult="{_mult_label(m)}"}}'
                f" {v:.6f}")
        mr = self.measured_hit_ratio()
        lines += [
            "# TYPE dnn_tpu_kvlens_measured_hit_ratio gauge",
            f"dnn_tpu_kvlens_measured_hit_ratio "
            f"{(mr if mr is not None else 0.0):.6f}",
            "# TYPE dnn_tpu_kvlens_accesses_total counter",
            f"dnn_tpu_kvlens_accesses_total {self.accesses}",
            "# TYPE dnn_tpu_kvlens_sampled_total counter",
            f"dnn_tpu_kvlens_sampled_total {self.sampled}",
            "# TYPE dnn_tpu_kvlens_thrash_refetch_blocks_total counter",
            f"dnn_tpu_kvlens_thrash_refetch_blocks_total "
            f"{self.refetch_blocks}",
            "# TYPE dnn_tpu_kvlens_thrash_chunk_seconds_total counter",
            f"dnn_tpu_kvlens_thrash_chunk_seconds_total "
            f"{self.thrash_chunk_seconds:.6f}",
            "# TYPE dnn_tpu_kvlens_thrash_migrated_bytes_total counter",
            f"dnn_tpu_kvlens_thrash_migrated_bytes_total "
            f"{self.thrash_migrated_bytes}",
            "# TYPE dnn_tpu_kvlens_evictions_total counter",
        ]
        for cause in sorted(self.evictions_by_cause):
            lines.append(
                f'dnn_tpu_kvlens_evictions_total{{cause="{cause}"}} '
                f"{self.evictions_by_cause[cause]}")
        return "\n".join(lines) + "\n"

    def prom_gauges(self) -> dict:
        """Weak scrape-time gauge closures for the serving registry
        (`_obs_gauges` idiom): the module-level metrics registry
        outlives any batcher, so the closures hold a weakref — a
        collected lens reads 0, never a dangling object."""
        ref = weakref.ref(self)

        def _g(fn):
            def read():
                lens = ref()
                if lens is None:
                    return 0.0
                v = fn(lens)
                return float(v) if v is not None else 0.0
            return read

        out = {}
        for m in self.mults:
            out[labeled("dnn_tpu_kvlens_pred_hit_ratio",
                        mult=_mult_label(m))] = _g(
                lambda lens, mm=m: lens.predicted_hit_ratio(mm))
        out["dnn_tpu_kvlens_measured_hit_ratio"] = _g(
            lambda lens: lens.measured_hit_ratio())
        out["dnn_tpu_kvlens_sampled_total"] = _g(
            lambda lens: lens.sampled)
        out["dnn_tpu_kvlens_thrash_refetch_blocks_total"] = _g(
            lambda lens: lens.refetch_blocks)
        out["dnn_tpu_kvlens_thrash_chunk_seconds_total"] = _g(
            lambda lens: lens.thrash_chunk_seconds)
        return out
