"""Model zoo and stage registry.

The reference hard-codes a registry `MODEL_PARTS_CLASSES = {0: ModelPart0_2Node,
1: ModelPart1_2Node}` (/root/reference/node.py:29-32) that must be hand-edited
to swap model families (its readme.md:100-108 says exactly that). Here the
registry is a first-class, config-selected model zoo: each `ModelSpec` knows
how to init params, run the full model, and partition itself into
`StageSpec`s for any supported number of pipeline parts.

A StageSpec is the rebuild of the reference's ModelPart* classes
(cifar_model_parts.py:29-58, partitions/gpt_model_parts.py:6-50): a pure
function over the slice of the param pytree named by `param_keys` — the
functional analog of `load_state_dict(strict=False)` keeping only your
layers (node.py:306).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One pipeline stage: a pure function plus the param keys it owns."""

    name: str
    apply: Callable[[Any, Any], Any]  # (params_slice, activation) -> activation
    param_keys: Tuple[str, ...]

    def slice_params(self, full_params):
        """Keep only this stage's entries of the full param pytree — the
        functional equivalent of the reference's strict=False per-part
        state-dict load (node.py:294-317)."""
        return {k: full_params[k] for k in self.param_keys}


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    name: str
    init: Callable[..., Any]  # (rng, **kw) -> params
    apply: Callable[[Any, Any], Any]  # (params, x) -> y; full model forward
    partition: Callable[[int], Sequence[StageSpec]]
    example_input: Callable[..., Any]
    supported_parts: Tuple[int, ...] = (1, 2)
    # Convert a foreign flat state dict (torch/HF names+layouts) into this
    # family's param pytree — the torch->TPU half of the reference's
    # torch.load path (node.py:296).
    convert_state_dict: Optional[Callable[[Dict[str, Any]], Any]] = None
    # Optional extras (model-family specific):
    config: Optional[Any] = None  # e.g. GPTConfig for transformer families
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)


_REGISTRY: Dict[str, ModelSpec] = {}


def register_model(spec: ModelSpec) -> ModelSpec:
    _REGISTRY[spec.name] = spec
    return spec


def get_model(name: str) -> ModelSpec:
    # Import built-in families lazily so `import dnn_tpu` stays cheap but
    # get_model("cifar_cnn") always works.
    if name not in _REGISTRY:
        import dnn_tpu.models  # noqa: F401  (registers built-ins)
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"Unknown model '{name}'. Available: {sorted(_REGISTRY)}"
        ) from None


def available_models():
    import dnn_tpu.models  # noqa: F401

    return sorted(_REGISTRY)
