"""Training on top of the inference framework.

The reference is inference-only (readme.md:112; its weights arrive as a
pre-trained `.pth`, node.py:294-317). This module goes beyond parity: the
same pure-functional models and the same pipeline runtime also train, via
`jax.value_and_grad` — including *through* the shard_map+ppermute pipeline
(ppermute and scan are differentiable, so pipeline-parallel training needs
no second code path; the backward ppermute rides the same ICI ring in the
reverse direction).

Three entry points:
  * `make_train_step`          — generic single-program step (any model).
  * `make_sharded_train_step`  — dp x tp step: params carry Megatron-style
    PartitionSpecs (`gpt_tp_specs`), the batch is sharded over "data", and
    GSPMD inserts the all-reduces (the scaling-book recipe: pick a mesh,
    annotate shardings, let XLA place collectives).
  * `make_pipeline_train_step` — pp step: loss through
    `spmd_pipeline_stacked`, per-stage HBM-resident block weights, grads
    and optimizer state sharded over the "stage" axis.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnn_tpu import obs as _obs
from dnn_tpu.analysis.shardcheck import contract
from dnn_tpu.chaos import inject as _chaos
from dnn_tpu.obs import flight as _flight
from dnn_tpu.obs import trainlens as _trainlens
from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS
from dnn_tpu.parallel.pipeline import (
    spmd_pipeline_interleaved,
    spmd_pipeline_stacked,
    spmd_pipeline_train_1f1b,
    split_microbatches,
)


# --------------------------------------------------------------------------
# losses
# --------------------------------------------------------------------------

def _token_nll(logits, targets, ignore_index: Optional[int]):
    """Per-token negative log-likelihood and its keep-mask — THE loss
    primitive cross_entropy and make_eval_step both build on (one
    definition, so train and eval math cannot drift)."""
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if ignore_index is None:
        mask = jnp.ones_like(nll)
    else:
        mask = (targets != ignore_index).astype(jnp.float32)
    return nll, mask


def cross_entropy(logits, targets, *, ignore_index: Optional[int] = None):
    """Token-level cross entropy, mean over non-ignored positions.
    logits (..., V) f32; targets (...) int."""
    nll, mask = _token_nll(logits, targets, ignore_index)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def next_token_loss(apply_fn: Callable, params, tokens, *, ignore_index=None):
    """Causal-LM loss: predict tokens[:, 1:] from tokens[:, :-1]."""
    logits = apply_fn(params, tokens[:, :-1])
    return cross_entropy(logits, tokens[:, 1:], ignore_index=ignore_index)


def make_eval_step(apply_fn: Callable, *,
                   ignore_index: Optional[int] = None):
    """Jitted per-batch evaluation step: (params, tokens (B, T)) ->
    (nll_sum, n_tokens) over the batch's non-ignored next-token targets.
    Build ONCE and reuse across evaluate() calls — a periodic in-training
    eval that rebuilt it would re-trace and re-compile the full forward
    every time."""

    @jax.jit
    def step(params, tokens):
        nll, mask = _token_nll(apply_fn(params, tokens[:, :-1]),
                               tokens[:, 1:], ignore_index)
        return jnp.sum(nll * mask), jnp.sum(mask)

    return step


def evaluate(apply_fn: Callable, params, batch_iter, *,
             ignore_index: Optional[int] = None, eval_step=None):
    """Held-out evaluation: TOKEN-WEIGHTED mean next-token loss and
    perplexity over an iterable of (B, T) token batches (per-token
    accumulation — a mean of per-batch means would bias the result
    whenever batches carry different non-ignored token counts, which
    ignore_index padding makes routine). Batches may differ in shape
    (each new shape compiles its own program). Pass a prebuilt
    `eval_step` (make_eval_step) when evaluating repeatedly — the
    default builds a fresh one per call. Returns {"loss", "perplexity",
    "batches", "tokens"}. The counterpart to fit() the reference cannot
    express — it has no loss at all (inference-only, SURVEY §5)."""
    step = eval_step or make_eval_step(apply_fn,
                                       ignore_index=ignore_index)
    total, tokens, n = 0.0, 0.0, 0
    for batch in batch_iter:
        s, m = step(params, jnp.asarray(batch))
        total += float(s)
        tokens += float(m)
        n += 1
    if n == 0:
        raise ValueError("evaluate needs at least one batch")
    if tokens == 0:
        # an all-ignored dataset would otherwise score a perfect-looking
        # loss 0 / ppl 1
        raise ValueError(
            "evaluate saw no non-ignored target tokens (every position "
            f"matched ignore_index={ignore_index})")
    mean = total / tokens
    return {"loss": mean, "perplexity": float(jnp.exp(mean)),
            "batches": n, "tokens": int(tokens)}


def distill_loss(student_apply: Callable, teacher_logits, student_params,
                 tokens, *, temperature: float = 2.0, alpha: float = 0.5,
                 ignore_index: Optional[int] = None):
    """Knowledge distillation: alpha * KL(teacher_T || student_T) * T^2
    + (1-alpha) * CE(student, next tokens) — the Hinton construction
    with the standard T^2 gradient rescale.

    `teacher_logits` (B, T-1, V) are PRECOMPUTED from the same tokens
    (run the teacher once per batch outside the student's grad;
    different-family teachers work — only vocabs must match, the same
    contract as speculative decoding). Wrap with functools.partial into
    make_train_step's loss_fn signature:

        step = make_train_step(
            lambda p, batch: distill_loss(
                student.apply, batch["teacher_logits"], p,
                batch["tokens"]),
            optimizer)
    """
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"alpha must be in [0, 1], got {alpha}")
    if temperature <= 0.0:
        raise ValueError(
            f"temperature must be > 0, got {temperature} (logits divide "
            "by it)")
    s_logits = student_apply(student_params, tokens[:, :-1])
    s_logits = s_logits.astype(jnp.float32)
    t_logits = teacher_logits.astype(jnp.float32)
    t_p = jax.nn.softmax(t_logits / temperature, axis=-1)
    s_logp = jax.nn.log_softmax(s_logits / temperature, axis=-1)
    t_logp = jax.nn.log_softmax(t_logits / temperature, axis=-1)
    kl = jnp.sum(t_p * (t_logp - s_logp), axis=-1)  # (B, T-1)
    targets = tokens[:, 1:]
    if ignore_index is not None:
        mask = (targets != ignore_index).astype(jnp.float32)
        kl_mean = jnp.sum(kl * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    else:
        kl_mean = jnp.mean(kl)
    soft = kl_mean * temperature ** 2
    hard = cross_entropy(s_logits, targets, ignore_index=ignore_index)
    return alpha * soft + (1.0 - alpha) * hard


# --------------------------------------------------------------------------
# generic step
# --------------------------------------------------------------------------

def _health_stats(grads, updates, params):
    """The gradient-health 3-vector the `grad_stats=True` steps return:
    [global grad-norm, update/param-norm ratio, nonfinite grad count] —
    fused into the step program (a handful of reductions next to a full
    backward is noise) and read back as ONE small f32 array per step.
    Donation-safe: built purely from values the step already computed,
    returned as a fresh output (no donated buffer is re-read)."""

    def sq(tree):
        return sum(jnp.sum(jnp.square(leaf.astype(jnp.float32)))
                   for leaf in jax.tree.leaves(tree))

    gnorm = jnp.sqrt(sq(grads))
    unorm = jnp.sqrt(sq(updates))
    pnorm = jnp.sqrt(sq(params))
    nonfinite = sum(jnp.sum(~jnp.isfinite(leaf))
                    for leaf in jax.tree.leaves(grads))
    return jnp.stack([gnorm, unorm / jnp.maximum(pnorm, 1e-12),
                      nonfinite.astype(jnp.float32)])


def poison_batch(batch):
    """NaN-poison every FLOAT leaf of a batch pytree (int token arrays
    cannot hold a NaN — the chaos train_fault's nan mode only makes
    sense for float inputs, and fit() applies it inside its data
    window so the poisoned batch flows through the real step)."""
    def bad(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return jnp.full_like(x, jnp.nan)
        return x

    return jax.tree.map(bad, batch)


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    *, accum_steps: int = 1, grad_stats: bool = False):
    """(params, opt_state, batch) -> (params, opt_state, loss). `loss_fn`
    is (params, batch) -> scalar. Jit-compiled; shardings of the inputs
    propagate (pass pre-sharded params for dp/tp/pp).

    `accum_steps > 1` runs gradient accumulation: the batch's leading axis
    splits into `accum_steps` microbatches, a `lax.scan` accumulates
    grads (one resident grad buffer + one microbatch's activations at a
    time — the single-device analog of the pipeline schedules'
    microbatching), and the optimizer applies their mean. Exact vs the
    full-batch step when the loss is a uniform mean over examples
    (cross_entropy without ignore_index); with masked losses the
    mean-of-means weights microbatches equally, the usual accumulation
    semantics.

    `grad_stats=True` fuses the gradient-health leg into the program:
    the step additionally returns the `_health_stats` 3-vector
    ([grad-norm, update/param-norm ratio, nonfinite count]) as a 4th
    output — one small readback per step, what trainlens.GradSentinel
    observes."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    if accum_steps == 1:
        @jax.jit
        def step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if grad_stats:
                return new_params, opt_state, loss, \
                    _health_stats(grads, updates, params)
            return new_params, opt_state, loss

        return step

    @jax.jit
    def step(params, opt_state, batch):
        def split(x):
            n = x.shape[0]
            if n % accum_steps:
                raise ValueError(
                    f"batch leading dim {n} not divisible by "
                    f"accum_steps {accum_steps}")
            return x.reshape(accum_steps, n // accum_steps, *x.shape[1:])

        micro = jax.tree.map(split, batch)

        def body(carry, mb):
            loss_sum, grads = carry
            l, g = jax.value_and_grad(loss_fn)(params, mb)
            return (loss_sum + l, jax.tree.map(jnp.add, grads, g)), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (loss_sum, grads), _ = jax.lax.scan(body, (0.0, zeros), micro)
        scale = 1.0 / accum_steps
        grads = jax.tree.map(lambda g: g * scale, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if grad_stats:
            return new_params, opt_state, loss_sum * scale, \
                _health_stats(grads, updates, params)
        return new_params, opt_state, loss_sum * scale

    return step


# --------------------------------------------------------------------------
# dp x tp sharding (Megatron-style specs for the GPT param layout)
# --------------------------------------------------------------------------

def _tp_base_spec(keys, nd, axis):
    """The Megatron-style key->sharding table shared by the per-layer and
    stacked layouts, covering both the GPT family's keys (qkv/fc/proj) and
    the LLaMA family's (q/k/v/gate/up shard their output features — whole
    heads / hidden slices per device; o/down shard input features, so
    GSPMD inserts one all-reduce per residual write). `nd` is the leaf
    rank WITHOUT any leading layer axis."""
    if nd < 2:
        return P()  # biases / norm params replicate
    if {"qkv", "fc", "q", "k", "v", "gate", "up"} & set(keys):
        return P(None, axis)        # (C, out): shard out dim
    if {"proj", "o", "down"} & set(keys):
        return P(axis, None)        # (out, C): shard in dim
    if "wte" in keys:
        return P(axis, None)        # (V, C): vocab-parallel embedding
    if "lm_head" in keys:
        return P(None, axis)        # (C, V): vocab-parallel logits
    return P()


@contract("train.gpt_dp_tp.params")
def gpt_tp_specs(params, *, axis: str = MODEL_AXIS):
    """PartitionSpecs for the GPT family's flat param dict
    (dnn_tpu/models/gpt.py init): attention qkv / mlp fc shard their output
    features, their projections shard input features (so each device owns
    whole heads / whole hidden slices and GSPMD inserts one all-reduce per
    residual write); embeddings and lm_head shard the vocab/embed dim;
    norms replicate."""

    def spec_for(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        return _tp_base_spec(keys, leaf.ndim, axis)

    return jax.tree_util.tree_map_with_path(spec_for, params)


# _tp_base_spec covers the LLaMA key family too (q/k/v/gate/up/o/down) —
# the dp x tp llama step's contract IS this builder, registered under its
# own name so the shardcheck audit verifies the llama program against it
contract("train.llama_dp_tp.params")(gpt_tp_specs)


def gpt_tp_specs_stacked(prepared, *, axis: str = MODEL_AXIS):
    """PartitionSpecs for the STACKED param layout (`gpt.prepare_stacked`:
    {'blocks': (L, ...) stacks, 'wte', 'wpe', 'ln_f', 'lm_head'}) — the
    same Megatron-style sharding as `gpt_tp_specs`, with block leaves
    carrying a leading (replicated) layer axis. Used to run the serving
    path (make_apply_stacked / make_generate) tensor-parallel: place
    `prepared` with these specs and GSPMD inserts the collectives."""

    def spec_for(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        stacked = "blocks" in keys
        base = _tp_base_spec(keys, leaf.ndim - (1 if stacked else 0), axis)
        if stacked and base != P():
            return P(None, *base)  # replicated leading layer axis
        return base

    return jax.tree_util.tree_map_with_path(spec_for, prepared)


def gpt_tp_pp_specs(stage_stacked, *, stage_axis: str = STAGE_AXIS,
                    model_axis: str = MODEL_AXIS):
    """PartitionSpecs for TP x PP: the stage-stacked GPT block tree
    ((S, L/S, ...) leaves) sharded over BOTH the pipeline and the tensor
    axis, for `spmd_pipeline_stacked(..., param_specs=...)` with
    `gpt.make_tp_block_fn` as the block function.

    Megatron placement per leaf (leading (stage, layer) axes always
    P(stage, None)):
      * qkv / fc kernels (S, L/S, C, out): column-parallel — output
        features shard over `model` (qkv must be shard-major reordered
        first, gpt.prepare_tp_blocks); their biases shard with the columns;
      * proj kernels (S, L/S, in, C): row-parallel — input features shard
        over `model`; their biases replicate (added once after the psum);
      * layer norms replicate over `model`.
    """

    def spec_for(path, leaf):
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        if {"qkv", "fc"} & set(keys):
            if leaf.ndim >= 4:  # kernel (S, L/S, in, out)
                return P(stage_axis, None, None, model_axis)
            return P(stage_axis, None, model_axis)  # bias (S, L/S, out)
        if "proj" in keys and leaf.ndim >= 4:
            return P(stage_axis, None, model_axis, None)
        return P(stage_axis)  # norms + row-parallel biases

    return jax.tree_util.tree_map_with_path(spec_for, stage_stacked)


def specs_to_shardings(mesh: Mesh, specs):
    """PartitionSpec pytree -> NamedSharding pytree (specs are themselves
    pytrees, hence the is_leaf guard)."""
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=lambda x: isinstance(x, P)
    )


def shard_pytree(tree, mesh: Mesh, specs):
    """Place a pytree on the mesh with the given PartitionSpecs."""
    return jax.device_put(tree, specs_to_shardings(mesh, specs))


def _spec_with_data_axis(spec, leaf, n_data: int, data_axis: str):
    """Insert `data_axis` into the first UNSHARDED dimension of `leaf`
    whose size tiles the data-axis extent; the existing (tp) entries are
    kept. No candidate dimension -> the spec is returned unchanged (the
    leaf stays replicated over data)."""
    entries = list(spec) if spec is not None else []
    entries += [None] * (leaf.ndim - len(entries))
    for ax in range(leaf.ndim):
        if entries[ax] is None and leaf.shape[ax] % n_data == 0 \
                and leaf.shape[ax] >= n_data:
            entries[ax] = data_axis
            return P(*entries)
    return spec if spec is not None else P()  # unchanged, as documented


@contract("train.zero1.opt_state")
def zero1_opt_state_specs(opt_state, params, param_specs, mesh: Mesh,
                          *, data_axis: str = DATA_AXIS):
    """ZeRO-1: PartitionSpecs that shard the OPTIMIZER STATE over the data
    axis (DeepSpeed stage-1 / optax-style state partitioning, built as
    GSPMD annotations instead of manual scatter/gather code). Param-shaped
    subtrees of the state (adam mu/nu, momentum buffers, ...) take their
    param's tp spec PLUS `data_axis` on the first free dimension — the
    moments live sliced 1/n per data column, and XLA derives the ZeRO
    collective schedule (reduce-scatter the grads into the update, shard
    the elementwise update math, all-gather the applied updates) from the
    shardings alone. Scalar leaves (step counts) replicate.

    `opt_state` may be a real state or `jax.eval_shape(optimizer.init,
    params)` output — only the tree structure and leaf shapes are read."""
    n_data = mesh.shape[data_axis]
    pdef = jax.tree.structure(params)

    def rec(node):
        try:
            if jax.tree.structure(node) == pdef:
                # is_leaf: P is a tuple subclass — without the guard the
                # traversal would descend INTO each PartitionSpec
                return jax.tree.map(
                    lambda spec, leaf: _spec_with_data_axis(
                        spec, leaf, n_data, data_axis),
                    param_specs, node,
                    is_leaf=lambda x: isinstance(x, P),
                )
        except Exception:  # structure() can reject exotic nodes — treat
            pass           # them per-field below
        if hasattr(node, "_fields"):  # optax NamedTuple states
            return type(node)(*(rec(getattr(node, f)) for f in node._fields))
        if isinstance(node, (list, tuple)):
            return type(node)(rec(c) for c in node)
        if isinstance(node, dict):
            return {k: rec(v) for k, v in node.items()}
        return P()  # scalar leaf (count etc.): replicated

    return rec(opt_state)


def init_zero1_opt_state(optimizer, params, param_specs, mesh: Mesh,
                         *, data_axis: str = DATA_AXIS):
    """Build the optimizer state directly INTO its ZeRO-1 shardings (no
    full-replica materialization). Returns (opt_state, opt_specs)."""
    shapes = jax.eval_shape(optimizer.init, params)
    specs = zero1_opt_state_specs(shapes, params, param_specs, mesh,
                                  data_axis=data_axis)
    opt_state = jax.jit(
        optimizer.init, out_shardings=specs_to_shardings(mesh, specs)
    )(params)
    return opt_state, specs


def fsdp_param_specs(params, mesh: Mesh, *, data_axis: str = DATA_AXIS,
                     base_specs=None):
    """FSDP / ZeRO-3: PartitionSpecs that shard the PARAMETERS THEMSELVES
    over the data axis — each device holds a 1/d slice of every weight,
    and GSPMD derives the FSDP collective schedule from the annotations
    alone: all-gather each weight just before its matmul (forward and
    backward), reduce-scatter its gradient, run the optimizer update on
    the local 1/d shard. No gather/scatter code is written here; the specs
    ARE the implementation (the scaling-book recipe, applied to weights).

    `base_specs` composes with tensor parallelism: pass the Megatron specs
    (gpt_tp_specs) and each leaf keeps its tp axis while the data axis
    lands on the first remaining free, divisible dimension — 2D
    {data, model} weight sharding. Leaves with no dimension divisible by
    the data-axis extent (tiny biases, scalar norms) stay as their base
    spec: replicated weights that XLA keeps resident, which is exactly
    what FSDP implementations do with small tensors.

    Optimizer state needs no separate treatment (unlike ZeRO-1's
    `zero1_opt_state_specs`): `optimizer.init` under jit propagates the
    param shardings into the moments, so adam mu/nu are born 1/d-sliced —
    ZeRO-2 (sharded grads via the reduce-scatter) and ZeRO-3 fall out of
    the same annotations. The reference has no training at all
    (readme.md:112); this surpasses it along the memory axis: peak
    per-device param+moment bytes drop ~1/d."""
    n_data = mesh.shape[data_axis]
    if base_specs is None:
        base_specs = jax.tree.map(lambda _: P(), params)

    def spec_for(spec, leaf):
        if data_axis in tuple(spec):  # already data-sharded (don't double)
            return spec
        return _spec_with_data_axis(spec, leaf, n_data, data_axis)

    return jax.tree.map(spec_for, base_specs, params,
                        is_leaf=lambda x: isinstance(x, P))


def make_sharded_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_specs,
    *,
    batch_axis: str = DATA_AXIS,
    zero1: bool = False,
    donate: bool = False,
    grad_stats: bool = False,
):
    """dp x tp train step. Params must be placed with `shard_pytree(params,
    mesh, param_specs)`; the batch is sharded over `batch_axis` here. The
    returned step keeps params/opt_state shardings stable across calls (no
    resharding churn), and gradient all-reduce over "data" plus tp
    collectives over "model" are inserted by GSPMD.

    `zero1=True` additionally pins the optimizer state to its ZeRO-1
    shardings (zero1_opt_state_specs): adam moments live 1/n-sliced over
    the data axis instead of replicated — pass a state built by
    `init_zero1_opt_state` (a replicated one is resharded on first
    step). Loss/params stay numerically identical to zero1=False; only
    memory and the collective schedule change.

    `donate=True` donates params and opt_state to the step (the sharded
    steady state: old and new params never coexist in HBM). Opt-in
    because donated buffers are invalidated — callers that reread the
    previous state after stepping (the default-off safety) must rebind
    from the step's results. The shardcheck audit lowers the donating
    variant and fails the gate if any donated sharded leaf loses its
    output alias (PRG003 under NamedSharding).

    `grad_stats=True` adds the gradient-health 3-vector as a 4th
    output (_health_stats) — its reductions all-reduce over the mesh
    under GSPMD, so the readback is the GLOBAL grad norm, not one
    shard's. Donation-safe: the stats are fresh outputs computed
    before the donated buffers are overwritten (the program audit's
    alias check covers the donating variant unchanged)."""
    param_shardings = specs_to_shardings(mesh, param_specs)
    batch_sharding = NamedSharding(mesh, P(batch_axis))
    # ZeRO-1 opt-state specs depend on the state's tree structure, which
    # only exists inside the traced step — resolved once, at first trace
    opt_sharding_cache = {}
    jit = jax.jit if not donate else (
        lambda f: jax.jit(f, donate_argnums=(0, 1)))

    @jit
    def step(params, opt_state, batch):
        batch = jax.lax.with_sharding_constraint(batch, batch_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        grads = jax.lax.with_sharding_constraint(grads, param_shardings)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if zero1:
            if "specs" not in opt_sharding_cache:
                # tracers carry shape/structure — all the spec builder reads
                opt_sharding_cache["specs"] = specs_to_shardings(
                    mesh, zero1_opt_state_specs(
                        opt_state, params, param_specs, mesh,
                        data_axis=batch_axis))
            opt_state = jax.lax.with_sharding_constraint(
                opt_state, opt_sharding_cache["specs"])
        new_params = optax.apply_updates(params, updates)
        new_params = jax.lax.with_sharding_constraint(
            new_params, param_shardings)
        if grad_stats:
            return new_params, opt_state, loss, \
                _health_stats(grads, updates, params)
        return new_params, opt_state, loss

    return step


def init_sharded(init_fn: Callable, rng, mesh: Mesh, specs_fn: Callable = gpt_tp_specs):
    """Init params directly into their tp shardings (no full-replica
    materialization on one device): eval_shape -> out_shardings -> jit.

    Caveat (this jax's legacy threefry, jax_threefry_partitionable
    False): GSPMD may partition the random-bit generation along the
    output shardings, in which case values differ from an un-jitted
    `init_fn(rng)` — whether they do depends on the op layout (GPT's qkv
    init happens to match, LLaMA's fused init does not). Values are
    deterministic for a fixed (key, mesh, specs); treat them as "a"
    random init, not "the" `init_fn(rng)` init. Under partitionable
    threefry (newer-jax default) the two agree bitwise."""
    shapes = jax.eval_shape(init_fn, rng)
    specs = specs_fn(shapes)
    params = jax.jit(init_fn, out_shardings=specs_to_shardings(mesh, specs))(rng)
    return params, specs


# --------------------------------------------------------------------------
# pipeline-parallel training
# --------------------------------------------------------------------------

def resume_or_init(ckpt_dir: Optional[str], init_state):
    """Resume from the newest checkpoint under `ckpt_dir` (template =
    `init_state`), or start fresh. Returns (state, start_step). The
    resume half of SURVEY §5's checkpoint mandate (the reference has
    neither — node.py:294-317 only ever loads)."""
    from dnn_tpu.io.train_ckpt import checkpoint_path, restore_train_state

    if ckpt_dir:
        t0 = time.perf_counter()
        try:
            state, step = restore_train_state(ckpt_dir, like=init_state)
        except FileNotFoundError:
            pass
        else:
            try:
                import os

                nbytes = os.path.getsize(checkpoint_path(ckpt_dir, step))
            except OSError:
                nbytes = 0
            _trainlens.note_ckpt_restored(
                step, time.perf_counter() - t0, nbytes)
            return state, step
    return init_state, 0


def fit(
    step_fn: Callable,
    state,
    batch_iter,
    *,
    num_steps: int,
    start_step: int = 0,
    ckpt_dir: Optional[str] = None,
    ckpt_every: int = 0,
    keep_checkpoints: int = 3,
    on_step: Optional[Callable] = None,
    advance_batches: bool = True,
    eval_every: int = 0,
    eval_fn: Optional[Callable] = None,
    clock=None,
    sentinel=None,
):
    """Generic training loop with periodic checkpointing, phase-attributed
    by trainlens (obs/trainlens.py).

    `step_fn(state, batch) -> (state, loss)` over any state pytree (wrap
    the make_*_train_step outputs to this signature); a step built with
    `grad_stats=True` may return `(state, loss, stats)` — the 3-vector
    feeds the sentinel. `batch_iter` yields batches. Saves every
    `ckpt_every` steps into `ckpt_dir` and prunes to `keep_checkpoints`.
    `eval_fn(step, state)` runs every `eval_every` steps inside its own
    attributed phase. Returns (state, last_loss).

    Observability (all behind the one-None/boolean obs gate):
      * `clock` (a trainlens.TrainClock; default the installed
        `active_trainlens()`) splits every iteration into the
        data/dispatch/wait/ckpt/eval/obs phases — fit BLOCKS on each
        step's loss (`jax.block_until_ready`), so "wait" is the real
        device window and the loop never silently runs ahead of a
        failing program;
      * compile telemetry installs once at entry, and the FIRST step +
        every checkpointed step emit a `train_step` flight event — a
        cold-compile stall is a /debugz event, not an opaque hang;
      * checkpoint saves/restores land duration+bytes histograms,
        freshness gauges, and `ckpt_saved` flight events
        (trainlens.note_ckpt_saved);
      * `sentinel` (a trainlens.GradSentinel) observes every step's
        loss (+ stats when the step returns them): grad_spike /
        loss_nan / train_stall flight events, incident bundle on
        divergence;
      * the chaos `train_fault` seam is consulted per iteration inside
        the data window: "sleep" stalls the input pipeline (the
        data_stall attribution vector), "nan" poisons the batch's
        float leaves (the sentinel's test vector).

    On resume (`start_step > 0`) the default `advance_batches=True` skips
    the first `start_step` batches, so a deterministic data pipeline
    restarted from scratch lines back up with the training step — without
    this a resumed run would silently re-train on the earliest batches.
    Pass False only when `batch_iter` is already positioned at
    `start_step`."""
    _obs.install_compile_telemetry()
    if clock is None:
        clock = _trainlens.active_trainlens()
    if advance_batches:
        for skipped in range(start_step):
            try:
                next(batch_iter)
            except StopIteration:
                raise ValueError(
                    f"batch_iter exhausted after {skipped} batches while "
                    f"skipping to resume step {start_step}; pass an "
                    "iterator that covers the resume point"
                ) from None

    loss = None
    first = True
    for step in range(start_step, num_steps):
        rec = clock.begin() if clock is not None else None
        try:
            batch = next(batch_iter)
        except StopIteration:
            raise ValueError(
                f"batch_iter exhausted at step {step} (wanted {num_steps}); "
                "pass an infinite iterator or lower num_steps"
            ) from None
        fault = _chaos.train_fault()
        if fault is not None:
            if fault["mode"] == "sleep":
                time.sleep(fault["delay_s"])
            elif fault["mode"] == "nan":
                batch = poison_batch(batch)
        if rec is not None:
            clock.mark(rec, "data")
        out = step_fn(state, batch)
        stats = None
        if len(out) == 3:
            state, loss, stats = out
        else:
            state, loss = out
        if rec is not None:
            clock.mark(rec, "dispatch")
        # block on the step's outputs: "wait" is the real device window,
        # and a NaN/crash surfaces at ITS step instead of steps later
        loss, stats = jax.block_until_ready((loss, stats))
        if rec is not None:
            clock.mark(rec, "wait")
        if ckpt_dir and ckpt_every and (step + 1) % ckpt_every == 0:
            t_ck = time.perf_counter()
            save_checkpoint_multihost(
                ckpt_dir, step + 1, state, keep=keep_checkpoints
            )
            _trainlens.note_ckpt_saved(
                step + 1, time.perf_counter() - t_ck,
                _ckpt_nbytes(ckpt_dir, step + 1), clock=clock)
            _flight.record("train_step", step=step + 1,
                           checkpointed=True)
        if rec is not None:
            clock.mark(rec, "ckpt")
        if eval_fn is not None and eval_every \
                and (step + 1) % eval_every == 0:
            eval_fn(step + 1, state)
        if rec is not None:
            clock.mark(rec, "eval")
        if first:
            # the first step carries the cold compile: its flight event
            # is what distinguishes "compiling" from "hung" in /debugz
            _flight.record("train_step", step=step + 1, first=True)
            first = False
        if sentinel is not None:
            sentinel.observe(step + 1, loss, stats)
        if on_step is not None:
            on_step(step + 1, loss)
        if rec is not None:
            clock.end(rec)
    return state, loss


def _ckpt_nbytes(ckpt_dir: str, step: int) -> int:
    """Size of the checkpoint a save just wrote (0 when this process is
    not the multihost writer — only process 0 has the file)."""
    import os

    from dnn_tpu.io.train_ckpt import checkpoint_path

    try:
        return os.path.getsize(checkpoint_path(ckpt_dir, step))
    except OSError:
        return 0


def save_checkpoint_multihost(ckpt_dir: str, step: int, state, *, keep: int = 3):
    """Checkpoint save that is correct under `jax.distributed`: every
    process walks the state's leaves in the same order and allgathers each
    non-fully-addressable one (a collective — all processes must reach the
    call), but only process 0 RETAINS the gathered value; the others drop
    each leaf immediately, so no host except the writer ever holds the full
    unsharded state (params + optimizer moments) at once. Only process 0
    writes, so N processes sharing one checkpoint directory never race on
    the rename pair in save_train_state. Single-process: a plain save."""
    from dnn_tpu.io.train_ckpt import cleanup_old_checkpoints, save_train_state

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        is_writer = jax.process_index() == 0
        leaves, treedef = jax.tree.flatten(state)
        gathered = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                full = multihost_utils.process_allgather(leaf, tiled=True)
                gathered.append(full if is_writer else None)
            else:
                gathered.append(leaf)
        if not is_writer:
            return
        state = jax.tree.unflatten(treedef, gathered)
    save_train_state(ckpt_dir, step, state)
    cleanup_old_checkpoints(ckpt_dir, keep=keep)


def make_pipeline_train_step(
    block_fn: Callable,
    embed_fn: Callable,
    head_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    num_microbatches: int = 1,
    axis_name: str = STAGE_AXIS,
    loss: Callable = cross_entropy,
    schedule: str = "gpipe",
    data_axis: Optional[str] = None,
    virtual_stages: int = 1,
    param_specs=None,
):
    """Pipeline-parallel LM training step.

    `stacked` block params live sharded P(stage) (each device holds its
    stage's blocks — same layout the inference engine uses); `aux` holds
    embed/head params (replicated).

    `data_axis` composes DATA parallelism with the pipeline over a 2D
    {data, stage} mesh (gpipe schedule only): the global batch shards over
    the data axis, every data column pipelines its slice over the stage
    axis, and the shard_map transpose psums block-param gradients across
    columns; embed/head/loss run under GSPMD, which inserts the remaining
    batch collectives. Same loss as the 1D run on the same global batch
    (fp-reassociation tolerance) — tested in tests/test_dp_pp.py.

    `schedule="gpipe"`: forward through the microbatched GPipe loop, then
    differentiate straight through it — the reverse of each ppermute hop is
    a ppermute in the opposite direction on the same ring. Autodiff keeps
    every microbatch's stage activations as residuals, so peak activation
    memory grows with num_microbatches.

    `schedule="1f1b"`: the fused one-forward-one-backward loop
    (spmd_pipeline_train_1f1b) — each microbatch's backward starts as soon
    as the last stage finishes its forward, bounding stashed activations
    at min(M, 2S-1) slots per device regardless of M. Same loss and
    gradients (parity-tested); choose it when activations dominate memory.

    `schedule="interleaved"`: the virtual-stage schedule — `stacked` must
    carry a leading (virtual_stages * S) CHUNK axis (each device owns
    `virtual_stages` non-adjacent layer chunks) and the bubble shrinks
    from (S-1)/(M+S-1) to (S-1)/(VM+S-1)
    (pipeline.spmd_pipeline_interleaved). Differentiated through like
    gpipe; same loss/grads.

    `param_specs` composes TENSOR parallelism inside each stage (TP x PP;
    with `data_axis` too, the full Megatron 3D {data, stage, model}
    recipe; gpipe schedule only): pass `gpt_tp_pp_specs(stacked)` plus a
    TP-aware `block_fn` (gpt.make_tp_block_fn over
    gpt.prepare_tp_blocks'd params). Grad/optimizer sharding follows the
    param specs — each device updates only its own weight shard; the
    shard_map transpose reassembles cross-shard cotangents exactly
    (loss/grad parity vs the 1D pipeline is pinned by
    tests/test_tp_pp.py).

    step(stacked, aux, opt_states, tokens) ->
        (stacked, aux, opt_states, loss_value)
    """
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            f"schedule must be gpipe|1f1b|interleaved, got {schedule!r}")
    if data_axis is not None and schedule != "gpipe":
        raise ValueError(
            "data_axis composition is implemented for the gpipe schedule "
            "only; 1f1b/interleaved run on a 1D stage mesh"
        )
    if param_specs is not None and schedule != "gpipe":
        raise ValueError(
            "param_specs (TP x PP) composition is implemented for the "
            "gpipe schedule only"
        )
    if schedule == "interleaved" and virtual_stages < 2:
        raise ValueError(
            "schedule='interleaved' needs virtual_stages >= 2 (1 is exactly "
            "gpipe; use that)")

    def gpipe_loss_and_grad(stacked, aux, tokens):
        def loss_fn(stacked, aux):
            x = embed_fn(aux, tokens[:, :-1])
            if schedule == "interleaved":
                h = spmd_pipeline_interleaved(
                    block_fn, stacked, x,
                    mesh=mesh, num_microbatches=num_microbatches,
                    virtual_stages=virtual_stages, axis_name=axis_name,
                )
            else:
                h = spmd_pipeline_stacked(
                    block_fn, stacked, x,
                    mesh=mesh, num_microbatches=num_microbatches,
                    axis_name=axis_name, data_axis=data_axis,
                    param_specs=param_specs,
                )
            logits = head_fn(aux, h)
            return loss(logits, tokens[:, 1:])

        return jax.value_and_grad(loss_fn, argnums=(0, 1))(stacked, aux)

    def f1b_loss_and_grad(stacked, aux, tokens):
        ids_mb = split_microbatches(tokens[:, :-1], num_microbatches)
        tgt_mb = split_microbatches(tokens[:, 1:], num_microbatches)
        lval, g_st, g_aux = spmd_pipeline_train_1f1b(
            block_fn, embed_fn,
            lambda ax, h, tgt: loss(head_fn(ax, h), tgt),
            stacked, aux, ids_mb, tgt_mb,
            mesh=mesh, axis_name=axis_name,
        )
        return lval, (g_st, g_aux)

    # interleaved shares the gpipe path (autodiff through the scheduled
    # forward); only 1f1b has its own fused loop
    loss_and_grad = (f1b_loss_and_grad if schedule == "1f1b"
                     else gpipe_loss_and_grad)

    @jax.jit
    def step(stacked, aux, opt_states, tokens):
        st_opt, aux_opt = opt_states
        lval, (g_st, g_aux) = loss_and_grad(stacked, aux, tokens)
        up_st, st_opt = optimizer.update(g_st, st_opt, stacked)
        stacked = optax.apply_updates(stacked, up_st)
        up_aux, aux_opt = optimizer.update(g_aux, aux_opt, aux)
        aux = optax.apply_updates(aux, up_aux)
        return stacked, aux, (st_opt, aux_opt), lval

    return step
