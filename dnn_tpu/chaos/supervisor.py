"""Stage supervisor: restart a dead or wedged serving process.

The recovery half of ROADMAP item 5's "watchdog detects, nothing
reacts": a `Supervisor` owns ONE serving child (a stage server or the
LM daemon), and

  * restarts it when it EXITS, with exponential backoff (reset after a
    stable uptime) and crash-loop detection — more than
    `crash_loop_max` restarts inside `crash_loop_window_s` records a
    `crash_loop` flight event and gives up (a config that can never
    boot must not be kill-9'd in a tight loop forever);
  * detects a WEDGED child (alive but unresponsive — the SIGSTOP /
    hung-driver shape the watchdog classifies in-process) by polling
    `health_url` with a hard per-poll timeout; `wedged_after`
    consecutive failures fire the `on_wedged` policy: "restart"
    (SIGKILL + restart), "drain" (POST /drainz, wait for in-flight
    work, then restart) or "none" (detect + record only — the passive
    503 behavior);
  * optionally runs `restore()` before each (re)launch — the
    checkpoint hook; `restore_latest_good` below restores the newest
    checkpoint that LOADS, failing loud per corrupt artifact — and
    `warm()` after health returns, so recovery is declared only once
    the child actually serves again (a cold restart's first-compile
    window is part of the outage, not of "recovered").

Flight events (`supervisor_*`) pair with the injections that caused
them: `stage_down`/`stage_wedged` on detection, `supervisor_restart`
on a completed recovery — `benchmarks/chaos_probe.py` asserts the
pairing from the dumped ring.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Callable, List, Optional

from dnn_tpu.obs import flight

__all__ = ["Supervisor", "restore_latest_good", "recover_backend"]


class Supervisor:
    """Supervise one serving child process.

    `spawn`: callable -> subprocess.Popen (re-invoked for every
    launch; argv closures keep restore/launch decisions in one place).
    `health_url`: an obs endpoint base (http://host:port) — or a
    CALLABLE returning one, resolved fresh per poll, so a child that
    rebinds an ephemeral port on relaunch stays pollable — whose
    `health_path` (default /healthz) is polled every
    `health_interval_s` with a `health_timeout_s` hard timeout; each
    poll opens a FRESH connection, so a previous poll wedged in a dead
    socket can never mask a recovery (the PR 7 stale-channel lesson,
    applied here). The injectable endpoint/path is what lets a fleet
    spawner (dnn_tpu/control/replicaset.py) supervise N replicas on N
    distinct metrics ports without subclassing; `drain_path` names the
    drain kicker the same way (default /drainz).
    `ready`: callable -> bool, polled after launch until the child
    serves (default: health_url reachable); `warm`: optional callable
    run once after ready — a real request through the child, so
    `supervisor_restart` means "serving", not "bound a port".
    """

    def __init__(self, spawn: Callable[[], subprocess.Popen], *,
                 name: str = "stage",
                 health_url=None,
                 health_path: str = "/healthz",
                 drain_path: str = "/drainz",
                 health_interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 wedged_after: int = 3,
                 on_wedged: str = "restart",
                 backoff_s: float = 0.5,
                 backoff_max_s: float = 15.0,
                 stable_after_s: float = 30.0,
                 crash_loop_max: int = 5,
                 crash_loop_window_s: float = 120.0,
                 ready_deadline_s: float = 120.0,
                 restore: Optional[Callable[[], None]] = None,
                 warm: Optional[Callable[[], None]] = None,
                 ready: Optional[Callable[[], bool]] = None):
        if on_wedged not in ("restart", "drain", "none"):
            raise ValueError(
                f"on_wedged must be restart|drain|none, got {on_wedged!r}")
        self.spawn = spawn
        self.name = name
        self.health_url = health_url
        self.health_path = health_path
        self.drain_path = drain_path
        self.health_interval_s = float(health_interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.wedged_after = int(wedged_after)
        self.on_wedged = on_wedged
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.stable_after_s = float(stable_after_s)
        self.crash_loop_max = int(crash_loop_max)
        self.crash_loop_window_s = float(crash_loop_window_s)
        self.ready_deadline_s = float(ready_deadline_s)
        self.restore = restore
        self.warm = warm
        self.ready = ready
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        # the supervisor state machine is DECLARED (and model-checked)
        # in analysis/protocol.SUPERVISOR — edit both together
        self.state = "init"  # init|up|restarting|crashloop|stopped
        self._restart_times: List[float] = []
        self._health_fails = 0
        self._ever_healthy = False  # boot grace: a child still importing
        # jax must not read as wedged before its first healthy poll
        self._launched_at = 0.0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"chaos-supervisor-{name}")

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "Supervisor":
        self._launch(first=True)
        self._thread.start()
        return self

    def stop(self, kill_child: bool = True):
        self._stop.set()
        self._thread.join(timeout=self.health_timeout_s
                          + self.health_interval_s + 5)
        if kill_child and self.proc is not None \
                and self.proc.poll() is None:
            try:
                self.proc.kill()
                self.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 — already-gone child
                pass
        self.state = "stopped"

    # -- fault-injection helpers (the chaos driver's hands) -------------

    def inject_kill(self):
        """SIGKILL the child NOW (the kill_stage fault). The run loop
        notices the exit and drives the ordinary restart path — the
        injection and the recovery use the same machinery production
        would."""
        p = self.proc
        if p is not None and p.poll() is None:
            p.kill()

    def inject_hang(self):
        """SIGSTOP the child (the hang_stage fault): alive but
        unresponsive — exactly the wedge shape. Recovery comes from the
        health poller's wedged policy, never from a SIGCONT."""
        p = self.proc
        if p is not None and p.poll() is None:
            os.kill(p.pid, signal.SIGSTOP)

    # -- internals -----------------------------------------------------

    def _health_base(self) -> Optional[str]:
        """Resolve the probe base URL: a plain string, or a callable
        re-evaluated per poll (ephemeral-port children)."""
        u = self.health_url
        if callable(u):
            try:
                u = u()
            except Exception:  # noqa: BLE001 — "don't know the URL
                return None    # yet" reads as not-healthy, not a crash
        return u

    def _healthy_once(self) -> bool:
        import urllib.request

        base = self._health_base()
        if self.health_url is None:
            return True
        if base is None:
            return False
        try:
            with urllib.request.urlopen(
                    base.rstrip("/") + self.health_path,
                    timeout=self.health_timeout_s) as r:
                return r.status == 200
        except Exception:  # noqa: BLE001 — any failure is "not healthy"
            return False

    def _wait_ready(self) -> bool:
        t_end = time.monotonic() + self.ready_deadline_s
        check = self.ready if self.ready is not None else self._healthy_once
        while time.monotonic() < t_end and not self._stop.is_set():
            if self.proc is not None and self.proc.poll() is not None:
                return False  # died during boot: the loop restarts it
            try:
                if check():
                    return True
            except Exception:  # noqa: BLE001 — not ready yet
                pass
            time.sleep(0.25)
        return False

    def _launch(self, first: bool = False):
        if self.restore is not None:
            try:
                self.restore()
            except Exception as e:  # noqa: BLE001 — a failed restore is
                # part of the incident record, not a supervisor death
                flight.record("supervisor_restore_failed", stage=self.name,
                              error=str(e)[:300])
        self.proc = self.spawn()
        self._launched_at = time.monotonic()
        self._health_fails = 0
        self._ever_healthy = False
        self.state = "up"
        if not first:
            ok = self._wait_ready()
            if ok:
                self._ever_healthy = True
            if ok and self.warm is not None:
                try:
                    self.warm()
                except Exception as e:  # noqa: BLE001
                    flight.record("supervisor_warm_failed",
                                  stage=self.name, error=str(e)[:300])
                    ok = False
            if ok:
                flight.record("supervisor_restart", stage=self.name,
                              restarts=self.restarts,
                              pid=self.proc.pid)

    def _crash_looping(self, now: float) -> bool:
        self._restart_times = [
            t for t in self._restart_times
            if now - t <= self.crash_loop_window_s]
        return len(self._restart_times) >= self.crash_loop_max

    def _restart(self, reason: str):
        now = time.monotonic()
        if self._crash_looping(now):
            self.state = "crashloop"
            flight.record("crash_loop", stage=self.name,
                          restarts=self.restarts,
                          window_s=self.crash_loop_window_s,
                          max=self.crash_loop_max)
            return
        self.state = "restarting"
        # exponential backoff over RECENT restarts only: a child that
        # stayed up past stable_after_s earns a fresh ladder
        recent = len(self._restart_times)
        if now - self._launched_at >= self.stable_after_s:
            recent = 0
            self._restart_times.clear()
        delay = min(self.backoff_s * (2 ** recent), self.backoff_max_s)
        flight.record("supervisor_backoff", stage=self.name,
                      reason=reason, delay_s=round(delay, 3),
                      attempt=recent + 1)
        if self._stop.wait(delay):
            return
        self._restart_times.append(time.monotonic())
        self.restarts += 1
        self._launch()

    def _kill_child(self):
        p = self.proc
        if p is None or p.poll() is not None:
            return
        try:
            p.kill()
            p.wait(timeout=10)
        except Exception:  # noqa: BLE001 — D-state child: move on
            pass

    def _drain_child(self) -> bool:
        """POST /drainz and wait (bounded) for the child to report
        drained / become unreachable — the graceful half of the drain
        policy; the caller restarts afterwards either way."""
        import urllib.request

        base = self._health_base()
        if base is None:
            return False
        try:
            req = urllib.request.Request(
                base.rstrip("/") + self.drain_path, method="POST",
                data=b"")
            with urllib.request.urlopen(
                    req, timeout=self.health_timeout_s) as r:
                ok = r.status in (200, 202)
        except Exception:  # noqa: BLE001 — a wedged child can't drain
            return False
        if not ok:
            return False
        t_end = time.monotonic() + max(self.ready_deadline_s, 10.0)
        while time.monotonic() < t_end and not self._stop.is_set():
            p = self.proc
            if p is not None and p.poll() is not None:
                return True  # drained and exited
            time.sleep(0.5)
        return False

    def _run(self):
        while not self._stop.is_set():
            p = self.proc
            if self.state == "crashloop":
                self._stop.wait(self.health_interval_s)
                continue
            if p is None or p.poll() is not None:
                rc = p.returncode if p is not None else None
                flight.record("stage_down", stage=self.name, rc=rc)
                self._restart(f"exit rc={rc}")
                continue
            if self.health_url is not None and self.state == "up":
                if self._healthy_once():
                    self._ever_healthy = True
                    self._health_fails = 0
                elif not self._ever_healthy:
                    # boot grace: never healthy yet — only the ready
                    # deadline (not the consecutive-failure count) can
                    # condemn a child that is still importing/compiling
                    if time.monotonic() - self._launched_at \
                            > self.ready_deadline_s:
                        flight.record("stage_wedged", stage=self.name,
                                      reason="never became ready",
                                      policy=self.on_wedged)
                        if self.on_wedged != "none":
                            self._kill_child()
                            self._restart("never ready")
                            continue
                else:
                    self._health_fails += 1
                    if self._health_fails >= self.wedged_after:
                        flight.record(
                            "stage_wedged", stage=self.name,
                            consecutive_failures=self._health_fails,
                            policy=self.on_wedged)
                        if self.on_wedged == "none":
                            self._health_fails = 0  # re-detect, re-record
                        else:
                            if self.on_wedged == "drain":
                                self._drain_child()
                            self._kill_child()
                            self._restart("wedged")
                            continue
            self._stop.wait(self.health_interval_s)


def restore_latest_good(ckpt_dir: str, like, *, max_back: int = 5):
    """Restore the newest checkpoint under `ckpt_dir` that actually
    LOADS. A corrupt newest artifact (the ckpt_corrupt fault, or real
    crash debris) fails loud — a `ckpt_restore_failed` flight event
    naming the file — and the walk falls back to the previous good one
    instead of serving garbage or dying. Returns (state, step, path);
    raises RuntimeError when nothing within `max_back` steps loads.

    `like` is the template pytree `io.train_ckpt.restore_train_state`
    needs (a freshly-initialized state of the right treedef)."""
    from dnn_tpu.io.train_ckpt import latest_checkpoint, restore_train_state

    if not os.path.isdir(ckpt_dir):
        raise RuntimeError(f"no checkpoint directory at {ckpt_dir!r}")
    candidates = []
    for name in sorted(os.listdir(ckpt_dir), reverse=True):
        if name.startswith("step_") and name.endswith(".npz"):
            candidates.append(os.path.join(ckpt_dir, name))
    if not candidates:
        latest = latest_checkpoint(ckpt_dir)
        if latest is None:
            raise RuntimeError(f"no checkpoints under {ckpt_dir!r}")
        candidates = [latest[0]]
    errors = []
    for path in candidates[:max_back]:
        try:
            state, step = restore_train_state(path, like)
            if errors:  # recovered past >=1 corrupt artifact: record it
                flight.record("ckpt_restore_recovered", path=path,
                              step=step, skipped=len(errors))
            return state, step, path
        except Exception as e:  # noqa: BLE001 — corrupt/truncated/foreign
            flight.record("ckpt_restore_failed", path=path,
                          error=str(e)[:300])
            errors.append((path, str(e)))
    raise RuntimeError(
        f"no loadable checkpoint in the newest {max_back} under "
        f"{ckpt_dir!r}; failures: "
        + "; ".join(f"{os.path.basename(p)}: {e[:80]}"
                    for p, e in errors))


def recover_backend(platform: Optional[str] = None, *,
                    deadline_s: float = 300.0):
    """The supervisor restart path for a WEDGED DEVICE BACKEND (no
    child process to restart — the wedge lives in the driver/plugin):
    a fresh subprocess re-initializes the platform from nothing and
    runs one real op, which is the only restart a user-space harness
    can give a device runtime. Returns (ok, detail). Used by bench.py's
    round driver when the probe reports wedged mid-round; `deadline_s`
    defaults to the longest healthy cold init the bench ladder allows
    (300 s), so a slow-but-recovering plugin is never re-declared dead
    by its own recovery probe."""
    from dnn_tpu.obs.watchdog import subprocess_device_probe

    flight.record("supervisor_device_restart", platform=platform,
                  deadline_s=deadline_s)
    ok, detail, timed_out = subprocess_device_probe(
        deadline_s, platform=platform)
    flight.record("supervisor_device_restart_done", ok=ok,
                  detail=detail[:200], timed_out=timed_out)
    return ok, detail
