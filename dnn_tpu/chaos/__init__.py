"""dnn_tpu.chaos: fault injection + the recovery machinery it forces.

The obs arc (PRs 3-5) made every failure mode *visible* — watchdog
wedges, SLO burn, flight-recorder timelines — but nothing *reacted*: a
dead stage failed every in-flight request permanently and a wedged
device 503'd until a human restarted the process (ROADMAP item 5).
This package is the other half:

  * `plan.FaultPlan` — a deterministic, seeded schedule of faults
    (stage kill/hang, injected device wedge, RPC/relay drop-delay-
    corrupt, KV-pool exhaustion, checkpoint corruption), loadable from
    JSON / a file / the `--chaos` CLI flag. In-process faults trigger
    on CALL COUNTERS through a seeded hash — never wall-clock
    randomness in traced or hot-path code — so the same plan + seed
    reproduces the same injection sequence bit-for-bit.
  * `inject.Injector` — the process-local seam driver. The comm
    client/service, the relay assembler, the LM batcher worker and the
    watchdog's probe path each consult it with a single is-None check
    when chaos is off. Every injection lands in the flight recorder as
    a `chaos_inject` event, so each induced incident is reconstructable
    from `/debugz`.
  * `supervisor.Supervisor` — restarts a dead or wedged serving child
    with exponential backoff and crash-loop detection, optionally
    restoring from the latest GOOD checkpoint
    (`restore_latest_good`) and re-warming before declaring recovery
    (`supervisor_restart` flight events pair with the injections).

`benchmarks/chaos_probe.py` closes the loop: open-loop load through a
real 2-stage pipeline under the standard FaultPlan, asserting
availability, p99-TTFT-after-recovery and inject/recovery event
pairing — resilience as a regression-asserted number, the way PR 6 did
MBU and PR 7 did bubble fraction.
"""

from dnn_tpu.chaos.inject import (  # noqa: F401
    Injector,
    active,
    corrupt_file,
    install,
    uninstall,
)
from dnn_tpu.chaos.plan import Fault, FaultPlan  # noqa: F401

__all__ = ["Fault", "FaultPlan", "Injector", "install", "uninstall",
           "active", "corrupt_file"]
