"""Process-local fault injector: the seams consult, the plan decides.

One injector per process, installed with `install(plan)` (the node CLI's
`--chaos` flag or a test). Seam entry points are module-level functions
that cost ONE global is-None check when chaos is off — the same
degradation discipline as `obs.flight.record`:

    perturb_rpc(seam, target)   comm client/service, before each RPC
                                attempt: may sleep (rpc_delay), raise a
                                retryable UNAVAILABLE (rpc_drop), or
                                raise PayloadCorruptError (rpc_corrupt)
    perturb_relay()             relay frame ingress (ChunkAssembler):
                                drop (frame vanishes -> upstream
                                deadline) or corrupt (PayloadCorrupt)
    kv_exhaust()                LM admission: True -> the admission
                                raises InsufficientBlocks (held-back /
                                requeue path under a full pool)
    step_fault()                LM batcher step: raises at the
                                scheduled step counter (worker-death /
                                requeue path)
    train_fault()               training loop (train.fit): non-None ->
                                a directive dict — {"mode": "nan"}
                                poisons the batch's float leaves (the
                                gradient-sentinel vector) or
                                {"mode": "sleep", "delay_s": s} stalls
                                the input pipeline (the data_stall
                                attribution vector)
    wedge_detail()              watchdog probe: non-None -> the probe
                                reports a structural timeout (wedged)
                                without touching any device

Every firing lands in the flight recorder as a `chaos_inject` event
(kind, seam, counter, target), so an induced incident reconstructs
from /debugz exactly like a real one. Decisions come from
`plan.decide(seed, seam, n)` — counter-indexed, seeded, no wall-clock
randomness (see plan.py's determinism contract).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dnn_tpu.chaos.plan import FaultPlan, decide

__all__ = ["Injector", "install", "uninstall", "active", "perturb_rpc",
           "perturb_relay", "kv_exhaust", "step_fault", "train_fault",
           "wedge_detail", "corrupt_file", "InjectedFault"]


class InjectedFault(Exception):
    """Marker base: every exception the injector raises derives from it
    (directly or via the transport's own error types), so logs can tell
    an induced failure from an organic one."""


def _record(kind: str, **fields):
    from dnn_tpu.obs import flight

    flight.record("chaos_inject", fault=kind, **fields)


def _injected_unavailable(detail: str):
    """A retryable transport error indistinguishable from a real
    UNAVAILABLE to the client's retry ladder (grpc imported lazily —
    the injector itself stays stdlib-only until an rpc fault fires)."""
    import grpc

    class _InjectedRpcError(grpc.RpcError, InjectedFault):
        def __init__(self, d):
            super().__init__(d)
            self._d = d

        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return self._d

    return _InjectedRpcError(detail)


class Injector:
    """Executes a FaultPlan's IN-PROCESS faults. Thread-safe: seams are
    hit from the gRPC event loop, the batcher worker and the watchdog
    thread concurrently; one lock guards the counters."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._counters: dict = {}      # seam -> consultations so far
        self._fired: dict = {}         # fault index -> firings so far
        self._t0 = time.monotonic()    # wedge windows anchor here
        self._wedge_until: Optional[float] = None  # manual activation
        self._wedge_logged = False
        self._faults = list(plan.inprocess_faults())

    # -- internals -----------------------------------------------------

    def _tick(self, seam: str) -> int:
        with self._lock:
            n = self._counters.get(seam, 0)
            self._counters[seam] = n + 1
            return n

    def _take(self, idx: int, fault) -> bool:
        """Consume one firing of fault `idx` if budget remains."""
        with self._lock:
            fired = self._fired.get(idx, 0)
            if fired >= fault.count:
                return False
            self._fired[idx] = fired + 1
            return True

    def _match_p(self, kinds, seam_group: str, n: int):
        """First budgeted probabilistic fault of `kinds` whose seam
        matches and whose seeded decision fires at counter n."""
        for idx, f in enumerate(self._faults):
            if f.kind not in kinds:
                continue
            if f.seam and f.seam != seam_group:
                continue
            if decide(self.plan.seed, f"{f.kind}:{f.seam}", n) < f.p \
                    and self._take(idx, f):
                return f
        return None

    # -- seams ---------------------------------------------------------

    def perturb_rpc(self, seam_group: str, target: str = ""):
        n = self._tick(f"rpc:{seam_group}")
        f = self._match_p(("rpc_drop", "rpc_delay", "rpc_corrupt"),
                          seam_group, n)
        if f is None:
            return
        _record(f.kind, seam=seam_group, n=n, target=target)
        if f.kind == "rpc_delay":
            time.sleep(f.delay_s)
            return
        if f.kind == "rpc_drop":
            raise _injected_unavailable(
                f"chaos: injected rpc drop (seam={seam_group}, n={n})")
        from dnn_tpu.io.serialization import PayloadCorruptError

        raise PayloadCorruptError(
            f"chaos: injected payload corruption (seam={seam_group}, "
            f"n={n})")

    def perturb_relay(self) -> bool:
        """Relay-frame seam. Returns True when the frame should be
        DROPPED (caller discards it); raises for corruption."""
        n = self._tick("relay")
        f = self._match_p(("relay_drop", "relay_corrupt"), "", n)
        if f is None:
            return False
        _record(f.kind, n=n)
        if f.kind == "relay_drop":
            return True
        from dnn_tpu.io.serialization import PayloadCorruptError

        raise PayloadCorruptError(
            f"chaos: injected relay frame corruption (n={n})")

    def kv_exhaust(self) -> bool:
        n = self._tick("kv")
        for f in self._faults:
            if f.kind != "kv_exhaust" or f.from_n < 0:
                continue
            if f.from_n <= n < f.from_n + f.count:
                _record("kv_exhaust", n=n)
                return True
        return False

    def step_fault(self):
        n = self._tick("step")
        for f in self._faults:
            if f.kind != "step_fault" or f.at_n < 0:
                continue
            if f.at_n <= n < f.at_n + f.count:
                _record("step_fault", n=n)
                raise RuntimeError(
                    f"chaos: injected device step fault (step n={n})")

    def train_fault(self) -> Optional[dict]:
        """Training-loop seam (train.fit's input phase): a `train_fault`
        fires at exact step counters and returns a DIRECTIVE rather
        than raising — the loop executes it inside its data window, so
        the injected cost lands exactly where the fault claims to live.
        `target` picks the mode: "nan" (default) poisons the batch's
        float leaves — the gradient-sentinel test vector — and "sleep"
        stalls for `delay_s` — the data_stall attribution vector."""
        n = self._tick("train")
        for f in self._faults:
            if f.kind != "train_fault" or f.at_n < 0:
                continue
            if f.at_n <= n < f.at_n + f.count:
                mode = f.target or "nan"
                _record("train_fault", n=n, mode=mode)
                return {"mode": mode, "delay_s": f.delay_s}
        return None

    def kv_migrate(self):
        """KV-tier migration seam (runtime/lm_server kvpull): a
        `kv_migrate_fault` severs the pull AS IF the donor died
        mid-migration — the adopter must take its kvtier_fallback
        path (re-prefill loud), never adopt partial blocks. Counter-
        positioned like step_fault for deterministic replay."""
        n = self._tick("kv_migrate")
        for f in self._faults:
            if f.kind != "kv_migrate_fault" or f.at_n < 0:
                continue
            if f.at_n <= n < f.at_n + f.count:
                _record("kv_migrate_fault", n=n)
                raise ConnectionError(
                    f"chaos: injected donor death mid-migration "
                    f"(pull n={n})")

    # -- wedge (watchdog probe hook) ------------------------------------

    def activate_wedge(self, duration_s: Optional[float] = None):
        """Manual wedge window (tests / the probe driver); None = until
        clear_wedge()."""
        with self._lock:
            self._wedge_until = (float("inf") if duration_s is None
                                 else time.monotonic() + duration_s)
            self._wedge_logged = False

    def clear_wedge(self):
        with self._lock:
            self._wedge_until = None
            self._wedge_logged = False

    def wedge_detail(self) -> Optional[str]:
        """Non-None while a wedge_device fault window is open: the
        watchdog probe reports THIS detail with timed_out=True instead
        of touching the device. Plan windows anchor at install time."""
        now = time.monotonic()
        active_f = None
        with self._lock:
            if self._wedge_until is not None and now < self._wedge_until:
                active_f = "manual"
            else:
                for f in self._faults:
                    if f.kind != "wedge_device":
                        continue
                    if f.at_s <= now - self._t0 < f.at_s + (
                            f.duration_s or float("inf")):
                        active_f = f"plan@{f.at_s:g}s"
                        break
            if active_f is None:
                self._wedge_logged = False
                return None
            first = not self._wedge_logged
            self._wedge_logged = True
        if first:  # once per window, not once per probe period
            _record("wedge_device", window=active_f)
        return f"chaos: injected device wedge ({active_f})"


# ----------------------------------------------------------------------
# module-level seam API (one global check when chaos is off)
# ----------------------------------------------------------------------

_active: Optional[Injector] = None


def install(plan) -> Injector:
    """Install `plan` (a FaultPlan, dict, or JSON/path string) as THIS
    process's injector. Replaces any previous one. Records the install
    as a flight event so the incident timeline starts with its cause."""
    global _active
    if isinstance(plan, Injector):
        inj = plan
    elif isinstance(plan, FaultPlan):
        inj = Injector(plan)
    elif isinstance(plan, dict):
        inj = Injector(FaultPlan.from_dict(plan))
    else:
        inj = Injector(FaultPlan.from_cli(str(plan)))
    _active = inj
    _record("install", seed=inj.plan.seed, faults=len(inj.plan.faults))
    return inj


def uninstall():
    global _active
    _active = None


def active() -> Optional[Injector]:
    return _active


def perturb_rpc(seam_group: str, target: str = ""):
    inj = _active
    if inj is not None:
        inj.perturb_rpc(seam_group, target)


def perturb_relay() -> bool:
    inj = _active
    return inj.perturb_relay() if inj is not None else False


def kv_exhaust() -> bool:
    inj = _active
    return inj.kv_exhaust() if inj is not None else False


def step_fault():
    inj = _active
    if inj is not None:
        inj.step_fault()


def train_fault() -> Optional[dict]:
    inj = _active
    return inj.train_fault() if inj is not None else None


def kv_migrate():
    inj = _active
    if inj is not None:
        inj.kv_migrate()


def wedge_detail() -> Optional[str]:
    inj = _active
    return inj.wedge_detail() if inj is not None else None


def corrupt_file(path: str, *, seed: int = 0, nbytes: int = 32) -> str:
    """Deterministically corrupt `nbytes` of `path` in place (seeded
    positions + values via plan.decide) — the ckpt_corrupt fault.
    Records a flight event naming the file; returns the path. The
    corruption targets the file BODY (offset >= 1) so a zero-length or
    1-byte file still changes detectably."""
    size = os.path.getsize(path)
    if size == 0:
        with open(path, "wb") as f:
            f.write(b"\x00")
        _record("ckpt_corrupt", path=path, bytes=1)
        return path
    with open(path, "r+b") as f:
        for i in range(nbytes):
            pos = int(decide(seed, f"corrupt:{path}", i) * size)
            f.seek(min(pos, size - 1))
            old = f.read(1)
            f.seek(min(pos, size - 1))
            f.write(bytes([old[0] ^ 0xFF if old else 0xFF]))
    _record("ckpt_corrupt", path=path, bytes=nbytes)
    return path
