"""FaultPlan: a deterministic, seeded schedule of injected faults.

A plan is data, not behavior: the process-level faults (`kill_stage`,
`hang_stage`) are executed by whoever supervises the processes (the
chaos probe's driver, `benchmarks/chaos_probe.py`); the in-process
faults are installed as an `inject.Injector` and consulted at the
seams (comm client/service, relay assembler, LM batcher worker,
watchdog probe).

Determinism contract: in-process faults fire on CALL COUNTERS through
`decide(seed, seam, n)` — a pure hash of (plan seed, seam name, call
index) — so a plan replays the identical injection sequence on every
run regardless of thread timing, and no `random`/wall-clock call ever
lands in a hot path or traced code. Process-level faults carry `at_s`
offsets (harness wall clock — the harness is not traced code).

Schema (JSON object or file; the `--chaos` CLI flag takes either a
path or inline JSON):

    {"seed": 0, "faults": [
      {"kind": "kill_stage",   "target": "node2", "at_s": 15},
      {"kind": "hang_stage",   "target": "node1", "at_s": 40},
      {"kind": "wedge_device", "at_s": 5, "duration_s": 8},
      {"kind": "rpc_drop",     "seam": "client", "p": 0.1, "count": 3},
      {"kind": "rpc_delay",    "seam": "stage",  "p": 0.05,
       "delay_s": 0.2, "count": 5},
      {"kind": "rpc_corrupt",  "seam": "client", "p": 0.1, "count": 2},
      {"kind": "relay_corrupt","p": 0.2, "count": 2},
      {"kind": "kv_exhaust",   "from_n": 4, "count": 3},
      {"kind": "step_fault",   "at_n": 10, "count": 1},
      {"kind": "train_fault",  "target": "nan", "at_n": 6, "count": 1},
      {"kind": "train_fault",  "target": "sleep", "at_n": 3,
       "count": 4, "delay_s": 0.05},
      {"kind": "ckpt_corrupt", "target": "/path/ckpt.npz"}
    ]}

`p` faults fire when decide() < p for a consulted call, up to `count`
times; `at_n`/`from_n` faults fire on exact counter positions. `kind`
values outside the known set fail loud at parse (a typo'd plan that
silently injects nothing would "pass" every chaos assertion).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import List, Optional

__all__ = ["Fault", "FaultPlan", "decide", "KINDS"]

# process-level (driven by the harness/supervisor) vs in-process
# (installed as an Injector) — partitioned so each consumer takes only
# the faults it can execute
PROCESS_KINDS = frozenset({"kill_stage", "hang_stage", "kill_donor"})
INPROCESS_KINDS = frozenset({
    "wedge_device", "rpc_drop", "rpc_delay", "rpc_corrupt",
    "relay_drop", "relay_corrupt", "kv_exhaust", "step_fault",
    "kv_migrate_fault", "train_fault",
})
FILE_KINDS = frozenset({"ckpt_corrupt"})
KINDS = PROCESS_KINDS | INPROCESS_KINDS | FILE_KINDS


def decide(seed: int, seam: str, n: int) -> float:
    """Pure, seeded decision value in [0, 1) for the n-th consultation
    of `seam` — the only 'randomness' an in-process fault may use.
    blake2s over the triple: stable across processes and Python runs
    (hash() is salted per process and would break replay)."""
    h = hashlib.blake2s(
        f"{seed}:{seam}:{n}".encode(), digest_size=8).digest()
    return int.from_bytes(h, "big") / 2.0 ** 64


@dataclasses.dataclass(frozen=True)
class Fault:
    """One scheduled fault. Unused fields stay at their defaults; see
    the module docstring for which fields each kind reads."""

    kind: str
    target: str = ""          # stage id / address / file path
    seam: str = ""            # rpc faults: "client" | "stage" | "" (any)
    at_s: float = 0.0         # process faults: offset from plan start
    duration_s: float = 0.0   # hang_stage / wedge_device window
    p: float = 0.0            # probabilistic in-process faults
    delay_s: float = 0.05     # rpc_delay sleep
    count: int = 1            # max firings for counter/probability faults
    at_n: int = -1            # step_fault: exact counter position
    from_n: int = -1          # kv_exhaust: first counter position

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (known: "
                f"{sorted(KINDS)})")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError(f"fault p must be in [0, 1], got {self.p}")
        if self.count < 1:
            raise ValueError(f"fault count must be >= 1, got {self.count}")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A seeded list of faults. `from_json` / `from_cli` parse the
    schema; `process_faults()` / `inprocess_faults()` partition it for
    the two executors."""

    faults: tuple
    seed: int = 0

    @classmethod
    def from_dict(cls, obj: dict) -> "FaultPlan":
        if not isinstance(obj, dict) or "faults" not in obj:
            raise ValueError(
                "a fault plan is an object with a 'faults' list "
                "(and an optional 'seed')")
        faults = []
        for f in obj["faults"]:
            known = {fld.name for fld in dataclasses.fields(Fault)}
            extra = set(f) - known
            if extra:
                raise ValueError(
                    f"unknown fault fields {sorted(extra)} in {f!r}")
            faults.append(Fault(**f))
        return cls(faults=tuple(faults), seed=int(obj.get("seed", 0)))

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "FaultPlan":
        with open(path) as f:
            return cls.from_json(f.read())

    @classmethod
    def from_cli(cls, arg: str) -> "FaultPlan":
        """The --chaos flag: a file path, or inline JSON (starts with
        '{')."""
        arg = arg.strip()
        if arg.startswith("{"):
            return cls.from_json(arg)
        if not os.path.exists(arg):
            raise ValueError(
                f"--chaos: {arg!r} is neither a readable file nor "
                "inline JSON")
        return cls.from_file(arg)

    def process_faults(self) -> List[Fault]:
        """kill/hang entries, sorted by at_s — the harness's timeline."""
        return sorted((f for f in self.faults if f.kind in PROCESS_KINDS),
                      key=lambda f: f.at_s)

    def inprocess_faults(self) -> List[Fault]:
        return [f for f in self.faults if f.kind in INPROCESS_KINDS]

    def file_faults(self) -> List[Fault]:
        return [f for f in self.faults if f.kind in FILE_KINDS]

    def to_dict(self) -> dict:
        return {"seed": self.seed,
                "faults": [dataclasses.asdict(f) for f in self.faults]}


def standard_plan(*, kill_target: str = "node2",
                  hang_target: str = "node1",
                  kill_at_s: float = 15.0,
                  hang_at_s: float = 40.0,
                  hang_duration_s: float = 120.0,
                  donor_kill_at_s: Optional[float] = None,
                  donor_target: str = "") -> FaultPlan:
    """THE standard FaultPlan the acceptance contract names: one stage
    kill plus one injected wedge (a hang the supervisor must detect and
    recover) during an open-loop run. `hang_duration_s` outlives any
    plausible health-poll detection window, so recovery always comes
    from the supervisor's kill+restart, never from the hang expiring.

    `donor_kill_at_s` (the KV-tier leg, dnn_tpu/kvtier) appends a
    `kill_donor` fault: the harness SIGKILLs the replica currently
    acting as a block-migration DONOR at that offset — mid-migration
    by construction when the driver times it inside a pull window.
    The asserted outcome (kv_tier probe / tests/test_kvtier.py): the
    donor's lease expires, the adopter re-prefills via its
    `kvtier_fallback` path with ZERO token divergence, and the pool
    high-water returns to baseline (zero leaked blocks)."""
    faults = [
        Fault(kind="kill_stage", target=kill_target, at_s=kill_at_s),
        Fault(kind="hang_stage", target=hang_target, at_s=hang_at_s,
              duration_s=hang_duration_s),
    ]
    if donor_kill_at_s is not None:
        faults.append(Fault(kind="kill_donor", target=donor_target,
                            at_s=float(donor_kill_at_s)))
    return FaultPlan(faults=tuple(faults))


__all__ += ["standard_plan", "PROCESS_KINDS", "INPROCESS_KINDS",
            "FILE_KINDS"]
