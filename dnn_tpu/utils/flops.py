"""FLOPs accounting and MFU (model FLOPs utilization).

Round-1 review: "vs torch-CPU is an honest but nearly information-free
comparison ... nothing reports MFU, the number that would actually prove
'fast on TPU'". This module supplies the accounting: analytic forward
FLOPs for the model families (matmuls + attention — the operations the MXU
executes; elementwise and gathers are noise at these shapes) and a peak-
FLOPs table per TPU generation, so every benchmark row can report
    mfu = achieved FLOPs/s / chip peak FLOPs/s.

Conventions (the standard MFU bookkeeping, e.g. the PaLM appendix):
  * a matmul (m, k) @ (k, n) costs 2*m*k*n FLOPs;
  * causal attention is charged the FULL T^2 score/value matmuls — that is
    what the dense einsum path executes, and it keeps MFU comparable with
    published numbers (flash kernels that skip masked tiles simply bank
    the savings as higher throughput at equal charged FLOPs);
  * training steps cost ~3x a forward (fwd + 2x bwd).
"""

from __future__ import annotations

from typing import Optional

import jax

# bf16 peak FLOPs/s per chip, by TPU generation. Matched as substrings of
# `jax.Device.device_kind` (e.g. "TPU v5 lite"); first hit wins, so more
# specific entries come first.
_TPU_PEAK_BF16 = (
    ("v5 lite", 197e12),   # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),   # Trillium
    ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)


def device_peak_flops(device: Optional[jax.Device] = None) -> Optional[float]:
    """bf16 peak FLOPs/s of `device` (default: the first default device), or
    None when unknown (CPU hosts, unrecognized accelerators) — callers omit
    the mfu field rather than publish a made-up one. DNN_TPU_PEAK_FLOPS
    overrides the table (the opt-in roofline for CPU hosts and
    accelerators the table doesn't know; utilization numbers against an
    operator-stated peak beat no numbers at all)."""
    import os

    env = _env_peak(os.environ.get("DNN_TPU_PEAK_FLOPS"))
    if env is not None:
        return env
    if device is None:
        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    kind = device.device_kind.lower()
    for sub, peak in _TPU_PEAK_BF16:
        if sub in kind:
            return peak
    return None


def _env_peak(raw) -> Optional[float]:
    """Parse an operator-stated roofline env var; garbage or <= 0 reads
    as unset (the degrade-don't-crash rule every env knob follows —
    DNN_TPU_PEAK_FLOPS=0 must mean "unknown", not ZeroDivisionError in
    every MFU consumer)."""
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        import logging

        logging.getLogger("dnn_tpu.utils").warning(
            "ignoring malformed peak override %r (want a number)", raw)
        return None
    return v if v > 0 else None


def gpt_forward_flops(cfg, batch: int, seq: int) -> float:
    """Analytic forward FLOPs for one GPT batch (dnn_tpu/models/gpt.py
    layout): per layer 24*T*C^2 of linear matmuls (qkv 6TC^2 + attn proj
    2TC^2 + mlp 8TC^2 + 8TC^2) plus 4*T^2*C of attention score/value
    matmuls, plus the 2*T*C*V lm_head."""
    c, l, v = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    per_seq = l * (24 * seq * c * c + 4 * seq * seq * c) + 2 * seq * c * v
    return float(batch) * per_seq


def llama_forward_flops(cfg, batch: int, seq: int) -> float:
    """Analytic forward FLOPs for one LLaMA batch
    (dnn_tpu/models/llama.py): per layer q 2TC^2 + k/v 2*2TC*(KV*D) +
    o 2TC^2 + SwiGLU 6TCF, plus the full-T^2 attention charge 4T^2C
    (GQA narrows the K/V PROJECTIONS and cache, not the score/value
    einsum FLOPs — every query head still attends), plus the 2TCV head."""
    c, l, v, f = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.d_ff
    kv_width = cfg.n_kv_head * cfg.head_dim
    per_seq = l * (2 * seq * c * c            # q proj
                   + 2 * 2 * seq * c * kv_width  # k + v projs
                   + 2 * seq * c * c          # o proj
                   + 6 * seq * c * f          # gate + up + down
                   + 4 * seq * seq * c)       # attention score/value
    return float(batch) * (per_seq + 2 * seq * c * v)


# ----------------------------------------------------------------------
# serving-shape accounting (dnn_tpu/obs/goodput.py): one DECODED token's
# FLOPs and HBM bytes. Decode runs T=1 forwards against a live cache, so
# the per-token cost depends on the CONTEXT (cache positions attended),
# not on a full-sequence T^2 charge — these helpers price what the decode
# program actually executes, which is what live MFU/MBU must divide by.
# ----------------------------------------------------------------------

def gpt_param_count(cfg) -> float:
    """Analytic parameter count of the GPT family (models/gpt.py layout:
    wte V*C + wpe block*C + per layer qkv 3C^2 + attn proj C^2 + mlp
    8C^2 + biases/norms ~4C, + lm_head V*C materialized untied + ln_f).
    Within ~0.1% of the real tree at gpt2 shapes — close enough for the
    weight-streaming MBU denominator."""
    c, l, v = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    per_layer = 12 * c * c + 13 * c  # qkv/proj/mlp kernels + their biases
    # + 2 layernorms (scale+bias)
    return float(v * c + cfg.block_size * c + l * per_layer
                 + 2 * c            # ln_f
                 + v * c)           # lm_head (materialized even when tied)


def llama_param_count(cfg) -> float:
    """Analytic parameter count of the LLaMA family (models/llama.py):
    embed V*C + per layer q C*(H*D) + k/v 2*C*(KV*D) + o (H*D)*C +
    SwiGLU 3*C*F + 2 RMSNorm scales, + final norm + lm_head (absent when
    tie_word_embeddings)."""
    c, l, v, f = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.d_ff
    q_width = cfg.n_head * cfg.head_dim
    kv_width = cfg.n_kv_head * cfg.head_dim
    per_layer = (c * q_width + 2 * c * kv_width + q_width * c
                 + 3 * c * f + 2 * c)
    head = 0 if getattr(cfg, "tie_word_embeddings", False) else v * c
    return float(v * c + l * per_layer + c + head)


def gpt_decode_token_flops(cfg, context: float) -> float:
    """FLOPs to decode ONE token with `context` live cache positions: the
    T=1 forward's linear matmuls (24*C^2 per layer: qkv 6C^2 + proj 2C^2
    + mlp 16C^2, the 2*m*k*n convention at m=1) plus the score/value
    matmuls against the cache (4*context*C per layer) plus the 2*C*V
    head. This is what the decode program executes — the live-MFU
    numerator, NOT the full-T^2 prefill charge."""
    c, l, v = cfg.n_embd, cfg.n_layer, cfg.vocab_size
    return l * (24.0 * c * c + 4.0 * context * c) + 2.0 * c * v


def llama_decode_token_flops(cfg, context: float) -> float:
    """LLaMA-family decode-token FLOPs at `context` live positions:
    q/o 2C*(H*D) each, k/v 2*C*(KV*D) each, SwiGLU 6*C*F, attention
    4*context*(H*D) (every query head attends the full context — GQA
    narrows the cache, not the score/value FLOPs), + the 2*C*V head."""
    c, l, v, f = cfg.n_embd, cfg.n_layer, cfg.vocab_size, cfg.d_ff
    q_width = cfg.n_head * cfg.head_dim
    kv_width = cfg.n_kv_head * cfg.head_dim
    per_layer = (2.0 * c * q_width + 2.0 * 2.0 * c * kv_width
                 + 2.0 * q_width * c + 6.0 * c * f
                 + 4.0 * context * q_width)
    return l * per_layer + 2.0 * c * v


def tree_weight_bytes(tree) -> float:
    """Total HBM bytes of a parameter pytree's array leaves, priced at
    the DEVICE layout: int8 at 1 byte/element (quantized kernels),
    int4/uint4 at their packed HALF byte (host numpy views pad to one
    byte, so a dtype.itemsize walk would overstate the weight-streaming
    MBU denominator 2x for int4 trees). The f32 scale rows quantized
    trees carry are counted at full width — they stream with the
    weights every decode step. This is THE weight-bytes accounting the
    serving goodput gauges use (obs/goodput.model_cost), so an
    LMServer(weights="int8") daemon's MBU prices its quantized stream
    correctly instead of flattering itself with f32 bytes."""
    total = 0.0
    for leaf in jax.tree_util.tree_leaves(tree):
        dt = getattr(leaf, "dtype", None)
        if dt is None:
            continue
        name = getattr(dt, "name", str(dt))
        if name in ("int4", "uint4"):
            total += leaf.size * 0.5
        else:
            total += leaf.size * dt.itemsize
    return float(total)


def kv_bytes_per_pos(cfg, *, kv_bytes: float = 2,
                     kv_dtype=None) -> float:
    """HBM bytes one cache POSITION occupies (K + V rows across all
    layers) — decode streams `context` of these per token, and prefill
    writes one per prompt position. GQA caches carry n_kv_head*head_dim
    per row; dense GPT carries C.

    `kv_dtype` overrides `kv_bytes` with EXACT accounting for the
    serving cache specs (runtime/kvcache.py): a dtype prices at its
    itemsize; the codec strings "int8"/"int4" price the quantized
    payload (int4 packs two elements per byte — pricing it at the
    1-byte host itemsize would overstate the MBU denominator 2x) PLUS
    the per-(position, head) f32 K and V scale rows the quantized
    codecs stream alongside."""
    kv_width = (cfg.n_kv_head * cfg.head_dim
                if hasattr(cfg, "n_kv_head") else cfg.n_embd)
    heads = (cfg.n_kv_head if hasattr(cfg, "n_kv_head") else cfg.n_head)
    if kv_dtype is not None:
        name = str(getattr(kv_dtype, "name", kv_dtype))
        if name in ("int8", "int4"):
            per_elem = 1.0 if name == "int8" else 0.5
            return float(2 * cfg.n_layer
                         * (kv_width * per_elem + heads * 4))
        import jax.numpy as jnp

        kv_bytes = jnp.dtype(kv_dtype).itemsize
    return float(2 * cfg.n_layer * kv_width * kv_bytes)


def decode_step_bytes(weight_bytes: float, kv_live_positions: float,
                      cfg, *, kv_bytes: int = 2) -> float:
    """HBM traffic of ONE decode step over a whole slot pool: the weights
    stream once per STEP (shared by every active row — batching's whole
    point) plus every live row's cache positions. `weight_bytes` is the
    total parameter bytes (count the real tree when you have it:
    goodput.ModelCost.from_prepared); `kv_live_positions` the summed
    live positions across active slots. The live-MBU numerator."""
    return float(weight_bytes) + float(kv_live_positions) * \
        kv_bytes_per_pos(cfg, kv_bytes=kv_bytes)


def _train_step_factor(batch: int, accum_steps: int, remat: bool) -> float:
    """The forward→train-step multiplier (the PaLM-appendix bookkeeping):
    3x a forward (fwd + backward's two matmuls per forward matmul), 4x
    under full rematerialization (the backward replays the forward).
    Microbatch accumulation does not change TOTAL step FLOPs — the
    forward is linear in batch, so `accum_steps` microbatches of B/a
    rows cost exactly one batch-B pass — but the divisibility check
    here catches the same misconfiguration make_train_step rejects, so
    the priced shape and the executed shape cannot drift apart."""
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if batch % accum_steps:
        raise ValueError(
            f"batch {batch} not divisible by accum_steps {accum_steps}")
    return 4.0 if remat else 3.0


def gpt_train_step_flops(cfg, batch: int, seq: int, *,
                         accum_steps: int = 1, remat: bool = False) -> float:
    """Training-step FLOPs for one GPT batch: factor x forward (3x, or
    4x with remat — the backward replays the forward). `accum_steps`
    validates the microbatch split but leaves the total unchanged
    (forward FLOPs are linear in batch). The trainlens MFU numerator
    (obs/trainlens.py) and the dev_gpt2_train_step row both price from
    this one walk."""
    return _train_step_factor(batch, accum_steps, remat) \
        * gpt_forward_flops(cfg, batch, seq)


def llama_train_step_flops(cfg, batch: int, seq: int, *,
                           accum_steps: int = 1, remat: bool = False) -> float:
    """Training-step FLOPs for one LLaMA batch — same factor bookkeeping
    as gpt_train_step_flops over the GQA/SwiGLU forward walk."""
    return _train_step_factor(batch, accum_steps, remat) \
        * llama_forward_flops(cfg, batch, seq)


def cifar_forward_flops(batch: int) -> float:
    """Forward FLOPs of the CIFAR CNN (dnn_tpu/models/cifar.py: conv 3->32,
    conv 32->64 on pooled maps, fc 4096->512, fc 512->10)."""
    conv1 = 2 * 32 * 32 * 32 * (3 * 3 * 3)
    conv2 = 2 * 16 * 16 * 64 * (3 * 3 * 32)
    fc1 = 2 * 4096 * 512
    fc2 = 2 * 512 * 10
    return float(batch) * (conv1 + conv2 + fc1 + fc2)


def cifar_forward_bytes(batch: int, *, dtype_bytes: int = 2) -> float:
    """Per-batch HBM traffic of the CIFAR forward, assuming XLA's typical
    fusion (bias/relu fused into each conv; pool, transpose, and each
    matmul read their input and write their output). The CNN is TINY —
    ~15.6 MFLOPs/image against ~0.27 MB of activation traffic — so its
    arithmetic intensity (~60 FLOPs/byte) sits far below a v5e's ridge
    point (~240 FLOPs/byte): the model is HBM-BOUND at any batch size,
    and its MFU ceiling is intensity/ridge (~24%), not 100%. The bench
    row reports this cap next to the measured MFU (VERDICT r2 weak #3).

    The cap is CONSERVATIVE: it charges every op boundary a full HBM
    round trip, but XLA keeps some producer->consumer tiles in VMEM (the
    conv1-padded forward measures ~39% MFU at B=1024 on a v5e —
    benchmarks/cifar_mfu_probe.py), so `roofline_frac` can legitimately
    exceed 1.0."""
    act = dtype_bytes * (
        32 * 32 * 3          # input read by conv1
        + 32 * 32 * 32 * 2   # conv1 write + pool1 read
        + 16 * 16 * 32 * 2   # pool1 write + conv2 read
        + 16 * 16 * 64 * 2   # conv2 write + pool2 read
        + 8 * 8 * 64 * 2     # pool2 write + transpose read
        + 4096 * 2           # transpose write + fc1 read
        + 512 * 2            # fc1 write + fc2 read
        + 10                 # fc2 write
    )
    weights = dtype_bytes * (27 * 32 + 288 * 64 + 4096 * 512 + 512 * 10
                             + 32 + 64 + 512 + 10)
    return float(batch) * act + weights  # weights stream once per batch


def roofline_items_per_sec(flops_per_item: float, bytes_per_item: float,
                           device: Optional[jax.Device] = None) -> Optional[float]:
    """min(compute, bandwidth) roofline for one benchmark item, or None
    off-TPU: the throughput ceiling the hardware admits for this op mix."""
    peak_f = device_peak_flops(device)
    peak_b = device_peak_hbm_bw(device)
    if peak_f is None or peak_b is None:
        return None
    return min(peak_f / flops_per_item, peak_b / bytes_per_item)


def mfu(flops_per_item: float, items_per_sec: float,
        device: Optional[jax.Device] = None) -> Optional[float]:
    """Achieved-FLOPs / peak, or None off-TPU. `flops_per_item` is the
    analytic cost of one benchmark item (an image, a token's share of a
    batch, ...); items_per_sec the measured rate."""
    peak = device_peak_flops(device)
    if peak is None:
        return None
    return flops_per_item * items_per_sec / peak


# HBM peak bandwidth (bytes/s) per chip, by TPU generation — same matching
# scheme as the FLOPs table. Decode throughput is bounded by this number,
# not by peak FLOPs (every generated token streams the weights + KV cache
# from HBM once), so decode rows report MBU, not MFU.
_TPU_PEAK_HBM = (
    ("v5 lite", 819e9),    # v5e: 819 GB/s
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6 lite", 1640e9),   # Trillium
    ("v6e", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def device_peak_hbm_bw(device: Optional[jax.Device] = None) -> Optional[float]:
    """HBM peak bytes/s of `device`, or None when unknown (CPU hosts).
    DNN_TPU_PEAK_HBM_BW overrides, like DNN_TPU_PEAK_FLOPS above."""
    import os

    env = _env_peak(os.environ.get("DNN_TPU_PEAK_HBM_BW"))
    if env is not None:
        return env
    if device is None:
        device = jax.devices()[0]
    if device.platform != "tpu":
        return None
    kind = device.device_kind.lower()
    for sub, bw in _TPU_PEAK_HBM:
        if sub in kind:
            return bw
    return None


def mbu(bytes_per_item: float, items_per_sec: float,
        device: Optional[jax.Device] = None) -> Optional[float]:
    """Memory-bandwidth utilization: achieved bytes/s / HBM peak, or None
    off-TPU. For decode, `bytes_per_item` is the bytes one generated token
    must stream (weights/batch + its rows of the KV cache) — the roofline
    that decides whether int8 weights/cache pay off."""
    peak = device_peak_hbm_bw(device)
    if peak is None:
        return None
    return bytes_per_item * items_per_sec / peak
