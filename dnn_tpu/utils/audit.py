"""Determinism / purity audit.

The reference's concurrency story is implicit (single asyncio loop, global
state written once — SURVEY §5 "Race detection: ABSENT"); there is nothing
to race because nothing is parallel. This framework IS parallel, so it
ships the TPU-native analog of a race detector: an audit that a compiled
program is (a) deterministic — repeated runs produce bit-identical outputs,
which fails if a collective's reduction order ever becomes
schedule-dependent — and (b) pure — it does not mutate its inputs, which
fails if buffer donation/aliasing is introduced accidentally.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np


def snapshot(tree):
    """Host copies of every leaf, for before/after comparison."""
    return jax.tree.map(lambda x: np.array(x), tree)


def assert_deterministic(fn: Callable, *args, runs: int = 3):
    """Run `fn(*args)` `runs` times; all outputs must be BIT-identical.
    Collectives (psum/ppermute reductions) with a fixed mesh and fixed
    inputs must not vary run to run — variation means the reduction order
    leaked into the result."""
    ref = jax.tree.map(np.array, fn(*args))
    for i in range(1, runs):
        out = jax.tree.map(np.array, fn(*args))
        jax.tree.map(
            lambda a, b, _i=i: np.testing.assert_array_equal(
                a, b, err_msg=f"output differs on run {_i}"
            ),
            ref, out,
        )
    return ref


def assert_pure(fn: Callable, *args):
    """Run `fn(*args)` and verify no input leaf changed — catches
    accidental donation/aliasing (donate_argnums, in-place dlpack views).
    Returns the output."""
    before = snapshot(args)
    out = fn(*args)
    jax.block_until_ready(out)
    after = snapshot(args)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            a, b, err_msg="input mutated by supposedly-pure function"
        ),
        before, after,
    )
    return out


def assert_collectives_consistent(fn: Callable, *args):
    """Static third leg of the audit triad: trace `fn(*args)` (abstract —
    nothing executes; args may be ShapeDtypeStructs) and require every
    cond/switch in the program to issue IDENTICAL collective sequences
    across its branches. This is the SPMD no-deadlock precondition the
    runtime checks above cannot see: a rank-divergent branch hangs a
    real mesh instead of producing a comparable wrong answer. Jaxpr walk
    by dnn_tpu/analysis/program.check_branch_collectives."""
    from dnn_tpu.analysis.program import check_branch_collectives

    closed = jax.make_jaxpr(fn)(*args)
    findings = check_branch_collectives(closed, getattr(
        fn, "__name__", "<fn>"))
    if findings:
        raise AssertionError(
            "divergent collective sequences across SPMD branches:\n" +
            "\n".join(f.message for f in findings))


def assert_deterministic_and_pure(fn: Callable, *args, runs: int = 3):
    assert_pure(fn, *args)
    return assert_deterministic(fn, *args, runs=runs)
