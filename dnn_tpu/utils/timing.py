"""Validated device timing.

The reference has no timers at all (SURVEY §5 — print logging only).
Measuring honestly on this TPU is nontrivial: the chip sits behind a
tunnel where `jax.block_until_ready` can return before device execution
finishes, a run's first measurements carry one-time dispatch overheads,
and per-sync round-trip cost dwarfs small kernels. `device_time` is the
framework's one blessed answer — every bench (bench.py, benchmarks/) uses
it so numbers are comparable.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def device_time(fn, *args, n1: int = 4, n2: int = 12, trials: int = 3) -> float:
    """Per-call device wall time of `fn(*args)` via the two-point slope
    method.

    Queue N calls back-to-back, force the dependency chain with a
    1-element host read of the last output (device execution is in-order,
    so the read completes only after all N), and take
    (t(n2) - t(n1)) / (n2 - n1) so the constant sync round-trip cancels.

    Validity guards (first-measurement effects were observed to skew a
    single slope by up to 2x in either direction): warm up past compile
    AND past the first few post-compile dispatches, evaluate t(n1) before
    t(n2) in a fixed order, and report the median slope of `trials`
    repeats.
    """

    def run(n):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        leaf = jax.tree.leaves(out)[0]
        np.asarray(leaf.ravel()[0])  # scalar pull -> full sync
        return time.perf_counter() - t0

    run(2)  # compile
    run(n1)  # absorb post-compile first-dispatch overhead
    slopes = []
    for _ in range(trials):
        t1 = run(n1)
        t2 = run(n2)
        slopes.append((t2 - t1) / (n2 - n1))
    slopes.sort()
    return slopes[len(slopes) // 2]
