"""Profiling / tracing spans — DEPRECATED shim over dnn_tpu.obs.profile.

This module predates the obs layer (dnn_tpu/obs); its profiler-span API
grew a duplicate in PR 3 and is now unified: `span` / `step_span` are
re-exports of `obs.profile.annotation` / `step_annotation`, which means
they RESPECT THE DNN_TPU_OBS GATE (the orphaned originals annotated even
with observability off). Existing callers keep working unchanged; new
code should import from `dnn_tpu.obs.profile`, and full captures should
go through `obs.profile.capture` / POST /profilez rather than the bare
`trace_to` kept here for compatibility.

`device_sync` / `timed_blocked` are NOT spans — they are the honest
device-completion barrier the benchmarks are built on — and live on
here as this module's real content.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax

from dnn_tpu.obs.profile import (  # noqa: F401 — deprecated re-exports
    annotation as span,
    step_annotation as step_span,
)


@contextlib.contextmanager
def trace_to(log_dir: str) -> Iterator[None]:
    """Capture a full profile (host + device) into `log_dir` for
    TensorBoard / Perfetto. Deprecated: prefer obs.profile.capture
    (bounded spool, busy-locking, flight-logged) for server use."""
    from dnn_tpu.obs import profile as _profile

    jax.profiler.start_trace(log_dir)
    try:
        # the deprecated `span` shim only annotates while a capture is
        # marked recording (annotation_ctx's hot-path gate) — mark this
        # legacy capture too, or trace_to + span silently loses spans
        with _profile.mark_recording():
            yield
    finally:
        jax.profiler.stop_trace()


def device_sync(out) -> None:
    """Force completion of all device work `out` depends on.

    `jax.block_until_ready` is NOT sufficient on this machine: the TPU sits
    behind a tunnel where readiness resolves before device execution
    finishes, so naive timing measures dispatch only (see bench.py). A
    1-element host read is the reliable barrier — device execution is
    in-order, so the read completes only after everything queued before it.
    """
    import numpy as np

    # Per-device queues are independent, so the barrier must touch every
    # device `out` lives on — one 1-element read per device (any array on
    # that device works: the read completes only after all work enqueued
    # before it on that device's in-order queue).
    per_device = {}
    for leaf in jax.tree.leaves(out):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                per_device[s.device] = s.data
    if per_device:
        for data in per_device.values():
            np.asarray(data.ravel()[0] if data.size else data)
    else:  # no jax array leaves
        jax.block_until_ready(out)


def timed_blocked(fn, *args) -> tuple:
    """Run `fn(*args)`, force device completion (`device_sync`), return
    (result, seconds). The honest way to time jit'd code — timing dispatch
    alone measures nothing (SURVEY §7 hard part 4)."""
    t0 = time.perf_counter()
    out = fn(*args)
    device_sync(out)
    return out, time.perf_counter() - t0
