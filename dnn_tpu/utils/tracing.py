"""Profiling / tracing spans.

The reference has no tracing at all (SURVEY §5 'Tracing/profiling:
ABSENT'). The TPU-native replacement is `jax.profiler`: named trace
annotations show up in TensorBoard/Perfetto timelines alongside the XLA
device ops, and `trace_to(dir)` captures a full device+host profile.

All helpers degrade to no-ops if profiling is unavailable, so library code
can annotate unconditionally.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def span(name: str) -> Iterator[None]:
    """Named host-side span, visible in captured profiles."""
    try:
        ctx = jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler unavailable
        ctx = contextlib.nullcontext()
    with ctx:
        yield


@contextlib.contextmanager
def step_span(step: int, name: str = "step") -> Iterator[None]:
    """Mark one pipeline/training step; XLA profilers group device ops
    under it."""
    try:
        ctx = jax.profiler.StepTraceAnnotation(name, step_num=step)
    except Exception:  # pragma: no cover
        ctx = contextlib.nullcontext()
    with ctx:
        yield


@contextlib.contextmanager
def trace_to(log_dir: str) -> Iterator[None]:
    """Capture a full profile (host + device) into `log_dir` for
    TensorBoard / Perfetto."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def device_sync(out) -> None:
    """Force completion of all device work `out` depends on.

    `jax.block_until_ready` is NOT sufficient on this machine: the TPU sits
    behind a tunnel where readiness resolves before device execution
    finishes, so naive timing measures dispatch only (see bench.py). A
    1-element host read is the reliable barrier — device execution is
    in-order, so the read completes only after everything queued before it.
    """
    import numpy as np

    # Per-device queues are independent, so the barrier must touch every
    # device `out` lives on — one 1-element read per device (any array on
    # that device works: the read completes only after all work enqueued
    # before it on that device's in-order queue).
    per_device = {}
    for leaf in jax.tree.leaves(out):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                per_device[s.device] = s.data
    if per_device:
        for data in per_device.values():
            np.asarray(data.ravel()[0] if data.size else data)
    else:  # no jax array leaves
        jax.block_until_ready(out)


def timed_blocked(fn, *args) -> tuple:
    """Run `fn(*args)`, force device completion (`device_sync`), return
    (result, seconds). The honest way to time jit'd code — timing dispatch
    alone measures nothing (SURVEY §7 hard part 4)."""
    t0 = time.perf_counter()
    out = fn(*args)
    device_sync(out)
    return out, time.perf_counter() - t0
