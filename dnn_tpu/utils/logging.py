"""Structured logging.

Replaces the reference's bare print() calls with `[{NODE_ID}]` prefixes
scattered through every code path (e.g. node.py:38-39, 120-122, 280-290 —
SURVEY §5 'Metrics / logging': stdout prints only, no levels, no files)
with stdlib logging: leveled, timestamped, and still carrying the node-id
prefix so operators see the familiar shape.

JSON mode (`DNN_TPU_LOG=json`, or `setup_logging(fmt="json")`): every
record becomes one JSON object per line — ts/level/logger/msg plus
node_id and, when the calling thread is inside an active request span,
the TRACE ID (dnn_tpu/obs/trace.py) — so fleet-collected logs correlate
with stitched traces: grep the trace id from /fleetz's request report
and the matching log lines fall out of every stage's stream. Plain-text
behavior is unchanged by default.
"""

from __future__ import annotations

import json
import logging
import os
import sys
from typing import Optional


class _NodeFilter(logging.Filter):
    def __init__(self, node_id: str):
        super().__init__()
        self.node_id = node_id

    def filter(self, record):
        record.node_id = self.node_id
        return True


class JSONFormatter(logging.Formatter):
    """One JSON object per record. The active trace id (the contextvar-
    backed ambient span, obs/trace.current_span) is injected when
    present — the correlation key between a stage's logs and the
    fleet's stitched cross-host traces."""

    def format(self, record):
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        node_id = getattr(record, "node_id", None)
        if node_id:
            out["node_id"] = node_id
        try:
            from dnn_tpu.obs.trace import current_span

            sp = current_span()
            if sp is not None and sp.trace_id is not None:
                out["trace_id"] = sp.trace_id
        except Exception:  # noqa: BLE001 — logging must never raise
            pass
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(level: str = "INFO", *, node_id: Optional[str] = None,
                  stream=None, fmt: Optional[str] = None):
    """Configure the dnn_tpu logger tree. `fmt` is "text" (default) or
    "json"; None consults DNN_TPU_LOG (json|text), so operators flip
    the whole fleet to structured logs with one env var and zero flag
    plumbing."""
    root = logging.getLogger("dnn_tpu")
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    root.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    if fmt is None:
        fmt = os.environ.get("DNN_TPU_LOG", "text").lower()
    if fmt == "json":
        handler.setFormatter(JSONFormatter())
    else:
        prefix = "[%(node_id)s] " if node_id else ""
        handler.setFormatter(
            logging.Formatter(
                f"%(asctime)s %(levelname)s %(name)s: {prefix}%(message)s",
                datefmt="%H:%M:%S",
            )
        )
    if node_id:
        handler.addFilter(_NodeFilter(node_id))
    root.addHandler(handler)
    root.propagate = False
    return root
