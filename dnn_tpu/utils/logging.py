"""Structured logging.

Replaces the reference's bare print() calls with `[{NODE_ID}]` prefixes
scattered through every code path (e.g. node.py:38-39, 120-122, 280-290 —
SURVEY §5 'Metrics / logging': stdout prints only, no levels, no files)
with stdlib logging: leveled, timestamped, and still carrying the node-id
prefix so operators see the familiar shape.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional


class _NodeFilter(logging.Filter):
    def __init__(self, node_id: str):
        super().__init__()
        self.node_id = node_id

    def filter(self, record):
        record.node_id = self.node_id
        return True


def setup_logging(level: str = "INFO", *, node_id: Optional[str] = None, stream=None):
    root = logging.getLogger("dnn_tpu")
    root.setLevel(getattr(logging, str(level).upper(), logging.INFO))
    root.handlers.clear()
    handler = logging.StreamHandler(stream or sys.stderr)
    prefix = "[%(node_id)s] " if node_id else ""
    handler.setFormatter(
        logging.Formatter(
            f"%(asctime)s %(levelname)s %(name)s: {prefix}%(message)s",
            datefmt="%H:%M:%S",
        )
    )
    if node_id:
        handler.addFilter(_NodeFilter(node_id))
    root.addHandler(handler)
    root.propagate = False
    return root
