"""Static HLO bytes audit for decode steps.

BASELINE.md's open long-context question names hypothesis (a): XLA
materializing a cache-sized (transposed) copy per decode step for the
(B, H, 1, S) matvec layout — a 2x+ traffic multiplier that would explain
the 13%-MBU `llama_mha_longctx_decode_dense` row without any new
measurement. The chip has been wedged for three rounds; this module
answers the question ON PAPER: `jax.jit(...).lower(...)` needs no healthy
backend (shapes ride `jax.eval_shape`, so even the 1.1B-parameter audit
costs no memory), and the resulting program text can be scanned for
cache-sized copies/transposes.

Two inspection levels, honestly distinct:

  * `optimize=False` — the StableHLO JAX emits. Platform-neutral: counts
    what the PROGRAM demands (an explicit transpose/copy of the cache in
    the traced math would be a framework bug, caught here).
  * `optimize=True` — the backend-optimized HLO after XLA's pipeline on
    THIS host's backend (CPU under the test suite). This is where
    materialization decisions live; a CPU count is a proxy for the TPU
    answer, labeled as such wherever it is recorded (BASELINE.md).

The counters are format-tolerant (StableHLO `tensor<8x12x256x64xf32>`
result types and classic HLO `f32[8,12,256,64]{...} opcode(...)` lines
alike), and "cache-sized" means >= one LAYER's K buffer — the layer scan
peels the leading L axis, so a per-step materialization shows up at
(B, H, S, D) scale while the hypothesis-(b) whole-cache copy shows up at
L times that. tests/test_hlo_audit.py pins both the parser and the
regression: the bucketed decode step lowers with ZERO cache-sized
transposes and ZERO cache-sized copies beyond the donated in-place
update.
"""

from __future__ import annotations

import re
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

__all__ = ["lowered_text", "op_result_sizes", "count_cache_sized",
           "count_aliased", "count_aliased_compiled", "gpt_decode_step",
           "llama_decode_step", "audit_decode_step"]

# `%3 = stablehlo.transpose %2 ... -> tensor<8x12x64x256xf32>` (the last
# tensor<...> on the line is the result type; rank-0 tensors have no dims)
_SHLO_OP = re.compile(r'=\s*"?(?:stablehlo|mhlo)\.([a-z_]+)')
_TENSOR = re.compile(r"tensor<((?:[0-9]+x)*)[a-z][a-z0-9]*>")
# `%copy.1 = f32[4,8,12,1040,64]{4,3,2,1,0} copy(...)`
_HLO_INST = re.compile(
    r"=\s*[a-z][a-z0-9]*\[([0-9,]*)\]\S*\s+([a-z][a-z0-9\-]*)\(")


def lowered_text(fn, *args, donate_argnums=(), optimize: bool = False) -> str:
    """Program text of jit(fn) at `args` (arrays OR ShapeDtypeStructs —
    pair with jax.eval_shape to audit shapes too big to build).
    optimize=False: the emitted StableHLO, no backend work; True: the
    backend-optimized HLO (compiles for THIS host's default backend)."""
    low = jax.jit(fn, donate_argnums=donate_argnums).lower(*args)
    if not optimize:
        return low.as_text()
    compiled = low.compile()
    return "\n".join(m.to_string() for m in compiled.runtime_executable()
                     .hlo_modules()) if hasattr(
        compiled, "runtime_executable") else compiled.as_text()


def op_result_sizes(text: str):
    """[(opcode, result_elem_count)] for every op in StableHLO or HLO
    text (see module docstring for the two formats)."""
    rows = []
    for line in text.splitlines():
        m = _SHLO_OP.search(line)
        if m:
            tensors = _TENSOR.findall(line)
            if not tensors:
                continue
            n = 1
            for d in tensors[-1].split("x"):
                if d:
                    n *= int(d)
            rows.append((m.group(1), n))
            continue
        m = _HLO_INST.search(line)
        if m:
            n = 1
            for d in m.group(1).split(","):
                if d:
                    n *= int(d)
            rows.append((m.group(2), n))
    return rows


def count_aliased(text: str) -> int:
    """Donated-input count in StableHLO program text: jit emits one
    `tf.aliasing_output` attribute per input buffer it aliases to an
    output. An arg passed via donate_argnums but NOT counted here was
    unusable (no shape/dtype-matching output) — the runtime pays a full
    copy of it per call. Consumed by the analyzer's donation-coverage
    check (dnn_tpu/analysis/program.donation_report)."""
    return text.count("tf.aliasing_output")


_ALIAS_PAIR = re.compile(r"\{[0-9,\s]*\}:\s*\(\d+,")


def count_aliased_compiled(hlo_text: str) -> int:
    """Donation aliasing at the COMPILED level: under GSPMD shardings
    jit lowers donations as `jax.buffer_donor` hints (no
    tf.aliasing_output at the StableHLO level — the aliasing decision
    belongs to XLA once partitioning is resolved), and the verdict lands
    in the optimized HLO's `input_output_alias={ {out}: (arg, ...) }`
    header. Counts those pairs; a donated sharded buffer missing here
    pays a full per-device copy every step. Consumed by the analyzer's
    sharded-donation check (dnn_tpu/analysis/shardcheck)."""
    m = re.search(r"input_output_alias=\{(.*?)\}\s*(?:\n|,\s*[a-z_]+=)",
                  hlo_text, re.S)
    if not m:
        return 0
    return len(_ALIAS_PAIR.findall(m.group(1)))


def count_cache_sized(text: str, min_elems: int,
                      ops: Sequence[str] = ("transpose", "copy"),
                      ) -> Dict[str, int]:
    """{opcode: count} of ops whose RESULT is at least `min_elems`
    elements — each one a cache-scale buffer the program materializes."""
    counts: Dict[str, int] = {}
    for op, n in op_result_sizes(text):
        if n >= min_elems and op in ops:
            counts[op] = counts.get(op, 0) + 1
    return counts


# ----------------------------------------------------------------------
# decode-step builders (abstract shapes — no weights are ever built)
# ----------------------------------------------------------------------

def _abstract(thunk):
    return jax.eval_shape(thunk)


def gpt_decode_step(cfg, *, batch: int, s_max: int, compute_dtype=None,
                    kv_dtype=None, attn_kernel=False):
    """(step_fn, abstract_args, layer_cache_elems) for ONE GPT-family
    decode step — the make_generate scan body at a traced position:
    step(prepared, cache, tok, pos) -> (last-token logits, cache)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime import generate as G

    def step(prepared, cache, tok, pos):
        logits, cache = G.forward_with_cache(
            prepared, tok[:, None], cache, pos, cfg=cfg,
            compute_dtype=compute_dtype, attn_kernel=attn_kernel)
        return logits[:, -1], cache

    cache_dtype = kv_dtype if kv_dtype is not None else (
        compute_dtype or jnp.float32)
    key = jax.random.PRNGKey(0)
    prepared = _abstract(
        lambda: gpt.prepare_stacked(gpt.init(key, cfg), cfg))
    cache = _abstract(lambda: G.init_cache(cfg, batch, s_max, cache_dtype))
    tok = jax.ShapeDtypeStruct((batch,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    layer_elems = batch * cfg.n_head * s_max * (cfg.n_embd // cfg.n_head)
    return step, (prepared, cache, tok, pos), layer_elems


def llama_decode_step(cfg, *, batch: int, s_max: int, compute_dtype=None,
                      kv_dtype=None, attn_kernel=False):
    """Same contract for the LLaMA family (GQA cache at KV-head width) —
    the family behind the 13%-MBU row (run with an MHA-width cfg to
    reproduce that exact shape)."""
    from dnn_tpu.models import gpt, llama

    def step(prepared, cache, tok, pos):
        logits, cache = llama.forward_with_cache(
            prepared, tok[:, None], cache, pos, cfg=cfg,
            compute_dtype=compute_dtype, attn_kernel=attn_kernel)
        return logits[:, -1], cache

    cache_dtype = kv_dtype if kv_dtype is not None else (
        compute_dtype or jnp.float32)
    key = jax.random.PRNGKey(0)
    prepared = _abstract(
        lambda: gpt.prepare_stacked(
            llama.init(key, cfg, dtype=compute_dtype or jnp.float32), cfg))
    cache = _abstract(
        lambda: llama.init_cache(cfg, batch, s_max, cache_dtype))
    tok = jax.ShapeDtypeStruct((batch,), jnp.int32)
    pos = jax.ShapeDtypeStruct((), jnp.int32)
    layer_elems = batch * cfg.n_kv_head * s_max * cfg.head_dim
    return step, (prepared, cache, tok, pos), layer_elems


def audit_decode_step(step_fn, args, layer_cache_elems, *,
                      optimize: bool = False, donate_cache: bool = True,
                      ops: Sequence[str] = ("transpose", "copy")) -> dict:
    """Lower one decode step and count cache-sized materializations.
    `donate_cache=True` marks the cache argument (position 1) donated, as
    every real decode loop does — without it the cache update itself
    legitimately copies and the count answers a question nobody asked."""
    text = lowered_text(step_fn, *args,
                        donate_argnums=(1,) if donate_cache else (),
                        optimize=optimize)
    counts = count_cache_sized(text, layer_cache_elems, ops=ops)
    return {
        "counts": counts,
        "total": sum(counts.values()),
        "min_elems": layer_cache_elems,
        "optimized": bool(optimize),
        "backend": jax.default_backend() if optimize else "none (StableHLO)",
    }


def _main():
    """Reproduce the BASELINE.md long-context audit: the 13%-MBU row's
    exact decode-step shape (TinyLlama widened to MHA, B=8, S=1536),
    StableHLO level plus this host's optimized HLO."""
    import dataclasses
    import json

    from dnn_tpu.models import llama

    mha_cfg = dataclasses.replace(
        llama.PRESETS["tinyllama-1.1b"],
        n_kv_head=llama.PRESETS["tinyllama-1.1b"].n_head, block_size=2048)
    step, args, layer = llama_decode_step(
        mha_cfg, batch=8, s_max=1536, compute_dtype=jnp.bfloat16,
        kv_dtype=jnp.bfloat16)
    out = {"shape": "tinyllama-mha B=8 S=1536 bf16",
           "stablehlo": audit_decode_step(step, args, layer),
           "optimized": audit_decode_step(step, args, layer,
                                          optimize=True)}
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    _main()
