"""Step metrics and observability counters.

The reference's only observability is ad-hoc stdout prints (SURVEY §5
'Metrics': node.py:38-39, 85-86, 120-122 — no levels, no counters, no
timers). This module supplies the rebuild's structured replacement: named
counters/gauges plus a latency reservoir with percentiles, emitting the
BASELINE.json metrics (images/sec, tokens/sec, p50 inter-stage latency) as
plain dicts / JSON lines.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile, q in [0, 100]."""
    if not values:
        raise ValueError("no samples")
    s = sorted(values)
    k = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[k]


class LatencyReservoir:
    """Bounded sample buffer for latency percentiles (seconds)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._samples: List[float] = []
        self._count = 0

    def record(self, seconds: float):
        self._count += 1
        if len(self._samples) < self.capacity:
            self._samples.append(seconds)
        else:  # deterministic ring replacement; keeps a sliding window
            self._samples[(self._count - 1) % self.capacity] = seconds

    @property
    def count(self) -> int:
        return self._count

    def quantiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        return {f"p{q}": percentile(self._samples, q) for q in qs}


class Metrics:
    """Thread-safe named counters, gauges, and latency reservoirs."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.latencies: Dict[str, LatencyReservoir] = {}

    def inc(self, name: str, value: float = 1.0):
        with self._lock:
            self.counters[name] += value

    def set(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = value

    def observe(self, name: str, seconds: float):
        with self._lock:
            if name not in self.latencies:
                self.latencies[name] = LatencyReservoir()
            self.latencies[name].record(seconds)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def snapshot(self) -> dict:
        with self._lock:
            out = {"counters": dict(self.counters), "gauges": dict(self.gauges)}
            out["latency"] = {
                k: {"count": r.count, **r.quantiles()} for k, r in self.latencies.items()
            }
            return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)


class _Timer:
    def __init__(self, metrics: Metrics, name: str):
        self.metrics, self.name = metrics, name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.observe(self.name, time.perf_counter() - self._t0)
        return False


class Throughput:
    """items/sec over a sliding wall-clock window — the BASELINE.json
    images/sec / tokens/sec counters."""

    def __init__(self):
        self._t0: Optional[float] = None
        self._items = 0

    def add(self, n: int):
        if self._t0 is None:
            self._t0 = time.perf_counter()
        self._items += n

    @property
    def per_sec(self) -> float:
        if self._t0 is None or self._items == 0:
            return 0.0
        dt = time.perf_counter() - self._t0
        return self._items / dt if dt > 0 else 0.0


# module-level default registry (imports are cheap; tests can make their own)
default_metrics = Metrics()
