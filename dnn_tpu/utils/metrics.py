"""Step metrics and observability counters.

The reference's only observability is ad-hoc stdout prints (SURVEY §5
'Metrics': node.py:38-39, 85-86, 120-122 — no levels, no counters, no
timers). This module supplies the rebuild's structured replacement: named
counters/gauges plus a latency reservoir with percentiles and fixed-bucket
histograms, emitting the BASELINE.json metrics (images/sec, tokens/sec,
p50 inter-stage latency) as plain dicts / JSON lines — and, for the
serving stack's `/metrics` endpoint (dnn_tpu/obs/http.py), as Prometheus
text exposition format (`render_prometheus`).

Label convention: a metric name may carry Prometheus-style labels inline —
`labeled("comm.retries_total", stage="node1")` ->
'comm.retries_total{stage="node1"}'. The renderer groups lines of one
family under a single # TYPE header; dots in family names become
underscores on the way out (Prometheus names allow [a-zA-Z0-9_:] only).
"""

from __future__ import annotations

import bisect
import json
import re
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional, Sequence


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile, q in [0, 100]."""
    if not values:
        raise ValueError("no samples")
    s = sorted(values)
    k = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[k]


def labeled(name: str, **labels) -> str:
    """Canonical labeled metric key: name{k="v",...}, keys sorted so the
    same label set always maps to the same registry entry. Values are
    stringified; '"' and '\\' are escaped per the exposition format."""
    if not labels:
        return name
    def esc(v):
        return str(v).replace("\\", r"\\").replace('"', r'\"')
    inner = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


class LatencyReservoir:
    """Bounded sample buffer for latency percentiles (seconds)."""

    def __init__(self, capacity: int = 4096):
        self.capacity = capacity
        self._samples: List[float] = []
        self._count = 0
        self._sum = 0.0

    def record(self, seconds: float):
        self._count += 1
        self._sum += seconds
        if len(self._samples) < self.capacity:
            self._samples.append(seconds)
        else:  # deterministic ring replacement; keeps a sliding window
            self._samples[(self._count - 1) % self.capacity] = seconds

    def record_many(self, values):
        """Batch form for Metrics.bulk — one call per step instead of
        one per sample (the per-step obs budget prices the difference)."""
        for v in values:
            self._count += 1
            self._sum += v
            if len(self._samples) < self.capacity:
                self._samples.append(v)
            else:
                self._samples[(self._count - 1) % self.capacity] = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantiles(self, qs=(50, 90, 99)) -> Dict[str, float]:
        """Empty-safe: no samples -> {} (a snapshot of a just-created
        reservoir must not raise; the /metrics endpoint scrapes whatever
        exists at that instant)."""
        if not self._samples:
            return {}
        return {f"p{q}": percentile(self._samples, q) for q in qs}


# Default latency buckets (seconds): µs-scale RPC hops up through
# multi-second generation calls — the le= upper bounds of the exported
# cumulative histogram.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


class Histogram:
    """Fixed-bucket cumulative histogram (Prometheus `histogram` type):
    per-bucket counts plus sum/count, so a scraper can derive rates and
    approximate quantiles without the reservoir's per-sample memory."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf overflow
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def snapshot(self) -> dict:
        cum, out = 0, {}
        for b, c in zip(self.bounds, self.counts):
            cum += c
            out[b] = cum
        return {"buckets": out, "sum": self.sum, "count": self.count}


class Metrics:
    """Thread-safe named counters, gauges, latency reservoirs, and
    histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.latencies: Dict[str, LatencyReservoir] = {}
        self.histograms: Dict[str, Histogram] = {}
        # last gauge_fns dict registered via bulk(): a producer passing
        # the SAME dict every step (the serving hot path) skips the
        # re-register until something could have changed ownership —
        # a clear(), a set()/set_fn() from any producer, a different
        # dict, or new entries in the same dict. Held STRONGLY so a
        # recycled id() can never alias a dead producer's dict (the
        # entries themselves are weak-bound closures by convention, so
        # this pins a small dict, never the producer).
        self._gauge_src = None
        self._gauge_src_len = -1

    def inc(self, name: str, value: float = 1.0):
        with self._lock:
            self.counters[name] += value

    def set(self, name: str, value: float):
        with self._lock:
            self.gauges[name] = value
            self._gauge_src = None  # may overwrite a bulk-owned series

    def set_fn(self, name: str, fn):
        """Register a CALLABLE gauge, evaluated at snapshot/render time —
        for values that must be fresh at scrape (a windowed rate decays
        while the producer is idle; a stored float would go stale)."""
        with self._lock:
            self.gauges[name] = fn
            self._gauge_src = None  # may overwrite a bulk-owned series

    def observe(self, name: str, seconds: float):
        with self._lock:
            if name not in self.latencies:
                self.latencies[name] = LatencyReservoir()
            self.latencies[name].record(seconds)

    def observe_hist(self, name: str, value: float,
                     buckets: Sequence[float] = DEFAULT_BUCKETS):
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(buckets)
            h.observe(value)

    def bulk(self, counters: Optional[Dict[str, float]] = None,
             gauges: Optional[Dict[str, float]] = None,
             observations: Optional[Dict[str, List[float]]] = None,
             gauge_fns: Optional[Dict[str, object]] = None,
             hists: Optional[Dict[str, List[float]]] = None,
             hist_buckets: Optional[Sequence[float]] = None):
        """Apply many updates under ONE lock acquisition — the hot-path
        form (a serving decode step updates ~10 series; per-call locking
        would cost 3-5x this). Semantics match inc/set/observe/set_fn;
        `gauge_fns` re-registers callable gauges idempotently, so the
        most recently active producer owns the series even across
        registry clear()s or multiple producers — but the re-register
        is SKIPPED when the same unchanged dict was already the most
        recent registrant (a hot-path producer passes its gauge dict
        every step; the N-entry update would be pure re-hashing).
        `hists` observe into
        fixed-bucket histograms (created with `hist_buckets`, default
        DEFAULT_BUCKETS — only consulted at first creation)."""
        with self._lock:
            if counters:
                for k, v in counters.items():
                    self.counters[k] += v
            if gauges:
                self.gauges.update(gauges)
            if gauge_fns:
                if (gauge_fns is not self._gauge_src
                        or len(gauge_fns) != self._gauge_src_len):
                    self.gauges.update(gauge_fns)
                    self._gauge_src = gauge_fns
                    self._gauge_src_len = len(gauge_fns)
            if observations:
                for k, vals in observations.items():
                    r = self.latencies.get(k)
                    if r is None:
                        r = self.latencies[k] = LatencyReservoir()
                    r.record_many(vals)
            if hists:
                for k, vals in hists.items():
                    h = self.histograms.get(k)
                    if h is None:
                        h = self.histograms[k] = Histogram(
                            hist_buckets or DEFAULT_BUCKETS)
                    for v in vals:
                        h.observe(v)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    @staticmethod
    def _gauge_val(v) -> float:
        if not callable(v):
            return v
        try:
            return float(v())
        except Exception:  # noqa: BLE001 — a dying producer must not
            return 0.0     # break every scrape

    def snapshot(self) -> dict:
        with self._lock:
            out = {"counters": dict(self.counters),
                   "gauges": {k: self._gauge_val(v)
                              for k, v in self.gauges.items()}}
            out["latency"] = {
                k: {"count": r.count, **r.quantiles()} for k, r in self.latencies.items()
            }
            if self.histograms:
                out["histogram"] = {k: h.snapshot()
                                    for k, h in self.histograms.items()}
            return out

    def json_line(self) -> str:
        return json.dumps(self.snapshot(), sort_keys=True)

    def clear(self):
        """Reset every series (tests / benchmark legs)."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.latencies.clear()
            self.histograms.clear()
            self._gauge_src = None  # producers must re-register


class _Timer:
    def __init__(self, metrics: Metrics, name: str):
        self.metrics, self.name = metrics, name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.metrics.observe(self.name, time.perf_counter() - self._t0)
        return False


class Throughput:
    """items/sec over a sliding wall-clock window (default 60 s) — the
    BASELINE.json images/sec / tokens/sec counters, and the
    `serving.tokens_per_sec` gauge the `/metrics` endpoint exports.

    A real window, not cumulative-since-first-add: events older than
    `window_s` roll off, so an idle server's rate decays to zero instead
    of averaging over its whole uptime. The denominator is the WALL
    window (`min(window_s, lifetime)`), never the span between the
    window's own events — dividing by event span reads ~1e9/s when one
    burst lands after an idle gap (one event, dt≈0), which is exactly
    the gauge spike a scraper must never see. `now` is injectable for
    tests."""

    def __init__(self, window_s: float = 60.0, now=time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._now = now
        self._t0 = now()  # lifetime start: pre-warmup reads under-report
        self._events: "deque[tuple[float, int]]" = deque()
        self._items = 0  # sum over the live window
        # producer (e.g. the batcher worker) and reader (the /metrics
        # scrape thread, via a callable gauge) are different threads;
        # _evict's check-then-popleft is not atomic without this
        self._lock = threading.Lock()

    def _evict(self, t: float):
        cutoff = t - self.window_s
        while self._events and self._events[0][0] < cutoff:
            _, n = self._events.popleft()
            self._items -= n

    def add(self, n: int):
        self.add_at(self._now(), n)

    def add_at(self, t: float, n: int):
        """add() with a caller-supplied timestamp — a producer updating
        several windows in one step (goodput's flops/bytes/tokens) reads
        the clock once and shares it; three clock reads per step were
        measurable against the serving obs budget."""
        with self._lock:
            self._evict(t)
            self._events.append((t, n))
            self._items += n

    @property
    def per_sec(self) -> float:
        t = self._now()
        with self._lock:
            self._evict(t)
            if not self._events or self._items == 0:
                return 0.0
            dt = min(self.window_s, max(t - self._t0, 1e-9))
            return self._items / dt

    def per_sec_with(self, extra: float, t_extra: float) -> float:
        """per_sec, also counting a producer-side PENDING accumulation
        of `extra` items stamped at `t_extra` (goodput batches its
        decode-step updates; a scrape between flushes must still read
        them). Pending older than the window is ignored, so an idle
        producer's unflushed tail decays to zero exactly like landed
        events do."""
        t = self._now()
        with self._lock:
            self._evict(t)
            items = self._items
            if extra and t_extra >= t - self.window_s:
                items += extra
            if not items:
                return 0.0
            dt = min(self.window_s, max(t - self._t0, 1e-9))
            return items / dt


# ----------------------------------------------------------------------
# Prometheus text exposition (version 0.0.4)
# ----------------------------------------------------------------------

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def _split_key(key: str):
    """'fam{k="v"}' -> (sanitized_family, '{k="v"}'); bare names pass
    through with an empty label part."""
    base, _, rest = key.partition("{")
    fam = _NAME_OK.sub("_", base)
    return fam, ("{" + rest) if rest else ""


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def render_prometheus(m: Metrics) -> str:
    """Render a Metrics registry as Prometheus text format: counters ->
    `counter`, gauges -> `gauge`, latency reservoirs -> `summary`
    (quantile 0.5/0.9/0.99 + _count/_sum), histograms -> `histogram`
    (cumulative _bucket{le=...} + _sum/_count). One # TYPE header per
    family, label sets preserved from `labeled()` keys."""
    snap_lock_free: Dict[str, list] = defaultdict(list)

    with m._lock:
        counters = dict(m.counters)
        gauges = {k: m._gauge_val(v) for k, v in m.gauges.items()}
        lats = {k: (r.count, r.sum, r.quantiles((50, 90, 99)))
                for k, r in m.latencies.items()}
        hists = {k: h.snapshot() for k, h in m.histograms.items()}

    fam_type: Dict[str, str] = {}

    def emit(key, kind, lines):
        fam, labels = _split_key(key)
        fam_type.setdefault(fam, kind)
        for suffix, extra, v in lines:
            lab = labels
            if extra:  # merge extra label into the existing set
                k2, v2 = extra
                pair = f'{k2}="{v2}"'
                lab = (labels[:-1] + "," + pair + "}") if labels \
                    else "{" + pair + "}"
            snap_lock_free[fam].append(f"{fam}{suffix}{lab} {_fmt(v)}")

    for k, v in sorted(counters.items()):
        emit(k, "counter", [("", None, v)])
    for k, v in sorted(gauges.items()):
        emit(k, "gauge", [("", None, v)])
    for k, (count, total, qs) in sorted(lats.items()):
        lines = [("", ("quantile", {"p50": "0.5", "p90": "0.9",
                                    "p99": "0.99"}[q]), v)
                 for q, v in qs.items()]
        lines += [("_sum", None, total), ("_count", None, count)]
        emit(k, "summary", lines)
    for k, snap in sorted(hists.items()):
        lines = [("_bucket", ("le", _fmt(b)), c)
                 for b, c in snap["buckets"].items()]
        lines += [("_bucket", ("le", "+Inf"), snap["count"]),
                  ("_sum", None, snap["sum"]),
                  ("_count", None, snap["count"])]
        emit(k, "histogram", lines)

    out = []
    for fam in sorted(snap_lock_free):
        out.append(f"# TYPE {fam} {fam_type[fam]}")
        out.extend(snap_lock_free[fam])
    return "\n".join(out) + ("\n" if out else "")


# module-level default registry (imports are cheap; tests can make their
# own). This is also the registry the obs layer (dnn_tpu/obs) exports at
# /metrics and feeds from the jax.monitoring compile listener.
default_metrics = Metrics()
