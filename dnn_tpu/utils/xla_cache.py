"""Bounding XLA compile-cache growth in long-lived processes.

Observed pathology (this environment's jaxlib CPU build): one process
that keeps compiling DISTINCT programs eventually segfaults inside the
XLA CPU compiler — the full test suite (600+ tests, several programs
each) dies at ~85% unless compiled executables drop between modules
(tests/conftest.py's between-modules `jax.clear_caches()` fixture).
`benchmarks/xla_cache_probe.py` probes minimal forms: 6000 distinct
TINY programs do NOT crash (flat RSS — the trigger is the suite's
program population, SPMD collectives/donation/scans, not raw count),
so the suite-scale evidence is the operative fact. A long-lived
serving daemon that keeps admitting new program shapes (models,
adapters, pooling variants, padded-length buckets) accumulates the
same compiled-artifact volume over days.

This module is the daemon-side guard: count the entries of the
process's OWN jitted entry points (`fn._cache_size()`, the same counter
tests/test_prefix_cache.py pins) and, when a budget is exceeded, call
`jax.clear_caches()` at a SAFE BOUNDARY — a moment the caller
guarantees no compiled program is mid-flight (the LM worker's idle
point: no active slots, empty queue). Cleared programs recompile
transparently on next use; steady-state servers (three programs) never
trip the budget, so the guard costs nothing until the pathology-shaped
workload appears.
"""

from __future__ import annotations

from typing import Callable, List

__all__ = ["jit_cache_entries", "CompileCacheGuard"]


def jit_cache_entries(*fns) -> int:
    """Total compiled-executable entries across `fns` (0 for anything
    without a `_cache_size` — plain callables pass through silently, so
    callers can register hooks without caring which are jitted)."""
    total = 0
    for f in fns:
        size = getattr(f, "_cache_size", None)
        if callable(size):
            total += int(size())
    return total


class CompileCacheGuard:
    """Budgeted `jax.clear_caches()` for a long-lived serving loop.

    `register(fn)` adds a jitted entry point (or a zero-arg callable
    returning a LIST of them — for lazily-created program families like
    the daemon's per-pooling embed fns; return a snapshot copy, not a
    live dict view, so the guard never iterates a structure another
    thread is inserting into). `add_busy_check(fn)` adds a zero-arg
    predicate; while any returns True the guard holds off — device work
    that runs OUTSIDE the calling loop (the daemon's embed endpoint
    runs on asyncio.to_thread) must register one AND flip the state it
    reads under `guard.lock` (the check and the clear run atomically
    under it, so a correctly-locked transition can never slip between
    them). `maybe_clear()` — call it ONLY at a safe boundary — clears
    every XLA cache when the registered entry count reaches `budget`.
    budget <= 0 disables."""

    def __init__(self, budget: int):
        import threading

        self.budget = int(budget)
        self.clears = 0  # observability: soak test + ops metrics
        self._fns: List[Callable] = []
        self._busy: List[Callable] = []
        # check+clear run atomically under this lock; out-of-loop device
        # work must flip its busy state UNDER THE SAME LOCK (the
        # daemon's embed path does), or the busy check could pass just
        # before the work enters its program and the clear land mid-
        # flight anyway
        self.lock = threading.Lock()

    def register(self, fn):
        self._fns.append(fn)
        return fn

    def add_busy_check(self, fn):
        self._busy.append(fn)
        return fn

    def _entries(self) -> int:
        flat = []
        for f in self._fns:
            if getattr(f, "_cache_size", None) is None and callable(f):
                try:
                    flat.extend(f())
                    continue
                except TypeError:
                    pass  # a plain non-jitted registrant: counts as 0
            flat.append(f)
        return jit_cache_entries(*flat)

    def maybe_clear(self) -> bool:
        if self.budget <= 0 or self._entries() < self.budget:
            return False
        with self.lock:  # atomic with the busy transitions (see __init__)
            if any(b() for b in self._busy):
                return False  # device work in flight on another thread
            import jax

            jax.clear_caches()
            self.clears += 1
            return True
