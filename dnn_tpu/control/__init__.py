"""dnn_tpu.control — the fleet front door (ROADMAP item 1).

Everything before this package serves ONE replica per model: the
hardened single-host stack (continuous batching, paged/quantized KV,
negotiated transport, chaos-supervised restart, SLO gauges) ends at a
single `node --serve_lm` process. This package is the first
control-plane subsystem — the stage that composes those primitives
into a *fleet*:

  * `replicaset.py` — replica lifecycle: spawn N `node --serve_lm`
    children through the existing `chaos.supervisor.Supervisor`
    (health/drain/respawn), each replica a declared state machine
    (idle/warming/serving/draining/dead — model-checked in
    `analysis/protocol.REPLICA` like breaker/drain/supervisor), plus
    signal scraping through the existing `obs.fleet.FleetCollector`.
  * `policy.py` — pluggable routing policy, the way `attn_kernel` and
    `transport` already are: `round_robin | least_queue | slo_burn`,
    fed by scrape-time signals the replicas already export (queue
    depth, KV-slot utilization, TTFT/ITL percentiles, error-budget
    burn rate), plus the `dnn_tpu_wanted_replicas` autoscaling signal.
  * `router.py` — a stdlib-asyncio gRPC front door speaking the
    EXISTING Generate/GenerateStream wire format, so `NodeClient`
    points at it unchanged: SLO-driven admission (sheds via the
    breaker/UNAVAILABLE ladder), per-hop `dl=` deadline re-tagging,
    dedup-key-aware session affinity, retry-on-sibling for draining
    replicas, and disaggregated prefill/decode routing.
  * `handoff.py` — the prefill->decode KV handoff wire format: a
    prefill replica computes the prompt's row cache
    (`ContinuousBatcher.export_prefill`), the payload rides the
    negotiated transport's grpc rung, and the decode replica adopts it
    (`submit(prefilled=...)`) — zero prompt FLOPs on the decode side.

CLI: `python -m dnn_tpu.control` spawns a whole fleet (router + N
supervised replicas); `node --route` runs the router alone against
explicit targets. Measured contract:
`benchmarks/fleet_serving_probe.py` (the run_all `fleet_serving` row).
"""

from dnn_tpu.control.policy import (  # noqa: F401
    POLICIES,
    ReplicaView,
    get_policy,
    shed_reason,
    wanted_replicas,
)

__all__ = ["POLICIES", "get_policy", "ReplicaView", "shed_reason",
           "wanted_replicas"]
