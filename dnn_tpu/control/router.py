"""Router: the fleet front door.

A stdlib-asyncio gRPC server speaking the EXISTING NodeService wire
format — SendTensor (generate / embed / prefill / kvput), the additive
GenerateStream, HealthCheck, SendMessage — so every client that talks
to one LM daemon (`NodeClient`, reference-built clients) points at the
router unchanged and gets a FLEET. Per request the router:

  1. ADMITS or SHEDS (SLO-driven): `policy.shed_reason` over the live
     replica views — when every candidate is saturated (the router's
     exact per-replica in-flight bound) or burning error budget past
     the configured rate, the request is shed with UNAVAILABLE, the
     status the whole client ladder (retry, breaker, chaos probe
     accounting) already treats as explicitly-rejected-retriable.
     Shedding is what keeps an overloaded fleet's queues short enough
     that admitted work finishes inside its deadline instead of
     degenerating into admit-then-deadline-cancel waste (STUDIES §17
     measures exactly that collapse on the unfronted baseline).
  2. PICKS a replica via the pluggable policy (`round_robin |
     least_queue | slo_burn`), honoring dedup-key session affinity:
     a `d=`/`h=` tagged request re-routes to the replica that saw the
     key before (the per-replica prefix cache and the server-side
     dedup join both only help on the same replica — until ROADMAP
     item 2's fleet-wide KV tier lands, affinity IS the cache policy).
  3. RE-TAGS the `dl=` deadline per hop: the forward carries only the
     caller's REMAINING budget (comm/client re-tags per attempt), so
     sibling retries can never over-spend a dying request.
  4. RETRIES ON A SIBLING when a replica answers UNAVAILABLE (draining
     /ConnectionRefused/breaker-open): a drained replica's handed-back
     queue lands on its siblings with no client involvement.
  5. DISAGGREGATES prefill/decode when the fleet is role-split: the
     prompt goes to a `role=prefill` replica (`export_prefill` — the
     full chunk loop, no slot held), the returned KV payload is
     installed on the chosen decode replica (`kvput:` + `h=`), and
     only then does the generate forward — the decode replica spends
     ZERO prompt FLOPs. The handoff rides the grpc rung of the
     negotiated transport (the LM daemon declines shm/device — those
     rungs fail loud when forced, like everywhere else) and is priced
     on the router's own gauges (handoff bytes/seconds) next to the
     goodput gauges the replicas already export.

The router's lifecycle is a declared state machine
(init/serving/shedding/draining/stopped — `analysis/protocol.ROUTER`,
model-checked both directions); transitions land in the flight ring as
`router_*` events. Autoscaling: the scrape-time
`dnn_tpu_wanted_replicas` gauge (policy.wanted_replicas) rides the
router's /metrics even though nothing consumes it yet.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, Optional, Set

import grpc
import numpy as np

from dnn_tpu import obs
from dnn_tpu.comm import transport as _tx
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.comm.service import _handlers, _tensor_arr, _tensor_msg
from dnn_tpu.control.policy import Policy, get_policy, shed_reason, \
    wanted_replicas
from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
from dnn_tpu.io.serialization import PayloadCorruptError
from dnn_tpu.utils.metrics import labeled

log = logging.getLogger("dnn_tpu.control")

__all__ = ["Router", "serve_router", "start_router_in_background"]


def _size_forward_executor(loop, router: "Router"):
    """Give the loop a default executor sized to the router's own
    admission bound. asyncio.to_thread rides the DEFAULT executor,
    whose stock size is min(32, cpu_count + 4) — on a small host that
    caps concurrent forwards at ~5 threads, an invisible throttle far
    below max_inflight_per_replica x replicas; the admission
    controller, not the executor, must be the concurrency bound."""
    import concurrent.futures

    n = max(16, router.max_inflight * len(router.replicaset.replicas)
            + 8)
    loop.set_default_executor(concurrent.futures.ThreadPoolExecutor(
        max_workers=n, thread_name_prefix="router-fwd"))

#: gRPC codes a sibling can plausibly do better on — everything else
#: (INVALID_ARGUMENT, DATA_LOSS, ...) is the REQUEST's fault and
#: passes through verbatim
_SIBLING_RETRIABLE = (grpc.StatusCode.UNAVAILABLE,)


class _Shed(Exception):
    """Internal: the admission decision said shed (reason in args)."""


def _affinity_key(request_id: str) -> Optional[str]:
    """The session-affinity key riding the request id: the dedup key
    (`d=`) or a KV-handoff handle (`h=`) — both only work on the
    replica that has seen them before."""
    for seg in (request_id or "").split(":"):
        if seg.startswith("d=") or seg.startswith("h="):
            return seg
    return None


def _role_ok(role: str, need: str) -> bool:
    return role == "both" or role == need


class Router:
    """NodeService servicer that routes across a ReplicaSet.

    `policy` is a name (`round_robin | least_queue | slo_burn`) or a
    prebuilt `control.policy.Policy`. `max_inflight_per_replica`
    bounds the router's outstanding forwards per replica (the
    admission controller's exact signal); `shed_burn` (None = off)
    additionally sheds when EVERY candidate's worst SLO burn rate is
    at or past it. `default_deadline_s` caps requests that propagate
    no `dl=` budget of their own. `retry_siblings` bounds how many
    OTHER replicas an UNAVAILABLE forward retries against (the drain
    hand-back path). `disagg="auto"` routes gen requests through the
    prefill->decode handoff whenever the fleet is actually role-split
    ("off" never does; "on" fails loud when it can't)."""

    def __init__(self, replicaset: ReplicaSet, *,
                 policy="least_queue",
                 default_deadline_s: float = 30.0,
                 max_inflight_per_replica: int = 8,
                 shed_burn: Optional[float] = None,
                 retry_siblings: int = 2,
                 disagg: str = "auto",
                 slots_hint: int = 4,
                 affinity_cap: int = 4096,
                 kvtier: str = "auto",
                 kv_block_len: int = 16,
                 kv_pull_timeout_s: float = 10.0):
        if disagg not in ("auto", "on", "off"):
            raise ValueError(
                f"disagg must be auto|on|off, got {disagg!r}")
        # fleet KV tier (dnn_tpu/kvtier): prefix-aware placement.
        #   "auto" — route a gen request to the replica the directory
        #     says holds its deepest prefix (when routable); otherwise
        #     pick by policy and INSTRUCT A PULL from the holder —
        #     affinity stops being a cache-correctness constraint;
        #   "pull" — never prefer the holder (the policy alone places),
        #     always instruct pulls — the migration-stress mode the
        #     kv_tier probe measures cross-replica hits under;
        #   "off"  — PR 12 behavior (dedup-key affinity only).
        if kvtier not in ("auto", "pull", "off"):
            raise ValueError(
                f"kvtier must be auto|pull|off, got {kvtier!r}")
        self._kvtier = kvtier
        self._kvdir = None
        self.kv_pull_timeout_s = float(kv_pull_timeout_s)
        self._kv_on_names: Set[str] = set()
        self._kv_on_ts = 0.0
        if kvtier != "off":
            from dnn_tpu.kvtier.directory import PrefixDirectory

            self._kvdir = PrefixDirectory(kv_block_len)
        self.replicaset = replicaset
        self.policy: Policy = policy if isinstance(policy, Policy) \
            else get_policy(policy)
        self.default_deadline_s = float(default_deadline_s)
        self.max_inflight = int(max_inflight_per_replica)
        self.shed_burn = shed_burn
        self.retry_siblings = int(retry_siblings)
        self.disagg = disagg
        self.slots_hint = int(slots_hint)
        # the router lifecycle machine is DECLARED (and model-checked)
        # in analysis/protocol.ROUTER — edit both together. All writes
        # under _lock (handlers run on the event loop; close()/serve()
        # may run on other threads).
        self._state = "init"  # init|serving|shedding|draining|stopped
        self._lock = threading.Lock()
        self._draining = False
        self._inflight: Dict[str, int] = {}
        self._clients: Dict[str, object] = {}
        self._affinity: "OrderedDict[str, str]" = OrderedDict()
        self._affinity_cap = int(affinity_cap)
        self._handle_seq = itertools.count()
        self.shed_total = 0
        # the capacity observatory (obs/caplens): demand from this
        # router's admission seam, capacity from its commits, the
        # cold-start ledger from the replicaset's lifecycle seams.
        # One lens per router; every hook below guards with one
        # `lens is not None` test (the kvlens overhead contract).
        self.caplens = None
        m = obs.metrics()
        if m is not None:
            from dnn_tpu.obs.caplens import CapLens

            self.caplens = CapLens(
                slots_per_replica=self.slots_hint,
                max_inflight=self.max_inflight,
                deadline_s=self.default_deadline_s)
            replicaset.attach_caplens(self.caplens)
            for k, fn in self.caplens.prom_gauges().items():
                m.set_fn(k, fn)
        self._install_gauges()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    # -- state machine -------------------------------------------------

    def start(self):
        """init -> serving (the gRPC server is about to take traffic)."""
        with self._lock:
            if self._state != "init":
                return
            self._state = "serving"
        obs.flight.record("router_start",
                          replicas=len(self.replicaset.replicas),
                          policy=self.policy.name)

    def _note_shed(self, reason: str):
        self.shed_total += 1
        lens = self.caplens
        if lens is not None:
            lens.on_shed(reason)
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("dnn_tpu_router_shed_total", reason=reason))
            m.inc(labeled("dnn_tpu_router_requests_total",
                          outcome="shed"))
        with self._lock:
            if self._state != "serving":
                return
            self._state = "shedding"
        obs.flight.record("router_shed", reason=reason)

    def _note_admitted(self):
        with self._lock:
            if self._state != "shedding":
                return
            self._state = "serving"
        obs.flight.record("router_unshed")

    def drain(self):
        """serving|shedding -> draining: stop admitting; in-flight
        forwards finish on their replicas. The serve loop exits once
        drained (serve_router watches the escalation event)."""
        with self._lock:
            if self._state in ("draining", "stopped"):
                return
            self._state = "draining"
            self._draining = True
        obs.flight.record("router_drain",
                          inflight=sum(self._inflight.values()))

    def close(self):
        with self._lock:
            already = self._state == "stopped"
            self._state = "stopped"
        if not already:
            obs.flight.record("router_stop")
        for c in self._clients.values():
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._clients.clear()

    # -- plumbing ------------------------------------------------------

    def _install_gauges(self):
        m = obs.metrics()
        if m is None:
            return
        ref = weakref.ref(self)

        def _queue():
            r = ref()
            return float(sum(r._inflight.values())) if r is not None \
                else 0.0

        def _wanted():
            r = ref()
            if r is None:
                return 0.0
            # v2 (obs/caplens): the audited what-if planner's verdict,
            # when it has evidence; the v1 occupancy heuristic until
            # then (and whenever obs is off)
            lens = r.caplens
            if lens is not None:
                n_live = sum(1 for v in r._views()
                             if v.state == "serving")
                w = lens.wanted_replicas(n_live=n_live)
                if w is not None:
                    return float(w)
            return float(wanted_replicas(
                r._views(), slots_hint=r.slots_hint,
                shedding=r.state == "shedding"))

        m.set_fn("dnn_tpu_router_queue_depth", _queue)
        m.set_fn("dnn_tpu_wanted_replicas", _wanted)

    def _client(self, handle: ReplicaHandle):
        c = self._clients.get(handle.name)
        if c is None:
            from dnn_tpu.comm.client import CircuitBreaker, NodeClient

            # tight breaker: during an outage the router must fail over
            # to a sibling within ~a second, not ride a 30 s cooldown
            c = NodeClient(handle.address, transport="grpc",
                           breaker=CircuitBreaker(
                               handle.address, threshold=3,
                               cooldown_s=0.5, max_cooldown_s=4.0))
            self._clients[handle.name] = c
        return c

    def _track(self, name: str):
        router = self

        class _Tracker:
            def __enter__(self):
                with router._lock:
                    router._inflight[name] = \
                        router._inflight.get(name, 0) + 1

            def __exit__(self, *exc):
                with router._lock:
                    router._inflight[name] = \
                        max(router._inflight.get(name, 1) - 1, 0)

        return _Tracker()

    def _views(self):
        views = self.replicaset.views()
        with self._lock:
            for v in views:
                v.inflight = self._inflight.get(v.name, 0)
        return views

    def _count(self, outcome: str):
        m = obs.metrics()
        if m is not None:
            m.inc(labeled("dnn_tpu_router_requests_total",
                          outcome=outcome))

    def _budget(self, rid: str) -> float:
        """The forward's total budget: a caller-supplied `dl=` tag is
        trusted AS-IS (the client re-tags remaining budget per attempt
        — clamping it would silently lower every explicit client
        deadline); only tagless requests get `default_deadline_s`."""
        inbound = _tx.extract_deadline(rid)
        return max(inbound if inbound is not None
                   else self.default_deadline_s, 0.001)

    # -- prefix-aware placement (dnn_tpu/kvtier) ------------------------

    def _kv_is_gen(self, rid: str, arr, need: str) -> bool:
        """Whether this request participates in prefix-aware placement
        (KV tier on, a decode-role gen forward with real tokens)."""
        if self._kvdir is None or need != "decode" or arr is None:
            return False
        rid_clean = _tx.strip_deadline(obs.strip_wire_tag(rid))
        return rid_clean.split(":")[0] == "gen"

    def _kv_replica_on(self, name: str) -> bool:
        """Scrape-evidenced: the replica exports kvtier residency, so
        it actually serves the radix store. Preferring a 'holder' (or
        instructing a pull onto a target) with no tier is pure loss —
        on a dense fleet the directory must never steer placement.
        Cached ~1 s: this runs up to twice per request and the views
        walk behind it costs a fleet-snapshot build."""
        now = time.monotonic()
        if now - self._kv_on_ts > 1.0:
            self._kv_on_names = {
                v.name for v in self._views()
                if v.kvtier_blocks is not None}
            self._kv_on_ts = now
        return name in self._kv_on_names

    def _kv_locate(self, rid: str, arr, need: str):
        """-> (prefer_replica or None, PrefixLocation or None) for a
        gen request when the KV tier is on. "auto" prefers the holder
        (placement follows the blocks); "pull" never does (placement
        follows the policy, the blocks follow the placement)."""
        if not self._kv_is_gen(rid, arr, need):
            return None, None
        loc = self._kvdir.locate(arr)
        if loc is None:
            return None, None
        prefer = (loc.replica if self._kvtier == "auto"
                  and self._kv_replica_on(loc.replica) else None)
        return prefer, loc

    async def _kv_maybe_pull(self, target: ReplicaHandle, arr, loc,
                             remaining: float):
        """Instruct `target` to pull `loc`'s blocks from their holder
        before the gen forward lands — ADVISORY end to end: a failed
        pull only costs the optimization (the replica re-prefills),
        recorded loud either way."""
        if loc is None or loc.replica == target.name:
            return
        donor = self.replicaset.replicas.get(loc.replica)
        if donor is None or donor.state not in ("serving", "draining"):
            return
        if not self._kv_replica_on(target.name):
            # the target has no radix store — a pull could only fail
            # (and on a dense fleet this path must cost nothing)
            return
        m = obs.metrics()
        try:
            with self._track(target.name):
                status = await asyncio.to_thread(
                    self._client(target).kv_pull_from, donor.address,
                    np.asarray(arr, np.int32)[
                        : loc.n_blocks * self._kvdir.block_len],
                    timeout=max(min(remaining,
                                    self.kv_pull_timeout_s), 0.5))
            if m is not None:
                m.inc("dnn_tpu_router_kvtier_pulls_total")
            if "kvtier_fallback" in (status or ""):
                obs.flight.record("kvtier_pull_fallback",
                                  target=target.name, donor=donor.name,
                                  detail=str(status)[:160])
        except Exception as e:  # noqa: BLE001 — advisory by contract
            obs.flight.record("kvtier_pull_failed", target=target.name,
                              donor=loc.replica,
                              error=f"{type(e).__name__}: {e}"[:160])

    def _kv_observe(self, arr, replica_name: str):
        if self._kvdir is not None and arr is not None:
            self._kvdir.observe(arr, replica_name)

    def _wants_disagg(self, rid_clean: str) -> bool:
        """gen requests take the prefill->decode handoff — except when
        the client already carries a handle (`h=`), or rides a LoRA
        adapter (`a=`: the decode-side `submit(prefilled=)` adoption
        rejects adapters, so those take the plain single-replica
        forward)."""
        if self.disagg == "off":
            return False
        segs = rid_clean.split(":")
        return segs[0] == "gen" and not any(
            s.startswith(("h=", "a=")) for s in segs)

    # -- admission + pick ----------------------------------------------

    def _admit(self, need: str, sticky: Optional[str],
               excluded: Set[str],
               prefer: Optional[str] = None) -> ReplicaHandle:
        """One admission decision: shed (raises _Shed) or the picked
        replica handle. Policy sees only routable candidates (serving,
        role-compatible, not excluded, below the inflight bound).
        `prefer` (prefix-aware placement, dnn_tpu/kvtier): route to
        this replica when it is routable — the directory says it holds
        the request's prefix blocks; overridden by dedup-key affinity
        (a `d=` join MUST land where the original runs)."""
        cands = [v for v in self._views()
                 if v.state == "serving" and v.name not in excluded
                 and _role_ok(v.role, need)]
        reason = shed_reason(cands, max_inflight=self.max_inflight,
                             shed_burn=self.shed_burn)
        if reason is not None:
            raise _Shed(reason)
        routable = [v for v in cands if v.inflight < self.max_inflight]
        names = {v.name for v in routable}
        pick = None
        if sticky is not None:
            bound = self._affinity.get(sticky)
            if bound in names:
                pick = bound
                self._affinity.move_to_end(sticky)
        if pick is None and prefer is not None and prefer in names:
            m = obs.metrics()
            if m is not None:
                m.inc("dnn_tpu_router_kvtier_route_hits_total")
            pick = prefer
        if pick is None:
            pick = self.policy.pick(routable).name
            if sticky is not None:
                self._affinity[sticky] = pick
                self._affinity.move_to_end(sticky)
                while len(self._affinity) > self._affinity_cap:
                    self._affinity.popitem(last=False)
        self._note_admitted()
        return self.replicaset.replicas[pick]

    def _disagg_active(self) -> bool:
        if self.disagg == "off":
            return False
        views = [v for v in self._views() if v.state == "serving"]
        split = (any(v.role == "prefill" for v in views)
                 and any(_role_ok(v.role, "decode") for v in views))
        if self.disagg == "on" and not split:
            raise _Shed("disagg_unsatisfiable")
        return split

    # -- the unary forward core ----------------------------------------

    async def _forward_unary(self, arr, rid: str, context, *,
                             need: str = "decode",
                             pinned: Optional[ReplicaHandle] = None,
                             sticky: Optional[str] = None,
                             fallback_rid: Optional[str] = None):
        """Route one unary request: admission, policy pick (or the
        `pinned` replica — the disagg path already placed the KV),
        deadline-capped forward, sibling retry on UNAVAILABLE. A
        caller-supplied `dl=` budget is trusted as-is (the client
        already re-tags remaining budget per attempt); only tagless
        requests get `default_deadline_s`. `fallback_rid` is the
        disagg path's escape hatch: the router-minted `h=` handle is
        staged ONLY on the pinned replica, so if that forward fails
        the retry loop reverts to the plain rid (decode-side prefill)
        instead of offering siblings a handle they never saw."""
        budget = self._budget(rid)
        t0 = time.monotonic()
        if sticky is None:
            sticky = _affinity_key(rid)
        excluded: Set[str] = set()
        attempts = self.retry_siblings + 1
        last = "no replica attempted"
        kv_gen = self._kv_is_gen(rid, arr, need)
        kv_prefer, kv_loc = self._kv_locate(rid, arr, need) if kv_gen \
            else (None, None)

        def _revert_to_plain():
            # fall back LOUD to plain decode-side prefill — same
            # counter/event as a handoff-leg failure
            nonlocal rid, sticky, fallback_rid
            m = obs.metrics()
            if m is not None:
                m.inc("dnn_tpu_router_handoff_fallback_total")
            obs.flight.record("handoff_fallback", error=last[:200])
            rid = fallback_rid
            sticky = _affinity_key(rid)
            fallback_rid = None

        for _ in range(attempts):
            remaining = budget - (time.monotonic() - t0)
            if remaining <= 0:
                self._count("deadline")
                await context.abort(
                    grpc.StatusCode.DEADLINE_EXCEEDED,
                    f"router budget {budget:.1f}s exhausted ({last})")
            was_pinned = pinned is not None
            if pinned is not None:
                target = pinned
                pinned = None  # a failed pinned forward falls back to
                # the ordinary pick on the next attempt
            else:
                try:
                    target = self._admit(need, sticky, excluded,
                                         prefer=kv_prefer)
                except _Shed as s:
                    self._note_shed(s.args[0])
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        f"router shedding: {s.args[0]}")
            if kv_loc is not None and target.name != kv_loc.replica:
                # placement went somewhere the blocks are NOT (holder
                # saturated/dead on "auto", policy pick on "pull"):
                # instruct the migration before the forward, once
                await self._kv_maybe_pull(target, arr, kv_loc,
                                          remaining)
                kv_loc = None
            client = self._client(target)
            try:
                # capacity signal: inflight BEFORE this dispatch — a
                # commit that rode a free slot is pure service time,
                # one that queued behind a full batch is not, and the
                # caplens planner must not learn the queue it simulates
                infl0 = self._inflight.get(target.name, 0)
                t_fwd = time.monotonic()
                with self._track(target.name):
                    status, result = await asyncio.to_thread(
                        client.send_tensor, arr, request_id=rid,
                        timeout=max(remaining, 0.001), retries=0)
                self._count("ok")
                lens = self.caplens
                if lens is not None:
                    lens.on_commit(
                        target.name, role=target.role,
                        tokens=int(result.size)
                        if result is not None else 0,
                        wall_s=time.monotonic() - t_fwd,
                        inflight_at_dispatch=infl0)
                if kv_gen:
                    # feed the directory: this replica now holds the
                    # prompt's blocks (admission inserted the path)
                    self._kv_observe(arr, target.name)
                if result is None:
                    return wc.TensorResponse(status=status)
                return wc.TensorResponse(
                    status=status, result_tensor=_tensor_msg(result))
            except grpc.RpcError as e:
                code = e.code() if hasattr(e, "code") else None
                if was_pinned and fallback_rid is not None \
                        and code != grpc.StatusCode.DEADLINE_EXCEEDED:
                    # the pinned (handle-tagged) forward failed —
                    # drain, breaker, or the decode replica REJECTING
                    # the adoption (adapter/speculative/consumed
                    # handle). Exclude the replica only when its
                    # health, not the handle, was the problem.
                    last = f"{target.name}: {code} (handoff)"
                    if code in _SIBLING_RETRIABLE:
                        excluded.add(target.name)
                    _revert_to_plain()
                    continue
                if code in _SIBLING_RETRIABLE:
                    # draining / dead / refusing replica: its queued
                    # work was handed back retriable — a SIBLING picks
                    # it up without the client ever seeing the drain
                    excluded.add(target.name)
                    if sticky is not None:
                        self._affinity.pop(sticky, None)
                    last = f"{target.name}: {code}"
                    obs.flight.record("router_retry_sibling",
                                      replica=target.name,
                                      code=str(code))
                    continue
                if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                    self._count("deadline")
                else:
                    self._count("error")
                await context.abort(
                    code or grpc.StatusCode.UNKNOWN,
                    e.details() if hasattr(e, "details")
                    else str(e))
            except PayloadCorruptError as e:
                excluded.add(target.name)
                last = f"{target.name}: payload corrupt ({e})"
                if was_pinned and fallback_rid is not None:
                    _revert_to_plain()
                continue
            except Exception as e:  # noqa: BLE001 — breaker-open and
                # connect-level failures: try a sibling
                excluded.add(target.name)
                if sticky is not None:
                    self._affinity.pop(sticky, None)
                last = f"{target.name}: {type(e).__name__}: {e}"
                if was_pinned and fallback_rid is not None:
                    _revert_to_plain()
                continue
        self._count("unroutable")
        await context.abort(
            grpc.StatusCode.UNAVAILABLE,
            f"no replica could serve the request (last: {last[:200]})")

    # -- disaggregated prefill/decode ----------------------------------

    async def _disagg_blocks(self, arr, rid: str, context,
                             budget: float):
        """Block-migration disaggregation (dnn_tpu/kvtier): the
        prefill replica STAGES the prompt's blocks into its radix
        store, the decode replica PULLS them over the lease rungs, and
        the generate forwards PLAIN — admission adopts the blocks from
        its own store, no single-use handle, and a warm decode replica
        pulls only what it is missing (zero bytes for a shared system
        prompt it has seen before — the thing the packed-row handoff
        re-shipped on every request). Returns the response, or None to
        fall back to the row-pack handoff (recorded loud). _Shed
        propagates to the caller's abort."""
        # precondition, SILENT: without scrape evidence of a radix
        # store on a serving prefill-capable replica, this fleet is a
        # PR 12 row-handoff fleet — skipping without a flight event
        # per request (not a failure, just not applicable)
        if not any(v.state == "serving"
                   and v.kvtier_blocks is not None
                   and _role_ok(v.role, "prefill")
                   for v in self._views()):
            return None
        m = obs.metrics()
        try:
            pre = self._admit("prefill", None, set())
            t_h = time.perf_counter()
            with self._track(pre.name):
                await asyncio.to_thread(
                    self._client(pre).kv_stage, arr,
                    timeout=max(budget / 2, 1.0))
            dec = self._admit("decode", _affinity_key(rid), set())
            with self._track(dec.name):
                pull_status = await asyncio.to_thread(
                    self._client(dec).kv_pull_from, pre.address, arr,
                    timeout=max(budget / 2, 1.0))
            if "kvtier_fallback" in (pull_status or ""):
                raise RuntimeError(
                    f"pull degraded: {str(pull_status)[:160]}")
            dt = time.perf_counter() - t_h
            if m is not None:
                m.observe("dnn_tpu_router_handoff_seconds", dt)
                m.inc("dnn_tpu_router_kvtier_pulls_total")
            obs.flight.record("kv_handoff", prefill=pre.name,
                              decode=dec.name, mode="blocks",
                              ms=round(dt * 1e3, 2))
            self._kv_observe(arr, dec.name)
        except _Shed:
            raise
        except Exception as e:  # noqa: BLE001 — ANY block-leg failure
            # degrades to the row-pack handoff, recorded loud
            if m is not None:
                m.inc("dnn_tpu_kvtier_fallback_total")
            obs.flight.record("kvtier_fallback",
                              error=f"{type(e).__name__}: {e}"[:200])
            return None
        return await self._forward_unary(arr, rid, context, pinned=dec)

    async def _forward_disagg(self, arr, rid: str, context):
        """gen request on a role-split fleet: prefill replica computes
        the KV, decode replica adopts it, generate forwards with the
        handle. When the KV tier is live the BLOCK-migration path runs
        first (stage + pull — kvtier/migrate.py) and the packed-row
        handoff is its fallback. Any handoff-leg failure falls back
        LOUD (flight event + counter) to plain decode-side prefill —
        availability beats disaggregation."""
        m = obs.metrics()
        budget = self._budget(rid)
        if self._kvdir is not None:
            try:
                resp = await self._disagg_blocks(arr, rid, context,
                                                 budget)
            except _Shed as s:
                self._note_shed(s.args[0])
                await context.abort(grpc.StatusCode.UNAVAILABLE,
                                    f"router shedding: {s.args[0]}")
            if resp is not None:
                return resp
        try:
            pre = self._admit("prefill", None, set())
            t_h = time.perf_counter()
            with self._track(pre.name):
                payload = await asyncio.to_thread(
                    self._client(pre).prefill_kv, arr,
                    timeout=max(budget / 2, 1.0))
            handle = f"rt{next(self._handle_seq)}"
            dec = self._admit("decode", _affinity_key(rid), set())
            with self._track(dec.name):
                await asyncio.to_thread(
                    self._client(dec).put_kv, handle, payload,
                    timeout=max(budget / 2, 1.0))
            dt = time.perf_counter() - t_h
            if m is not None:
                m.inc("dnn_tpu_router_handoff_bytes_total",
                      int(payload.size))
                m.observe("dnn_tpu_router_handoff_seconds", dt)
            obs.flight.record("kv_handoff", prefill=pre.name,
                              decode=dec.name, bytes=int(payload.size),
                              ms=round(dt * 1e3, 2))
        except _Shed as s:
            self._note_shed(s.args[0])
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                f"router shedding: {s.args[0]}")
        except Exception as e:  # noqa: BLE001 — ANY handoff failure
            # degrades to decode-side prefill, recorded loud
            if m is not None:
                m.inc("dnn_tpu_router_handoff_fallback_total")
            obs.flight.record("handoff_fallback",
                              error=f"{type(e).__name__}: {e}"[:200])
            return await self._forward_unary(arr, rid, context)
        return await self._forward_unary(
            arr, f"{rid}:h={handle}", context, pinned=dec,
            fallback_rid=rid)

    # --- RPC implementations (wire names fixed by the protocol) --------

    async def SendTensor(self, request: pb.TensorRequest,
                         context) -> pb.TensorResponse:
        if self._draining:
            self._count("draining")
            await context.abort(
                grpc.StatusCode.UNAVAILABLE,
                "router draining: retry against another front door")
        try:
            arr = _tensor_arr(request.tensor)
        except PayloadCorruptError as e:
            await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
        rid = request.request_id or ""
        rid_clean = _tx.strip_deadline(obs.strip_wire_tag(rid))
        lens = self.caplens
        if lens is not None:
            lens.on_arrival(arr.size if arr is not None else 0,
                            scenario=rid_clean.split(":", 1)[0]
                            or "other")
        if rid_clean == "prefill" or rid_clean.startswith("prefill:"):
            return await self._forward_unary(arr, rid, context,
                                             need="prefill")
        if rid_clean.startswith("kvput:"):
            # client-driven kvput-then-generate: bind the handle key
            # NOW so the upcoming `h=<key>` generate re-routes to the
            # replica that staged it
            key = rid_clean.split(":", 1)[1]
            return await self._forward_unary(arr, rid, context,
                                             sticky=f"h={key}")
        if self._wants_disagg(rid_clean):
            try:
                disagg = self._disagg_active()
            except _Shed as s:
                self._note_shed(s.args[0])
                await context.abort(grpc.StatusCode.UNAVAILABLE,
                                    f"router shedding: {s.args[0]}")
            if disagg:
                return await self._forward_disagg(arr, rid, context)
        return await self._forward_unary(arr, rid, context)

    async def GenerateStream(self, request: pb.TensorRequest, context):
        """Streaming passthrough: one upstream replica stream, tokens
        relayed as they arrive. NOT sibling-retried (a stream is
        stateful — tokens already delivered) and never disaggregated
        (the handoff is a pre-admission hop; streams keep the simple
        path — README documents the caveat)."""
        if self._draining:
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                "router draining")
        try:
            arr = _tensor_arr(request.tensor)
        except PayloadCorruptError as e:
            await context.abort(grpc.StatusCode.DATA_LOSS, str(e))
        rid = request.request_id or ""
        lens = self.caplens
        if lens is not None:
            lens.on_arrival(arr.size if arr is not None else 0,
                            scenario="stream")
        budget = self._budget(rid)
        kv_gen = self._kv_is_gen(rid, arr, "decode")
        kv_prefer, kv_loc = self._kv_locate(rid, arr, "decode") \
            if kv_gen else (None, None)
        try:
            target = self._admit("decode", _affinity_key(rid), set(),
                                 prefer=kv_prefer)
        except _Shed as s:
            self._note_shed(s.args[0])
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                f"router shedding: {s.args[0]}")
        if kv_loc is not None and target.name != kv_loc.replica:
            await self._kv_maybe_pull(target, arr, kv_loc, budget)
        if kv_gen:
            self._kv_observe(arr, target.name)
        client = self._client(target)
        loop = asyncio.get_running_loop()
        q: "asyncio.Queue" = asyncio.Queue()
        stop = threading.Event()

        def pump():
            with self._track(target.name):
                try:
                    for resp in client.send_tensor_stream(
                            arr, request_id=rid, timeout=budget):
                        loop.call_soon_threadsafe(
                            q.put_nowait, ("resp", resp))
                        if stop.is_set():
                            break
                    loop.call_soon_threadsafe(q.put_nowait,
                                              ("done", None))
                except BaseException as e:  # noqa: BLE001 — surfaced
                    loop.call_soon_threadsafe(q.put_nowait, ("err", e))

        infl0 = self._inflight.get(target.name, 0)
        t_fwd = time.monotonic()
        n_resp = 0
        threading.Thread(target=pump, daemon=True,
                         name="router-stream-pump").start()
        try:
            while True:
                kind, val = await q.get()
                if kind == "resp":
                    n_resp += 1
                    yield val
                elif kind == "done":
                    self._count("ok")
                    if lens is not None:
                        lens.on_commit(
                            target.name, role=target.role,
                            tokens=n_resp,
                            wall_s=time.monotonic() - t_fwd,
                            inflight_at_dispatch=infl0)
                    return
                else:
                    self._count("error")
                    if isinstance(val, grpc.RpcError):
                        await context.abort(
                            val.code() or grpc.StatusCode.UNKNOWN,
                            val.details() if hasattr(val, "details")
                            else str(val))
                    await context.abort(grpc.StatusCode.UNAVAILABLE,
                                        str(val)[:200])
        finally:
            stop.set()  # client went away: the pump breaks at its next
            # token and its generator's finally cancels the upstream RPC

    async def HealthCheck(self, request: pb.Empty,
                          context) -> pb.HealthCheckResponse:
        healthy = (not self._draining
                   and bool(self.replicaset.serving()))
        return pb.HealthCheckResponse(is_healthy=healthy)

    async def SendMessage(self, request: pb.MessageRequest,
                          context) -> pb.MessageReply:
        """Hellos declined (the router fronts the grpc rung); "!stats"
        answers the router's own view; any other text forwards to a
        decode replica (the tokenizer text front, routed)."""
        if request.sender_id.startswith(_tx.HELLO_SENDER):
            return pb.MessageReply(
                confirmation_text=_tx.decline_hello(
                    "router fronts the grpc rung"))
        if request.message_text == "!stats":
            views = self._views()
            with self._lock:
                state = self._state
            return pb.MessageReply(confirmation_text=(
                f"[router] state={state} policy={self.policy.name} "
                f"replicas="
                + ",".join(f"{v.name}:{v.state}:{v.role}"
                           f"(inflight={v.inflight})" for v in views)
                + f" shed_total={self.shed_total}"))
        if self._draining:
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                "router draining")
        try:
            target = self._admit("decode",
                                 _affinity_key(request.sender_id), set())
        except _Shed as s:
            self._note_shed(s.args[0])
            await context.abort(grpc.StatusCode.UNAVAILABLE,
                                f"router shedding: {s.args[0]}")
        client = self._client(target)
        with self._track(target.name):
            reply = await asyncio.to_thread(
                client.send_message, request.sender_id,
                request.message_text, self.default_deadline_s)
        return pb.MessageReply(confirmation_text=reply)

    # -- obs endpoint --------------------------------------------------

    def statusz(self) -> dict:
        """The router's /statusz: its own state plus one component per
        replica (lifecycle state + role) — the FleetCollector treats
        the router as a first-class target off this shape."""
        with self._lock:
            state = self._state
        as_watchdog = {"init": "degraded", "serving": "ok",
                       "shedding": "degraded", "draining": "draining",
                       "stopped": "wedged"}[state]
        comps = {}
        for r in self.replicaset.replicas.values():
            comps[r.name] = {
                "state": {"serving": "ok", "idle": "degraded",
                          "warming": "degraded",
                          "draining": "degraded"}.get(r.state, "wedged"),
                "detail": f"replica state={r.state} role={r.role} "
                          f"addr={r.address}",
                "role": r.role,
            }
        return {"state": as_watchdog, "router_state": state,
                "role": "router", "policy": self.policy.name,
                "components": comps}


async def serve_router(replicaset: ReplicaSet, *, port: int,
                       metrics_port: Optional[int] = None,
                       **router_kwargs) -> int:
    """Serve the front door and block until termination — the router
    analog of `serve_lm`. SIGTERM drains (admission closes UNAVAILABLE,
    in-flight forwards finish) and exits 0."""
    import signal

    router = Router(replicaset, **router_kwargs)
    srv = None
    if metrics_port is not None:
        srv = obs.serve_metrics(
            metrics_port, status=router.statusz,
            fleet=replicaset.collector,
            caplens=router.caplens,
            healthy=lambda: not router._draining
            and bool(replicaset.serving()))
    server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
    server.add_generic_rpc_handlers((_handlers(router),))
    if server.add_insecure_port(f"[::]:{port}") == 0:
        raise RuntimeError(f"failed to bind router to [::]:{port}")
    await server.start()
    _size_forward_executor(asyncio.get_running_loop(), router)
    router.start()
    log.info("router listening on [::]:%d (%d replicas, policy=%s)",
             port, len(replicaset.replicas),
             router.policy.name)
    drained = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_sigterm():
        log.info("SIGTERM: router draining")
        router.drain()
        loop.call_soon_threadsafe(drained.set)

    try:
        loop.add_signal_handler(signal.SIGTERM, _on_sigterm)
    except (NotImplementedError, ValueError, RuntimeError):
        pass
    term = asyncio.ensure_future(server.wait_for_termination())
    drain_w = asyncio.ensure_future(drained.wait())
    try:
        await asyncio.wait({term, drain_w},
                           return_when=asyncio.FIRST_COMPLETED)
        return 0
    finally:
        try:
            await server.stop(grace=1)
        except asyncio.CancelledError:
            pass
        for t in (term, drain_w):
            if not t.done():
                t.cancel()
            try:
                await t
            except BaseException:  # noqa: BLE001 — reaped, not consulted
                pass
        router.close()
        if srv is not None:
            srv.close()


def start_router_in_background(replicaset: ReplicaSet, *, port: int,
                               **router_kwargs):
    """Test/probe helper: router on a daemon thread; returns
    (router, stop_callback) — mirrors start_lm_server_in_background."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state: dict = {}

    async def _run():
        try:
            router = Router(replicaset, **router_kwargs)
            server = grpc.aio.server(options=_tx.GRPC_MSG_OPTIONS)
            server.add_generic_rpc_handlers((_handlers(router),))
            if server.add_insecure_port(f"[::]:{port}") == 0:
                raise RuntimeError(f"failed to bind router to :{port}")
            await server.start()
            _size_forward_executor(asyncio.get_running_loop(), router)
            router.start()
            state["router"], state["server"] = router, server
            state["done"] = asyncio.Event()
        except BaseException as e:
            state["error"] = e
            raise
        finally:
            started.set()
        await state["done"].wait()
        await asyncio.sleep(0.05)

    def _main():
        asyncio.set_event_loop(loop)
        try:
            loop.run_until_complete(_run())
        except BaseException:
            if "error" not in state:
                raise

    t = threading.Thread(target=_main, daemon=True)
    t.start()
    if not started.wait(timeout=30):
        raise RuntimeError("router failed to start")
    if "error" in state:
        t.join(timeout=5)
        raise RuntimeError(
            f"router failed to start: {state['error']}") \
            from state["error"]

    def stop():
        async def _stop():
            await state["server"].stop(grace=0.2)
            state["done"].set()

        asyncio.run_coroutine_threadsafe(_stop(), loop).result(timeout=10)
        state["router"].close()
        t.join(timeout=5)

    stop.router = state["router"]
    return state["router"], stop
