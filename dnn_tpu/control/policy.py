"""Routing policy: which replica serves the next request.

Pluggable the way `attn_kernel` and `transport` already are — a policy
is a name in `POLICIES` resolved at router construction, and the whole
interface is one method over one dataclass, so adding a policy is a
registry entry, not a router edit.

Signals come from two places with very different freshness:

  * scrape-time rows the replicas already export (queue depth, KV-slot
    utilization, TTFT/ITL percentiles, error-budget burn rates) —
    polled by the ReplicaSet's FleetCollector on its interval, so they
    lag by up to one poll;
  * the router's OWN per-replica in-flight count — exact, updated on
    every forward, and the only signal that survives a replica whose
    obs endpoint is down.

Every policy therefore treats the scraped fields as OPTIONAL (None =
unknown) and falls back to `inflight`; a fleet with no obs endpoints
at all degrades to round-robin-by-load instead of failing.

Pure stdlib — no jax, no grpc — so policies unit-test as goldens with
injected signals (tests/test_control.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Dict, List, Optional

__all__ = ["ReplicaView", "Policy", "POLICIES", "get_policy",
           "shed_reason", "wanted_replicas"]

ROLES = ("prefill", "decode", "both")


@dataclasses.dataclass
class ReplicaView:
    """One replica as the policy sees it: lifecycle + freshest signals.

    `inflight` is the router's local count of forwards currently
    outstanding against this replica (exact); everything else is the
    last scrape (None = never scraped / endpoint down / older build).
    `burn` maps SLO name -> error-budget burn rate (>= 1.0 means the
    objective is being violated right now)."""

    name: str
    state: str = "serving"          # replicaset lifecycle state
    role: str = "both"              # prefill | decode | both
    inflight: int = 0
    queue_depth: Optional[float] = None
    kv_util: Optional[float] = None
    ttft_p99_ms: Optional[float] = None
    inter_token_p99_ms: Optional[float] = None
    tokens_per_sec: Optional[float] = None
    burn: Optional[Dict[str, float]] = None
    # KV-tier residency (dnn_tpu/kvtier): non-None iff the replica
    # exports dnn_tpu_kvtier_blocks — i.e. it actually serves the
    # radix store. The router's prefix-aware placement and pull
    # instructions gate on this: preferring a "holder" (or pulling
    # onto a target) with no tier is pure loss.
    kvtier_blocks: Optional[float] = None

    @property
    def burn_max(self) -> Optional[float]:
        if not self.burn:
            return None
        return max(self.burn.values())

    def load(self) -> float:
        """Best-known load: scraped queue depth plus the router's own
        in-flight count (the scrape lags one poll; the local count
        covers the gap — and is the whole signal when scraping is
        off)."""
        q = self.queue_depth if self.queue_depth is not None else 0.0
        return float(q) + float(self.inflight)


class Policy:
    """Base: `pick` one of `cands` (non-empty, all routable). Policies
    must be deterministic given the same views + internal state — the
    test goldens depend on it."""

    name = "base"

    def pick(self, cands: List[ReplicaView]) -> ReplicaView:
        raise NotImplementedError


class RoundRobin(Policy):
    """Strict rotation over the candidate NAMES (not list positions, so
    a replica dropping out mid-rotation doesn't double-serve its
    neighbor)."""

    name = "round_robin"

    def __init__(self):
        self._counter = itertools.count()

    def pick(self, cands: List[ReplicaView]) -> ReplicaView:
        ordered = sorted(cands, key=lambda v: v.name)
        return ordered[next(self._counter) % len(ordered)]


class LeastQueue(Policy):
    """Lowest load (scraped queue depth + local in-flight); name-order
    tiebreak keeps it deterministic."""

    name = "least_queue"

    def pick(self, cands: List[ReplicaView]) -> ReplicaView:
        return min(cands, key=lambda v: (v.load(), v.name))


class SloBurn(Policy):
    """Goodput-aware pick (the Gemma-on-TPU serving comparison's
    per-replica goodput accounting as the routing signal): score each
    replica by how close it is to violating its objectives, then by
    load, then by tail latency. Burn rate dominates — a replica
    burning error budget at 2x gets no new work while a quiet sibling
    exists, whatever the queue depths say — because queue depth leads
    the SLO breach by seconds while burn rate IS the breach."""

    name = "slo_burn"

    # weights: one unit of burn rate outranks ~8 queued requests; tail
    # latency breaks the remaining ties at 1/100 ms granularity
    W_BURN, W_LOAD, W_TTFT = 8.0, 1.0, 0.01

    def score(self, v: ReplicaView) -> float:
        burn = v.burn_max if v.burn_max is not None else 0.0
        ttft = v.ttft_p99_ms if v.ttft_p99_ms is not None else 0.0
        return (self.W_BURN * burn + self.W_LOAD * v.load()
                + self.W_TTFT * ttft)

    def pick(self, cands: List[ReplicaView]) -> ReplicaView:
        return min(cands, key=lambda v: (self.score(v), v.name))


POLICIES = {p.name: p for p in (RoundRobin, LeastQueue, SloBurn)}


def get_policy(name: str) -> Policy:
    """Resolve a policy NAME to a fresh instance (policies carry
    internal state — round_robin's counter — so sharing one across
    routers would entangle their rotations)."""
    cls = POLICIES.get(name)
    if cls is None:
        raise ValueError(
            f"unknown routing policy {name!r}; choose one of "
            f"{sorted(POLICIES)}")
    return cls()


# ----------------------------------------------------------------------
# admission (SLO-driven shedding) + the autoscaling signal
# ----------------------------------------------------------------------

def shed_reason(cands: List[ReplicaView], *,
                max_inflight: int,
                shed_burn: Optional[float] = None) -> Optional[str]:
    """Admission decision for ONE arriving request: None = admit, else
    the shed reason (the router maps it onto the existing
    breaker/UNAVAILABLE ladder — UNAVAILABLE is the status every
    dnn_tpu client already treats as retriable-elsewhere).

    Sheds when EVERY candidate is saturated — `max_inflight` bounds the
    router's outstanding forwards per replica (the exact, local
    signal: it is what keeps an overloaded fleet's queues short enough
    that admitted work still finishes inside its deadline, instead of
    the admit-then-deadline-cancel waste a FIFO queue degenerates to)
    — or when every candidate's worst error-budget burn rate is at or
    past `shed_burn` (None disables the burn gate)."""
    if not cands:
        return "no_serving_replica"
    if all(v.inflight >= max_inflight for v in cands):
        return "saturated"
    if shed_burn is not None:
        burns = [v.burn_max for v in cands]
        if all(b is not None and b >= shed_burn for b in burns):
            return "slo_burn"
    return None


def wanted_replicas(views: List[ReplicaView], *,
                    slots_hint: int = 4,
                    max_replicas: int = 64,
                    shedding: bool = False) -> int:
    """The `dnn_tpu_wanted_replicas` autoscaling signal (ROADMAP item
    1: emitted even though nothing consumes it yet): how many SERVING
    replicas this fleet's current pressure calls for.

    Derivation — queue depth plus burn rate, the two signals that lead
    a breach: pressure = total queued work / total slot capacity of
    the serving replicas (`slots_hint` per replica when the scrape
    doesn't say). Want enough replicas to bring pressure to ~1; any
    objective burning >= 1 adds one more (latency objectives breach
    before queues look deep); `shedding=True` (the router is actively
    turning arrivals away RIGHT NOW) wants at least one more whatever
    the queues say — admission control keeps replica queues short
    precisely when demand exceeds the fleet, so queue depth alone is
    blind to the pressure the shed counter carries; a fleet with zero
    queue everywhere, no shedding and all burns < 0.25 can give one
    back (never below 1)."""
    serving = [v for v in views if v.state == "serving"]
    n = len(serving)
    if n == 0:
        return 1
    cap = max(n * slots_hint, 1)
    queued = sum(v.load() for v in serving)
    want = max(n, math.ceil(n * queued / cap)) if queued > cap else n
    burns = [v.burn_max for v in serving if v.burn_max is not None]
    if burns and max(burns) >= 1.0:
        want += 1
    if shedding:
        want = max(want, n + 1)
    elif (queued == 0 and n > 1
          and all(b < 0.25 for b in burns or [0.0])):
        want = n - 1
    return max(1, min(want, max_replicas))
