"""CLI: spawn a whole LM fleet — router + N supervised replicas.

    python -m dnn_tpu.control --port 50550 --replicas 2 --model gpt2 \
        [--roles both,both | --roles prefill,decode] \
        [--policy round_robin|least_queue|slo_burn] \
        [--base_port 50600] [--metrics_base_port 50700] \
        [--slots 4] [--max_len N] [--kv auto] [--seed 0] \
        [--metrics_port P] [--replica_arg "--weights=int8" ...]

Each replica is a real `node --serve_lm` child under its own
`chaos.supervisor.Supervisor` (restart-with-backoff, wedged detection
against its OWN metrics port); the router serves the NodeService wire
format on `--port`, so `NodeClient("host:PORT")` — or a reference-built
client — talks to the fleet unchanged. `--metrics_port` additionally
serves the router's obs endpoint, whose /fleetz is the ReplicaSet's
collector view (per-replica role, router queue, shed counts, the
`dnn_tpu_wanted_replicas` autoscaling gauge).

For routing across ALREADY-RUNNING replicas use `node --route`
(attach mode, no spawning). Ctrl-C / SIGTERM drains and exits 0.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys
import tempfile

from dnn_tpu.control.policy import POLICIES, ROLES
from dnn_tpu.utils.logging import setup_logging

log = logging.getLogger("dnn_tpu.control")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dnn_tpu.control",
        description="Fleet front door: router + N supervised "
                    "`node --serve_lm` replicas")
    p.add_argument("--port", type=int, required=True,
                   help="router gRPC port (NodeClient points here)")
    p.add_argument("--model", required=True,
                   help="model-zoo name every replica serves")
    p.add_argument("--replicas", type=int, default=2,
                   help="replica count (ignored when --roles is given)")
    p.add_argument("--roles", default=None,
                   help="comma-separated per-replica roles "
                        "(prefill|decode|both) — a role-split list "
                        "turns on disaggregated prefill/decode")
    p.add_argument("--policy", choices=sorted(POLICIES),
                   default="least_queue")
    p.add_argument("--base_port", type=int, default=None,
                   help="first replica gRPC port (default: port+50)")
    p.add_argument("--metrics_base_port", type=int, default=None,
                   help="first replica obs port (default: base_port+50)")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="router's own obs endpoint (serves /fleetz)")
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max_len", type=int, default=None)
    p.add_argument("--kv", choices=["paged", "dense", "auto"],
                   default="auto")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_inflight", type=int, default=8,
                   help="router admission bound: outstanding forwards "
                        "per replica before new arrivals shed")
    p.add_argument("--shed_burn", type=float, default=None,
                   help="additionally shed when every candidate's "
                        "worst SLO burn rate reaches this (needs the "
                        "replicas to run --slo_* objectives)")
    p.add_argument("--default_deadline_s", type=float, default=30.0)
    p.add_argument("--kvtier", choices=["auto", "pull", "off"],
                   default="auto",
                   help="prefix-aware placement over the fleet KV "
                        "tier (dnn_tpu/kvtier); 'off' = dedup-key "
                        "affinity only")
    p.add_argument("--replica_arg", action="append", default=None,
                   help="extra argv token passed to every replica "
                        "child (repeatable), e.g. "
                        "--replica_arg=--weights=int8")
    p.add_argument("--log_level", default="INFO")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, node_id="router")
    if args.roles:
        roles = [r.strip() for r in args.roles.split(",") if r.strip()]
    else:
        roles = ["both"] * args.replicas
    bad = [r for r in roles if r not in ROLES]
    if bad or not roles:
        log.error("--roles must be a non-empty comma list of %s, got %r",
                  "|".join(ROLES), args.roles)
        return 1
    if any(r == "prefill" for r in roles) and \
            not any(r in ("decode", "both") for r in roles):
        log.error("a prefill-only fleet can serve no generate request; "
                  "add a decode/both replica")
        return 1
    base_port = args.base_port if args.base_port is not None \
        else args.port + 50
    metrics_base = args.metrics_base_port \
        if args.metrics_base_port is not None else base_port + 50
    extra = []
    for tok in args.replica_arg or []:
        # accept both --replica_arg=--flag=v and --replica_arg --flag v
        extra += tok.split() if " " in tok else [tok]

    from dnn_tpu.control.replicaset import ReplicaSet
    from dnn_tpu.control.router import serve_router

    with tempfile.TemporaryDirectory(prefix="dnn_tpu_fleet_") as tmp:
        try:
            rset = ReplicaSet.spawn_lm_fleet(
                tmp, model=args.model, base_port=base_port,
                metrics_base_port=metrics_base, roles=roles,
                slots=args.slots, max_len=args.max_len,
                seed=args.seed, kv=args.kv, extra_args=extra)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("fleet spawn failed: %s", e)
            return 1
        rset.start()
        log.info("spawned %d replicas (roles=%s); waiting for first "
                 "serving replica", len(roles), ",".join(roles))
        try:
            rc = asyncio.run(serve_router(
                rset, port=args.port, metrics_port=args.metrics_port,
                policy=args.policy, kvtier=args.kvtier,
                max_inflight_per_replica=args.max_inflight,
                shed_burn=args.shed_burn,
                default_deadline_s=args.default_deadline_s))
        except KeyboardInterrupt:
            log.info("shutting down fleet")
            rc = 0
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("router failed: %s", e)
            rc = 1
        finally:
            rset.stop()
    return rc


if __name__ == "__main__":
    sys.exit(main())
