"""Prefill->decode KV handoff: the wire format.

Disaggregated serving (ROADMAP item 1's second leg) moves the prompt's
computed KV from a PREFILL replica to a DECODE replica. The payload is
`ContinuousBatcher.export_prefill`'s output — the transient row cache's
leaves (the same pytree `submit` builds during convoy admission) plus
the final chunk's true-last logit row (so the decode side samples the
first token exactly as the convoy path would, draw-for-draw) — packed
here into ONE 1-D uint8 tensor so it rides the existing SendTensor
wire message on the negotiated transport's grpc rung unchanged.
(The shm/device rungs would move these bytes zero-copy, but the LM
daemon declines negotiation today — explicit shm/device against it
fails loud, exactly like every other unprovable rung; ROADMAP item 2's
paged-block migration is the real zero-copy fix.)

Format: magic + length-prefixed JSON header (leaf shapes/dtypes, the
geometry fingerprint both sides must agree on) + the raw leaf bytes in
C order. Non-numpy cache dtypes ship viewed as same-width integers
(bfloat16 <-> uint16); int4 caches are rejected at export — their
packed jax representation has no stable host view to ship.

Pure numpy + stdlib; both the router (no jax) and the serving stack
import it.
"""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

import numpy as np

__all__ = ["pack", "unpack", "HandoffFormatError"]

_MAGIC = b"dnnkv1\n"

# dtypes shipped as themselves; anything else must have a registered
# same-width integer view (below) or is rejected loud
_VIEW_AS = {"bfloat16": "uint16"}


class HandoffFormatError(ValueError):
    """A payload this module cannot pack or parse — corrupt bytes, an
    unsupported cache dtype, or a header/byte-length mismatch. A
    ValueError so server endpoints map it to INVALID_ARGUMENT."""


def _dtype_name(arr: np.ndarray) -> str:
    return arr.dtype.name


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import ml_dtypes  # jax dependency; only needed for bf16 payloads

    try:
        return np.dtype(getattr(ml_dtypes, name))
    except AttributeError:
        raise HandoffFormatError(
            f"handoff payload names unknown dtype {name!r}") from None


def _wire_view(arr: np.ndarray) -> Tuple[np.ndarray, str]:
    """-> (same-bytes array in a wire-safe dtype, original dtype name)."""
    arr = np.ascontiguousarray(arr)
    name = _dtype_name(arr)
    view = _VIEW_AS.get(name)
    if view is not None:
        return arr.view(np.dtype(view)), name
    try:
        np.dtype(name)  # a stock numpy dtype ships as itself
    except TypeError:
        raise HandoffFormatError(
            f"cache dtype {name!r} has no handoff wire form (int4 "
            "caches cannot hand off; serve the prefill/decode split "
            "with f32/bf16/int8 KV)") from None
    return arr, name


def pack(payload: Dict) -> np.ndarray:
    """{'row': [leaves], 'logits_row': (V,), 'prompt_len': int,
    'fingerprint': dict} -> one 1-D uint8 array (the wire tensor)."""
    leaves: List[np.ndarray] = [np.asarray(x) for x in payload["row"]]
    logits = np.ascontiguousarray(np.asarray(payload["logits_row"]))
    chunks, specs = [], []
    for leaf in leaves + [logits]:
        wire, name = _wire_view(leaf)
        chunks.append(wire.tobytes())
        specs.append({"shape": list(leaf.shape), "dtype": name,
                      "bytes": len(chunks[-1])})
    header = json.dumps({
        "v": 1,
        "prompt_len": int(payload["prompt_len"]),
        "fingerprint": payload.get("fingerprint") or {},
        "leaves": specs[:-1],
        "logits": specs[-1],
    }).encode()
    buf = b"".join([_MAGIC, len(header).to_bytes(4, "big"), header]
                   + chunks)
    return np.frombuffer(buf, np.uint8)


def _read_leaf(body: memoryview, off: int, spec: dict
               ) -> Tuple[np.ndarray, int]:
    n = int(spec["bytes"])
    if off + n > len(body):
        raise HandoffFormatError(
            "handoff payload truncated: header promises more leaf "
            "bytes than the tensor carries")
    dt = _resolve_dtype(spec["dtype"])
    wire_dt = np.dtype(_VIEW_AS.get(spec["dtype"], spec["dtype"]))
    arr = np.frombuffer(body[off:off + n], wire_dt)
    if wire_dt is not dt and wire_dt != dt:
        arr = arr.view(dt)
    try:
        arr = arr.reshape(spec["shape"])
    except ValueError:
        raise HandoffFormatError(
            f"handoff leaf bytes do not match shape {spec['shape']} "
            f"dtype {spec['dtype']}") from None
    return arr, off + n


def unpack(buf) -> Dict:
    """Inverse of pack: the wire tensor -> {'row': [leaves],
    'logits_row', 'prompt_len', 'fingerprint'}. Raises
    HandoffFormatError (a ValueError) on anything malformed — a decode
    replica must answer INVALID_ARGUMENT, never adopt garbage KV."""
    raw = np.asarray(buf, np.uint8).tobytes()
    if not raw.startswith(_MAGIC):
        raise HandoffFormatError(
            "not a KV handoff payload (bad magic) — was this tensor "
            "produced by ContinuousBatcher.export_prefill?")
    at = len(_MAGIC)
    if len(raw) < at + 4:
        raise HandoffFormatError("handoff payload truncated (no header)")
    hlen = int.from_bytes(raw[at:at + 4], "big")
    at += 4
    try:
        head = json.loads(raw[at:at + hlen].decode())
    except (ValueError, UnicodeDecodeError):
        raise HandoffFormatError(
            "handoff header is not valid JSON") from None
    at += hlen
    body = memoryview(raw)
    leaves = []
    off = at
    for spec in head.get("leaves", []):
        leaf, off = _read_leaf(body, off, spec)
        leaves.append(leaf)
    logits, off = _read_leaf(body, off, head["logits"])
    return {
        "row": leaves,
        "logits_row": logits,
        "prompt_len": int(head["prompt_len"]),
        "fingerprint": head.get("fingerprint") or {},
    }
