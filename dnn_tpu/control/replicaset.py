"""ReplicaSet: replica lifecycle for the fleet front door.

One `ReplicaHandle` per LM replica: its gRPC address, its obs endpoint,
its serving `role` (prefill | decode | both — the disaggregation
attribute), optionally a `chaos.supervisor.Supervisor` that owns the
real `node --serve_lm` child process (spawn / restart-with-backoff /
wedged detection — nothing here re-implements recovery; the PR 8
machinery IS the recovery), and the replica's lifecycle state machine:

    idle -> warming -> serving -> draining -> dead -> (respawn) warming

The table is DECLARED in `analysis/protocol.REPLICA` and model-checked
both directions by the CI gate, exactly like breaker/drain/supervisor
— edit the two together. Transitions land in the flight ring
(`replica_*` events), so a fleet incident reconstructs from /debugz
the way a chaos incident does (STUDIES §13/§17).

`ReplicaSet` owns the handles plus the monitor thread that drives the
machines off fresh health probes, and (when the replicas expose obs
endpoints) an `obs.fleet.FleetCollector` scraping the signals the
routing policies consume — queue depth, KV-slot utilization, TTFT/ITL
percentiles, burn rates (`views()` merges them into
`policy.ReplicaView` rows). Attach mode (no supervisor) wraps already-
running endpoints — tests and `node --route` use it; the spawning mode
is `ReplicaSet.spawn_lm_fleet` / `python -m dnn_tpu.control`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Dict, List, Optional

from dnn_tpu.control.policy import ROLES, ReplicaView
from dnn_tpu.obs import flight

__all__ = ["ReplicaHandle", "ReplicaSet", "lm_replica_argv"]


class ReplicaHandle:
    """One replica: endpoints + lifecycle state (+ optional Supervisor).

    `address` is the gRPC host:port `NodeClient` dials; `obs_url` the
    replica's observability base (http://host:port) — health probes and
    signal scraping ride it when present, else health falls back to a
    fresh gRPC HealthCheck per poll (fresh per poll for the same reason
    the Supervisor's is: a probe wedged in a dead socket must never
    mask a recovery). The state attr is written ONLY under `_lock`;
    the monitor thread and the owning ReplicaSet are the writers, the
    router reads.
    """

    def __init__(self, name: str, address: str, *,
                 obs_url: Optional[str] = None,
                 role: str = "both",
                 supervisor=None):
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        self.name = name
        self.address = address
        self.obs_url = obs_url.rstrip("/") if obs_url else None
        self.role = role
        self.supervisor = supervisor
        # the replica lifecycle machine is DECLARED (and model-checked)
        # in analysis/protocol.REPLICA — edit both together
        self.state = "idle"  # idle|warming|serving|draining|dead
        self._lock = threading.Lock()
        self._health_fails = 0
        # lifecycle stamps: every transition event carries its duration
        # (spawn->ready, ready->drain), so the cold-start ledger
        # (obs/caplens) and a future autoscaler read ONE event stream
        self.t_spawn: Optional[float] = None
        self.t_ready: Optional[float] = None
        self._caplens = None  # set by ReplicaSet.attach_caplens

    # -- lifecycle entry points (ReplicaSet/monitor-thread callers) ----

    def start(self):
        """idle -> warming: launch the supervised child (attach mode
        has nothing to launch — the probe loop promotes it the moment
        its endpoint answers)."""
        with self._lock:
            if self.state != "idle":
                return
            self.state = "warming"
        self.t_spawn = time.monotonic()
        self.t_ready = None
        flight.record("replica_spawn", replica=self.name,
                      role=self.role, address=self.address,
                      supervised=self.supervisor is not None)
        lens = self._caplens
        if lens is not None:
            lens.spawn_begin(self.name, self.role, now=self.t_spawn)
        if self.supervisor is not None:
            self.supervisor.start()

    def drain(self) -> bool:
        """serving -> draining: close the replica's admission (POST
        /drainz — the PR 8 drain; queued work hands back retriable and
        the router's retry-on-sibling picks it up). Returns False when
        the replica has no obs endpoint to drain through."""
        import urllib.request

        with self._lock:
            if self.state != "serving":
                return False
            self.state = "draining"
        t = time.monotonic()
        flight.record("replica_drain", replica=self.name,
                      served_s=round(t - self.t_ready, 3)
                      if self.t_ready is not None else None)
        lens = self._caplens
        if lens is not None:
            lens.spawn_gone(self.name)
        if self.obs_url is None:
            return False
        try:
            req = urllib.request.Request(
                self.obs_url + "/drainz", method="POST", data=b"")
            with urllib.request.urlopen(req, timeout=5.0) as r:
                return r.status in (200, 202)
        except Exception:  # noqa: BLE001 — a dead replica can't drain;
            return False   # the monitor will mark it dead shortly

    def kill(self):
        """SIGKILL the supervised child NOW (the chaos hand): the
        supervisor notices the exit and respawns; the monitor drives
        dead -> warming -> serving off the same health probes
        production would."""
        if self.supervisor is not None:
            self.supervisor.inject_kill()

    # -- monitor-thread transitions ------------------------------------

    def _mark_serving(self):
        with self._lock:
            prev, self.state = self.state, "serving"
        if prev != "serving":
            t = time.monotonic()
            self.t_ready = t
            flight.record("replica_ready", replica=self.name,
                          role=self.role,
                          spawn_to_ready_s=round(t - self.t_spawn, 3)
                          if self.t_spawn is not None else None)
            lens = self._caplens
            if lens is not None:
                lens.spawn_ready(self.name, now=t)

    def _mark_dead(self, reason: str):
        with self._lock:
            prev, self.state = self.state, "dead"
        if prev != "dead":
            t = time.monotonic()
            flight.record("replica_dead", replica=self.name,
                          was=prev, reason=reason,
                          alive_s=round(t - self.t_spawn, 3)
                          if self.t_spawn is not None else None)
            lens = self._caplens
            if lens is not None:
                lens.spawn_gone(self.name)

    def _mark_respawning(self):
        with self._lock:
            prev, self.state = self.state, "warming"
        if prev != "warming":
            self.t_spawn = time.monotonic()
            self.t_ready = None
            flight.record("replica_respawn", replica=self.name)
            lens = self._caplens
            if lens is not None:
                lens.spawn_begin(self.name, self.role,
                                 now=self.t_spawn)

    # -- health --------------------------------------------------------

    def _healthy_once(self, timeout_s: float) -> bool:
        """One FRESH health probe. Obs endpoint when present (200 =
        healthy; 503 covers wedged AND draining); gRPC HealthCheck
        otherwise."""
        if self.obs_url is not None:
            import urllib.request

            try:
                with urllib.request.urlopen(
                        self.obs_url + "/healthz", timeout=timeout_s) as r:
                    return r.status == 200
            except Exception:  # noqa: BLE001 — unreachable = unhealthy
                return False
        from dnn_tpu.comm.client import NodeClient

        probe = NodeClient(self.address, breaker=False, transport="grpc")
        try:
            return probe.health_check(timeout=timeout_s)
        finally:
            probe.close()


class ReplicaSet:
    """The fleet's replica collection + the monitor that keeps each
    handle's lifecycle machine current.

    `scrape=True` (default, when every handle has an obs_url) runs an
    `obs.fleet.FleetCollector` over the replica endpoints —
    spans are NOT polled (poll_traces=False): the router wants signal
    rows at its poll cadence, not trace stitching."""

    def __init__(self, replicas: List[ReplicaHandle], *,
                 interval_s: float = 1.0,
                 health_timeout_s: float = 2.0,
                 dead_after: int = 3,
                 scrape: bool = True):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas: Dict[str, ReplicaHandle] = {
            r.name: r for r in replicas}
        self.interval_s = float(interval_s)
        self.health_timeout_s = float(health_timeout_s)
        self.dead_after = int(dead_after)
        self.collector = None
        if scrape and all(r.obs_url for r in replicas):
            from dnn_tpu.obs.fleet import FleetCollector

            self.collector = FleetCollector(
                {r.name: r.obs_url for r in replicas},
                interval_s=self.interval_s,
                timeout_s=self.health_timeout_s,
                poll_traces=False)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.caplens = None

    def attach_caplens(self, lens):
        """Wire the capacity observatory (obs/caplens) into the
        lifecycle seams: every handle's spawn/ready/drain transition
        feeds the cold-start ledger, and the lens reads each child's
        boot/compile gauges through this set's collector (its default
        `signals` source, unless the lens already has one)."""
        self.caplens = lens
        for r in self.replicas.values():
            r._caplens = lens
            if lens is None:
                continue
            # backfill spawns that predate the lens (the usual order:
            # fleet starts, THEN the router builds its lens) — the
            # handles' stamps keep the walls honest
            if r.t_spawn is not None and r.state in ("warming",
                                                     "serving"):
                lens.spawn_begin(r.name, r.role, now=r.t_spawn)
                if r.t_ready is not None:
                    lens.spawn_ready(r.name, now=r.t_ready)
        if lens is not None and lens._signals is None \
                and self.collector is not None:
            lens._signals = self.collector.boot_signals

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ReplicaSet":
        for r in self.replicas.values():
            r.start()
        if self.collector is not None:
            self.collector.start()
        self._thread = threading.Thread(
            target=self._monitor, daemon=True, name="control-replicaset")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.collector is not None:
            self.collector.close()
        for r in self.replicas.values():
            if r.supervisor is not None:
                r.supervisor.stop()

    def wait_serving(self, n: int = 1, deadline_s: float = 180.0) -> bool:
        """Block until >= n replicas reach `serving` (boot includes a
        jax import + first compile — the deadline defaults generous)."""
        t_end = time.monotonic() + deadline_s
        while time.monotonic() < t_end:
            if len(self.serving()) >= n:
                return True
            if self._stop.wait(0.25):
                return False
        return False

    # -- the monitor ---------------------------------------------------

    def _tick_one(self, r: ReplicaHandle):
        sup = r.supervisor
        child_gone = (
            sup is not None and
            (sup.proc is None or sup.proc.poll() is not None
             or sup.state in ("restarting", "crashloop")))
        if r.state == "dead":
            # a supervised child the Supervisor relaunched re-enters
            # warming immediately; an ATTACHED endpoint (no supervisor)
            # re-enters only once it actually answers healthy again —
            # its next probe then promotes it to serving
            if sup is not None:
                if not child_gone:
                    r._mark_respawning()
            elif r._healthy_once(self.health_timeout_s):
                r._mark_respawning()
            return
        healthy = (not child_gone) and r._healthy_once(
            self.health_timeout_s)
        if healthy:
            r._health_fails = 0
            if r.state in ("warming", "serving"):
                r._mark_serving()
            # draining stays draining while the endpoint still answers
            # (it 503s once the drain takes; unreachable ends it below)
            return
        if r.state == "warming":
            # boot grace for SUPERVISED children is the Supervisor's
            # ready_deadline job — the monitor only condemns one whose
            # child is actually gone. An attached endpoint has no boot
            # story: consecutive failures send it back to dead (a
            # drained/stopped server must not read "warming" forever)
            if child_gone:
                r._mark_dead("child exited during boot")
            elif sup is None:
                r._health_fails += 1
                if r._health_fails >= self.dead_after:
                    r._mark_dead(f"{r._health_fails} consecutive "
                                 "health failures while warming")
            return
        r._health_fails += 1
        if child_gone or r._health_fails >= self.dead_after:
            r._mark_dead("child gone" if child_gone
                         else f"{r._health_fails} consecutive health "
                              "failures")

    def _monitor(self):
        while not self._stop.wait(self.interval_s):
            for r in list(self.replicas.values()):
                try:
                    self._tick_one(r)
                except Exception:  # noqa: BLE001 — one replica's probe
                    pass           # blowing up must not stop the fleet

    # -- views (what the router/policies consume) ----------------------

    def serving(self) -> List[ReplicaHandle]:
        return [r for r in self.replicas.values()
                if r.state == "serving"]

    def views(self) -> List[ReplicaView]:
        """Every replica as a `policy.ReplicaView`: lifecycle state from
        the handles, signals from the collector's freshest rows (None
        when scraping is off / a row is missing — policies degrade to
        the router's local inflight counts)."""
        rows: Dict[str, dict] = {}
        if self.collector is not None:
            try:
                rows = self.collector.fleetz().get("stages") or {}
            except Exception:  # noqa: BLE001 — a scrape hiccup must
                rows = {}      # not take routing down
        out = []
        for r in self.replicas.values():
            row = rows.get(r.name) or {}
            out.append(ReplicaView(
                name=r.name, state=r.state,
                role=row.get("role") or r.role,
                queue_depth=row.get("queue_depth"),
                kv_util=row.get("kv_util"),
                ttft_p99_ms=row.get("ttft_p99_ms"),
                inter_token_p99_ms=row.get("inter_token_p99_ms"),
                tokens_per_sec=row.get("tokens_per_sec"),
                burn=row.get("slo_burn"),
                kvtier_blocks=row.get("kvtier_blocks"),
            ))
        return out

    # -- spawning real replicas ----------------------------------------

    @classmethod
    def spawn_lm_fleet(cls, tmpdir: str, *, model: str,
                       base_port: int, metrics_base_port: int,
                       roles: List[str],
                       slots: int = 4,
                       max_len: Optional[int] = None,
                       seed: int = 0,
                       kv: str = "auto",
                       extra_args: Optional[List[str]] = None,
                       env: Optional[dict] = None,
                       interval_s: float = 1.0,
                       ready_deadline_s: float = 240.0,
                       slo_args: Optional[List[str]] = None
                       ) -> "ReplicaSet":
        """Spawn len(roles) real `node --serve_lm` children, each under
        its own `chaos.supervisor.Supervisor` polling that child's OWN
        obs endpoint (the injectable ready-probe URL — distinct
        metrics ports without subclassing). Config JSONs land in
        `tmpdir`, which must outlive the set (supervisors respawn from
        them)."""
        import subprocess

        from dnn_tpu.chaos.supervisor import Supervisor

        handles = []
        for i, role in enumerate(roles):
            name = f"r{i}"
            port = base_port + i
            mport = metrics_base_port + i
            cfg = {"nodes": [{"id": name,
                              "address": f"127.0.0.1:{port}",
                              "part_index": 0}],
                   "num_parts": 1, "model": model, "device_type": "cpu"}
            cfg_path = os.path.join(tmpdir, f"replica_{name}.json")
            with open(cfg_path, "w") as f:
                json.dump(cfg, f)
            argv = lm_replica_argv(
                name, cfg_path, metrics_port=mport, role=role,
                slots=slots, max_len=max_len, seed=seed, kv=kv,
                extra_args=extra_args)
            child_env = dict(os.environ, JAX_PLATFORMS="cpu")
            child_env.pop("XLA_FLAGS", None)
            if env:
                child_env.update(env)

            def spawn(argv=argv, child_env=child_env):
                return subprocess.Popen(
                    argv, env=child_env, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL)

            obs_url = f"http://127.0.0.1:{mport}"
            handles.append(ReplicaHandle(
                name, f"127.0.0.1:{port}", obs_url=obs_url, role=role,
                supervisor=Supervisor(
                    spawn, name=name, health_url=obs_url,
                    health_interval_s=1.0, health_timeout_s=2.0,
                    wedged_after=3, on_wedged="restart",
                    backoff_s=0.5, ready_deadline_s=ready_deadline_s)))
        return cls(handles, interval_s=interval_s)


def lm_replica_argv(node_id: str, config_path: str, *,
                    metrics_port: int, role: str = "both",
                    slots: int = 4, max_len: Optional[int] = None,
                    seed: int = 0, kv: str = "auto",
                    extra_args: Optional[List[str]] = None) -> List[str]:
    """The replica child's command line — one place, so the CLI
    (`python -m dnn_tpu.control`), the fleet probe, and tests spawn
    byte-identical children."""
    argv = [sys.executable, "-m", "dnn_tpu.node",
            "--node_id", node_id, "--config", config_path,
            "--serve_lm", "--role", role,
            "--slots", str(slots), "--seed", str(seed), "--kv", kv,
            "--metrics_port", str(metrics_port)]
    if max_len is not None:
        argv += ["--max_len", str(max_len)]
    if extra_args:
        argv += list(extra_args)
    return argv
