"""Version-compat helpers shared by the Pallas kernels."""

from __future__ import annotations


def _compiler_params(pltpu, **kw):
    """jax renamed TPUCompilerParams -> CompilerParams across releases;
    resolve whichever this jax ships (the kernels are otherwise
    version-agnostic, and the interpret-mode CI path must not die on the
    name)."""
    cls = getattr(pltpu, "CompilerParams", None) or \
        getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)
