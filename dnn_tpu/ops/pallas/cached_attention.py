"""Pallas TPU kernel for attention AGAINST A KV CACHE — the serving hot
loop (decode + chunked prefill).

Why `flash_attention.py` doesn't cover this: the cache path's masking is
positional against a PREALLOCATED buffer — query token i (at absolute
position pos+i) may attend cache columns <= pos+i, where `pos` is a
RUNTIME value (a decode slot's current length, a prefill chunk's start).
The flash kernel's causal offset is a compile-time constant baked into the
kernel closure; specializing on it would recompile per chunk index and per
decode length — exactly what the serving runtime's three-program contract
forbids (dnn_tpu/runtime/serving.py). Here the limit arrives as a small
array input instead, one scalar per (batch, head) program, so ONE compiled
kernel serves every chunk start and every slot position.

Second serving-specific capability: the cache may be stored int8 with
per-(position, head) scales (dnn_tpu/runtime/kvcache.Int8KV). The kernel
streams the int8 bytes directly from HBM and folds the scales into the
score matrix / probability matrix inside VMEM — the dequantized cache
never exists in HBM, which is the entire point of quantizing a
bandwidth-bound loop. (The XLA einsum path expresses the same math, but
whether the f32 upcast fuses into the dot or materializes is the
compiler's choice; the kernel makes the 1-byte-per-element read a
guarantee.)

Decode is the degenerate case T=1 with a per-slot position vector — same
kernel, block_q=1 grid row.

Numerics: online softmax (running row max / row sum) in f32, identical to
`reference_cached_attention` below, which is also the fallback for
non-TPU backends and non-tiling shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30


from dnn_tpu.ops.pallas._compat import _compiler_params  # noqa: E402


# ----------------------------------------------------------------------
# reference (fallback + test oracle) — the kvcache.py einsum math
# ----------------------------------------------------------------------

def reference_cached_attention(q, k, v, pos, *, ks=None, vs=None):
    """q (B, H, T, D) at absolute positions pos[b] + t; k/v (B, H, S, D)
    cache buffers (any float dtype, or int8 with `ks`/`vs` scales
    (B, H, S)); pos (B,) int32. Row (b, t) attends columns
    <= pos[b] + t. Returns (B, H, T, D) f32."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if ks is not None:
        s = s * ks[:, :, None, :]
    s = s / jnp.sqrt(d)
    cols = jnp.arange(k.shape[2])
    rows = jnp.arange(q.shape[2])
    limit = pos[:, None, None, None] + rows[None, None, :, None]
    s = jnp.where(cols[None, None, None, :] <= limit, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    if vs is not None:
        p = p * vs[:, :, None, :]
    return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


# ----------------------------------------------------------------------
# kernel
# ----------------------------------------------------------------------

def _cached_attn_kernel(pos_ref, q_ref, k_ref, v_ref, *rest,
                        scale, block_q, block_s, quant):
    from jax.experimental import pallas as pl

    # the quant variant carries two extra scale inputs; the float variant
    # omits them entirely (no placeholder traffic — see _kernel_call)
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest

    qi = pl.program_id(1)
    si = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # base position for this (batch, head) program: a RUNTIME scalar, read
    # from the scalar-prefetch ref (SMEM) — scalars driving control flow
    # must not come from VMEM vector lanes on real hardware
    pos = pos_ref[pl.program_id(0)]
    # dead cache block iff its first column exceeds the block's largest
    # row limit (pos + last row index). Unlike flash_attention this is a
    # DYNAMIC predicate — pl.when skips the block's COMPUTE (the BlockSpec
    # pipeline still fetches every block; the bandwidth story is the int8
    # byte width and fused dequant, not block skipping).
    live = si * block_s <= pos + (qi + 1) * block_q - 1

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_s, d) — int8 streams raw
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_s)
        if quant:
            s = s * ks_ref[0]  # (1, block_s) per-position K scales
        s = s * scale

        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_s), 0) + qi * block_q
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_s), 1) + si * block_s
        s = jnp.where(cols <= pos + rows, s, _NEG_BIG)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if quant:
            # V scale folds into the (small) probability matrix; the raw
            # int8 V contracts directly (scales commute — kvcache.py)
            pv = p * vs_ref[0]
        else:
            pv = p
        v = v_ref[0].astype(jnp.float32)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(si == ns - 1)
    def _finish():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def _kernel_call(q3, k3, v3, pos1d, ks3, vs3, *, block_q, block_s, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    s_len = k3.shape[1]
    nq, ns = t // block_q, s_len // block_s
    quant = ks3 is not None
    kernel = functools.partial(
        _cached_attn_kernel, scale=1.0 / (d ** 0.5), block_q=block_q,
        block_s=block_s, quant=quant,
    )
    # index maps gain a TRAILING scalar-prefetch ref argument (unused here
    # — blocks are addressed by grid coordinates alone)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, qi, si, p: (b, qi, 0))
    sspec = pl.BlockSpec((1, block_s, d), lambda b, qi, si, p: (b, si, 0))
    scale_spec = pl.BlockSpec((1, 1, block_s),
                              lambda b, qi, si, p: (b, 0, si))
    in_specs = [qspec, sspec, sspec]
    args = [q3, k3, v3]
    if quant:
        in_specs += [scale_spec, scale_spec]
        args += [ks3, vs3]
    # pos rides scalar prefetch: the whole (bh,) vector lands in SMEM and
    # each program reads its scalar — the supported pattern for runtime
    # values steering pl.when control flow on real hardware
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nq, ns),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pos1d, *args)


def cached_attention(q, k, v, pos, *, ks=None, vs=None, block_q=128,
                     block_s=128, interpret=None):
    """Cache attention with runtime position limits (see module docstring).

    q (B, H, T, D); k/v (B, H, S, D) — float, or int8 with ks/vs (B, H, S)
    scales; pos (B,) int32 base positions (row t attends cols
    <= pos[b] + t). Returns (B, H, T, D) f32.

    Dispatches to the Pallas kernel on TPU when S tiles by `block_s`
    (T tiles by block_q, or T < block_q which shrinks the q block);
    otherwise runs the identical-math reference. `interpret=True` forces
    the kernel in interpreter mode (CPU CI)."""
    b, h, t, d = q.shape
    s_len = k.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        if not on_tpu:
            return reference_cached_attention(q, k, v, pos, ks=ks, vs=vs)
        interpret = False
    if t <= block_q:
        block_q = t  # decode: T=1 -> one q row per program
    tiles = (s_len % block_s == 0 and t % block_q == 0)
    if not tiles:
        return reference_cached_attention(q, k, v, pos, ks=ks, vs=vs)

    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, s_len, d)
    v3 = v.reshape(bh, s_len, d)
    # per-(batch, head) base position: heads share their batch row's limit
    pos1d = jnp.repeat(pos.astype(jnp.int32), h)
    ks3 = ks.reshape(bh, 1, s_len).astype(jnp.float32) if ks is not None else None
    vs3 = vs.reshape(bh, 1, s_len).astype(jnp.float32) if vs is not None else None
    out = _kernel_call(q3, k3, v3, pos1d, ks3, vs3, block_q=block_q,
                       block_s=block_s, interpret=interpret)
    return out.reshape(b, h, t, d)


# ----------------------------------------------------------------------
# decode-specialized kernel (T=1 steps; all query rows share the slot's
# position limit)
# ----------------------------------------------------------------------
#
# Why the general kernel above fails at decode: with block_q=1 its grid is
# (B*H, 1, S/128) — thousands of programs each DMAing a 128-row cache tile
# (~32 KB), a latency-bound pipeline that measured 23x SLOWER than the XLA
# einsum at S=4096 (benchmarks/attn_kernel_probe.py). Decode attention is
# pure bandwidth: the right shape is FEW programs streaming BIG blocks.
# This kernel folds all heads into one program — grid (B, S/block_s),
# each step DMAing an (Hk, block_s, D) K and V slab (hundreds of KB) —
# and clamps the cache index map at the slot's live limit, so blocks past
# `pos` are never fetched (Pallas skips the copy when consecutive grid
# steps map to the same block): per-step traffic scales with the ACTIVE
# context, not the allocation.
#
# MEASURED VERDICT (v5e, benchmarks/attn_kernel_probe.py, B=8 H=12 D=64):
# this shape wins at moderate context (1.8x at S=256, 1.2x at S=1024 bf16)
# but XLA's einsum decode attention is already near-bandwidth-optimal on
# this chip — 600-700 GB/s at S=16384 INCLUDING the fused int8 dequant
# (int8 runs 1.7x faster than bf16 einsum, i.e. the byte reduction is
# fully realized with no materialized float cache) — while this kernel
# tops out ~200 GB/s: with D=64 the cache block's minor dim fills only
# half of the 128 VMEM lanes, so every DMA moves half-empty tiles.
# Consequence: `attn_kernel` stays OFF by default; the einsum is the
# decode hot path, and this kernel is (a) the runtime-position chunked
# prefill program (which flash_attention.py cannot express) and (b) the
# 1-byte-read guarantee should a future XLA stop fusing the int8 upcast.
#
# The query is (B, Hk, R, D): R rows per KV head, ALL sharing their
# slot's limit pos[b]. R=1 is plain MHA decode; R=G covers GQA's folded
# query groups (models/llama.py decode) — the fold that the general
# kernel's +row masking contract had to exclude.


def reference_decode_attention(q, k, v, pos, *, ks=None, vs=None):
    """q (B, Hk, R, D) decode rows; every row of slot b attends cache
    columns <= pos[b]. k/v (B, Hk, S, D) float — or int8 with ks/vs
    (B, Hk, S) scales. Returns (B, Hk, R, D) f32. Identical math to
    FloatKV/Int8KV.attend_rows' einsum (dnn_tpu/runtime/kvcache.py)."""
    d = q.shape[-1]
    s = jnp.einsum("bhrd,bhsd->bhrs", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if ks is not None:
        s = s * ks[:, :, None, :]
    s = s / jnp.sqrt(d)
    cols = jnp.arange(k.shape[2])
    s = jnp.where(cols[None, None, None, :] <= pos[:, None, None, None],
                  s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    if vs is not None:
        p = p * vs[:, :, None, :]
    return jnp.einsum("bhrs,bhsd->bhrd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


def _decode_attn_kernel(pos_ref, q_ref, k_ref, v_ref, *rest,
                        scale, block_s, quant):
    from jax.experimental import pallas as pl

    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest

    si = pl.program_id(1)
    ns = pl.num_programs(1)

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = pos_ref[pl.program_id(0)]
    # blocks past the live limit: index map re-targets them at the limit
    # block (no DMA — see _decode_call) and compute is skipped here
    live = si * block_s <= pos

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)   # (Hk, R, d)
        k = k_ref[0].astype(jnp.float32)   # (Hk, block_s, d)
        hk, r, d = q.shape
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (Hk, R, block_s)
        if quant:
            s = s * ks_ref[0][:, None, :]
        s = s * scale
        s2 = s.reshape(hk * r, block_s)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (hk * r, block_s), 1) + si * block_s
        s2 = jnp.where(cols <= pos, s2, _NEG_BIG)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s2.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s2 - m_new)  # (Hk*R, block_s)
        if quant:
            # V scales broadcast over the R query rows of each KV head
            pv = p.reshape(hk, r, block_s) * vs_ref[0][:, None, :]
        else:
            pv = p.reshape(hk, r, block_s)
        v = v_ref[0].astype(jnp.float32)   # (Hk, block_s, d)
        out = jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (Hk, R, d)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + out.reshape(hk * r, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(si == ns - 1)
    def _finish():
        hk, r, d = q_ref.shape[1:]
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).reshape(hk, r, d) \
            .astype(o_ref.dtype)


def _decode_call(q, k, v, pos1d, ks, vs, *, block_s, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hk, r, d = q.shape
    s_len = k.shape[2]
    ns = s_len // block_s
    quant = ks is not None
    kernel = functools.partial(
        _decode_attn_kernel, scale=1.0 / (d ** 0.5), block_s=block_s,
        quant=quant,
    )

    # cache blocks clamp their index at the slot's last LIVE block:
    # consecutive grid steps past the limit map to the same block, and the
    # Pallas TPU pipeline skips the copy when a block index repeats —
    # dead allocation is never streamed.
    def _cache_map(bi, si, p):
        return (bi, 0, jnp.minimum(si, p[bi] // block_s), 0)

    def _scale_map(bi, si, p):
        return (bi, 0, jnp.minimum(si, p[bi] // block_s))

    qspec = pl.BlockSpec((1, hk, r, d), lambda bi, si, p: (bi, 0, 0, 0))
    cspec = pl.BlockSpec((1, hk, block_s, d), _cache_map)
    in_specs = [qspec, cspec, cspec]
    args = [q, k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, hk, block_s), _scale_map)] * 2
        args += [ks, vs]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, ns),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((hk * r, 128), jnp.float32),  # running row max
            pltpu.VMEM((hk * r, 128), jnp.float32),  # running row sum
            pltpu.VMEM((hk * r, d), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, r, d), jnp.float32),
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pos1d, *args)


# ----------------------------------------------------------------------
# paged flash-decode kernel (block-table cache: runtime/paged_kvcache.py)
# ----------------------------------------------------------------------
#
# The paged pool's einsum baseline MATERIALIZES a dense (B, H, S_max, D)
# view of every slot's blocks each step (PagedKV.gather_view) — a full
# logical-cache copy in HBM before attention even starts, which is the
# one place the paged layout pays bandwidth the dense layout doesn't.
# This kernel removes the materialization: the slot's block TABLE rides
# scalar prefetch, and each grid step's index map chases the table to DMA
# the PHYSICAL block straight from the pool into VMEM. Two clamps do the
# live-length work:
#   * logical blocks past the slot's live limit re-target the last live
#     block (repeated index -> the Pallas pipeline skips the copy), so
#     per-step traffic scales with each slot's ACTUAL context — the pool
#     analog of _decode_call's position clamp;
#   * columns past `pos` are masked inside the online softmax as usual.
# int8 pools stream their 1-byte payload with the per-(position, head)
# scales folded in VMEM, exactly like the dense decode kernel. (int4
# pools stay on the einsum: sub-byte VMEM loads are not wired.)


def reference_paged_decode_attention(q, kp, vp, tables, pos, *, ks=None,
                                     vs=None):
    """Oracle for the paged kernel: gather the dense view, then the
    dense decode reference. q (B, Hk, R, D); kp/vp (n_blocks, Hk, bp, D)
    pool; tables (B, nb_max) int32; pos (B,). Returns (B, Hk, R, D) f32."""
    b, nb = tables.shape
    bp = kp.shape[2]

    def view(leaf):
        g = jnp.take(leaf, tables.reshape(-1), axis=0)
        hk = g.shape[1]
        rest = g.shape[3:]
        g = g.reshape(b, nb, hk, bp, *rest)
        g = jnp.moveaxis(g, 1, 2)
        return g.reshape(b, hk, nb * bp, *rest)

    return reference_decode_attention(
        q, view(kp), view(vp), pos,
        ks=view(ks) if ks is not None else None,
        vs=view(vs) if vs is not None else None)


def _paged_decode_kernel(pos_ref, tab_ref, q_ref, k_ref, v_ref, *rest,
                         scale, block_len, quant):
    from jax.experimental import pallas as pl

    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest

    si = pl.program_id(1)
    ns = pl.num_programs(1)

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    pos = pos_ref[pl.program_id(0)]
    live = si * block_len <= pos

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)   # (Hk, R, d)
        k = k_ref[0].astype(jnp.float32)   # (Hk, block_len, d)
        hk, r, d = q.shape
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )  # (Hk, R, block_len)
        if quant:
            s = s * ks_ref[0][:, None, :]
        s = s * scale
        s2 = s.reshape(hk * r, block_len)
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (hk * r, block_len), 1) + si * block_len
        s2 = jnp.where(cols <= pos, s2, _NEG_BIG)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s2.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s2 - m_new)
        if quant:
            pv = p.reshape(hk, r, block_len) * vs_ref[0][:, None, :]
        else:
            pv = p.reshape(hk, r, block_len)
        v = v_ref[0].astype(jnp.float32)
        out = jax.lax.dot_general(
            pv, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + out.reshape(hk * r, d)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(si == ns - 1)
    def _finish():
        hk, r, d = q_ref.shape[1:]
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).reshape(hk, r, d) \
            .astype(o_ref.dtype)


def paged_decode_attention(q, kp, vp, tables, pos, *, ks=None, vs=None,
                           interpret=None):
    """Fused paged decode attention (see the section comment above).

    q (B, Hk, R, D) — R query rows per KV head, all attending logical
    columns <= pos[b] of their slot; kp/vp (n_blocks, Hk, bp, D) block
    pool — float, or int8 with ks/vs (n_blocks, Hk, bp) scales; tables
    (B, nb_max) int32 logical->physical block map; pos (B,) int32.
    Returns (B, Hk, R, D) f32, identical math to the gather_view einsum
    (reference_paged_decode_attention is the oracle).

    Dispatches to the Pallas kernel on TPU; otherwise runs the
    reference. `interpret=True` forces the kernel in interpreter mode
    (CPU CI runs the real table-chasing index maps)."""
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        if not on_tpu:
            return reference_paged_decode_attention(
                q, kp, vp, tables, pos, ks=ks, vs=vs)
        interpret = False
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hk, r, d = q.shape
    nb_max = tables.shape[1]
    bp = kp.shape[2]
    quant = ks is not None
    kernel = functools.partial(
        _paged_decode_kernel, scale=1.0 / (d ** 0.5), block_len=bp,
        quant=quant,
    )

    # the block table chases through scalar prefetch: logical block si of
    # slot bi lives at physical pool block tab[bi * nb_max + si], and
    # blocks past the live limit re-target the last LIVE logical block
    # (repeated physical index -> no DMA)
    def _pool_map(bi, si, p, tab):
        return (tab[bi * nb_max + jnp.minimum(si, p[bi] // bp)], 0, 0, 0)

    def _scale_map(bi, si, p, tab):
        return (tab[bi * nb_max + jnp.minimum(si, p[bi] // bp)], 0, 0)

    qspec = pl.BlockSpec((1, hk, r, d), lambda bi, si, p, tab: (bi, 0, 0, 0))
    cspec = pl.BlockSpec((1, hk, bp, d), _pool_map)
    in_specs = [qspec, cspec, cspec]
    args = [q, kp, vp]
    if quant:
        in_specs += [pl.BlockSpec((1, hk, bp), _scale_map)] * 2
        args += [ks.astype(jnp.float32), vs.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb_max),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((hk * r, 128), jnp.float32),  # running row max
            pltpu.VMEM((hk * r, 128), jnp.float32),  # running row sum
            pltpu.VMEM((hk * r, d), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hk, r, d), jnp.float32),
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pos.astype(jnp.int32), tables.reshape(-1).astype(jnp.int32), *args)


def decode_attention(q, k, v, pos, *, ks=None, vs=None, block_s=512,
                     interpret=None):
    """Decode-step cache attention (see the section comment above).

    q (B, Hk, R, D) — R query rows per KV head, all attending columns
    <= pos[b] of their slot; k/v (B, Hk, S, D) float or int8 with ks/vs
    (B, Hk, S) scales; pos (B,) int32. Returns (B, Hk, R, D) f32.

    Dispatches to the Pallas streaming kernel on TPU when S tiles by a
    {512, 256, 128} block; otherwise runs the identical-math reference.
    `interpret=True` forces the kernel in interpreter mode (CPU CI)."""
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        if not on_tpu:
            return reference_decode_attention(q, k, v, pos, ks=ks, vs=vs)
        interpret = False
    s_len = k.shape[2]
    for bs in (block_s, 256, 128):
        if s_len % bs == 0:
            block_s = bs
            break
    else:
        return reference_decode_attention(q, k, v, pos, ks=ks, vs=vs)
    pos1d = pos.astype(jnp.int32)
    ks_f = ks.astype(jnp.float32) if ks is not None else None
    vs_f = vs.astype(jnp.float32) if vs is not None else None
    return _decode_call(q, k, v, pos1d, ks_f, vs_f, block_s=block_s,
                        interpret=interpret)
