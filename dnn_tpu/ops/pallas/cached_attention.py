"""Pallas TPU kernel for attention AGAINST A KV CACHE — the serving hot
loop (decode + chunked prefill).

Why `flash_attention.py` doesn't cover this: the cache path's masking is
positional against a PREALLOCATED buffer — query token i (at absolute
position pos+i) may attend cache columns <= pos+i, where `pos` is a
RUNTIME value (a decode slot's current length, a prefill chunk's start).
The flash kernel's causal offset is a compile-time constant baked into the
kernel closure; specializing on it would recompile per chunk index and per
decode length — exactly what the serving runtime's three-program contract
forbids (dnn_tpu/runtime/serving.py). Here the limit arrives as a small
array input instead, one scalar per (batch, head) program, so ONE compiled
kernel serves every chunk start and every slot position.

Second serving-specific capability: the cache may be stored int8 with
per-(position, head) scales (dnn_tpu/runtime/kvcache.Int8KV). The kernel
streams the int8 bytes directly from HBM and folds the scales into the
score matrix / probability matrix inside VMEM — the dequantized cache
never exists in HBM, which is the entire point of quantizing a
bandwidth-bound loop. (The XLA einsum path expresses the same math, but
whether the f32 upcast fuses into the dot or materializes is the
compiler's choice; the kernel makes the 1-byte-per-element read a
guarantee.)

Decode is the degenerate case T=1 with a per-slot position vector — same
kernel, block_q=1 grid row.

Numerics: online softmax (running row max / row sum) in f32, identical to
`reference_cached_attention` below, which is also the fallback for
non-TPU backends and non-tiling shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30


# ----------------------------------------------------------------------
# reference (fallback + test oracle) — the kvcache.py einsum math
# ----------------------------------------------------------------------

def reference_cached_attention(q, k, v, pos, *, ks=None, vs=None):
    """q (B, H, T, D) at absolute positions pos[b] + t; k/v (B, H, S, D)
    cache buffers (any float dtype, or int8 with `ks`/`vs` scales
    (B, H, S)); pos (B,) int32. Row (b, t) attends columns
    <= pos[b] + t. Returns (B, H, T, D) f32."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if ks is not None:
        s = s * ks[:, :, None, :]
    s = s / jnp.sqrt(d)
    cols = jnp.arange(k.shape[2])
    rows = jnp.arange(q.shape[2])
    limit = pos[:, None, None, None] + rows[None, None, :, None]
    s = jnp.where(cols[None, None, None, :] <= limit, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    if vs is not None:
        p = p * vs[:, :, None, :]
    return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32),
                      preferred_element_type=jnp.float32)


# ----------------------------------------------------------------------
# kernel
# ----------------------------------------------------------------------

def _cached_attn_kernel(pos_ref, q_ref, k_ref, v_ref, *rest,
                        scale, block_q, block_s, quant):
    from jax.experimental import pallas as pl

    # the quant variant carries two extra scale inputs; the float variant
    # omits them entirely (no placeholder traffic — see _kernel_call)
    if quant:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_scr, l_scr, acc_scr = rest

    qi = pl.program_id(1)
    si = pl.program_id(2)
    ns = pl.num_programs(2)

    @pl.when(si == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # base position for this (batch, head) program: a RUNTIME scalar, read
    # from the scalar-prefetch ref (SMEM) — scalars driving control flow
    # must not come from VMEM vector lanes on real hardware
    pos = pos_ref[pl.program_id(0)]
    # dead cache block iff its first column exceeds the block's largest
    # row limit (pos + last row index). Unlike flash_attention this is a
    # DYNAMIC predicate — pl.when skips the block's COMPUTE (the BlockSpec
    # pipeline still fetches every block; the bandwidth story is the int8
    # byte width and fused dequant, not block skipping).
    live = si * block_s <= pos + (qi + 1) * block_q - 1

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_s, d) — int8 streams raw
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_s)
        if quant:
            s = s * ks_ref[0]  # (1, block_s) per-position K scales
        s = s * scale

        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_s), 0) + qi * block_q
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_s), 1) + si * block_s
        s = jnp.where(cols <= pos + rows, s, _NEG_BIG)

        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        if quant:
            # V scale folds into the (small) probability matrix; the raw
            # int8 V contracts directly (scales commute — kvcache.py)
            pv = p * vs_ref[0]
        else:
            pv = p
        v = v_ref[0].astype(jnp.float32)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(si == ns - 1)
    def _finish():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def _kernel_call(q3, k3, v3, pos1d, ks3, vs3, *, block_q, block_s, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    s_len = k3.shape[1]
    nq, ns = t // block_q, s_len // block_s
    quant = ks3 is not None
    kernel = functools.partial(
        _cached_attn_kernel, scale=1.0 / (d ** 0.5), block_q=block_q,
        block_s=block_s, quant=quant,
    )
    # index maps gain a TRAILING scalar-prefetch ref argument (unused here
    # — blocks are addressed by grid coordinates alone)
    qspec = pl.BlockSpec((1, block_q, d), lambda b, qi, si, p: (b, qi, 0))
    sspec = pl.BlockSpec((1, block_s, d), lambda b, qi, si, p: (b, si, 0))
    scale_spec = pl.BlockSpec((1, 1, block_s),
                              lambda b, qi, si, p: (b, 0, si))
    in_specs = [qspec, sspec, sspec]
    args = [q3, k3, v3]
    if quant:
        in_specs += [scale_spec, scale_spec]
        args += [ks3, vs3]
    # pos rides scalar prefetch: the whole (bh,) vector lands in SMEM and
    # each program reads its scalar — the supported pattern for runtime
    # values steering pl.when control flow on real hardware
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nq, ns),
        in_specs=in_specs,
        out_specs=qspec,
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bh, t, d), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(pos1d, *args)


def cached_attention(q, k, v, pos, *, ks=None, vs=None, block_q=128,
                     block_s=128, interpret=None):
    """Cache attention with runtime position limits (see module docstring).

    q (B, H, T, D); k/v (B, H, S, D) — float, or int8 with ks/vs (B, H, S)
    scales; pos (B,) int32 base positions (row t attends cols
    <= pos[b] + t). Returns (B, H, T, D) f32.

    Dispatches to the Pallas kernel on TPU when S tiles by `block_s`
    (T tiles by block_q, or T < block_q which shrinks the q block);
    otherwise runs the identical-math reference. `interpret=True` forces
    the kernel in interpreter mode (CPU CI)."""
    b, h, t, d = q.shape
    s_len = k.shape[2]
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        if not on_tpu:
            return reference_cached_attention(q, k, v, pos, ks=ks, vs=vs)
        interpret = False
    if t <= block_q:
        block_q = t  # decode: T=1 -> one q row per program
    tiles = (s_len % block_s == 0 and t % block_q == 0)
    if not tiles:
        return reference_cached_attention(q, k, v, pos, ks=ks, vs=vs)

    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, s_len, d)
    v3 = v.reshape(bh, s_len, d)
    # per-(batch, head) base position: heads share their batch row's limit
    pos1d = jnp.repeat(pos.astype(jnp.int32), h)
    ks3 = ks.reshape(bh, 1, s_len).astype(jnp.float32) if ks is not None else None
    vs3 = vs.reshape(bh, 1, s_len).astype(jnp.float32) if vs is not None else None
    out = _kernel_call(q3, k3, v3, pos1d, ks3, vs3, block_q=block_q,
                       block_s=block_s, interpret=interpret)
    return out.reshape(b, h, t, d)
