"""Flash attention as a Pallas TPU kernel.

The hot op of the GPT family (SURVEY.md §5 "long-context"). Online-softmax
blockwise attention: never materializes the (T, T) score matrix in HBM —
scores live in VMEM one (block_q, block_k) tile at a time, with running
row-max / row-sum rescaling (the flash-attention recurrence).

Grid: (batch*heads, q_blocks, k_blocks); the k dimension is sequential
("arbitrary") so the f32 accumulator scratch persists across k steps, while
batch/head/q blocks parallelize. Causal masking skips fully-masked k blocks
outright (upper triangle), so causal costs ~half the FLOPs of full.

Falls back to the jnp reference implementation (numerically identical math)
when not running on TPU, when shapes don't tile, or when the sequence is too
short to be worth a kernel launch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30  # finite "minus infinity": keeps exp()/max() NaN-free


# ----------------------------------------------------------------------
# reference path (also the off-TPU fallback and the test oracle)
# ----------------------------------------------------------------------

def reference_attention(q, k, v, *, causal=True):
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / jnp.sqrt(d)
    if causal:
        t, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t, sk), dtype=bool), k=sk - t)
        s = jnp.where(mask, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


# ----------------------------------------------------------------------
# pallas kernel
# ----------------------------------------------------------------------

def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, causal, scale, block_q, block_k, offset
):
    """`offset = S - T` aligns the causal mask bottom-right (query t attends
    to keys <= t + offset), matching reference_attention's tril(k=S-T) —
    the KV-cache decode convention when S > T."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: k block is dead iff its first col exceeds the max valid col of
    # this q block's last row (qi*bq + bq - 1 + offset).
    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1 + offset)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k)

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows + offset >= cols, s, _NEG_BIG)

        m_prev = m_scr[:, :1]  # (block_q, 1) row stats, lane-broadcast storage
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def _flash_tpu(q, k, v, *, causal, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, t, d = q.shape
    s_len = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    bh = b * h
    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, s_len, d)
    v3 = v.reshape(bh, s_len, d)
    nq, nk = t // block_q, s_len // block_k

    kernel = functools.partial(
        _flash_kernel,
        causal=causal,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        offset=s_len - t,
    )
    out = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return out.reshape(b, h, t, d)


def flash_attention(q, k, v, *, causal=True, block_q=128, block_k=128, interpret=None):
    """(B, H, T, D) scaled-dot-product attention. Dispatches to the Pallas
    TPU kernel when shapes tile cleanly on a TPU backend; otherwise runs the
    numerically-identical jnp reference (so `use_flash=True` is always safe —
    the review contract of dnn_tpu/ops/attention.py)."""
    t, s_len = q.shape[2], k.shape[2]
    if causal:
        block_k = block_q  # diagonal-block masking assumes square tiles
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = False
        if not on_tpu:
            return reference_attention(q, k, v, causal=causal)
    tiles = t % block_q == 0 and s_len % block_k == 0 and t >= block_q and s_len >= block_k
    if not tiles or (causal and s_len < t):
        # s < t causal (queries before the first key) is a degenerate case
        # the kernel's masking doesn't model — use the reference path.
        return reference_attention(q, k, v, causal=causal)
    return _flash_tpu(q, k, v, causal=causal, block_q=block_q, block_k=block_k, interpret=interpret)
