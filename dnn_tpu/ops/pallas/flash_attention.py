"""Flash attention as a Pallas TPU kernel.

The hot op of the GPT family (SURVEY.md §5 "long-context"). Online-softmax
blockwise attention: never materializes the (T, T) score matrix in HBM —
scores live in VMEM one (block_q, block_k) tile at a time, with running
row-max / row-sum rescaling (the flash-attention recurrence).

Grid: (batch*heads, q_blocks, k_blocks); the k dimension is sequential
("arbitrary") so the f32 accumulator scratch persists across k steps, while
batch/head/q blocks parallelize. Causal masking skips fully-masked k blocks
outright (upper triangle), so causal costs ~half the FLOPs of full.

Falls back to the jnp reference implementation (numerically identical math)
when not running on TPU, when shapes don't tile, or when the sequence is too
short to be worth a kernel launch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_BIG = -1e30  # finite "minus infinity": keeps exp()/max() NaN-free


from dnn_tpu.ops.pallas._compat import _compiler_params  # noqa: E402


# ----------------------------------------------------------------------
# reference path (also the off-TPU fallback and the test oracle)
# ----------------------------------------------------------------------

def reference_attention(q, k, v, *, causal=True):
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / jnp.sqrt(d)
    if causal:
        t, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t, sk), dtype=bool), k=sk - t)
        s = jnp.where(mask, s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", p.astype(v.dtype), v)


# ----------------------------------------------------------------------
# pallas kernel
# ----------------------------------------------------------------------

def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, causal, scale, block_q, block_k, offset
):
    """`offset = S - T` aligns the causal mask bottom-right (query t attends
    to keys <= t + offset), matching reference_attention's tril(k=S-T) —
    the KV-cache decode convention when S > T."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_BIG)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal: k block is dead iff its first col exceeds the max valid col of
    # this q block's last row (qi*bq + bq - 1 + offset).
    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1 + offset)

    @pl.when(live)
    def _step():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # (block_q, block_k)

        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + qi * block_q
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows + offset >= cols, s, _NEG_BIG)

        m_prev = m_scr[:, :1]  # (block_q, 1) row stats, lane-broadcast storage
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_scr[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _finish():
        o_ref[0] = (acc_scr[...] / l_scr[:, :1]).astype(o_ref.dtype)


def _fwd_lse_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                    *, causal, scale, block_q, block_k, offset):
    """Forward that additionally writes the per-row logsumexp (lane-broadcast
    to 128, the TPU row-stat storage convention — see the lse residual note
    in _flash_tpu_fwd). Shares the step math with _flash_kernel via
    delegation so the two can never drift."""
    from jax.experimental import pallas as pl

    _flash_kernel(
        q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
        causal=causal, scale=scale, block_q=block_q, block_k=block_k,
        offset=offset,
    )

    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == nk - 1)
    def _save_lse():
        lse_ref[0] = m_scr[...] + jnp.log(l_scr[...])


def _recompute_pds(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qi, ki,
                   *, causal, scale, block_q, block_k, offset):
    """Shared backward-step recompute (single source — the dq and dkv
    kernels must apply identical masking/scaling or dQ silently disagrees
    with dK/dV): rebuild the normalized probabilities P from the saved
    logsumexp, then dS = P * (dP - D). Returns (q, k, do, p, ds) in f32."""
    q = q_ref[0].astype(jnp.float32)    # (block_q, d)
    k = k_ref[0].astype(jnp.float32)    # (block_k, d)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)  # (block_q, d)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + qi * block_q
        cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + ki * block_k
        s = jnp.where(rows + offset >= cols, s, _NEG_BIG)
    p = jnp.exp(s - lse_ref[0][:, :1])  # normalized probs (block_q, block_k)
    dp = jax.lax.dot_general(            # dO V^T
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    ds = p * (dp - di_ref[0][:, :1])
    return q, k, do, p, ds


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, dq_ref,
                   acc_scr, *, causal, scale, block_q, block_k, offset):
    """dQ for one q block, accumulated over the (sequential) k-block grid
    axis: dQ = scale * dS K."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1 + offset)

    @pl.when(live)
    def _step():
        _, k, _, _, ds = _recompute_pds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qi, ki,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            offset=offset,
        )
        acc_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = (acc_scr[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, causal, scale, block_q, block_k, offset):
    """dK and dV for one k block, accumulated over the (sequential) q-block
    grid axis: dV = P^T dO, dK = scale * dS^T Q."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    live = (not causal) or (ki * block_k <= qi * block_q + block_q - 1 + offset)

    @pl.when(live)
    def _step():
        q, _, do, p, ds = _recompute_pds(
            q_ref, k_ref, v_ref, do_ref, lse_ref, di_ref, qi, ki,
            causal=causal, scale=scale, block_q=block_q, block_k=block_k,
            offset=offset,
        )
        dv_scr[...] += jax.lax.dot_general(          # P^T dO  (block_k, d)
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dk_scr[...] += jax.lax.dot_general(          # dS^T Q  (block_k, d)
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _qspec(block_q, d):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0))


def _kspec(block_k, d):
    from jax.experimental import pallas as pl

    return pl.BlockSpec((1, block_k, d), lambda bh_, qi, ki: (bh_, ki, 0))


def _call_fwd(q3, k3, v3, *, causal, block_q, block_k, interpret, with_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, t, d = q3.shape
    s_len = k3.shape[1]
    nq, nk = t // block_q, s_len // block_k
    common = dict(causal=causal, scale=1.0 / (d ** 0.5), block_q=block_q,
                  block_k=block_k, offset=s_len - t)
    out_shape = [jax.ShapeDtypeStruct((bh, t, d), q3.dtype)]
    out_specs = [_qspec(block_q, d)]
    if with_lse:
        kernel = functools.partial(_fwd_lse_kernel, **common)
        out_shape.append(jax.ShapeDtypeStruct((bh, t, 128), jnp.float32))
        out_specs.append(_qspec(block_q, 128))
    else:
        kernel = functools.partial(_flash_kernel, **common)
    res = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[_qspec(block_q, d), _kspec(block_k, d), _kspec(block_k, d)],
        out_specs=out_specs if with_lse else out_specs[0],
        out_shape=out_shape if with_lse else out_shape[0],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running row sum
            pltpu.VMEM((block_q, d), jnp.float32),  # output accumulator
        ],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3)
    return res if with_lse else (res, None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_tpu(q, k, v, causal, block_q, block_k, interpret):
    """Pallas flash attention with a custom VJP: `jax.grad` through
    `use_flash=True` runs the recompute-based backward kernels below instead
    of failing (pallas_call has no autodiff rule). Inference-only calls take
    this primal path and never pay the logsumexp write."""
    b, h, t, d = q.shape
    bh = b * h
    out, _ = _call_fwd(
        q.reshape(bh, t, d), k.reshape(bh, k.shape[2], d),
        v.reshape(bh, v.shape[2], d),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        with_lse=False,
    )
    return out.reshape(b, h, t, d)


def _flash_tpu_fwd(q, k, v, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    bh = b * h
    out, lse = _call_fwd(
        q.reshape(bh, t, d), k.reshape(bh, k.shape[2], d),
        v.reshape(bh, v.shape[2], d),
        causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
        with_lse=True,
    )
    # lse residual is (bh, t, 128) lane-broadcast f32 — the TPU-native row
    # stat layout (row vectors must live along sublanes to broadcast against
    # (block_q, block_k) score tiles; a (bh, t) array would put them in
    # lanes and force an in-kernel transpose). 128 lanes of redundancy cost
    # 128*T*4B per head — noise next to the (T, T) scores flash avoids.
    return out.reshape(b, h, t, d), (q, k, v, out.reshape(b, h, t, d), lse)


def _flash_tpu_bwd(causal, block_q, block_k, interpret, residuals, do):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    q, k, v, o, lse = residuals
    b, h, t, d = q.shape
    s_len = k.shape[2]
    bh = b * h
    nq, nk = t // block_q, s_len // block_k

    # D_i = rowsum(dO * O): elementwise + reduce — jnp, not a kernel, and
    # stored lane-broadcast like lse.
    di = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    di = jnp.broadcast_to(di.reshape(bh, t, 1), (bh, t, 128))

    q3 = q.reshape(bh, t, d)
    k3 = k.reshape(bh, s_len, d)
    v3 = v.reshape(bh, s_len, d)
    do3 = do.reshape(bh, t, d).astype(q.dtype)

    common = dict(causal=causal, scale=1.0 / (d ** 0.5), block_q=block_q,
                  block_k=block_k, offset=s_len - t)
    row_specs = [_qspec(block_q, d), _kspec(block_k, d), _kspec(block_k, d),
                 _qspec(block_q, d), _qspec(block_q, 128), _qspec(block_q, 128)]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=row_specs,
        out_specs=_qspec(block_q, d),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, di)

    # dkv grid iterates k blocks in the parallel axis, q blocks sequentially;
    # index maps therefore swap roles: grid = (bh, ki, qi).
    def kblock(block, width):
        return pl.BlockSpec((1, block, width), lambda bh_, ki, qi: (bh_, ki, 0))

    def qblock(block, width):
        return pl.BlockSpec((1, block, width), lambda bh_, ki, qi: (bh_, qi, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[qblock(block_q, d), kblock(block_k, d), kblock(block_k, d),
                  qblock(block_q, d), qblock(block_q, 128), qblock(block_q, 128)],
        out_specs=[kblock(block_k, d), kblock(block_k, d)],
        out_shape=[jax.ShapeDtypeStruct((bh, s_len, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, s_len, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, dimension_semantics=("parallel", "parallel", "arbitrary")
        ),
        interpret=interpret,
    )(q3, k3, v3, do3, lse, di)

    return (dq.reshape(b, h, t, d), dk.reshape(b, h, s_len, d),
            dv.reshape(b, h, s_len, d))


_flash_tpu.defvjp(_flash_tpu_fwd, _flash_tpu_bwd)


def flash_attention(q, k, v, *, causal=True, block_q=128, block_k=128, interpret=None):
    """(B, H, T, D) scaled-dot-product attention. Dispatches to the Pallas
    TPU kernel when shapes tile cleanly on a TPU backend; otherwise runs the
    numerically-identical jnp reference (so `use_flash=True` is always safe —
    the review contract of dnn_tpu/ops/attention.py)."""
    t, s_len = q.shape[2], k.shape[2]
    if causal:
        block_k = block_q  # diagonal-block masking assumes square tiles
    on_tpu = jax.default_backend() == "tpu"
    if interpret is None:
        interpret = False
        if not on_tpu:
            return reference_attention(q, k, v, causal=causal)
    tiles = t % block_q == 0 and s_len % block_k == 0 and t >= block_q and s_len >= block_k
    if not tiles or (causal and s_len < t):
        # s < t causal (queries before the first key) is a degenerate case
        # the kernel's masking doesn't model — use the reference path.
        return reference_attention(q, k, v, causal=causal)
    return _flash_tpu(q, k, v, causal, block_q, block_k, interpret)
