from dnn_tpu.ops.nn import (
    conv2d,
    max_pool2d,
    linear,
    relu,
    gelu,
    softmax,
    layer_norm,
    embedding,
)
from dnn_tpu.ops.attention import causal_self_attention

__all__ = [
    "conv2d",
    "max_pool2d",
    "linear",
    "relu",
    "gelu",
    "softmax",
    "layer_norm",
    "embedding",
    "causal_self_attention",
]
