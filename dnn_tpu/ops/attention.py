"""Multi-head causal self-attention.

The reference's GPT partitions delegate attention to a nanoGPT-style `Block`
imported from a `model.py` that is absent from its repo
(/root/reference/partitions/gpt_model_parts.py:4); this module re-authors
that math TPU-first:

  * one fused qkv projection (a single big MXU matmul),
  * attention computed per head via einsum (XLA maps these onto the MXU),
  * optional Pallas flash-attention kernel on TPU for long sequences
    (dnn_tpu/ops/pallas/flash_attention.py) with this jnp version as the
    numerically-identical fallback / ground truth.

Shapes: x is (B, T, C); params:
  {"qkv": {"kernel": (C, 3C), "bias": (3C,)},
   "proj": {"kernel": (C, C), "bias": (C,)}}
"""

from __future__ import annotations

import jax.numpy as jnp

from dnn_tpu.ops.nn import linear


def split_heads(x, n_head):
    b, t, c = x.shape
    return x.reshape(b, t, n_head, c // n_head).transpose(0, 2, 1, 3)  # (B, H, T, D)


def merge_heads(x):
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


# Measured on the v5e chip (bf16, gpt2-small shapes): XLA's fused attention
# beats the Pallas kernel up to S=2048 (ratios 0.66-0.73), flash wins from
# S=4096 (1.42x) where XLA's materialized (B,H,T,T) scores start thrashing
# HBM. "auto" switches on the flash kernel at this crossover.
FLASH_AUTO_THRESHOLD = 4096


def causal_self_attention(params, x, *, n_head, use_flash=False, compute_dtype=None):
    """Full causal MHA: fused qkv matmul -> per-head attention -> out proj.

    `use_flash`: True routes the inner attention through the Pallas TPU
    kernel (falls back to the jnp path off-TPU or for tiny shapes); False
    uses the XLA einsum path; "auto" picks flash when the sequence length
    reaches FLASH_AUTO_THRESHOLD (the measured crossover — see above).
    `compute_dtype` (e.g. bf16) casts the matmul operands for the MXU.
    """
    qkv = linear(params["qkv"], x, compute_dtype=compute_dtype)  # (B, T, 3C)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = (split_heads(t, n_head) for t in (q, k, v))

    if use_flash == "auto":
        use_flash = x.shape[-2] >= FLASH_AUTO_THRESHOLD  # static under jit

    # Single source of truth for the attention math: the flash kernel and
    # its jnp reference live in one module, so both paths share numerics.
    from dnn_tpu.ops.pallas.flash_attention import flash_attention, reference_attention

    if use_flash:
        y = flash_attention(q, k, v, causal=True)
    else:
        y = reference_attention(q, k, v, causal=True)

    y = merge_heads(y)
    return linear(params["proj"], y, compute_dtype=compute_dtype)


def rope_cos_sin(positions, head_dim, *, theta=10000.0):
    """cos/sin tables for rotary position embedding at absolute
    `positions` (any shape P...), HF half-split convention: frequencies
    1/theta^(2i/d) over the first half of the head dim, tables tiled to
    the full dim. Returns (cos, sin) of shape (*P, head_dim), f32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # (*P, d/2)
    emb = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(x, cos, sin):
    """Rotate head vectors x (..., T, D) by per-position tables
    (T, D) — torch rotate_half convention: the two halves of the head dim
    form the rotation pairs (NOT interleaved even/odd lanes; matching HF
    weights requires matching this layout)."""
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x.astype(jnp.float32) * cos + rotated.astype(jnp.float32) * sin
            ).astype(x.dtype)
