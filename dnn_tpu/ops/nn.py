"""Core neural-net ops as pure functions over parameter pytrees.

TPU-first conventions:
  * Activations are NHWC and weights HWIO — the layouts XLA tiles best onto
    the TPU MXU (the reference is NCHW PyTorch; see
    /root/reference/cifar_model_parts.py:10-26 for the ops this module must
    be able to express).
  * Everything is a pure function of (params, x): jit/vmap/shard_map safe,
    no module objects, no Python-side state.
  * Matmul-bearing ops accept a `compute_dtype` so models can run bf16 on
    the MXU while keeping f32 params.

Parameter pytrees are plain dicts:
  conv2d:    {"kernel": (kh, kw, in_ch, out_ch), "bias": (out_ch,)}
  linear:    {"kernel": (in_features, out_features), "bias": (out_features,)}
  layer_norm:{"scale": (dim,), "bias": (dim,)}
  embedding: {"embedding": (vocab, dim)}
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(params, x, *, stride=(1, 1), padding="SAME", compute_dtype=None):
    """2-D convolution, NHWC activations / HWIO kernel.

    Equivalent capability to torch nn.Conv2d as used by the reference CNN
    (/root/reference/cifar_model_parts.py:9,11 — k3 s1 p1 == SAME).
    """
    kernel = params["kernel"]
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        kernel = kernel.astype(compute_dtype)
    out = lax.conv_general_dilated(
        x,
        kernel,
        window_strides=stride,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    bias = params.get("bias")
    if bias is not None:
        out = out + bias.astype(out.dtype)
    return out


def max_pool2d(x, *, window=(2, 2), stride=(2, 2)):
    """Max pooling over spatial dims of an NHWC tensor.

    Reference: torch nn.MaxPool2d(kernel_size=2, stride=2, padding=0)
    (/root/reference/cifar_model_parts.py:10).
    """
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, *window, 1),
        window_strides=(1, *stride, 1),
        padding="VALID",
    )


def linear(params, x, *, compute_dtype=None, accum_dtype=None):
    """Dense layer: x @ kernel + bias. kernel is (in, out) — already the
    layout XLA wants for an MXU matmul (torch stores (out, in); the
    checkpoint converter transposes — see dnn_tpu/io/checkpoint.py).

    `compute_dtype` casts the matmul operands (e.g. bf16 for the MXU) and
    casts the result back to the input dtype. `accum_dtype` instead keeps
    the accumulator dtype as the output (`preferred_element_type`) — e.g.
    compute_dtype=bf16 + accum_dtype=f32 reads bf16 operands but returns
    f32, the idiom for a logits head.

    Also accepts int8 weight-only-quantized params ({"q", "scale"} instead
    of {"kernel"} — see dnn_tpu/quant.py). Every matmul path in the
    framework (block forward, KV-cache decode, serving, pipeline stages)
    funnels through this function, so quantized checkpoints work
    everywhere without per-path plumbing.

    A `"lora"` entry ({a, b, sel} — built by lora.lora_view for
    per-request multi-adapter serving) adds the selected low-rank delta
    on top of whichever base path ran — float or quantized (the
    QLoRA-style combination: int8 base weights + per-slot float
    adapters).

    Reference: torch nn.Linear (/root/reference/cifar_model_parts.py:12-13).
    """
    lora = params.get("lora")
    if "q" in params:
        base = (_linear_int4 if params["q"].dtype == jnp.int4
                else _linear_int8)
        out = base(params, x, compute_dtype=compute_dtype,
                   accum_dtype=accum_dtype)
        if lora is not None:
            out = out + _lora_delta(lora, x, compute_dtype).astype(out.dtype)
        return out
    kernel = params["kernel"]
    orig_dtype = x.dtype
    if compute_dtype is not None:
        x = x.astype(compute_dtype)
        kernel = kernel.astype(compute_dtype)
    if accum_dtype is not None:
        out = lax.dot_general(
            x, kernel,
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=accum_dtype,
        )
    else:
        out = x @ kernel
    bias = params.get("bias")
    if bias is not None:
        out = out + bias.astype(out.dtype)
    if lora is not None:
        out = out + _lora_delta(lora, x, compute_dtype).astype(out.dtype)
    if accum_dtype is None and compute_dtype is not None:
        out = out.astype(orig_dtype)
    return out


def _lora_delta(lora, x, compute_dtype):
    """Per-slot low-rank delta for multi-adapter serving (see
    lora.lora_view): x (B, T, C) against adapter stacks a (N, C, r) /
    b (N, r, O), selected per batch row by the one-hot sel (B, N).

    Computed for ALL N adapters then masked by sel — N x the (tiny)
    rank-r flops, but no gather of weight-sized operands and no dynamic
    shapes: the TPU-friendly trade at serving-realistic N. The one-hot
    contraction folds into each einsum, so what actually runs is two
    batched rank-r matmuls."""
    a, b, sel = lora["a"], lora["b"], lora["sel"]
    dt = compute_dtype if compute_dtype is not None else x.dtype
    sel = sel.astype(dt)
    xa = jnp.einsum("btc,ncr,bn->btr", x.astype(dt), a.astype(dt), sel)
    return jnp.einsum("btr,nro,bn->bto", xa, b.astype(dt), sel)


def _linear_int8(params, x, *, compute_dtype=None, accum_dtype=None):
    """Weight-only int8 dense layer: out = (x @ q) * scale + bias.

    `q` is the int8 kernel, `scale` the per-output-channel dequant factor
    (dnn_tpu/quant.py). The int8->compute_dtype convert fuses into the
    dot's operand read, so the kernel's HBM traffic is 1 byte/weight —
    the win this exists for: decode steps are weight-bandwidth-bound, so
    int8 weights roughly double decode throughput at large model sizes.
    Per-channel scales commute with the contraction, so scaling the
    *output* columns is exact (not an approximation of scaling weights).
    """
    q = params["q"]
    orig_dtype = x.dtype
    cd = compute_dtype if compute_dtype is not None else x.dtype
    acc = accum_dtype if accum_dtype is not None else cd
    out = lax.dot_general(
        x.astype(cd), q.astype(cd),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=acc,
    )
    # scale is (..., 1, out); drop the kept contraction axis for broadcast
    out = out * params["scale"][..., 0, :].astype(acc)
    bias = params.get("bias")
    if bias is not None:
        out = out + bias.astype(out.dtype)
    if accum_dtype is None and compute_dtype is not None:
        out = out.astype(orig_dtype)
    return out


def _linear_int4(params, x, *, compute_dtype=None, accum_dtype=None):
    """Weight-only GROUP-WISE int4 dense layer (dnn_tpu/quant.py
    quantize_tensor_int4): q (in, out) native jnp.int4, scale
    (in/group, out) f32. Group scales do not commute with the full
    contraction, so the dot runs batched per group —
    out = sum_G (x_G @ q_G) * scale_G — which XLA lowers to one batched
    MXU matmul plus an epilogue multiply-and-reduce on the (small)
    per-group outputs; the s4->compute convert fuses into the operand
    read, so kernel HBM traffic is 0.5 bytes/weight. Stacked (L, ...)
    trees arrive here already layer-sliced by the blocks scan, exactly
    like the int8 path."""
    q, scale = params["q"], params["scale"]
    orig_dtype = x.dtype
    cd = compute_dtype if compute_dtype is not None else x.dtype
    acc = accum_dtype if accum_dtype is not None else cd
    in_dim, out_dim = q.shape[-2], q.shape[-1]
    g_count = scale.shape[-2]
    gsz = in_dim // g_count
    qg = q.reshape(g_count, gsz, out_dim)
    xg = x.reshape(*x.shape[:-1], g_count, gsz)
    out = jnp.einsum("...gi,gio->...go", xg.astype(cd), qg.astype(cd),
                     preferred_element_type=acc)
    out = (out * scale.astype(acc)).sum(axis=-2)
    bias = params.get("bias")
    if bias is not None:
        out = out + bias.astype(out.dtype)
    if accum_dtype is None and compute_dtype is not None:
        out = out.astype(orig_dtype)
    return out


def relu(x):
    return jax.nn.relu(x)


def gelu(x):
    """tanh-approximate GELU (the GPT-2 nonlinearity)."""
    return jax.nn.gelu(x, approximate=True)


def softmax(x, axis=-1):
    """Reference: torch nn.Softmax(dim=1) on (B, 10) logits
    (/root/reference/cifar_model_parts.py:15,25)."""
    return jax.nn.softmax(x, axis=axis)


def layer_norm(params, x, *, eps=1e-5):
    """LayerNorm over the last dim (torch nn.LayerNorm semantics, biased
    variance, as in GPT-2)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    y = y * params["scale"].astype(jnp.float32) + params["bias"].astype(jnp.float32)
    return y.astype(x.dtype)


def embedding(params, ids):
    """Token/position embedding lookup.

    Reference: torch nn.Embedding via wte/wpe
    (/root/reference/partitions/gpt_model_parts.py:9-10,16-18).
    """
    return jnp.take(params["embedding"], ids, axis=0)


def silu(x):
    """SiLU / swish (the LLaMA-family gate nonlinearity)."""
    return jax.nn.silu(x)


def rms_norm(params, x, *, eps=1e-6, plus_one=False):
    """RMSNorm over the last dim (LLaMA-family normalization: no mean
    subtraction, no bias — torch LlamaRMSNorm semantics, f32 statistics).

    `plus_one=True` scales by (1 + w) instead of w — the Gemma-family
    convention (torch GemmaRMSNorm), whose checkpoints store the scale
    as a zero-centered delta."""
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    scale = params["scale"].astype(jnp.float32)
    if plus_one:
        scale = 1.0 + scale
    return (y * scale).astype(x.dtype)
