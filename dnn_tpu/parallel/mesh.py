"""Device-mesh construction.

The reference's topology is a list of (node, address, part_index) entries
(config.json:3-14) with next-hop resolution by part_index+1
(node.py:262-271). The TPU-native equivalent: `part_index` becomes a
coordinate on the "stage" axis of a `jax.sharding.Mesh`, and the "hop" is
`lax.ppermute` over ICI instead of a gRPC call (BASELINE.json north star).

Axis conventions used across the framework:
  "data"   — data parallelism (batch sharding, gradient psum)
  "stage"  — pipeline parallelism (the reference's only axis)
  "model"  — tensor parallelism (Megatron-style head/mlp sharding)
  "seq"    — sequence/context parallelism (ring attention)
  "expert" — expert parallelism (MoE expert sharding, all_to_all dispatch)
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

STAGE_AXIS = "stage"
DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def make_mesh(axes: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh with named axes, e.g. {"data": 2, "stage": 2, "model": 2}.

    Axis order follows dict order; put the fastest-varying (most
    bandwidth-hungry, usually "model") axis last so it lands on the
    innermost/closest ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = list(axes.values())
    need = int(np.prod(sizes)) if sizes else 1
    if len(devices) < need:
        raise ValueError(
            f"mesh {axes} needs {need} devices, have {len(devices)} "
            f"({[str(d) for d in devices[:4]]}...)"
        )
    grid = np.array(devices[:need], dtype=object).reshape(sizes)
    return Mesh(grid, tuple(axes.keys()))


def mesh_from_config(config, devices: Optional[Sequence] = None) -> Mesh:
    """TopologyConfig -> Mesh. `num_parts` (the reference's stage count,
    config.json:16) sizes the "stage" axis; any extra axes come from the
    extended `mesh` config key."""
    axes = dict(config.mesh) if config.mesh else {}
    axes.setdefault(STAGE_AXIS, config.num_parts)
    if axes[STAGE_AXIS] != config.num_parts:
        raise ValueError(
            f"config.mesh['stage']={axes[STAGE_AXIS]} conflicts with "
            f"num_parts={config.num_parts}"
        )
    return make_mesh(axes, devices)
