"""Ring attention: sequence/context parallelism over a "seq" mesh axis.

The reference caps sequence length with a hard assert
(`T <= config.block_size`, /root/reference/partitions/gpt_model_parts.py:15)
and has no attention sharding of any kind (SURVEY §5 'Long-context:
ABSENT'). This module supplies the long-context capability the rebuild
treats as first-class: Q, K, V are sharded along the sequence dimension
across the mesh's "seq" axis; each device computes attention of its local
queries against one K/V block at a time while the K/V blocks travel the
ring via `lax.ppermute` (one ICI hop per step), accumulating with the
online-softmax recurrence — so the full (T, T) score matrix never exists
anywhere, and per-device memory is O(T/n).

Causality is resolved block-wise from ring positions: a K/V block that
originated at a later shard is fully masked, the diagonal block gets the
triangular mask, earlier blocks attend fully. All devices run the same
program (SPMD); dead blocks cost one masked matmul rather than a branch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dnn_tpu.parallel.mesh import SEQ_AXIS

_NEG_BIG = -1e30  # finite -inf, matches dnn_tpu/ops/pallas/flash_attention.py


def _block_attend(q, k, v, m, l, acc, mask):
    """One online-softmax accumulation step against a K/V block.
    q (B,H,Tq,D); k,v (B,H,Tk,D); m,l (B,H,Tq,1); acc (B,H,Tq,D);
    mask (Tq,Tk) bool (True = attend)."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / jnp.sqrt(d)
    s = jnp.where(mask[None, None], s, _NEG_BIG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhts,bhsd->bhtd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def ring_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS, causal: bool = True):
    """Per-device body (call inside shard_map). q/k/v are the local sequence
    shards, (B, H, T_local, D); returns the local output shard.

    GQA-aware: q's row dim may be G * T_local with k/v at T_local and KV
    heads (the group folded into rows — see llama._gqa_scores_attend);
    each group of rows then shares its position's causal mask, i.e. the
    triangular mask tiles G times down the rows. K/V rotate the ring at
    KV-head width — the narrow blocks are GQA's ICI-bandwidth win here,
    exactly as the narrow cache is its HBM win at decode."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_kv = k.shape[2]
    g = q.shape[2] // t_kv  # 1 for MHA; the folded group count for GQA
    if q.shape[2] != g * t_kv:
        raise ValueError(
            f"q rows {q.shape[2]} must be a multiple of K/V rows {t_kv}")
    qf = q.astype(jnp.float32)

    tri = jnp.tile(jnp.tril(jnp.ones((t_kv, t_kv), dtype=bool)), (g, 1))
    full = jnp.ones((g * t_kv, t_kv), dtype=bool)

    def _mask_for(i):
        # this K/V block originated at shard (my - i) mod n
        src = (my - i) % n
        if not causal:
            return full
        # src == my: diagonal (triangular); src < my: past (full);
        # src > my: future (dead). Select via where on the mask.
        mask = jnp.where(src == my, tri, full)
        return jnp.logical_and(mask, (src <= my)[..., None, None])

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = _block_attend(qf, k_cur, v_cur, m, l, acc, _mask_for(i))
        # rotate K/V one hop around the ring: shard j's block moves to j+1.
        # Rotation comes AFTER the attend so XLA can overlap the transfer
        # with the matmuls (the attend does not depend on the permute).
        k_nxt = lax.ppermute(k_cur, axis_name, [(j, (j + 1) % n) for j in range(n)])
        v_nxt = lax.ppermute(v_cur, axis_name, [(j, (j + 1) % n) for j in range(n)])
        return (k_nxt, v_nxt, m, l, acc), None

    b, h, t_q, d = q.shape
    init = (
        k, v,
        jnp.full((b, h, t_q, 1), _NEG_BIG, jnp.float32),
        jnp.zeros((b, h, t_q, 1), jnp.float32),
        jnp.zeros((b, h, t_q, d), jnp.float32),
    )
    # scan the first n-1 blocks (each followed by a rotation), then attend
    # the final block outside the loop — its rotation would be dead weight
    # (one wasted ICI hop per K and V per call, and per backward).
    (k_last, v_last, m, l, acc), _ = lax.scan(step, init, jnp.arange(n - 1))
    m, l, acc = _block_attend(qf, k_last, v_last, m, l, acc, _mask_for(n - 1))
    # fully-masked rows (none exist for causal self-attention since the
    # diagonal block always contributes) would have l == 0; guard anyway.
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, *, mesh: Mesh, axis_name: str = SEQ_AXIS, causal: bool = True):
    """Sharded entry: q/k/v are global (B, H, T, D) arrays; T is split over
    `axis_name`. Output is the full attention result, identical (up to
    float error) to dnn_tpu.ops.pallas.flash_attention.reference_attention."""
    n = mesh.shape[axis_name]
    if q.shape[2] % n != 0:
        raise ValueError(f"sequence length {q.shape[2]} not divisible by ring size {n}")
    body = functools.partial(ring_attention_local, axis_name=axis_name, causal=causal)
    spec = P(None, None, axis_name, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
