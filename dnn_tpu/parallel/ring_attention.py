"""Ring attention: sequence/context parallelism over a "seq" mesh axis.

The reference caps sequence length with a hard assert
(`T <= config.block_size`, /root/reference/partitions/gpt_model_parts.py:15)
and has no attention sharding of any kind (SURVEY §5 'Long-context:
ABSENT'). This module supplies the long-context capability the rebuild
treats as first-class: Q, K, V are sharded along the sequence dimension
across the mesh's "seq" axis; each device computes attention of its local
queries against one K/V block at a time while the K/V blocks travel the
ring via `lax.ppermute` (one ICI hop per step), accumulating with the
online-softmax recurrence — so the full (T, T) score matrix never exists
anywhere, and per-device memory is O(T/n).

Causality is resolved block-wise from ring positions: a K/V block that
originated at a later shard is fully masked, the diagonal block gets the
triangular mask, earlier blocks attend fully. All devices run the same
program (SPMD); dead blocks cost one masked matmul rather than a branch.

Sliding windows (Mistral-class) ride a BANDED ring schedule: the mask
adds the lower bound `q_pos - k_pos < window`, and — because a block
more than ceil(window / T_local) hops old is out-of-window for EVERY
query on every shard — the ring stops after that many hops instead of
circulating all n blocks: compute AND ICI cost drop from O(T) to
O(window) per shard, the seq-parallel form of the rolling cache's
decode win.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dnn_tpu.parallel.mesh import SEQ_AXIS

_NEG_BIG = -1e30  # finite -inf, matches dnn_tpu/ops/pallas/flash_attention.py


def _block_attend(q, k, v, m, l, acc, mask):
    """One online-softmax accumulation step against a K/V block.
    q (B,H,Tq,D); k,v (B,H,Tk,D); m,l (B,H,Tq,1); acc (B,H,Tq,D);
    mask (Tq,Tk) bool (True = attend)."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) / jnp.sqrt(d)
    s = jnp.where(mask[None, None], s, _NEG_BIG)
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + p.sum(axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhts,bhsd->bhtd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def ring_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS,
                         causal: bool = True, window=None):
    """Per-device body (call inside shard_map). q/k/v are the local sequence
    shards, (B, H, T_local, D); returns the local output shard.

    GQA-aware: q's row dim may be G * T_local with k/v at T_local and KV
    heads (the group folded into rows — see llama._gqa_scores_attend);
    each group of rows then shares its position's causal mask, i.e. the
    masks tile G times down the rows. K/V rotate the ring at KV-head
    width — the narrow blocks are GQA's ICI-bandwidth win here, exactly
    as the narrow cache is its HBM win at decode.

    `window` (static int, causal only) adds the sliding-window lower
    bound AND shortens the ring to its live hops (module docstring —
    out-of-window blocks are never fetched)."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_kv = k.shape[2]
    g = q.shape[2] // t_kv  # 1 for MHA; the folded group count for GQA
    if q.shape[2] != g * t_kv:
        raise ValueError(
            f"q rows {q.shape[2]} must be a multiple of K/V rows {t_kv}")
    if window is not None:
        if not causal:
            raise ValueError("window= requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    qf = q.astype(jnp.float32)

    full = jnp.ones((g * t_kv, t_kv), dtype=bool)
    # absolute positions resolve every block-wise mask: local query row r
    # (group fold repeats positions every t_kv rows) sits at
    # my*t_kv + r%t_kv; block i's keys originated at shard (my-i) mod n.
    # Blocks from LATER shards come out fully masked by delta < 0 alone
    # (their positions all exceed the local queries') — no special case.
    q_pos = my * t_kv + (jnp.arange(g * t_kv) % t_kv)

    def _mask_for(i):
        src = (my - i) % n
        if not causal:
            return full
        k_pos = src * t_kv + jnp.arange(t_kv)
        delta = q_pos[:, None] - k_pos[None, :]  # (Gq rows, Tk)
        keep = delta >= 0
        if window is not None:
            keep = jnp.logical_and(keep, delta < window)
        return keep

    def step(carry, i):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = _block_attend(qf, k_cur, v_cur, m, l, acc, _mask_for(i))
        # rotate K/V one hop around the ring: shard j's block moves to j+1.
        # Rotation comes AFTER the attend so XLA can overlap the transfer
        # with the matmuls (the attend does not depend on the permute).
        k_nxt = lax.ppermute(k_cur, axis_name, [(j, (j + 1) % n) for j in range(n)])
        v_nxt = lax.ppermute(v_cur, axis_name, [(j, (j + 1) % n) for j in range(n)])
        return (k_nxt, v_nxt, m, l, acc), None

    # banded schedule: block i's MINIMUM query-key delta is
    # (i-1)*t_kv + 1 (newest local query vs the block's newest key), so
    # the block is fully out-of-window as soon as that reaches `window`
    # — live hops = ceil((window-1)/t_kv) + 1, capped at n (static
    # count: same program on all devices, just a shorter scan).
    n_live = n
    if window is not None and causal:
        n_live = min(n, -(-(window - 1) // t_kv) + 1)

    b, h, t_q, d = q.shape
    init = (
        k, v,
        jnp.full((b, h, t_q, 1), _NEG_BIG, jnp.float32),
        jnp.zeros((b, h, t_q, 1), jnp.float32),
        jnp.zeros((b, h, t_q, d), jnp.float32),
    )
    # scan the first n_live-1 blocks (each followed by a rotation), then
    # attend the final live block outside the loop — its rotation would
    # be dead weight (one wasted ICI hop per K and V per call, and per
    # backward).
    (k_last, v_last, m, l, acc), _ = lax.scan(step, init,
                                              jnp.arange(n_live - 1))
    m, l, acc = _block_attend(qf, k_last, v_last, m, l, acc,
                              _mask_for(n_live - 1))
    # fully-masked rows (none exist for causal self-attention since the
    # diagonal block always contributes) would have l == 0; guard anyway.
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, *, mesh: Mesh, axis_name: str = SEQ_AXIS,
                   causal: bool = True, window=None):
    """Sharded entry: q/k/v are global (B, H, T, D) arrays; T is split over
    `axis_name`. Output is the full attention result, identical (up to
    float error) to dnn_tpu.ops.pallas.flash_attention.reference_attention
    (band-masked when `window` is set — the banded ring schedule)."""
    n = mesh.shape[axis_name]
    if q.shape[2] % n != 0:
        raise ValueError(f"sequence length {q.shape[2]} not divisible by ring size {n}")
    body = functools.partial(ring_attention_local, axis_name=axis_name,
                             causal=causal, window=window)
    spec = P(None, None, axis_name, None)
    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)
