from dnn_tpu.parallel.mesh import make_mesh, mesh_from_config
from dnn_tpu.parallel.pipeline import (
    RelayExecutor,
    spmd_pipeline,
    split_microbatches,
    merge_microbatches,
)

__all__ = [
    "make_mesh",
    "mesh_from_config",
    "RelayExecutor",
    "spmd_pipeline",
    "split_microbatches",
    "merge_microbatches",
]
