"""Mixture-of-Experts FFN with expert parallelism (EP).

The reference has no MoE anywhere (SURVEY.md §2: "no MoE modules exist" —
verified absence), so this module is pure capability extension, designed
TPU-first rather than ported:

  * GShard-style top-k routing with STATIC capacity: dispatch/combine are
    dense one-hot tensors consumed by einsums — static shapes, no
    data-dependent control flow under jit, and the expert FFNs run as one
    batched (E, cap, D) x (E, D, F) matmul that tiles straight onto the
    MXU. Tokens beyond an expert's capacity are dropped (their combine
    weight is zero); callers keep a residual connection so dropped tokens
    pass through unchanged — the standard MoE contract.
  * Tokens are routed in GROUPS (the GShard "group" = the EP shard unit):
    capacity is per (group, expert), so the grouped dense path and the
    expert-parallel path compute IDENTICAL results — the parity invariant
    the tests pin down.
  * Expert parallelism: `moe_ffn_ep` runs under `shard_map` with groups
    sharded over the "expert" mesh axis and expert weights sharded on
    their leading E axis. Tokens travel to their experts and back via
    `jax.lax.all_to_all` (XLA AllToAll over ICI) — the TPU-native
    equivalent of the dispatch the reference would have done with gRPC
    sends, and the 4th collective family the framework uses (ppermute /
    psum / all_gather already ride the pipeline, dp×tp, and ring paths).

Routing is computed in f32 regardless of compute dtype (router logits are
tiny and routing decisions must not flip with the activation dtype).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dnn_tpu.ops.nn import gelu
from dnn_tpu.parallel.mesh import EXPERT_AXIS


def moe_capacity(tokens_per_group: int, n_experts: int, top_k: int,
                 capacity_factor: float) -> int:
    """Static per-(group, expert) slot count: the expected k*S/E load times
    the capacity factor, floored at 1."""
    return max(1, int(math.ceil(top_k * tokens_per_group * capacity_factor / n_experts)))


def init_moe(rng, n_embd: int, n_experts: int, d_ff: Optional[int] = None,
             dtype=jnp.float32):
    """Param pytree for one MoE FFN layer.

    Expert weights are EXPERT-MAJOR stacked arrays — (E, D, F) / (E, F, D) —
    so EP shards them with a plain P("expert") on the leading axis and the
    dense path consumes them as one batched matmul."""
    d_ff = 4 * n_embd if d_ff is None else d_ff
    kr, k1, k2 = jax.random.split(rng, 3)
    scale_in = 1.0 / math.sqrt(n_embd)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": {"kernel": jax.random.normal(kr, (n_embd, n_experts), dtype) * scale_in},
        "wi": jax.random.normal(k1, (n_experts, n_embd, d_ff), dtype) * scale_in,
        "bi": jnp.zeros((n_experts, d_ff), dtype),
        "wo": jax.random.normal(k2, (n_experts, d_ff, n_embd), dtype) * scale_out,
        "bo": jnp.zeros((n_experts, n_embd), dtype),
    }


def route_topk(gate_logits, *, top_k: int, capacity: int, normalize: bool = True):
    """One group's routing: (S, E) f32 gate logits -> dispatch/combine.

    Returns:
      dispatch: (S, E, cap) 0/1 — token s occupies slot c of expert e;
      combine:  (S, E, cap) f32 — dispatch weighted by the (optionally
                renormalized) router probability;
      aux: dict with "load" (E,) fraction of tokens per expert and
           "importance" (E,) mean router prob — the load-balance loss
           ingredients (Shazeer et al.'s aux loss; see load_balance_loss).

    Selection is iterative argmax (k rounds); slot positions are the
    running per-expert count in token order, so results are deterministic
    and order-stable. Tokens whose slot index >= capacity are dropped from
    that expert (combine weight 0)."""
    s, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)  # (S, E)

    remaining = probs
    counts = jnp.zeros((e,), jnp.int32)
    dispatch = jnp.zeros((s, e, capacity), jnp.float32)
    weight_sum = jnp.zeros((s, 1), jnp.float32)
    picked = []
    for _ in range(top_k):
        sel = jax.nn.one_hot(jnp.argmax(remaining, axis=-1), e, dtype=jnp.float32)
        remaining = remaining * (1.0 - sel)
        # slot index: tokens before me this round + slots used by earlier rounds
        pos = (jnp.cumsum(sel, axis=0) - sel) + counts[None, :].astype(jnp.float32)
        keep = (pos < capacity) * sel  # (S, E)
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        dispatch = dispatch + keep[..., None] * slot
        w = (probs * keep).sum(axis=-1, keepdims=True)  # this round's weight
        weight_sum = weight_sum + w
        picked.append((keep, probs * keep))
        counts = counts + sel.sum(axis=0).astype(jnp.int32)

    combine = jnp.zeros_like(dispatch)
    denom = jnp.maximum(weight_sum, 1e-9) if normalize else 1.0
    for keep, w in picked:
        slot_w = (w / denom if normalize else w).sum(axis=-1)  # (S,)
        combine = combine + dispatch * (keep * slot_w[:, None])[..., None]

    aux = {
        # realized fraction of SELECTIONS per expert (normalized by k*S, so
        # it sums to <= 1 and uniform routing gives exactly 1/E per expert
        # for any top_k — the convention load_balance_loss assumes)
        "load": dispatch.sum(axis=(0, 2)) / (s * top_k),
        "importance": probs.mean(axis=0),               # mean router prob per expert
    }
    return dispatch, combine, aux


def load_balance_loss(aux) -> jax.Array:
    """Switch-Transformer load-balance term: E * <load, importance>, with
    `load` the per-expert fraction of selections (normalized by k — see
    route_topk's aux). Equals 1.0 under perfectly uniform routing for any
    top_k; add `alpha * (loss - 1.0)` (alpha ~1e-2) to the training
    objective to keep experts busy."""
    e = aux["load"].shape[-1]
    return e * jnp.sum(aux["load"] * aux["importance"], axis=-1).mean()


def init_moe_gated(rng, n_embd: int, n_experts: int, d_ff: int,
                   dtype=jnp.float32):
    """Param pytree for a GATED (SwiGLU) MoE FFN layer — the Mixtral
    expert shape: per-expert gate/up/down projections, no biases.
    Expert-major stacking exactly as init_moe (EP shards the leading
    axis; the dense path batches over it)."""
    kr, kg, ku, kd = jax.random.split(rng, 4)
    scale_in = 1.0 / math.sqrt(n_embd)
    scale_out = 1.0 / math.sqrt(d_ff)
    return {
        "router": {"kernel": jax.random.normal(
            kr, (n_embd, n_experts), dtype) * scale_in},
        "wg": jax.random.normal(kg, (n_experts, n_embd, d_ff), dtype) * scale_in,
        "wu": jax.random.normal(ku, (n_experts, n_embd, d_ff), dtype) * scale_in,
        "wd": jax.random.normal(kd, (n_experts, d_ff, n_embd), dtype) * scale_out,
    }


def _expert_ffn_gated(params, expert_in, *, compute_dtype):
    """(E, cap, D) tokens through each expert's SwiGLU —
    silu(x@wg) * (x@wu) @ wd, one batched matmul triple (the Mixtral
    expert). Same dtype recipe as _expert_ffn: f32 accumulation,
    operands in compute_dtype.

    Accepts int8 weight-only-quantized stacks (quant.quantize_tree):
    per-(expert, out-channel) `*_scale` factors fold as exact epilogue
    multiplies on the f32 accumulators; the int8->compute convert fuses
    into the einsum operand read — 1 byte/weight of expert HBM traffic,
    the bandwidth win MoE decode exists for."""
    wg, wu, wd = params["wg"], params["wu"], params["wd"]
    sg, su, sd = (params.get(k) for k in ("wg_scale", "wu_scale",
                                          "wd_scale"))
    x = expert_in
    cd = compute_dtype if compute_dtype is not None else (
        jnp.float32 if wg.dtype == jnp.int8 else None)
    if cd is not None:
        x = x.astype(cd)
        wg, wu, wd = (w.astype(cd) for w in (wg, wu, wd))
    g = jnp.einsum("ecd,edf->ecf", x, wg,
                   preferred_element_type=jnp.float32)
    u = jnp.einsum("ecd,edf->ecf", x, wu,
                   preferred_element_type=jnp.float32)
    if sg is not None:
        g = g * sg  # (E, 1, F) broadcasts over capacity
    if su is not None:
        u = u * su
    h = jax.nn.silu(g) * u
    if cd is not None:
        h = h.astype(cd)
    out = jnp.einsum("ecf,efd->ecd", h, wd,
                     preferred_element_type=jnp.float32)  # f32
    if sd is not None:
        out = out * sd
    return out


def _expert_ffn(params, expert_in, *, activation, compute_dtype):
    """(E, cap, D) tokens through each expert's 2-layer FFN, one batched
    matmul pair — or, when the params carry the gated stack ("wg"), the
    SwiGLU expert (_expert_ffn_gated; `activation` is then unused).
    Accumulate in f32, ride operands in compute_dtype.

    Accepts int8 weight-only-quantized expert stacks (dnn_tpu/quant.py):
    `wi`/`wo` as int8 with per-(expert, out-channel) `wi_scale`/`wo_scale`
    (E, 1, out). Per-channel scales commute with the contraction, so the
    dequant is an exact epilogue on the f32 accumulator; the int8->
    compute_dtype convert fuses into the einsum's operand read, keeping
    the experts' HBM traffic at 1 byte/weight — MoE decode is the most
    weight-bandwidth-bound path in the framework (E experts' weights
    stream for one token's worth of FLOPs)."""
    if "wg" in params:
        return _expert_ffn_gated(params, expert_in,
                                 compute_dtype=compute_dtype)
    wi, bi, wo, bo = params["wi"], params["bi"], params["wo"], params["bo"]
    wi_scale, wo_scale = params.get("wi_scale"), params.get("wo_scale")
    x = expert_in
    if compute_dtype is not None:
        x, wi, wo = x.astype(compute_dtype), wi.astype(compute_dtype), wo.astype(compute_dtype)
    h = jnp.einsum("ecd,edf->ecf", x, wi,
                   preferred_element_type=jnp.float32)
    if wi_scale is not None:
        h = h * wi_scale  # (E, 1, ff) broadcasts over capacity
    h = activation(h + bi[:, None, :].astype(jnp.float32))
    if compute_dtype is not None:
        h = h.astype(compute_dtype)
    out = jnp.einsum("ecf,efd->ecd", h, wo,
                     preferred_element_type=jnp.float32)
    if wo_scale is not None:
        out = out * wo_scale
    return out + bo[:, None, :].astype(jnp.float32)  # f32


def _group_dispatch(params, xg, *, top_k, capacity, normalize):
    """Routing for one (S, D) group -> dispatch/combine/aux (f32)."""
    logits = xg.astype(jnp.float32) @ params["router"]["kernel"].astype(jnp.float32)
    return route_topk(logits, top_k=top_k, capacity=capacity, normalize=normalize)


def moe_ffn(params, x, *, top_k: int = 2, capacity_factor: float = 1.25,
            groups: int = 1, activation=gelu, compute_dtype=None,
            return_aux: bool = False, normalize: bool = True):
    """Dense (single-program) MoE FFN: (B, T, D) -> (B, T, D).

    Tokens are routed in `groups` independent groups (B*T must divide by
    groups); with groups == n_devices this computes exactly what
    `moe_ffn_ep` computes on an n-device mesh — the parity contract.
    Output does NOT include the residual; callers add it (dropped tokens
    then degrade to identity, the standard MoE fallback)."""
    b, t, d = x.shape
    n_tok = b * t
    if n_tok % groups:
        raise ValueError(f"B*T={n_tok} not divisible by groups={groups}")
    s = n_tok // groups
    e = params["wg" if "wg" in params else "wi"].shape[0]
    capacity = moe_capacity(s, e, top_k, capacity_factor)

    xg = x.reshape(groups, s, d)
    # normalize=False (Qwen2-MoE norm_topk_prob) keeps the RAW softmax
    # probabilities as combine weights instead of renormalizing the
    # selected top-k (Mixtral's convention)
    dispatch, combine, aux = jax.vmap(
        lambda g: _group_dispatch(params, g, top_k=top_k, capacity=capacity,
                                  normalize=normalize)
    )(xg)

    expert_in = jnp.einsum("gsec,gsd->gecd", dispatch,
                           xg.astype(jnp.float32))  # (G, E, cap, D)
    out = jax.vmap(
        lambda ein: _expert_ffn(params, ein, activation=activation,
                                compute_dtype=compute_dtype)
    )(expert_in)  # (G, E, cap, D) f32
    y = jnp.einsum("gsec,gecd->gsd", combine, out).reshape(b, t, d).astype(x.dtype)
    if return_aux:
        return y, {k: v.mean(axis=0) for k, v in aux.items()}
    return y


def moe_ffn_local(params_local, xg, *, top_k, capacity, axis_name,
                  activation=gelu, compute_dtype=None,
                  normalize: bool = True):
    """Per-device EP body (call inside shard_map): this device's group
    (S, D) + its shard of the experts -> (S, D).

    The two `all_to_all`s are the expert dispatch fabric: tokens leave for
    the device that owns their expert and come back combined — XLA
    AllToAll over ICI, replacing the reference's per-hop gRPC sends."""
    dispatch, combine, _aux = _group_dispatch(
        # router weights are replicated; only expert weights are sharded
        params_local, xg, top_k=top_k, capacity=capacity,
        normalize=normalize,
    )
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, xg.astype(jnp.float32))
    if compute_dtype is not None:
        # round BEFORE the hop: _expert_ffn casts to compute_dtype anyway,
        # and rounding commutes with the permutation, so this halves the
        # dispatch collective's ICI bytes with bit-identical output vs the
        # dense path (which rounds the same values device-locally)
        expert_in = expert_in.astype(compute_dtype)
    # (E, cap, D) -> (E/n, n*cap, D): send each expert-block to its owner,
    # gather every device's tokens for my experts
    expert_in = jax.lax.all_to_all(
        expert_in, axis_name, split_axis=0, concat_axis=1, tiled=True
    )
    out = _expert_ffn(params_local, expert_in, activation=activation,
                      compute_dtype=compute_dtype)
    # inverse exchange: (E/n, n*cap, D) -> (E, cap, D)
    out = jax.lax.all_to_all(
        out, axis_name, split_axis=1, concat_axis=0, tiled=True
    )
    y = jnp.einsum("sec,ecd->sd", combine, out)
    return y.astype(xg.dtype)


def make_moe_ffn_ep(mesh: Mesh, *, top_k: int = 2, capacity_factor: float = 1.25,
                    axis_name: str = EXPERT_AXIS, activation=gelu,
                    compute_dtype=None):
    """Expert-parallel MoE FFN over `mesh`'s "expert" axis.

    apply(params, x): x (B, T, D) with B divisible by the axis size; the
    BATCH is sharded over the expert axis (each device's local batch is
    its routing group — dp and ep share the axis, the standard MoE mesh
    layout), expert weights shard P("expert") on their leading E axis,
    router/bias params replicate. Equals moe_ffn(groups=n) exactly."""
    n = mesh.shape[axis_name]

    def _param_specs(params):
        # every expert-stack leaf (wi/wo/biases and, when quantized, the
        # wi_scale/wo_scale factors) has leading dim E -> shard P(axis);
        # the router replicates (tokens route locally, pre-dispatch)
        return {
            k: ({"kernel": P()} if k == "router" else P(axis_name))
            for k in params
        }

    def apply(params, x):
        b, t, d = x.shape
        if b % n:
            raise ValueError(f"batch {b} not divisible by expert-axis size {n}")
        e = params["wg" if "wg" in params else "wi"].shape[0]
        if e % n:
            raise ValueError(f"{e} experts not divisible by expert-axis size {n}")
        s = (b // n) * t
        capacity = moe_capacity(s, e, top_k, capacity_factor)

        def local(params_local, x_local):
            bl = x_local.shape[0]
            xg = x_local.reshape(bl * t, d)
            y = moe_ffn_local(
                params_local, xg, top_k=top_k, capacity=capacity,
                axis_name=axis_name, activation=activation,
                compute_dtype=compute_dtype,
            )
            return y.reshape(bl, t, d)

        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(_param_specs(params), P(axis_name)),
            out_specs=P(axis_name),
            check_vma=False,
        )(params, x)

    return apply
