"""Ulysses-style (all-to-all) sequence parallelism for attention.

The second long-context strategy next to ring attention
(dnn_tpu/parallel/ring_attention.py), trading its n-step ppermute ring
for two all_to_all collectives:

  activations are SEQUENCE-sharded everywhere except inside attention.
  At the attention boundary an all_to_all re-shards Q/K/V from
  (B, H, T/n, D) to (B, H/n, T, D) — every device sees ALL positions for
  its subset of heads — so attention itself is the plain dense causal
  kernel with no masking gymnastics; a second all_to_all restores
  sequence sharding for the position-wise rest of the block.

When to pick which (the standard trade): Ulysses moves 2x the attention
activation bytes in two dense collectives and needs n_head % n == 0 but
keeps the (T, T) work in one local kernel (flash-friendly); the ring
keeps bytes-per-step minimal and head-count free but serializes K/V
rotation over n ppermute steps. Both produce bit-comparable results to
dense attention — parity is pinned in tests/test_ulysses.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dnn_tpu.parallel.mesh import SEQ_AXIS


def ulysses_attention_local(q, k, v, *, axis_name: str = SEQ_AXIS,
                            causal: bool = True):
    """Per-device attention body (call inside shard_map over the seq axis).

    q/k/v: (B, H, T_local, D) — this device's sequence shard, all heads.
    Returns (B, H, T_local, D). Requires H divisible by the axis size.
    """
    from dnn_tpu.ops.pallas.flash_attention import flash_attention

    n = lax.axis_size(axis_name)  # static inside shard_map
    h = q.shape[1]
    if h % n != 0:
        raise ValueError(f"n_head {h} not divisible by seq-axis size {n}")
    # seq-sharded -> head-sharded: split heads across devices, gather the
    # full sequence (chunks arrive in device order, so T stays contiguous).
    # One collective over the stacked qkv — same bytes as three, one launch.
    qkv = jnp.stack((q, k, v))  # (3, B, H, T_local, D)
    qkv = lax.all_to_all(qkv, axis_name, split_axis=2, concat_axis=3, tiled=True)
    q, k, v = qkv[0], qkv[1], qkv[2]  # (B, H/n, T, D)
    # flash dispatches to the Pallas kernel on TPU at tileable shapes and
    # to the dense jnp reference elsewhere — this is what makes the
    # gathered-full-T attention viable at the long contexts Ulysses
    # targets (a dense (T, T) score matrix would not be)
    y = flash_attention(q, k, v, causal=causal)
    # head-sharded -> seq-sharded: inverse exchange
    return lax.all_to_all(y, axis_name, split_axis=2, concat_axis=1, tiled=True)
