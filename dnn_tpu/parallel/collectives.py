"""Thin, named wrappers over XLA collectives.

This module is the rebuild of the reference's entire communication layer
(gRPC `SendTensor` unary RPCs with raw numpy payloads and a fresh insecure
channel per hop — node.py:70-94, node_service.proto:26-35): one stage->stage
activation hop becomes a single `CollectivePermute` over ICI, and the
"return the result to the first node" path (config.json:17, dead code in the
reference — SURVEY §3.3) becomes a ring shift back to coordinate 0.
"""

from __future__ import annotations

import jax
from jax import lax


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.axis_size(axis_name)


def shift_right(x, axis_name: str, *, wrap: bool = False):
    """Send x from stage i to stage i+1 (the SendTensor hop, node.py:70-85).
    Non-wrapping by default: stage 0 receives zeros, like having no
    predecessor."""
    n = lax.axis_size(axis_name)
    perm = [(i, i + 1) for i in range(n - 1)]
    if wrap:
        perm.append((n - 1, 0))
    return lax.ppermute(x, axis_name, perm)


def shift_left(x, axis_name: str, *, wrap: bool = False):
    n = lax.axis_size(axis_name)
    perm = [(i + 1, i) for i in range(n - 1)]
    if wrap:
        perm.append((0, n - 1))
    return lax.ppermute(x, axis_name, perm)


def rotate(x, axis_name: str, offset: int = 1):
    """Circular shift by `offset` along the axis (ring-attention building
    block: K/V blocks travel the ring one hop per step)."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def from_last_to_first(x, axis_name: str):
    """Move a value from the last stage to stage 0 — the working version of
    the reference's never-dialed `return_to_node_id` (node.py:272-277)."""
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, [(n - 1, 0)])


def psum(x, axis_name: str):
    return lax.psum(x, axis_name)

# NOTE on manual tensor parallelism (gpt.make_tp_block_fn): classic
# Megatron needs an explicit conjugate collective pair (`f`/`g`: identity
# fwd + all-reduce bwd at column-parallel inputs, all-reduce fwd +
# identity bwd at row-parallel outputs). Under jax.shard_map a bare
# `lax.psum` at the row-parallel output is sufficient — shard_map's AD
# tracks per-axis replication and emits the exact transposes itself
# (verified by gradient-parity tests in tests/test_tp_pp.py; hand-written
# custom_vjp equivalents of the Megatron pair actually BREAK that
# accounting and scale sharded-leaf grads by 1/tp — don't add them back).


def all_gather(x, axis_name: str, *, axis=0, tiled=False):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)
