"""Multi-host (multi-process) initialization: the DCN half of the
transport story.

The reference scales across machines with per-node gRPC processes relaying
tensors over TCP (/root/reference/node.py:70-94). The TPU-native
equivalent is `jax.distributed`: every host runs the SAME SPMD program,
`jax.devices()` spans all hosts, and XLA routes collectives over ICI
within a pod slice and DCN across slices — the transport disappears into
the compiler. One `Mesh` covers both: intra-host axes ride ICI, cross-host
axes ride DCN, behind the same `ppermute`/`psum` interface the single-host
runtimes already use (SURVEY §7 hard part 5).

Config (extends the reference JSON schema, SURVEY §2/C9):

    "distributed": {
        "coordinator_address": "10.0.0.1:9255",
        "num_processes": 2,
        "process_id": 0          # or omit and pass per-host via CLI/env
    }

`initialize_from_config` is a no-op for single-process runs, so the same
config files work on one host.
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

import jax

log = logging.getLogger("dnn_tpu.multihost")


@dataclasses.dataclass(frozen=True)
class DistributedConfig:
    coordinator_address: str
    num_processes: int
    process_id: Optional[int] = None  # resolvable from env/CLI per host

    @classmethod
    def from_dict(cls, d: dict) -> "DistributedConfig":
        return cls(
            coordinator_address=d["coordinator_address"],
            num_processes=int(d["num_processes"]),
            # `"process_id": null` in JSON means "set per host" — same as absent
            process_id=(int(d["process_id"]) if d.get("process_id") is not None else None),
        )


def resolve_process_id(dist: DistributedConfig, override: Optional[int] = None) -> int:
    """Process id precedence: explicit override (CLI flag) > config key >
    DNN_TPU_PROCESS_ID env var."""
    if override is not None:
        return override
    if dist.process_id is not None:
        return dist.process_id
    env = os.environ.get("DNN_TPU_PROCESS_ID")
    if env is not None:
        return int(env)
    raise ValueError(
        "process_id not set: pass --process_id, set it in the config's "
        "'distributed' block, or export DNN_TPU_PROCESS_ID"
    )


def initialize_from_config(
    dist: Optional[DistributedConfig], *, process_id: Optional[int] = None
) -> bool:
    """Join the multi-host job described by `dist` (None or 1 process ==
    single-host no-op). Must run before first backend use. Returns True if
    jax.distributed was initialized. After this, `jax.devices()` is global
    across hosts and `jax.local_devices()` is this host's slice."""
    if dist is None or dist.num_processes <= 1:
        return False
    pid = resolve_process_id(dist, process_id)
    jax.distributed.initialize(
        coordinator_address=dist.coordinator_address,
        num_processes=dist.num_processes,
        process_id=pid,
    )
    log.info(
        "joined distributed job: process %d/%d, coordinator %s, "
        "%d global / %d local devices",
        pid, dist.num_processes, dist.coordinator_address,
        jax.device_count(), jax.local_device_count(),
    )
    return True


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
        "local_devices": jax.local_device_count(),
    }
