"""Pipeline-parallel runtimes.

Two executors for the reference's core capability — "split a model into
sequential parts, run each part on a different device, relay activations"
(readme.md:1-3, node.py:35-105) — redesigned for TPU:

1. `RelayExecutor` — device-per-stage sequential relay. Execution semantics
   identical to the reference (one request traverses the chain, stage i+1
   starts after stage i finishes — SURVEY §3.3), but each hop is a
   device-to-device transfer of a jit output instead of a gRPC unary RPC
   with numpy-bytes payloads. Handles arbitrarily heterogeneous stages.

2. `spmd_pipeline` — the TPU-native fast path. One SPMD program over a
   Mesh "stage" axis: every device runs the same compiled step; activations
   move stage->stage with `lax.ppermute` (XLA CollectivePermute over ICI);
   microbatches flow in a GPipe schedule (M microbatches through S stages in
   M+S-1 steps, all stages busy in steady state). The reference cannot
   overlap stages at all — its nested-RPC design holds every hop open for
   the full downstream latency (node.py:84, SURVEY §3.3).

Heterogeneous stages are uniformized for SPMD by flattening + zero-padding
activations to one (microbatch, F) buffer and `lax.switch`-ing on the
stage coordinate. The SPMD contract this relies on — every switch branch
(= every stage program) issues the IDENTICAL collective sequence, else
ranks deadlock — is enforced statically: the analyzer's program pass
walks the traced pipeline's jaxpr and compares branch collective
signatures (dnn_tpu/analysis/program.check_branch_collectives, PRG001;
pinned by tests/test_analysis.py::test_pipeline_audit_collectives_consistent),
so a stage fn that grows a psum the others lack fails CI before it can
hang a mesh. The buffer dtype follows the payloads (see
_buffer_dtype): single-dtype pipelines ride natively (bf16 hops cost bf16
bytes over ICI), mixed pipelines use an f32 carrier with integer payloads
bitcast in (exact for all of int32, not just ints < 2^24). Homogeneous
stacks (transformer blocks) should use `spmd_pipeline_stacked` instead,
which shards one block's params per stage and skips the switch entirely.
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnn_tpu.analysis.shardcheck import contract as _shardcheck_contract
from dnn_tpu.obs.profile import annotation_ctx as _prof_annotation
from dnn_tpu.parallel.mesh import STAGE_AXIS


# ----------------------------------------------------------------------
# microbatch helpers
# ----------------------------------------------------------------------

def split_microbatches(x, num_microbatches: int):
    """(B, ...) -> (M, B//M, ...). The reference has no microbatching (batch
    size 1 end to end, node.py:147,151); this is the upgrade that makes the
    pipeline actually parallel."""
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {num_microbatches}")
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])


def merge_microbatches(y):
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])


# ----------------------------------------------------------------------
# 1. relay executor (reference semantics, TPU devices)
# ----------------------------------------------------------------------

class RelayExecutor:
    """Sequential stage relay across explicit devices.

    Mirrors the reference pipeline one-to-one: stage i's jitted program runs
    on device i, the output is handed to device i+1 (XLA device-to-device
    copy — the rebuilt SendTensor hop), and the final output returns to the
    host (the rebuilt result_tensor response chain, node.py:88-105).

    This IS the `device` rung of the pluggable transport ladder
    (comm/transport.py), in its same-process form: the hop is a direct
    `jax.device_put` of the jit output with zero host serialization —
    what the gRPC edge negotiates per hop when both stages share a
    process (the mailbox ticket path), this executor does inline. Hop
    metrics/spans carry the `transport="device"` label so the fleet
    view compares rungs directly.
    """

    #: negotiated-transport label for this executor's hops
    transport = "device"

    def __init__(self, stage_fns: Sequence[Callable], stage_params: Sequence[Any], devices=None):
        if len(stage_fns) != len(stage_params):
            raise ValueError("one params pytree per stage required")
        devices = list(devices if devices is not None else jax.devices())
        self.devices = [devices[i % len(devices)] for i in range(len(stage_fns))]
        # Params are committed to their stage's device once, at load time —
        # the HBM-resident analog of each node loading its slice at startup
        # (node.py:294-317).
        self.stage_params = [
            jax.device_put(p, d) for p, d in zip(stage_params, self.devices)
        ]
        self.stage_fns = [jax.jit(fn) for fn in stage_fns]
        # populated by record_timings runs (per-stage compute only; hop
        # latency needs the slope method — see measure_hop_latency)
        self.last_stage_times: Optional[List[float]] = None

    def __call__(self, x, *, record_timings: bool = False):
        if not record_timings:
            for i, (fn, params, dev) in enumerate(
                    zip(self.stage_fns, self.stage_params, self.devices)):
                # host annotation per stage hop: a profiler capture
                # (POST /profilez, obs/profile.py) names each relay stage
                # on the host track. annotation_ctx, not the generator
                # `annotation` form — this runs once per hop per decode
                # step, where the generator shape costs ~30 µs/call even
                # with nothing recording (STUDIES.md §9)
                with _prof_annotation(f"relay.stage{i}"):
                    x = fn(params, jax.device_put(x, dev))
            self.last_stage_times = None
            return x

        from dnn_tpu import obs
        from dnn_tpu.utils.metrics import labeled
        from dnn_tpu.utils.tracing import device_sync

        stages = []
        m = obs.metrics()
        for i, (fn, params, dev) in enumerate(
                zip(self.stage_fns, self.stage_params, self.devices)):
            xd = jax.device_put(x, dev)
            device_sync(xd)
            t1 = time.perf_counter()
            x = fn(params, xd)
            device_sync(x)
            dt = time.perf_counter() - t1
            stages.append(dt)
            if m is not None:
                # per-stage compute in the shared registry — the relay
                # runtime's contribution to the /metrics breakdown
                m.observe(labeled("relay.stage_compute_seconds", stage=i,
                                  transport=self.transport),
                          dt)
        self.last_stage_times = stages
        return x

    def measure_hop_latency(self, x, *, n1: int = 2, n2: int = 8) -> List[float]:
        """One-way device-to-device transfer time per inter-stage hop,
        measured honestly (SURVEY §7 hard part 4).

        A naive `device_put + sync` sample would be dominated by the
        host/tunnel round trip, not the transfer (see bench.py). Instead,
        ping-pong the *actual activation entering stage i* between the two
        stage devices n times back-to-back (an async dependency chain), sync
        once, and take the two-point slope (t(n2) - t(n1)) / (n2 - n1) so
        the constant sync RTT cancels; halve the per-pair slope for the
        one-way time. Returns one entry per hop (stage i-1 -> stage i;
        stage 0 has no incoming hop)."""
        from dnn_tpu.utils.tracing import device_sync

        acts = []  # activation entering each stage, as produced upstream
        for fn, params, dev in zip(self.stage_fns, self.stage_params, self.devices):
            acts.append(x)
            x = fn(params, jax.device_put(x, dev))
        device_sync(x)

        hops = []
        for i in range(1, len(self.devices)):
            a, b = self.devices[i - 1], self.devices[i]
            act = jax.device_put(acts[i], a)
            device_sync(act)

            def run(n):
                y = act
                t0 = time.perf_counter()
                for _ in range(n):
                    y = jax.device_put(jax.device_put(y, b), a)
                device_sync(y)
                return time.perf_counter() - t0

            run(1)  # warmup
            # clamp: on fast transports the slope can jitter below zero,
            # which is pure measurement noise, not a latency
            hops.append(max(0.0, (run(n2) - run(n1)) / (n2 - n1) / 2.0))
        from dnn_tpu import obs
        from dnn_tpu.utils.metrics import labeled

        m = obs.metrics()
        if m is not None:
            for i, h in enumerate(hops, start=1):
                m.observe(labeled("relay.hop_seconds", hop=i,
                                  transport=self.transport), h)
        return hops


# ----------------------------------------------------------------------
# 2. SPMD microbatched pipeline (shard_map + ppermute)
# ----------------------------------------------------------------------

def _flat_size(shape) -> int:
    return int(np.prod(shape)) if shape else 1


def _buffer_dtype(dtypes):
    """Carrier dtype for a ring buffer holding payloads of `dtypes`.

    One payload dtype -> carry it natively (a bf16 pipeline pays bf16 ICI
    bytes per hop, half of f32; an all-int pipeline rides exactly). Mixed
    dtypes -> an f32 buffer; float payloads upcast losslessly and integer
    payloads are BITCAST in (exact for the full int32 range — no "ints fit
    in f32 below 2^24" assumption). Bitcasting is safe here because the
    hop path is pure data movement (ppermute / select / pad / slice):
    nothing arithmetic ever touches the carrier bits.
    """
    dtypes = {jnp.dtype(d) for d in dtypes}
    if len(dtypes) == 1:
        return next(iter(dtypes))
    for d in dtypes:
        if d.itemsize > 4:
            raise ValueError(
                f"cannot carry {d} on a mixed-dtype pipeline ring (the "
                "carrier is 32-bit); cast integer ids to int32 / floats "
                "to float32"
            )
    return jnp.dtype(jnp.float32)


def _pad_flat(y, width, buf_dtype=jnp.float32):
    flat = y.reshape(y.shape[0], -1)
    if flat.dtype != buf_dtype:
        if jnp.issubdtype(flat.dtype, jnp.integer):
            # mixed-dtype buffer: ints bitcast into the f32 carrier
            flat = lax.bitcast_convert_type(flat.astype(jnp.int32), jnp.float32)
            flat = flat.astype(buf_dtype)  # no-op (carrier is f32)
        else:
            flat = flat.astype(buf_dtype)
    return jnp.pad(flat, ((0, 0), (0, width - flat.shape[1])))


def _unpad(buf, shape, dtype, buf_dtype=jnp.float32):
    mb = buf.shape[0]
    flat = buf[:, : _flat_size(shape[1:])]
    # mirror of _pad_flat: integer payloads on the mixed (f32-carrier) ring
    # were bitcast in, so bitcast them back out; everything else astypes
    if jnp.dtype(buf_dtype) != jnp.dtype(dtype) and jnp.issubdtype(dtype, jnp.integer):
        flat = lax.bitcast_convert_type(flat, jnp.int32).astype(dtype)
    return flat.reshape(mb, *shape[1:]).astype(dtype)


def pack_stage_params(stage_params):
    """Heterogeneous per-stage param pytrees -> one (S, W) f32 HOST (numpy)
    array (each stage's leaves flattened, concatenated, zero-padded to the
    widest stage) + per-stage unpack metadata. Sharded P(stage), this is
    what lets `spmd_pipeline` place each stage's weights on its own device:
    lax.switch executes only the selected branch (XLA Case), but branch
    OPERANDS must exist on every device — packing turns "operand = all
    stages' params, replicated" into "operand = my (1, W) shard".

    Packing runs in numpy on the host on purpose: the whole (S, W) array
    must never materialize in one device's HBM (that would cap model size
    at single-device memory — the opposite of per-stage placement).
    Callers `jax.device_put` the result with a P(stage) NamedSharding,
    which sends each row directly to its stage's device.

    Carrier dtype: when every leaf shares one float dtype, the packed
    array keeps it — a bf16 model's per-device row is bf16, not a 2x-HBM
    f32 upcast. Mixed float dtypes ride an f32 carrier (lossless for
    bf16/f16/f32; a mix including f64 is rejected rather than silently
    truncated). Integer leaves are rejected outright (params are float in
    every shipped family, and silent bitcast here would be invisible to
    readers of the packed array) — keep integer-param models on
    `param_placement="replicated"`."""
    per_stage, dtypes = [], set()
    for p in stage_params:
        leaves, treedef = jax.tree.flatten(p)
        arrs = []
        for leaf in leaves:
            arr = np.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                raise ValueError(
                    f"pack_stage_params supports float leaves only, got "
                    f"{arr.dtype}; use spmd_pipeline(..., "
                    f"param_placement='replicated') for non-float params"
                )
            arrs.append(arr)
            dtypes.add(jnp.dtype(arr.dtype))
        per_stage.append((treedef, arrs))

    if len(dtypes) == 1:
        carrier = dtypes.pop()
    else:
        carrier = jnp.dtype(np.float32)
        wide = [d for d in dtypes if d.itemsize > 4]
        if wide:
            raise ValueError(
                f"pack_stage_params: mixed param dtypes {sorted(map(str, dtypes))} "
                f"would silently truncate {sorted(map(str, wide))} through the "
                f"f32 carrier; cast params to one dtype or use "
                f"spmd_pipeline(..., param_placement='replicated')"
            )

    flats, metas = [], []
    for treedef, arrs in per_stage:
        vecs = [a.astype(carrier).reshape(-1) for a in arrs]
        leafmeta = [(a.shape, jnp.dtype(a.dtype)) for a in arrs]
        flats.append(np.concatenate(vecs) if vecs else np.zeros((0,), carrier))
        metas.append((treedef, leafmeta))
    width = max((f.shape[0] for f in flats), default=1) or 1
    packed = np.stack([np.pad(f, (0, width - f.shape[0])) for f in flats])
    return packed, metas


def _unpack_stage(vec, meta):
    """(W,) packed vector -> the stage's param pytree (inverse of one row
    of pack_stage_params)."""
    treedef, leafmeta = meta
    leaves, off = [], 0
    for shape, dtype in leafmeta:
        n = _flat_size(shape)
        leaves.append(lax.slice(vec, (off,), (off + n,)).reshape(shape).astype(dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _stage_shapes(stage_fns, stage_params, x_shape_dtype):
    """Trace per-stage input/output shapes (static — the reference discovers
    them at runtime from the wire header, node_service.proto:28-29)."""
    shapes = [x_shape_dtype]
    for fn, p in zip(stage_fns, stage_params):
        out = jax.eval_shape(fn, p, shapes[-1])
        shapes.append(jax.ShapeDtypeStruct(out.shape, out.dtype))
    return shapes


def _gpipe_loop(
    stage_step, inputs_buf, num_stages, num_microbatches, mb, width_hop, width_out, axis_name,
    out_dtype=jnp.float32,
):
    """The schedule, run per-device inside shard_map: at step t, stage d
    works on microbatch t-d; outputs hop to d+1 via ppermute.

    `stage_step(buf) -> (hop, out)`: `hop` (mb, width_hop) feeds the next
    stage; `out` (mb, width_out) is the pipeline product, only meaningful on
    the last stage. Hop and output widths are separate on purpose — for LM
    pipelines the final logits are ~vocab/hidden times wider than the
    inter-stage activations, and must never ride the ppermute ring. The hop
    buffer dtype is whatever `inputs_buf` carries (see _buffer_dtype); the
    out buffer is always the final stage's OWN dtype — unlike the hop ring
    it passes through an arithmetic psum, so bitcast carriage would be
    unsafe there (FTZ can flush denormal bit patterns), and it never mixes
    dtypes anyway.
    """
    m_count = num_microbatches
    steps = m_count + num_stages - 1
    d = lax.axis_index(axis_name)
    is_last = d == num_stages - 1

    out_buf = jnp.zeros((m_count + 1, mb, width_out), out_dtype)  # slot M = scratch
    buf0 = inputs_buf[0]

    def step(carry, t):
        buf, out = carry
        hop_y, out_y = stage_step(buf)

        # collect on the last stage: microbatch m = t - (S-1)
        m = t - (num_stages - 1)
        valid = jnp.logical_and(is_last, jnp.logical_and(m >= 0, m < m_count))
        write_idx = jnp.where(valid, jnp.clip(m, 0, m_count - 1), m_count)
        out = lax.dynamic_update_index_in_dim(out, out_y, write_idx, 0)

        # hop: my output becomes stage d+1's next input
        recv = lax.ppermute(hop_y, axis_name, [(i, i + 1) for i in range(num_stages - 1)])
        nxt = jnp.clip(t + 1, 0, m_count - 1)
        fresh = lax.dynamic_index_in_dim(inputs_buf, nxt, 0, keepdims=False)
        buf = jnp.where(d == 0, fresh, recv)
        return (buf, out), None

    (_, out_buf), _ = lax.scan(step, (buf0, out_buf), jnp.arange(steps))
    out = out_buf[:m_count]
    # only the last stage holds real data; replicate it to everyone
    return lax.psum(jnp.where(is_last, out, jnp.zeros_like(out)), axis_name)


def spmd_pipeline(
    stage_fns: Sequence[Callable],
    stage_params: Sequence[Any],
    x,
    *,
    mesh: Mesh,
    num_microbatches: int = 1,
    axis_name: str = STAGE_AXIS,
    param_placement: str = "auto",
    packed=None,
):
    """Heterogeneous-stage SPMD pipeline.

    All ranks run one program; each applies its own stage via `lax.switch`
    on the stage coordinate. Activations ride a uniform padded buffer
    (ppermute needs one shape on every rank — the SPMD answer to the
    reference's per-hop dynamic wire shapes) whose dtype follows the
    payloads (_buffer_dtype): native when uniform, f32 carrier with
    integer payloads bitcast in — exact over the whole int32 range — when
    mixed.

    `param_placement`:
      * "auto" (default): per-stage packed placement when the params are
        concrete values (or `packed=` is given); replicated when they are
        tracers (caller jits/grads with params as arguments — packing is
        impossible mid-trace, and output is placement-independent).
      * "stage": stage params are packed into one (S, W) array sharded
        over the stage axis (pack_stage_params), so each device's HBM
        holds only its own stage's weights (padded to the widest stage) —
        the per-stage-HBM north star, now for heterogeneous models too.
        Long-lived callers (the engine) should pack ONCE at load time and
        pass `packed=(packed_array, metas)`; otherwise the pack runs
        inside this call. Raises if the params are tracers and no
        `packed=` was supplied (an explicit placement request must not be
        silently downgraded).
      * "replicated": all weights on all devices, no pack/unpack work in
        the branches — right for models whose params are smaller than
        their activations.

    Returns the final stage's output with microbatches re-merged.
    """
    num_stages = len(stage_fns)
    if mesh.shape[axis_name] != num_stages:
        raise ValueError(
            f"mesh axis '{axis_name}' has size {mesh.shape[axis_name]}, "
            f"need {num_stages} (one device per stage)"
        )
    if param_placement not in ("auto", "stage", "replicated"):
        raise ValueError(
            f"param_placement must be auto|stage|replicated, got {param_placement!r}"
        )

    x_mb = split_microbatches(x, num_microbatches)
    mb = x_mb.shape[1]
    shapes = _stage_shapes(
        stage_fns, stage_params, jax.ShapeDtypeStruct(x_mb.shape[1:], x_mb.dtype)
    )
    # Hop buffer carries stage INPUTS (shapes[0..S-1]); the final output
    # (often vocab-wide logits) gets its own width and never rides the ring.
    width_hop = max(_flat_size(s.shape[1:]) for s in shapes[:-1])
    width_out = _flat_size(shapes[-1].shape[1:])
    out_shape, out_dtype = shapes[-1].shape, shapes[-1].dtype
    buf_dtype = _buffer_dtype([s.dtype for s in shapes[:-1]])

    inputs_buf = _pad_flat(
        x_mb.reshape(num_microbatches * mb, -1), width_hop, buf_dtype
    ).reshape(num_microbatches, mb, width_hop)

    sharded = param_placement in ("auto", "stage")
    if sharded and packed is None:
        if any(isinstance(l, jax.core.Tracer) for l in jax.tree.leaves(stage_params)):
            if param_placement == "stage":
                raise ValueError(
                    "param_placement='stage' with traced stage_params: "
                    "packing is impossible mid-trace. Pack once outside the "
                    "jit and pass packed=(array, metas) (what the engine "
                    "does), or use param_placement='replicated'/'auto'."
                )
            sharded = False  # auto: replicated semantics, identical output
    if sharded:
        if packed is None:
            packed_arr, metas = pack_stage_params(stage_params)
            packed_arr = jax.device_put(
                packed_arr, NamedSharding(mesh, P(axis_name))
            )
        else:
            packed_arr, metas = packed

    def make_branch(i):
        fn, in_s, in_dt = stage_fns[i], shapes[i].shape, shapes[i].dtype
        is_last = i == num_stages - 1

        def branch(params_vec, buf):
            # trace-time scope: device timelines (obs/profile.py) name
            # each pipeline stage's ops instead of one fused switch blob
            with jax.named_scope(f"pipeline.stage{i}"):
                sp = _unpack_stage(params_vec, metas[i]) if sharded else stage_params[i]
                xin = _unpad(buf, (mb, *in_s[1:]) if len(in_s) > 0 else (mb,), in_dt, buf_dtype)
                y = fn(sp, xin)
                if is_last:
                    return (jnp.zeros((mb, width_hop), buf_dtype),
                            _pad_flat(y, width_out, out_dtype))
                return _pad_flat(y, width_hop, buf_dtype), jnp.zeros((mb, width_out), out_dtype)

        return branch

    branches = [make_branch(i) for i in range(num_stages)]

    def per_device(params_local, inputs):
        d = lax.axis_index(axis_name)
        vec = params_local[0] if sharded else params_local

        def stage_step(buf):
            return lax.switch(d, branches, vec, buf)

        return _gpipe_loop(
            stage_step, inputs, num_stages, num_microbatches, mb,
            width_hop, width_out, axis_name, out_dtype=out_dtype,
        )

    result = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(axis_name) if sharded else P(), P()),
        out_specs=P(), check_vma=False,
    )(packed_arr if sharded else jnp.zeros(()), inputs_buf)

    y = _unpad(
        result.reshape(num_microbatches * mb, width_out),
        (num_microbatches * mb, *out_shape[1:]),
        out_dtype, out_dtype,
    )
    return y


def spmd_pipeline_train_1f1b(
    block_fn: Callable,
    embed_fn: Callable,
    head_loss_fn: Callable,
    stacked_params,
    aux_params,
    ids_mb,
    tgt_mb,
    *,
    mesh: Mesh,
    axis_name: str = STAGE_AXIS,
):
    """Fused 1F1B pipeline-parallel loss+grad (one fwd + one bwd per
    microbatch, interleaved).

    GPipe + `jax.grad` (make_pipeline_train_step) keeps every microbatch's
    stage activations alive between the forward and backward sweeps — peak
    live activations grow O(M). 1F1B starts each microbatch's backward as
    soon as the last stage finishes its forward, so a stage frees its
    stashed activation after at most one ring traversal: the stash here is
    a static ring of K = min(M, 2S-1) slots per device, independent of M.

    Schedule (step t, device d, S stages, M microbatches):
      forward of microbatch m runs at t = m + d;
      backward of microbatch m runs at t = 2(S-1) - d + m + 1
    so the last stage's backward trails its forward by one step, gradients
    ride a reverse ppermute ring one hop per step, and the whole loop is
    M + 2S - 1 lockstep scan iterations.

    Memory-for-compute trade vs GPipe, made explicit: embed is folded into
    stage 0 and head+loss into the last stage (nothing M-sized outlives
    the loop — embed grads come from re-linearizing embed_fn at stage 0's
    backward, head grads from the last stage's), but SPMD lockstep means
    every device evaluates both the mid-stage and the last-stage vjp forms
    each step and selects — the head+loss vjp runs S times oftener than
    mathematically needed. Right when activations dominate (long sequence,
    many microbatches, big models); wrong when the head dominates (tiny
    model, huge vocab, short sequences).

    Args: `stacked_params` (S, per_stage, ...) sharded P(stage); `aux_params`
    replicated (embed + head weights); `ids_mb`/`tgt_mb` (M, mb, T) int.
    `embed_fn(aux, ids) -> x`; `block_fn(local, x) -> y` shape-preserving;
    `head_loss_fn(aux, h, tgt) -> scalar` (mean over the microbatch's
    tokens). Returns (loss, d_stacked, d_aux) — loss/grads averaged over
    microbatches; d_stacked sharded P(stage) like its params.
    """
    num_stages = mesh.shape[axis_name]
    m_count = ids_mb.shape[0]
    if m_count < 1:
        raise ValueError("need at least one microbatch")
    k_slots = min(m_count, 2 * num_stages - 1)
    steps = m_count + 2 * num_stages - 1
    fwd_perm = [(i, i + 1) for i in range(num_stages - 1)]
    bwd_perm = [(i, i - 1) for i in range(1, num_stages)]

    param_specs = jax.tree.map(lambda _: P(axis_name), stacked_params)
    stacked_params = jax.device_put(
        stacked_params, NamedSharding(mesh, P(axis_name))
    )
    x_shape = jax.eval_shape(embed_fn, aux_params, ids_mb[0])

    def per_device(params, aux, ids, tgt):
        local = jax.tree.map(lambda p: p[0], params)
        d = lax.axis_index(axis_name)
        is_first = d == 0
        is_last = d == num_stages - 1

        stash = jnp.zeros((k_slots, *x_shape.shape), x_shape.dtype)
        g_stacked = jax.tree.map(jnp.zeros_like, local)
        g_aux = jax.tree.map(jnp.zeros_like, aux)
        loss_acc = jnp.zeros((), jnp.float32)
        fwd_buf = jnp.zeros(x_shape.shape, x_shape.dtype)
        bwd_buf = jnp.zeros(x_shape.shape, x_shape.dtype)

        def step(carry, t):
            stash, g_stacked, g_aux, loss_acc, fwd_buf, bwd_buf = carry

            # ---- backward stash READ first: with K = 2S-1 slots, stage 0's
            # forward write of microbatch m+K lands in the same slot, same
            # step, as its backward read of microbatch m — the read must
            # see the old value (mb m's stash is dead right after) ----
            m_b = t - (2 * (num_stages - 1) - d + 1)
            active_b = jnp.logical_and(m_b >= 0, m_b < m_count)
            mi_b = jnp.clip(m_b, 0, m_count - 1)
            x_st = lax.dynamic_index_in_dim(stash, mi_b % k_slots, 0, False)
            ids_b = lax.dynamic_index_in_dim(ids, mi_b, 0, False)
            tgt_b = lax.dynamic_index_in_dim(tgt, mi_b, 0, False)

            # ---- forward wave: microbatch m_f = t - d ----
            m_f = t - d
            active_f = jnp.logical_and(m_f >= 0, m_f < m_count)
            mi_f = jnp.clip(m_f, 0, m_count - 1)
            x0 = embed_fn(aux, lax.dynamic_index_in_dim(ids, mi_f, 0, False))
            x_in = jnp.where(is_first, x0.astype(fwd_buf.dtype), fwd_buf)
            slot_f = mi_f % k_slots
            stash = jnp.where(
                active_f,
                lax.dynamic_update_index_in_dim(stash, x_in, slot_f, 0),
                stash,
            )
            y = block_fn(local, x_in)
            fwd_next = lax.ppermute(y.astype(fwd_buf.dtype), axis_name, fwd_perm)

            # ---- backward wave: microbatch m_b (read above) ----

            # last stage: d(loss_mb)/d(local, aux, x) seeded by the loss
            lval, vjp_last = jax.vjp(
                lambda lp, ax, xx: head_loss_fn(ax, block_fn(lp, xx), tgt_b),
                local, aux, x_st,
            )
            dp_l, daux_l, dx_l = vjp_last(jnp.ones((), lval.dtype))
            # mid/first stage: d(block)/d(local, x) seeded by the grad hop
            _, vjp_mid = jax.vjp(lambda lp, xx: block_fn(lp, xx), local, x_st)
            dp_m, dx_m = vjp_mid(bwd_buf.astype(x_shape.dtype))

            dp = jax.tree.map(lambda a, b: jnp.where(is_last, a, b), dp_l, dp_m)
            dx = jnp.where(is_last, dx_l, dx_m)
            # stage 0 additionally backprops its dx through embed
            _, vjp_emb = jax.vjp(lambda ax: embed_fn(ax, ids_b), aux)
            (daux_e,) = vjp_emb(dx.astype(x_shape.dtype))

            g_stacked = jax.tree.map(
                lambda g, u: g + jnp.where(active_b, u, jnp.zeros_like(u)),
                g_stacked, dp,
            )
            g_aux = jax.tree.map(
                lambda g, ul, ue: g
                + jnp.where(jnp.logical_and(active_b, is_last), ul, jnp.zeros_like(ul))
                + jnp.where(jnp.logical_and(active_b, is_first), ue, jnp.zeros_like(ue)),
                g_aux, daux_l, daux_e,
            )
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(active_b, is_last), lval.astype(jnp.float32), 0.0
            )
            bwd_next = lax.ppermute(dx.astype(bwd_buf.dtype), axis_name, bwd_perm)

            return (stash, g_stacked, g_aux, loss_acc, fwd_next, bwd_next), None

        (_, g_stacked, g_aux, loss_acc, _, _), _ = lax.scan(
            step,
            (stash, g_stacked, g_aux, loss_acc, fwd_buf, bwd_buf),
            jnp.arange(steps),
        )
        inv_m = 1.0 / m_count
        # aux grads and loss live on single stages; psum replicates them.
        # stacked grads stay per-stage (sharded like their params).
        g_aux = jax.tree.map(lambda g: lax.psum(g * inv_m, axis_name), g_aux)
        loss = lax.psum(loss_acc * inv_m, axis_name)
        g_stacked = jax.tree.map(lambda g: (g * inv_m)[None], g_stacked)
        return loss, g_stacked, g_aux

    return jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(param_specs, P(), P(), P()),
        out_specs=(P(), param_specs, P()),
        check_vma=False,
    )(stacked_params, aux_params, ids_mb, tgt_mb)


def interleaved_schedule_steps(num_stages: int, virtual_stages: int,
                               num_microbatches: int) -> int:
    """Sub-step count of the interleaved schedule: V*M + S - 1. Each
    sub-step costs 1/V of a device's layers, so relative to GPipe's
    V*(M + S - 1) sub-step-equivalents the bubble shrinks from
    (S-1)/(M+S-1) to (S-1)/(VM+S-1)."""
    return virtual_stages * num_microbatches + num_stages - 1


def spmd_pipeline_interleaved(
    block_fn: Callable,
    stacked_params,
    x,
    *,
    mesh: Mesh,
    num_microbatches: int,
    virtual_stages: int,
    axis_name: str = STAGE_AXIS,
):
    """Interleaved (virtual-stage) pipeline over stacked homogeneous chunks
    — the Megatron-style schedule that cuts the pipeline bubble.

    Layer-chunk j of V*S chunks lives on device j % S, so each device owns
    V non-adjacent chunks and a microbatch makes V circuits of the ring.
    Sub-step t on device d serves (chunk c, microbatch m) by the standard
    interleaved order (groups of S microbatches sweep all V chunks before
    the next group enters):

        k = t - d;  g = k // (V*S);  c = (k % (V*S)) // S
        m = g*S + k % S

    Every consecutive global sub-stage (c*S + d -> c*S + d + 1) is one
    wrapping ppermute hop one sub-step later, so the whole schedule is one
    lockstep `lax.scan` of V*M + S - 1 sub-steps, each applying 1/V of a
    device's layers — against GPipe's (M + S - 1) full-stage steps that's
    the bubble dropping from (S-1)/(M+S-1) to (S-1)/(VM+S-1)
    (interleaved_schedule_steps pins the arithmetic; the wrap hops are the
    price, V-1 extra ring circuits of ICI traffic per microbatch).

    `stacked_params` carries a leading (V*S,) chunk axis in LAYER order
    (chunk j = layers [j*Lc, (j+1)*Lc)); `block_fn(chunk_params, x) -> y`
    shape-preserving. `num_microbatches` must divide by the stage count
    (the interleaved ordering is defined on full groups). virtual_stages=1
    degrades to exactly the GPipe dataflow (wrap hops never observed).

    Training composes via autodiff like the stacked GPipe path: reverse-AD
    re-runs the scan backwards with reversed ppermutes, so
    train.make_pipeline_train_step(schedule="interleaved") gets the same
    loss/grads as gpipe/1f1b (parity-tested) with the shorter schedule.
    """
    num_stages = mesh.shape[axis_name]
    v = virtual_stages
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    leading = {p.shape[0] for p in jax.tree.leaves(stacked_params)}
    if leading != {v * num_stages}:
        raise ValueError(
            f"stacked_params leading axis {leading} != virtual_stages * "
            f"num_stages = {v * num_stages}"
        )
    if num_microbatches % num_stages:
        raise ValueError(
            f"num_microbatches {num_microbatches} must divide by the stage "
            f"count {num_stages} for the interleaved ordering"
        )
    m_count = num_microbatches
    x_mb = split_microbatches(x, m_count)
    mb = x_mb.shape[1]

    # chunk-major -> (S, V) so P(stage) gives device d chunks {c*S + d}
    def reorder(p):
        return p.reshape(v, num_stages, *p.shape[1:]).swapaxes(0, 1)

    params_sv = jax.tree.map(reorder, stacked_params)
    param_specs = jax.tree.map(lambda _: P(axis_name), params_sv)
    params_sv = jax.device_put(params_sv, NamedSharding(mesh, P(axis_name)))

    trail = x_mb.shape[2:]
    buf_dtype = x_mb.dtype
    flat = x_mb.reshape(m_count, mb, -1)
    width = flat.shape[-1]
    steps = interleaved_schedule_steps(num_stages, v, m_count)
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]  # wrapping

    def per_device(params, inputs):
        local = jax.tree.map(lambda p: p[0], params)  # (V, Lc, ...)
        d = lax.axis_index(axis_name)
        is_last = d == num_stages - 1
        out_buf = jnp.zeros((m_count + 1, mb, width), buf_dtype)  # slot M = scratch
        buf = jnp.zeros((mb, width), buf_dtype)

        def step(carry, t):
            buf, out = carry
            k = t - d
            valid = jnp.logical_and(k >= 0, k < v * m_count)
            kc = jnp.clip(k, 0, v * m_count - 1)
            g = kc // (v * num_stages)
            j = kc % (v * num_stages)
            c = j // num_stages
            m = g * num_stages + j % num_stages

            fresh = lax.dynamic_index_in_dim(inputs, m, 0, keepdims=False)
            start = jnp.logical_and(d == 0, c == 0)
            xin = jnp.where(start, fresh, buf)
            chunk = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
                local,
            )
            y = block_fn(chunk, xin.reshape(mb, *trail)) \
                .reshape(mb, -1).astype(buf_dtype)

            done = jnp.logical_and(
                valid, jnp.logical_and(is_last, c == v - 1))
            widx = jnp.where(done, m, m_count)
            out = lax.dynamic_update_index_in_dim(out, y, widx, 0)
            buf = lax.ppermute(y, axis_name, perm)
            return (buf, out), None

        (_, out_buf), _ = lax.scan(step, (buf, out_buf), jnp.arange(steps))
        out = out_buf[:m_count]
        return lax.psum(
            jnp.where(is_last, out, jnp.zeros_like(out)), axis_name)

    result = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(), check_vma=False,
    )(params_sv, flat)

    return result.reshape(m_count * mb, *trail)


def stacked_param_placement(stacked_params, *, axis_name: str = STAGE_AXIS):
    """The declared placement contract of the stacked pipeline: every
    leaf of the (S, ...)-stacked param tree shards its leading stage
    axis — each device holds exactly its own stage's 1/S slice (the
    HBM-resident per-stage weights of BASELINE.json's north star).
    Registered as the `pipeline.stacked_param_placement` sharding
    contract: the analysis gate lowers spmd_pipeline_stacked and fails
    if any leaf's compiled placement drifts from this declaration."""
    return jax.tree.map(lambda _: P(axis_name), stacked_params)


_shardcheck_contract("pipeline.stacked_param_placement")(
    stacked_param_placement)


def spmd_pipeline_stacked(
    block_fn: Callable,
    stacked_params,
    x,
    *,
    mesh: Mesh,
    num_microbatches: int = 1,
    axis_name: str = STAGE_AXIS,
    data_axis: Optional[str] = None,
    param_specs=None,
):
    """Homogeneous-stage SPMD pipeline over stacked params.

    `stacked_params` has a leading stage axis (S, ...) that lives sharded
    P('stage', ...) — each device holds only its own stage's slice (the
    HBM-resident per-stage weights of BASELINE.json's north star). No
    switch, no padding: this is the fast path for transformer block stacks.
    `block_fn(params_slice, x) -> y` must map (mb, ...) -> (mb, ...) with an
    unchanged shape.

    `data_axis` composes data parallelism with the pipeline (a 2D
    {data, stage} mesh): each microbatch's BATCH dim shards over the data
    axis, so every data column runs the same pipeline on its batch slice —
    stage params replicate across data columns (their spec doesn't mention
    the axis), ppermute hops stay within a column, and under `jax.grad`
    the shard_map transpose psums the param cotangents over data columns
    automatically — dp×pp with no extra code at the call site.

    `param_specs` composes TENSOR parallelism with the pipeline (TP x PP,
    the Megatron 3D recipe with `data_axis`): a PartitionSpec pytree for
    `stacked_params` whose leading dim is the stage axis and whose trailing
    dims may shard over a `model` axis (e.g. train.gpt_tp_pp_specs). The
    supplied `block_fn` must then be TP-aware — compute on its local weight
    shard and combine partial sums over the model axis itself
    (gpt.make_tp_block_fn). Activations stay replicated over the model
    axis: hops ppermute within each model column, and the ring pays one
    activation per hop regardless of tp. Default None keeps the 1D
    P(stage) placement."""
    num_stages = mesh.shape[axis_name]
    x_mb = split_microbatches(x, num_microbatches)
    mb = x_mb.shape[1]
    d_size = mesh.shape[data_axis] if data_axis else 1
    if mb % d_size:
        raise ValueError(
            f"microbatch size {mb} not divisible by data axis size {d_size}"
        )
    mb_local = mb // d_size

    if param_specs is None:
        param_specs = stacked_param_placement(stacked_params,
                                              axis_name=axis_name)
    # map over the PARAMS tree: flatten_up_to stops at its array leaves, so
    # the P specs (themselves tuples) come through whole
    stacked_params = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
        stacked_params, param_specs,
    )

    # flatten trailing dims into the buffer width for the generic loop; the
    # ring carries the activation's OWN dtype (bf16 pipelines pay bf16 ICI
    # bytes per ppermute hop, not 2x in f32)
    trail = x_mb.shape[2:]
    buf_dtype = x_mb.dtype
    flat = x_mb.reshape(num_microbatches, mb, -1)

    def per_device_wrapped(params, inputs):
        local = jax.tree.map(lambda p: p[0], params)

        def stage_step(buf):
            xin = buf.reshape(mb_local, *trail)
            y = block_fn(local, xin).reshape(mb_local, -1).astype(buf_dtype)
            return y, y  # uniform shapes: hop and output coincide

        return _gpipe_loop(
            stage_step, inputs, num_stages, num_microbatches, mb_local,
            flat.shape[-1], flat.shape[-1], axis_name, out_dtype=buf_dtype,
        )

    result = jax.shard_map(
        per_device_wrapped,
        mesh=mesh,
        in_specs=(param_specs, P(None, data_axis)),
        out_specs=P(None, data_axis),
        check_vma=False,
    )(stacked_params, flat)

    return result.reshape(num_microbatches * mb, *trail)
