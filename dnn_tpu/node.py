"""Node CLI — drop-in replacement for the reference's entrypoint.

Same flags as /root/reference/node.py:212-216:

    python -m dnn_tpu.node --node_id node1 --config ./config.json \
        [--input_image img.png] [--serve] [--log_level INFO]

Behavior by mode:

  * Default (TPU single-controller): the whole pipeline runs on the local
    mesh — `part_index` maps to stage coordinates, hops are ppermute, and
    if `--input_image` is given the client path runs end to end and prints
    `FINAL PREDICTION (Index): N` exactly like node.py:192. The reference
    needed N machines + N terminals for this; here one process does it
    with zero gRPC hops (BASELINE.json north star).

  * `--serve` (distributed edge mode): behave like one reference node —
    host this node's stage behind the gRPC NodeService and relay to
    `next_node` by address. Wire-compatible with reference nodes. In this
    mode a node with part_index 0 and `--input_image` also initiates
    inference after a short delay (node.py:203-207,332-337).

  * `--serve_lm` (LM daemon): long-lived generation server on this node's
    port — SendTensor carries prompt token ids, the response carries the
    generated tokens, and all in-flight requests decode together through
    the continuous-batching pool (runtime/lm_server.py). The LM analog of
    the reference's serving-process shape (node.py:114-133).
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import sys

import numpy as np

from dnn_tpu.config import TopologyConfig
from dnn_tpu.io.preprocess import load_image_or_dummy
from dnn_tpu.runtime.engine import PipelineEngine
from dnn_tpu.utils.logging import setup_logging

log = logging.getLogger("dnn_tpu.node")

# cold-start ledger feed (obs/caplens): the spawn->first-token wall is
# attributed from gauges the CHILD measures about itself — the parent
# only scrapes. Stamped in main() (process age = exec + interpreter +
# imports) and _serve_lm() (weight-load / pre-ready compile spans).
_BOOT: dict = {}


def _proc_age_s() -> float:
    """Seconds since this process exec'd (Linux /proc; 0.0 elsewhere
    — the imports bucket degrades, the ledger's coverage says so)."""
    try:
        import os

        with open("/proc/self/stat") as f:
            stat = f.read()
        # starttime is field 22; comm (field 2) may hold spaces, so
        # split after its closing paren
        start_ticks = float(stat.rsplit(")", 1)[1].split()[19])
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        return max(0.0, uptime - start_ticks / os.sysconf("SC_CLK_TCK"))
    except Exception:  # noqa: BLE001 — non-Linux / hardened /proc
        return 0.0


def _compile_total_s() -> float:
    """Current jax_compile_seconds_total (obs/compile_watch) — lets
    boot spans subtract the compile time that landed inside them."""
    from dnn_tpu import obs

    m = obs.metrics()
    if m is None:
        return 0.0
    try:
        return float(m.snapshot()["counters"].get(
            "jax_compile_seconds_total", 0.0))
    except Exception:  # noqa: BLE001 — scrape must not break boot
        return 0.0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dnn_tpu.node",
        description="Run a pipeline node / the whole pipeline (reference-compatible CLI)",
    )
    p.add_argument("--node_id", required=True, help="Unique ID for this node (e.g. node1)")
    p.add_argument("--config", required=True, help="Path to the JSON configuration file")
    p.add_argument("--input_image", help="Input image path (part_index 0 initiates inference)")
    p.add_argument("--generate", type=int, metavar="N", default=None,
                   help="GPT families: decode N new tokens through the "
                        "pipeline (pipeline-parallel KV cache on the spmd "
                        "runtime) and print them")
    p.add_argument("--prompt_ids", default=None,
                   help="Comma-separated prompt token ids for --generate "
                        "(default: a single BOS-like token 0)")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="Sampling temperature for --generate (0 = greedy)")
    p.add_argument("--top_k", type=int, default=None,
                   help="Top-k sampling cutoff for --generate")
    p.add_argument("--top_p", type=float, default=None,
                   help="Nucleus (top-p) sampling cutoff for --generate / "
                        "--serve_lm")
    p.add_argument("--min_p", type=float, default=None,
                   help="--serve_lm: drop tokens below min_p x the top "
                        "token's probability (per-request m= overrides)")
    p.add_argument("--repetition_penalty", type=float, default=None,
                   help="--serve_lm: HF-style repetition penalty over each "
                        "request's tokens (per-request r= overrides)")
    p.add_argument("--seed", type=int, default=0,
                   help="Sampling rng seed for --generate")
    p.add_argument("--beam", type=int, default=None, metavar="K",
                   help="--generate: deterministic beam search with K beams "
                        "instead of sampling (dense GPT family; "
                        "runtime/beam.py)")
    p.add_argument("--eos_id", type=int, default=None,
                   help="--beam: end-of-sequence token id (finished beams "
                        "freeze; output pads with it)")
    p.add_argument("--length_penalty", type=float, default=0.0,
                   help="--beam: GNMT length-penalty alpha (0 = off)")
    p.add_argument("--lora", default=None, metavar="NPZ",
                   help="LoRA adapter artifact (dnn_tpu.lora.save_lora) "
                        "merged into the model weights at load — every "
                        "mode then serves the fine-tuned model")
    p.add_argument("--serve_adapter", action="append", default=None,
                   metavar="NPZ",
                   help="--serve_lm: serve this LoRA adapter PER REQUEST "
                        "alongside the base model (repeatable; requests "
                        "pick one with the a=IDX request-id option, "
                        "0-based in flag order). Unlike --lora, the base "
                        "weights stay unmerged — one pool serves every "
                        "adapter mix")
    p.add_argument("--serve", action="store_true",
                   help="Host this node's stage behind gRPC (reference-interop mode)")
    p.add_argument("--transport", choices=["auto", "grpc", "shm", "device"],
                   default=None,
                   help="--serve: inter-stage hop transport "
                        "(comm/transport.py). 'auto' (default, or the "
                        "config's `transport` key) negotiates "
                        "device -> shm -> grpc per hop at a "
                        "wire-compatible handshake — reference peers "
                        "land on grpc; 'grpc' pins the reference wire "
                        "path; explicit 'device'/'shm' FAIL LOUD when "
                        "the hop cannot prove them (same process / "
                        "same host)")
    p.add_argument("--serve_lm", action="store_true",
                   help="GPT families: run the continuous-batching LM daemon "
                        "on this node's port — SendTensor(prompt ids) answers "
                        "with generated tokens (runtime/lm_server.py)")
    p.add_argument("--role", choices=["prefill", "decode", "both"],
                   default="both",
                   help="--serve_lm: this replica's fleet role "
                        "(dnn_tpu/control): a front door routes prompt "
                        "prefill exports to 'prefill' replicas and "
                        "generation to 'decode'/'both' — the "
                        "disaggregated split. Advisory (every endpoint "
                        "still serves); advertised on /statusz and the "
                        "dnn_tpu_replica_role gauge")
    p.add_argument("--route", action="store_true",
                   help="run the FLEET FRONT DOOR on this node's port "
                        "instead of a model: route Generate/"
                        "GenerateStream across --route_targets replicas "
                        "with SLO-driven admission, session affinity "
                        "and sibling retry (dnn_tpu/control/router.py; "
                        "NodeClient — or a reference-built client — "
                        "points at it unchanged). To also SPAWN the "
                        "replicas, use `python -m dnn_tpu.control`")
    p.add_argument("--route_targets", default=None,
                   help="--route: comma-separated replica gRPC "
                        "addresses (host:port)")
    p.add_argument("--route_signals", default=None,
                   help="--route: comma-separated replica obs base "
                        "URLs (http://host:port), one per target in "
                        "order — enables signal-fed policies "
                        "(least_queue/slo_burn read queue depth, "
                        "KV-slot utilization, latency percentiles and "
                        "SLO burn from each replica's /metrics) and "
                        "HTTP health probing; omitted, health falls "
                        "back to gRPC HealthCheck and policies to the "
                        "router's own in-flight counts")
    p.add_argument("--policy",
                   choices=["round_robin", "least_queue", "slo_burn"],
                   default="least_queue",
                   help="--route: routing policy (dnn_tpu/control/"
                        "policy.py)")
    p.add_argument("--kvtier", choices=["auto", "pull", "off"],
                   default="auto",
                   help="--route: prefix-aware placement over the "
                        "fleet KV tier (dnn_tpu/kvtier) — 'auto' "
                        "routes to the replica holding a prompt's "
                        "prefix blocks (else instructs a pull), "
                        "'pull' always places by policy and migrates "
                        "the blocks, 'off' restores dedup-key "
                        "affinity only")
    p.add_argument("--slots", type=int, default=4,
                   help="--serve_lm: concurrent decode slots in the pool")
    p.add_argument("--max_len", type=int, default=None,
                   help="--serve_lm: max sequence length per slot "
                        "(default: model block_size)")
    p.add_argument("--draft_model", default=None,
                   help="--serve_lm: model-zoo name of a DRAFT model — "
                        "enables speculative continuous batching (each "
                        "step commits up to spec_k+1 tokens per slot; "
                        "runtime/serving_spec.py)")
    p.add_argument("--draft_weights", default=None,
                   help="--serve_lm: checkpoint for the draft model "
                        "(.pth/npz/safetensors; random init if omitted)")
    p.add_argument("--spec_k", type=int, default=4,
                   help="--serve_lm: draft proposals per speculative step")
    p.add_argument("--kv", choices=["paged", "dense", "auto"],
                   default="auto",
                   help="--serve_lm: KV cache layout. 'auto' (default) "
                        "serves the PAGED block pool whenever this "
                        "configuration can page — block-granular "
                        "admission by actual request length — and falls "
                        "back to the dense per-slot pool otherwise "
                        "(recorded as a kv_fallback_dense flight event); "
                        "'dense' opts out; 'paged' fails loud when "
                        "paging is impossible")
    p.add_argument("--kv_dtype", choices=["f32", "bf16", "int8", "int4"],
                   default=None,
                   help="--serve_lm: KV cache storage dtype (default: "
                        "the model's compute dtype). int8/int4 quantize "
                        "the cache with per-(position, head) scales — "
                        "4x/8x less cache bandwidth per decode step than "
                        "f32 (runtime/kvcache.Int8KV/Int4KV; int4 costs "
                        "more rounding error — see README 'Decode hot "
                        "path')")
    p.add_argument("--paged_blocks", type=int, default=0,
                   help="--serve_lm: paged KV cache — shared pool of this "
                        "many blocks (0 with --kv=paged/auto auto-sizes "
                        "to the dense pool's capacity; see "
                        "runtime/paged_kvcache.py)")
    p.add_argument("--block_len", type=int, default=16,
                   help="--serve_lm: positions per paged-cache block")
    p.add_argument("--kv_lease_ttl_s", type=float, default=30.0,
                   help="--serve_lm: KV-tier migration lease TTL "
                        "(dnn_tpu/kvtier): a staged block export an "
                        "adopter never pulls/acks is reclaimed after "
                        "this many seconds (lease_expire/lease_reclaim "
                        "flight events)")
    p.add_argument("--kv_handoff_ttl_s", type=float, default=120.0,
                   help="--serve_lm: kvput inbox TTL — a staged "
                        "prefill handoff nobody consumes is swept "
                        "after this many seconds (kvput_expired "
                        "flight event; <= 0 disables)")
    p.add_argument("--prefix_cache", type=int, default=0,
                   help="--serve_lm: prefix-cache capacity (LRU entries); "
                        "requests sharing a prompt prefix skip re-prefilling "
                        "identical chunks. 0 disables (default). Each entry "
                        "holds one transient row cache in HBM")
    p.add_argument("--decode_buckets", action="store_true",
                   help="--serve_lm: length-aware bucketed decode — the "
                        "dense slot-pool cache grows bucket-by-bucket so "
                        "decode bytes/step track the LIVE context "
                        "instead of max_len (runtime/decode_buckets.py; "
                        "dense pools only)")
    p.add_argument("--prompt_pad", type=int, default=None,
                   help="--serve_lm: prompt padding bucket (one prefill "
                        "compilation; default min(64, max_len))")
    p.add_argument("--weights", choices=["f32", "int8"], default="f32",
                   help="--serve_lm: served weight precision. 'int8' "
                        "quantizes the model ONCE at startup (symmetric "
                        "per-output-channel, quant.py) — ~4x fewer "
                        "weight bytes streamed per decode step; the "
                        "goodput MBU gauges price the quantized stream "
                        "exactly (utils/flops.tree_weight_bytes)")
    p.add_argument("--prefill_chunk_tokens", type=int, default=0,
                   metavar="N",
                   help="--serve_lm: interleaved chunked prefill — fold "
                        "one N-token prompt chunk of an admitting "
                        "request into each decode step (the mixed "
                        "program) instead of convoying the whole "
                        "prefill through submit. 0 (default) keeps the "
                        "convoy path. JSON-mode constraints ride the "
                        "interleave (the grammar DFA advances on "
                        "device)")
    p.add_argument("--overlap", action="store_true",
                   help="--serve_lm: double-buffered dispatch — the "
                        "worker dispatches step N+1's device work "
                        "before committing step N's tokens, hiding "
                        "host bookkeeping under the device step "
                        "(tokens surface one step later). JSON-mode "
                        "constraints ride the overlap (the device DFA "
                        "walk is idempotent under the replayed step)")
    p.add_argument("--tokenizer", default=None,
                   help="--serve_lm: text endpoint tokenizer — 'bytes' "
                        "(UTF-8 bytes as ids; any vocab >= 256) or a LOCAL "
                        "HF tokenizer directory. SendMessage then serves "
                        "prompt text -> generated text")
    p.add_argument("--process_id", type=int, default=None,
                   help="This host's process id for multi-host (config 'distributed') runs")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="--serve/--serve_lm: also serve the observability "
                        "endpoint on this port over plain HTTP — GET "
                        "/metrics (Prometheus text format), /trace "
                        "(Chrome-trace JSON of recent request spans), "
                        "/debugz (flight-recorder ring), /statusz "
                        "(watchdog per-component state), /healthz, POST "
                        "/profilez?ms=N (on-demand jax.profiler capture) "
                        "(dnn_tpu/obs; 0 = ephemeral port)")
    p.add_argument("--fleet_port", type=int, default=None, metavar="PORT",
                   help="--serve/--serve_lm: ALSO run the fleet "
                        "collector in this process and serve the merged "
                        "/fleetz view on this port (dnn_tpu/obs/"
                        "fleet.py; 0 = ephemeral). Stage endpoints come "
                        "from --fleet_targets, or from the config's "
                        "node hosts + --metrics_port when omitted — the "
                        "convention where every node passes the same "
                        "--metrics_port")
    p.add_argument("--fleet_targets", default=None,
                   help="comma-separated obs endpoint base URLs "
                        "(http://host:port) for --fleet_port, one per "
                        "stage")
    p.add_argument("--fleet_interval", type=float, default=None,
                   help="--fleet_port: poll period in seconds "
                        "(default 5)")
    p.add_argument("--slo_ttft_ms", type=float, default=None,
                   help="--serve_lm: TTFT objective in ms — 99%% of "
                        "requests (see --slo_target) must see their "
                        "first token within it; exported as the "
                        "dnn_tpu_slo_burn_rate{slo=\"ttft\"} "
                        "error-budget gauge with a flight event on "
                        "breach (dnn_tpu/obs/goodput.py)")
    p.add_argument("--slo_itl_ms", type=float, default=None,
                   help="--serve_lm: inter-token latency objective in "
                        "ms (slo=\"inter_token\" burn-rate gauge)")
    p.add_argument("--slo_avail", type=float, default=None,
                   help="--serve_lm: availability objective as a "
                        "success fraction, e.g. 0.999 "
                        "(slo=\"availability\" burn-rate gauge)")
    p.add_argument("--slo_target", type=float, default=None,
                   help="--serve_lm: fraction of requests that must "
                        "meet each latency objective (default 0.99; "
                        "needs at least one --slo_* objective)")
    p.add_argument("--chaos", default=None, metavar="PLAN",
                   help="--serve/--serve_lm: install a fault-injection "
                        "plan in THIS process (dnn_tpu/chaos; a JSON "
                        "file path or inline JSON). Deterministic "
                        "seeded injections — RPC drop/delay/corrupt, "
                        "relay-frame faults, KV-pool exhaustion, "
                        "device-step faults, watchdog wedge windows — "
                        "each recorded as a chaos_inject flight event "
                        "so the induced incident reconstructs from "
                        "/debugz")
    p.add_argument("--on_wedged", choices=["503", "restart", "drain"],
                   default="503",
                   help="--serve_lm: policy when the watchdog declares "
                        "wedged (warm-up grace preserved). '503' "
                        "(default): passive — /healthz 503s until a "
                        "human acts. 'restart': exit with code 43 so a "
                        "supervisor (--supervise, or any process "
                        "manager) relaunches from the latest "
                        "checkpoint. 'drain': finish in-flight decodes "
                        "within the drain grace, hand queued work back "
                        "retriable, then exit 43. Needs --watchdog_s")
    p.add_argument("--supervise", action="store_true",
                   help="run the serving mode as a SUPERVISED CHILD "
                        "process: this process respawns it on death "
                        "with exponential backoff + crash-loop cap, "
                        "and (with --metrics_port) polls its /healthz "
                        "to catch wedged-but-alive children — the "
                        "--on_wedged policy then applies from outside "
                        "too (dnn_tpu/chaos/supervisor.py)")
    p.add_argument("--watchdog_s", type=float, default=None, metavar="S",
                   help="--serve_lm: run the hung-device watchdog with "
                        "this probe period in seconds (subprocess-bounded "
                        "device probe + decode heartbeat; /healthz "
                        "degrades ok|degraded|wedged and /statusz carries "
                        "detail — dnn_tpu/obs/watchdog.py). Off unless "
                        "given")
    p.add_argument("--log_level", default="INFO")
    return p


def _initiate_local(engine: PipelineEngine, image_path: str, *, announce: bool = True) -> int:
    """Single-controller client path: preprocess -> full pipeline -> argmax
    (rebuilds initiate_inference, node.py:137-200, minus the RPCs).
    `announce=False` computes without printing (multi-host: every process
    runs the same program, only process 0 speaks)."""
    x, used_dummy = load_image_or_dummy(image_path)
    if used_dummy and image_path:
        log.warning("input image unavailable; using dummy data (node.py:149-154 behavior)")
    pred = engine.predict(x)
    if announce:
        print(f"***** FINAL PREDICTION (Index): {pred} *****")
    return pred


async def _initiate_edge(engine: PipelineEngine, node_id: str, image_path: str,
                         health_deadline: float = 30.0):
    """Edge-mode initiator: run stage 0 locally, relay downstream over gRPC
    (start_inference_after_delay + initiate_inference, node.py:137-207).
    Instead of the reference's blind 2-second sleep before initiating
    (node.py:203-207), poll the next node's HealthCheck until it comes up
    (bounded by `health_deadline`) — late-starting downstream nodes are
    normal during rollout, not errors.

    The sync gRPC client calls run in a thread executor so this node's own
    server handlers stay responsive while the pipeline round-trip is in
    flight (the reference simply blocks inside one event loop, node.py:181).
    """
    from dnn_tpu.comm.client import NodeClient, pipeline_budget

    loop = asyncio.get_running_loop()
    cfg = engine.config
    me = cfg.node_by_id(node_id)
    nxt = cfg.next_node(me)
    x, used_dummy = load_image_or_dummy(image_path)
    if used_dummy:
        log.warning("input image unavailable; using dummy data")
    y = np.asarray(engine.run_stage(me.part_index, x))
    if nxt is None:
        print(f"***** FINAL PREDICTION (Index): {int(np.argmax(y))} *****")
        return
    client = NodeClient(nxt.address)
    if not await loop.run_in_executor(
        None, lambda: client.wait_healthy(deadline=health_deadline)
    ):
        log.error("next node %s not healthy after %.0fs", nxt.address, health_deadline)
        return
    status, result = await loop.run_in_executor(
        None, lambda: client.send_tensor(
            y, request_id="dnn_tpu_pipe_001",
            timeout=pipeline_budget(cfg.num_parts),
        )
    )
    log.info("pipeline status: %s", status)
    if result is not None:
        print(f"***** FINAL PREDICTION (Index): {int(np.argmax(result))} *****")
    else:
        log.error("no result tensor in response chain")


def main(argv=None) -> int:
    import time as _time

    _BOOT["imports_s"] = _proc_age_s()
    _BOOT["t_main"] = _time.monotonic()
    raw_argv = list(sys.argv[1:] if argv is None else argv)
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, node_id=args.node_id)

    if args.supervise:
        if not (args.serve or args.serve_lm):
            log.error("--supervise applies to the serving modes "
                      "(--serve / --serve_lm)")
            return 1
        return _supervise(args, raw_argv)

    try:
        config = TopologyConfig.from_json(args.config)
    except FileNotFoundError:
        log.error("Config file not found at '%s'", args.config)
        return 1
    except (ValueError, KeyError) as e:
        log.error("Invalid config '%s': %s", args.config, e)
        return 1

    try:
        me = config.node_by_id(args.node_id)
    except KeyError as e:
        log.error("%s", e)
        return 1

    if args.role != "both" and not args.serve_lm:
        log.error("--role applies to --serve_lm (the replica's fleet "
                  "role; the router's own role is implicit)")
        return 1
    if (args.route_targets or args.route_signals) and not args.route:
        log.error("--route_targets/--route_signals apply only with "
                  "--route")
        return 1
    if args.route:
        # front-door mode: no model, no engine — the router is pure
        # control plane over the listed replicas
        if args.serve or args.serve_lm or args.generate is not None:
            log.error("--route is a standalone mode (no --serve/"
                      "--serve_lm/--generate)")
            return 1
        if not args.route_targets:
            log.error("--route needs --route_targets (comma-separated "
                      "replica host:port addresses); to spawn replicas "
                      "too, use `python -m dnn_tpu.control`")
            return 1
        return _route(args, config, me)

    if config.device_type == "cpu":
        # Platform choice must land before first backend use; on hosts where
        # a TPU plugin wins selection regardless of JAX_PLATFORMS (see
        # tests/conftest.py), the in-process config update is the only
        # override that sticks. The update never raises — whether it took
        # effect is verified after the backend initializes, below.
        import jax

        jax.config.update("jax_platforms", "cpu")

    if config.distributed is not None:
        # multi-host: join the jax.distributed job before any backend use so
        # jax.devices() spans all hosts (dnn_tpu/parallel/multihost.py)
        from dnn_tpu.parallel.multihost import initialize_from_config

        try:
            initialize_from_config(config.distributed, process_id=args.process_id)
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("distributed initialization failed: %s", e)
            return 1

    # --serve hosts ONE stage (the reference's per-node role): build the
    # engine in stage role so an 8-part config serves fine from a 1-device
    # host; full role only when this process drives the whole pipeline.
    import time as _time

    _BOOT["t_engine0"] = _time.monotonic()
    _BOOT["compile_at_engine0"] = _compile_total_s()
    try:
        engine = PipelineEngine(config, role="stage" if args.serve else "full",
                                lora_path=args.lora)
    except Exception as e:  # noqa: BLE001 — CLI boundary: checkpoint loads
        # raise FileNotFoundError/unpickling errors etc.; exit with a clean
        # one-liner like the reference does for every config problem
        # (node.py:296, 226-258) instead of a traceback.
        log.error("engine construction failed: %s", e)
        return 1
    _BOOT["engine_wall_s"] = _time.monotonic() - _BOOT["t_engine0"]
    _BOOT["compile_in_engine_s"] = max(
        0.0, _compile_total_s() - _BOOT["compile_at_engine0"])
    log.info(
        "node=%s part=%d/%d runtime=%s model=%s",
        me.id, me.part_index, config.num_parts - 1, engine.runtime, config.model,
    )
    if config.device_type == "cpu":
        import jax

        if jax.default_backend() != "cpu":
            # config update above came too late (backend was already up)
            log.warning(
                "device_type=cpu requested but backend is '%s' — the JAX "
                "backend was initialized before this CLI ran",
                jax.default_backend(),
            )

    if args.generate is None and (args.beam is not None
                                  or args.eos_id is not None
                                  or args.length_penalty != 0.0):
        log.error("--beam/--eos_id/--length_penalty only apply to "
                  "--generate; pass --generate N")
        return 1
    if args.generate is not None and args.beam is None and (
            args.eos_id is not None or args.length_penalty != 0.0):
        # the sampling decode path has no EOS/length-penalty support —
        # error rather than silently dropping the flags
        log.error("--eos_id/--length_penalty apply to beam search only; "
                  "pass --beam K alongside --generate")
        return 1
    if args.watchdog_s is not None and not args.serve_lm:
        log.error("--watchdog_s applies to --serve_lm only (the watchdog "
                  "monitors the LM daemon's decode loop)")
        return 1
    slo_objectives = any(v is not None for v in (
        args.slo_ttft_ms, args.slo_itl_ms, args.slo_avail))
    if (slo_objectives or args.slo_target is not None) \
            and not args.serve_lm:
        log.error("--slo_* flags apply to --serve_lm only (SLO tracking "
                  "lives on the LM daemon's request stream)")
        return 1
    if args.slo_target is not None and not slo_objectives:
        # a target without an objective would silently track nothing
        log.error("--slo_target needs at least one objective "
                  "(--slo_ttft_ms / --slo_itl_ms / --slo_avail)")
        return 1
    if args.fleet_port is not None and not (args.serve or args.serve_lm):
        log.error("--fleet_port applies to the serving modes; for a "
                  "standalone collector use `python -m dnn_tpu.obs "
                  "fleet --serve PORT`")
        return 1
    if (args.fleet_targets or args.fleet_interval is not None) \
            and args.fleet_port is None:
        # silent no-op would read as "the fleet view is live"
        log.error("--fleet_targets/--fleet_interval apply only with "
                  "--fleet_port")
        return 1
    fleet_srv = fleet_col = None
    if args.fleet_port is not None:
        # fleet collector riding this serving process (obs/fleet.py):
        # polls every stage's obs endpoint, serves the merged /fleetz
        from dnn_tpu import obs
        from dnn_tpu.obs.fleet import FleetCollector, targets_from_config

        try:
            if args.fleet_targets:
                targets = [u.strip() for u in args.fleet_targets.split(",")
                           if u.strip()]
            elif args.metrics_port:
                targets = targets_from_config(config, args.metrics_port)
            else:
                raise ValueError(
                    "--fleet_port needs --fleet_targets, or a nonzero "
                    "--metrics_port to derive them from the config")
            fleet_col = FleetCollector(
                targets,
                interval_s=args.fleet_interval
                if args.fleet_interval is not None else 5.0).start()
            fleet_srv = obs.serve_metrics(args.fleet_port,
                                          fleet=fleet_col)
            log.info("fleet collector on http://127.0.0.1:%d/fleetz "
                     "(%d stages)", fleet_srv.port,
                     len(fleet_col.targets))
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("fleet collector setup failed: %s", e)
            return 1
    if args.serve_adapter and not args.serve_lm:
        # per-request adapters exist only in the LM daemon's slot pool —
        # error rather than silently serving the base model
        log.error("--serve_adapter applies to --serve_lm only; to serve a "
                  "single merged fine-tune in other modes use --lora")
        return 1
    if (args.min_p is not None or args.repetition_penalty is not None) \
            and not args.serve_lm:
        log.error("--min_p/--repetition_penalty apply to --serve_lm only")
        return 1

    if args.on_wedged != "503" and not args.serve_lm:
        log.error("--on_wedged applies to --serve_lm (the watchdog's "
                  "escalation policy) or alongside --supervise")
        return 1
    if args.on_wedged != "503" and args.watchdog_s is None:
        log.error("--on_wedged %s needs --watchdog_s (the watchdog is "
                  "what declares wedged)", args.on_wedged)
        return 1
    if args.chaos is not None:
        if not (args.serve or args.serve_lm):
            log.error("--chaos applies to the serving modes (--serve / "
                      "--serve_lm)")
            return 1
        from dnn_tpu import chaos

        try:
            chaos.install(chaos.FaultPlan.from_cli(args.chaos))
            log.warning("chaos fault plan INSTALLED (%s) — injected "
                        "faults will be recorded as chaos_inject "
                        "flight events", args.chaos[:120])
        except (ValueError, OSError) as e:
            log.error("--chaos plan invalid: %s", e)
            return 1

    if args.transport is not None and not args.serve:
        # BEFORE the serve_lm dispatch: `--serve_lm --transport shm`
        # must fail loud here, not silently serve grpc (the LM daemon
        # declines negotiation — prompt payloads are bytes-tiny)
        log.error("--transport applies to --serve (the gRPC edge "
                  "deployment's inter-stage hops); the LM daemon and "
                  "single-controller runs do not negotiate hops")
        return 1

    if args.serve or args.serve_lm:
        # black box for the long-lived serving modes: an unhandled crash
        # dumps the flight-recorder ring to $DNN_TPU_OBS_DIR before the
        # process dies (dnn_tpu/obs/flight.py; idempotent with the
        # LMServer's own install)
        from dnn_tpu import obs

        if obs.enabled():
            obs.flight.install_crash_dump()

    if args.serve_lm:
        return _serve_lm(engine, args)

    if args.serve:
        from dnn_tpu.comm.service import serve_stage

        async def _run():
            tasks = [asyncio.create_task(serve_stage(
                engine, args.node_id, metrics_port=args.metrics_port,
                transport=args.transport))]
            if me.part_index == 0 and args.input_image:
                tasks.append(asyncio.create_task(
                    _initiate_edge(engine, args.node_id, args.input_image)
                ))
            await asyncio.gather(*tasks)

        try:
            asyncio.run(_run())
        except KeyboardInterrupt:
            log.info("shutting down")
        except Exception as e:  # noqa: BLE001 — CLI boundary: bind/address
            # failures exit with a clean one-liner (node.py:124-126), not a
            # traceback
            log.error("serve failed: %s", e)
            return 1
        return 0

    # single-controller mode
    if args.generate is not None:
        if config.distributed is not None and config.distributed.num_processes > 1:
            # the decode loop is a single-controller program for now; a
            # silently different behavior (image forward) would be worse
            # than an honest error
            log.error("--generate is not supported on multi-host runs yet")
            return 1
        return _generate_local(engine, args)

    if config.distributed is not None and config.distributed.num_processes > 1:
        # Multi-host SPMD: EVERY process must execute the same program — a
        # host that exits here would strand the others' collectives over
        # the global mesh. All hosts run the full pipeline on the same
        # input (the standard run-the-same-script-everywhere JAX pattern);
        # only process 0 announces the result.
        import jax

        # NOTE: every host must feed identical input (replicated SPMD
        # operand) — run this CLI with the same --input_image path on
        # shared storage, or no image at all (deterministic dummy).
        _initiate_local(engine, args.input_image,
                        announce=jax.process_index() == 0)
        return 0

    if args.input_image or me.part_index == 0:
        _initiate_local(engine, args.input_image)
    else:
        log.info("nothing to do for non-initiator node in single-controller mode "
                 "(use --serve for distributed edge mode)")
    return 0


def _route(args, config, me) -> int:
    """Front-door mode (dnn_tpu/control): serve the router on this
    node's port across already-running replicas (attach mode — nothing
    is spawned; `python -m dnn_tpu.control` owns the spawn-everything
    shape). SIGTERM drains and exits 0."""
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import serve_router

    if me.port is None:
        log.error("node '%s' has no IP:Port address in the config; the "
                  "router needs one to bind", args.node_id)
        return 1
    targets = [t.strip() for t in args.route_targets.split(",")
               if t.strip()]
    signals = [u.strip() for u in (args.route_signals or "").split(",")
               if u.strip()]
    if signals and len(signals) != len(targets):
        log.error("--route_signals must list one obs URL per "
                  "--route_targets entry (%d vs %d)", len(signals),
                  len(targets))
        return 1
    try:
        handles = [
            ReplicaHandle(f"r{i}", addr,
                          obs_url=signals[i] if signals else None)
            for i, addr in enumerate(targets)]
        rset = ReplicaSet(handles).start()
    except Exception as e:  # noqa: BLE001 — CLI boundary
        log.error("router setup failed: %s", e)
        return 1
    log.info("routing %d replicas (policy=%s, signals=%s)",
             len(targets), args.policy, "scraped" if signals else "local")
    try:
        return asyncio.run(serve_router(
            rset, port=me.port, metrics_port=args.metrics_port,
            policy=args.policy, kvtier=args.kvtier,
            # the directory must index at the REPLICAS' block
            # granularity or locate/pull truncate at the wrong depth
            kv_block_len=args.block_len))
    except KeyboardInterrupt:
        log.info("router shutting down")
        return 0
    except Exception as e:  # noqa: BLE001 — CLI boundary (bind etc.)
        log.error("router failed: %s", e)
        return 1
    finally:
        rset.stop()


def _supervise(args, raw_argv) -> int:
    """Supervisor-parent mode: spawn the SAME node command (minus
    --supervise) as a child and keep it alive — restart-with-backoff on
    death (including the deliberate EXIT_RESTART=43 a wedged-policy
    escalation uses), crash-loop cap, and — with --metrics_port — a
    fresh-connection /healthz poll that catches wedged-but-alive
    children and applies the --on_wedged policy from OUTSIDE the
    process (a hung process cannot run its own policy). Blocks until
    Ctrl-C; returns 1 when the child crash-loops."""
    import subprocess
    import time as _time

    from dnn_tpu.chaos.supervisor import Supervisor

    child_argv, skip = [], False
    for a in raw_argv:
        if skip:
            skip = False
            continue
        if a == "--supervise":
            continue
        if not args.serve_lm and (a == "--on_wedged"
                                  or a.startswith("--on_wedged=")):
            # the stage server has no in-process wedged policy; the
            # flag configures THIS supervisor only (both argparse
            # spellings: '--on_wedged restart' and '--on_wedged=restart')
            skip = a == "--on_wedged"
            continue
        child_argv.append(a)
    cmd = [sys.executable, "-m", "dnn_tpu.node"] + child_argv
    health = None
    if args.metrics_port:
        health = f"http://127.0.0.1:{args.metrics_port}"
    elif args.metrics_port == 0:
        log.warning("--supervise with --metrics_port 0 (ephemeral): "
                    "the supervisor cannot poll an unknown port — "
                    "wedged-but-alive children will not be detected")
    policy = {"503": "none", "restart": "restart",
              "drain": "drain"}[args.on_wedged]
    log.info("supervising: %s (health=%s, on_wedged=%s)",
             " ".join(cmd), health or "process-exit only", policy)
    sup = Supervisor(lambda: subprocess.Popen(cmd),
                     name=args.node_id, health_url=health,
                     on_wedged=policy,
                     health_interval_s=2.0, health_timeout_s=3.0,
                     ready_deadline_s=180.0)
    sup.start()
    try:
        while True:
            if sup.state == "crashloop":
                log.error("child crash-looped; giving up (see "
                          "crash_loop flight event)")
                return 1
            _time.sleep(1.0)
    except KeyboardInterrupt:
        log.info("supervisor shutting down")
        sup.stop()
        return 0


def _kv_dtype_arg(name):
    """--kv_dtype CLI spelling -> the batcher's kv_dtype spec: dtypes for
    the float widths, the codec strings for the quantized caches
    (runtime/generate.init_cache dispatches on exactly these)."""
    if name is None or name in ("int8", "int4"):
        return name
    import jax.numpy as jnp

    return {"f32": jnp.float32, "bf16": jnp.bfloat16}[name]


def _serve_lm(engine: PipelineEngine, args) -> int:
    """Long-lived LM daemon: the reference's defining serving-process shape
    (node.py:114-133) with the continuous batcher as the workload. Every
    GPT family serves; MoE plugs its routed FFN into the same pool."""
    from dnn_tpu.models.gpt import GPTConfig, prepare_stacked
    from dnn_tpu.models.gpt_moe import GPTMoEConfig
    from dnn_tpu.models.llama import LlamaConfig, LlamaFamilyRows
    from dnn_tpu.runtime.lm_server import serve_lm

    cfg = engine.spec.config
    ffn, family = None, None
    if isinstance(cfg, GPTMoEConfig):
        from dnn_tpu.runtime.generate_moe import moe_cache_ffn

        ffn = moe_cache_ffn(cfg, compute_dtype=engine.compute_dtype)
    elif isinstance(cfg, LlamaConfig):
        family = LlamaFamilyRows(cfg, compute_dtype=engine.compute_dtype)
    elif type(cfg) is not GPTConfig:
        log.error("--serve_lm requires a GPT-family model; '%s' (config %s) "
                  "is not one", engine.config.model, type(cfg).__name__)
        return 1
    me = engine.config.node_by_id(args.node_id)
    if me.port is None:
        log.error("node '%s' has no IP:Port address in the config; the LM "
                  "daemon needs one to bind", args.node_id)
        return 1
    tokenizer = None
    if args.tokenizer:
        # CLI boundary: a bad --tokenizer (vocab too small, missing HF
        # dir, vocab mismatch) exits with a clean one-liner, not a
        # traceback — same contract as every other config failure here
        try:
            if args.tokenizer == "bytes":
                from dnn_tpu.io.tokenizer import ByteTokenizer

                tokenizer = ByteTokenizer(cfg.vocab_size)
            else:
                from dnn_tpu.io.tokenizer import load_hf_tokenizer

                tokenizer = load_hf_tokenizer(args.tokenizer)
            tok_vocab = getattr(tokenizer, "vocab_size", None)
            if tok_vocab is not None and tok_vocab > cfg.vocab_size:
                raise ValueError(
                    f"tokenizer vocab {tok_vocab} exceeds the model's "
                    f"vocab_size {cfg.vocab_size} — out-of-range ids would "
                    f"gather garbage embeddings silently")
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("tokenizer setup failed: %s", e)
            return 1
    import time as _time

    _t_prep = _time.monotonic()
    _compile_at_prep = _compile_total_s()
    prepared = prepare_stacked(engine.params, cfg)
    _BOOT["prepare_wall_s"] = _time.monotonic() - _t_prep
    _BOOT["compile_in_prepare_s"] = max(
        0.0, _compile_total_s() - _compile_at_prep)
    lora_kwargs = {}
    if args.serve_adapter:
        from dnn_tpu.lora import adapters_to_stacked, load_lora

        try:
            ads, alphas = [], []
            for path in args.serve_adapter:
                ad, alpha = load_lora(path)
                if any(p.split("/")[0].startswith("h_") for p in ad):
                    # training layout -> the prepared serving layout
                    ad = adapters_to_stacked(ad, cfg.n_layer)
                ads.append(ad)
                alphas.append(alpha)
            lora_kwargs = {"lora_adapters": ads, "lora_alphas": alphas}
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("--serve_adapter setup failed: %s", e)
            return 1
    spec_kwargs = {}
    if args.draft_model:
        # speculative serving: load/init the draft family from the zoo
        import jax as _jax

        from dnn_tpu.registry import get_model

        try:
            d_spec = get_model(args.draft_model)
            d_cfg = d_spec.config
            if d_cfg is None or not isinstance(d_cfg, GPTConfig) or \
                    isinstance(d_cfg, GPTMoEConfig):
                raise ValueError(
                    f"--draft_model must name a dense GPT-family zoo "
                    f"entry, got '{args.draft_model}'")
            if d_cfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {d_cfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size}")
            if args.draft_weights:
                from dnn_tpu.io import checkpoint as ckpt

                sd = ckpt.load_checkpoint(args.draft_weights)
                if ckpt.is_native_flat(sd):
                    d_params = ckpt.flat_to_params(sd)
                elif d_spec.convert_state_dict is not None:
                    d_params = d_spec.convert_state_dict(sd)
                else:
                    raise ValueError(
                        f"draft checkpoint {args.draft_weights} is in a "
                        f"foreign layout and '{args.draft_model}' has no "
                        "converter")
            else:
                log.warning("no --draft_weights; draft uses random init "
                            "(wiring/testing only — a random draft "
                            "accepts ~nothing)")
                d_params = d_spec.init(_jax.random.PRNGKey(0))
            spec_kwargs = {
                "draft_cfg": d_cfg,
                "draft_prepared": prepare_stacked(d_params, d_cfg),
                "spec_k": args.spec_k,
            }
        except Exception as e:  # noqa: BLE001 — CLI boundary
            log.error("draft model setup failed: %s", e)
            return 1
    slo = None
    if any(v is not None for v in (args.slo_ttft_ms, args.slo_itl_ms,
                                   args.slo_avail)):
        from dnn_tpu.obs.goodput import SLOConfig

        slo = SLOConfig(
            ttft_s=args.slo_ttft_ms / 1e3
            if args.slo_ttft_ms is not None else None,
            inter_token_s=args.slo_itl_ms / 1e3
            if args.slo_itl_ms is not None else None,
            availability=args.slo_avail,
            target=args.slo_target
            if args.slo_target is not None else 0.99)
    if args.prefill_chunk_tokens or args.overlap:
        log.info("overlap/interleave serving enabled "
                 "(prefill_chunk_tokens=%d, overlap=%s): JSON-mode "
                 "constraints ride this hot path too (the grammar DFA "
                 "walks on device)",
                 args.prefill_chunk_tokens, args.overlap)
    # publish the boot gauges the caplens cold-start ledger scrapes:
    # each bucket is an independent child-side measurement (weight
    # spans subtract the compile seconds that landed inside them, so
    # compile stays its own bucket); the serve-bind span after this
    # point is deliberately UNattributed — coverage reports it
    from dnn_tpu import obs as _obs

    _m = _obs.metrics()
    if _m is not None:
        _imports = float(_BOOT.get("imports_s", 0.0))
        _weight = max(0.0, _BOOT.get("engine_wall_s", 0.0)
                      - _BOOT.get("compile_in_engine_s", 0.0)) \
            + max(0.0, _BOOT.get("prepare_wall_s", 0.0)
                  - _BOOT.get("compile_in_prepare_s", 0.0))
        _ready = _imports + (_time.monotonic()
                             - _BOOT.get("t_main", _time.monotonic()))
        _m.bulk(gauges={
            "dnn_tpu_boot_imports_seconds": round(_imports, 4),
            "dnn_tpu_boot_weight_load_seconds": round(_weight, 4),
            "dnn_tpu_boot_compile_preready_seconds":
                round(_compile_total_s(), 4),
            "dnn_tpu_boot_ready_total_seconds": round(_ready, 4),
        })
    try:
        rc = asyncio.run(serve_lm(
            cfg, prepared, port=me.port, slots=args.slots, slo=slo,
            on_wedged=args.on_wedged, role=args.role,
            **spec_kwargs,
            max_len=args.max_len, prompt_pad=args.prompt_pad,
            temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, min_p=args.min_p,
            repetition_penalty=args.repetition_penalty,
            compute_dtype=engine.compute_dtype, seed=args.seed, ffn=ffn,
            family=family, default_max_new=args.generate or 32,
            metrics_port=args.metrics_port,
            watchdog=args.watchdog_s,
            tokenizer=tokenizer, prefix_cache=args.prefix_cache,
            kv=args.kv, kv_dtype=_kv_dtype_arg(args.kv_dtype),
            paged_blocks=args.paged_blocks, block_len=args.block_len,
            decode_buckets=args.decode_buckets,
            weights=args.weights,
            kv_lease_ttl_s=args.kv_lease_ttl_s,
            kv_handoff_ttl_s=args.kv_handoff_ttl_s,
            prefill_chunk_tokens=args.prefill_chunk_tokens,
            overlap=args.overlap,
            # the daemon's clients choose options per request, so the
            # per-slot bias capability is on at this edge — except for
            # speculative serving, whose batcher rejects per-request
            # bias anyway (the buffer would be dead weight). Constraints
            # (JSON mode, j=) share the gate: on for every dense
            # configuration INCLUDING overlap/interleave (the grammar
            # DFA walks on device — serving.py), off only for
            # speculative serving, whose k-token verify the per-token
            # masks cannot gate (the batcher rejects constraint= loud).
            allow_logit_bias=not spec_kwargs,
            allow_constraints=not spec_kwargs,
            **lora_kwargs,
        ))
    except KeyboardInterrupt:
        log.info("shutting down")
        return 0
    except Exception as e:  # noqa: BLE001 — CLI boundary (bind failures etc.)
        log.error("LM serve failed: %s", e)
        return 1
    # EXIT_RESTART (43) from a wedged-policy escalation rides through to
    # the supervisor; 0 is a clean (drained) shutdown
    return rc or 0


def _generate_local(engine: PipelineEngine, args) -> int:
    """CLI decode mode: prompt ids -> N generated tokens, pipeline-parallel
    when the engine runs spmd (the serving capability the reference's GPT
    partitions lack — one stateless forward is all they can do,
    gpt_model_parts.py:36-50)."""
    import jax

    if args.prompt_ids:
        try:
            ids = [int(s) for s in args.prompt_ids.split(",") if s.strip()]
        except ValueError:
            log.error("--prompt_ids must be comma-separated integers, got %r",
                      args.prompt_ids)
            return 1
        if not ids:
            log.error("--prompt_ids contained no token ids: %r", args.prompt_ids)
            return 1
    else:
        ids = [0]
    try:
        if args.beam is not None:
            # any explicit --beam takes the deterministic path (beam 1 ==
            # greedy; invalid K surfaces beam.py's own validation)
            toks = engine.generate_beam(
                np.asarray([ids], np.int32),
                max_new_tokens=args.generate,
                beam_size=args.beam,
                eos_id=args.eos_id,
                length_penalty=args.length_penalty,
            )
        else:
            toks = engine.generate(
                np.asarray([ids], np.int32),
                max_new_tokens=args.generate,
                temperature=args.temperature,
                top_k=args.top_k,
                top_p=args.top_p,
                rng=jax.random.PRNGKey(args.seed),
            )
    except (ValueError, RuntimeError) as e:
        log.error("generation failed: %s", e)
        return 1
    out = ",".join(str(int(t)) for t in np.asarray(toks)[0])
    print(f"***** GENERATED TOKENS: {out} *****")
    return 0


if __name__ == "__main__":
    sys.exit(main())
