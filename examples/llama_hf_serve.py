"""HF checkpoint -> LLaMA LM daemon -> concurrent clients, end to end.

The modern-LM analog of the reference's "trained .pth -> nodes -> answer"
loop (/root/reference/node.py:137-200): take a HuggingFace
LlamaForCausalLM checkpoint (any size whose shapes match a preset — here
a tiny random-init model so the example runs offline), convert it
torch-free, start the continuous-batching LM daemon on the wire protocol
a reference node speaks, and drive it with concurrent clients.

  1. BUILD or LOAD a LlamaForCausalLM state dict (.pth). With
     --hf-checkpoint, any torch-saved LLaMA state dict whose shapes match
     --preset is used; otherwise a tiny random-init model is synthesized
     (transformers is installed; no network needed).
  2. CONVERT via io.checkpoint.llama_params_from_state_dict (zip+pickle
     parser, no torch import on the serving side) and verify logit parity
     against the torch model when it is available.
  3. SERVE: `--serve_lm`-equivalent daemon in-process
     (runtime/lm_server.start_lm_server_in_background) with the LLaMA
     family adapter — GQA KV-head-width cache, RoPE per slot position.
  4. GENERATE from several concurrent clients (NodeClient.generate);
     greedy outputs are checked token-for-token against the solo decoder.

Run:  python examples/llama_hf_serve.py [--preset llama-test] [--port 59301]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def make_tiny_checkpoint(path: str, cfg) -> None:
    """Torch-save a random-init HF LlamaForCausalLM matching `cfg`."""
    import torch
    import transformers

    from dnn_tpu.models.llama import to_hf_config

    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(to_hf_config(cfg)).eval()
    torch.save(model.state_dict(), path)
    print(f"[1] synthesized random-init HF checkpoint -> {path}")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="llama-test",
                    help="llama preset the checkpoint shapes must match")
    ap.add_argument("--hf-checkpoint", default=None,
                    help="torch-saved LlamaForCausalLM state dict (.pth); "
                         "default: synthesize a tiny random-init one")
    ap.add_argument("--port", type=int, default=59301)
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--max-new", type=int, default=8)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (needed where the "
                         "accelerator plugin is unavailable)")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict, load_checkpoint
    from dnn_tpu.models import gpt, llama
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    cfg = llama.PRESETS[args.preset]
    ckpt = args.hf_checkpoint
    if ckpt is None:
        ckpt = os.path.join(tempfile.mkdtemp(prefix="llama_hf_"), "model.pth")
        make_tiny_checkpoint(ckpt, cfg)

    # 2. torch-free conversion, with logit parity vs torch when available
    params = llama_params_from_state_dict(load_checkpoint(ckpt))
    prepared = gpt.prepare_stacked(params, cfg)
    print(f"[2] converted {ckpt} -> {cfg.n_layer}-layer LLaMA "
          f"(H={cfg.n_head}, KV={cfg.n_kv_head})")
    try:
        import torch
        import transformers
    except ImportError:
        print("[2] torch/transformers unavailable; skipping parity check")
    else:
        sd = torch.load(ckpt, map_location="cpu")
        # mirror the checkpoint's own tying (TinyLlama/LLaMA-3.2 ship no
        # lm_head.weight; the converter falls back to the tied embedding)
        tie = "lm_head.weight" not in sd
        hf = transformers.LlamaForCausalLM(llama.to_hf_config(
            cfg, tie_word_embeddings=tie,
            attn_implementation="eager")).eval()
        # strict=False: extra buffers (old-transformers inv_freq etc.)
        # must not kill an optional sanity check
        hf.load_state_dict(sd, strict=False)
        probe = np.arange(1, 9, dtype=np.int64)[None] % cfg.vocab_size
        with torch.no_grad():
            want = hf(torch.from_numpy(probe)).logits.numpy()
        got = np.asarray(llama.make_apply(cfg)(
            params, jnp.asarray(probe, jnp.int32)))
        np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
        assert (got.argmax(-1) == want.argmax(-1)).all()
        print("[2] conversion logit-parity vs torch OK "
              f"(max diff {np.abs(got - want).max():.2e})")

    # 3. daemon with the LLaMA family adapter
    _t, stop = start_lm_server_in_background(
        cfg, prepared, port=args.port, slots=args.slots,
        max_len=min(64, cfg.block_size), prompt_pad=16,
        family=llama.LlamaFamilyRows(cfg), default_max_new=args.max_new)
    print(f"[3] LM daemon on :{args.port} ({args.slots} slots)")

    try:
        prompts = [np.array(p, np.int32) for p in
                   ([1, 2, 3, 4], [9, 8, 7], [5, 6])]
        results = [None] * len(prompts)

        def call(i):
            c = NodeClient(f"127.0.0.1:{args.port}")
            results[i] = c.generate(prompts[i], max_new_tokens=args.max_new)
            c.close()

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)

        solo = llama.make_generate(cfg, max_new_tokens=args.max_new)
        for i, p in enumerate(prompts):
            want = np.asarray(solo(prepared, p[None, :].astype(np.int32),
                                   jax.random.PRNGKey(0)))[0]
            assert results[i] is not None, f"request {i} hung"
            assert (results[i] == want).all(), (
                f"daemon tokens != solo decode for prompt {i}")
            print(f"[4] prompt {p.tolist()} -> {results[i].tolist()} "
                  f"(== solo decode)")
        print("DONE: concurrent daemon generation token-matches the solo "
              "decoder on converted HF weights")
    finally:
        stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
