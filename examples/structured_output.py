"""Structured output + embeddings, end to end on a tiny random model.

Three modern serving patterns the reference framework cannot express
(its one RPC returns a single forward's tensor, node.py:35-105):

  1. JSON mode — a grammar forces syntactically valid JSON from ANY
     model, even an untrained one;
  2. enum choice — classification by constrained generation ("answer
     with exactly one of these labels");
  3. embeddings — pooled hidden states for retrieval/similarity.

Run: python examples/structured_output.py   (CPU-safe, ~1 min)
"""

import json
import os
import sys

import jax
import numpy as np

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from dnn_tpu.models import gpt, llama
from dnn_tpu.runtime.constrain import (
    TokenConstraint,
    byte_vocab,
    choice_regex,
)
from dnn_tpu.runtime.embeddings import make_embed
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = llama.PRESETS["llama-test"]  # V=256: token id == byte


def main():
    params = llama.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    srv = ContinuousBatcher(
        CFG, prepared, slots=2, max_len=CFG.block_size, prompt_pad=8,
        family=llama.LlamaFamilyRows(CFG), allow_constraints=True,
        temperature=1.0)
    vocab = byte_vocab(CFG.vocab_size)

    # 1. JSON mode: a schema-shaped regex
    schema = r"\{\"label\": \"[a-z]{3,8}\", \"confidence\": 0\.[0-9]{2}\}"
    c_json = TokenConstraint.from_regex(schema, vocab)
    rid = srv.submit(np.asarray([72, 105]), max_new_tokens=48, seed=1,
                     constraint=c_json)
    srv.drain()
    text = bytes(int(t) for t in srv.results[rid]).decode()
    print("JSON mode:   ", text, "->", json.loads(text))

    # 2. enum choice: constrained classification
    labels = ["positive", "negative", "neutral"]
    c_enum = TokenConstraint.from_regex(choice_regex(labels), vocab)
    rid = srv.submit(np.asarray([34, 56, 78]), max_new_tokens=16, seed=2,
                     constraint=c_enum)
    srv.drain()
    picked = bytes(int(t) for t in srv.results[rid]).decode()
    assert picked in labels
    print("enum choice: ", picked)

    # 3. embeddings: cosine similarity of pooled hidden states
    embed = make_embed(CFG, pooling="mean")
    docs = [b"the cat sat on the mat", b"a cat on a mat", b"tax law 2026"]
    ids = np.zeros((3, 24), np.int32)
    lengths = np.zeros((3,), np.int32)
    for i, d in enumerate(docs):
        ids[i, :len(d)] = list(d)
        lengths[i] = len(d)
    vecs = np.array(embed(prepared, ids, lengths))  # writable copy
    vecs /= np.linalg.norm(vecs, axis=1, keepdims=True)
    sim = vecs @ vecs.T
    print("similarity:  ", {f"{i}-{j}": round(float(sim[i, j]), 3)
                            for i in range(3) for j in range(i + 1, 3)})


if __name__ == "__main__":
    main()
