"""Flagship end-to-end example: train -> checkpoint -> serve -> interop.

The reference's whole demo is "trained .pth -> split across nodes -> image
in -> class out" (/root/reference/node.py:137-200, 294-325) — but its
trained weights were stripped from the mirror and it cannot train new ones
(inference-only, readme.md:112). This script performs the complete loop the
reference only implies, TPU-first:

  1. TRAIN the CIFAR CNN (dnn_tpu/models/cifar.py) with the generic train
     step on the default backend (the real TPU chip when present);
  2. EVALUATE test accuracy;
  3. SAVE a native .npz checkpoint AND EXPORT a torch-layout
     `cifar10_model.pth` — re-supplying the reference's missing blob with
     weights its unmodified loader accepts (tests/test_interop_reference.py
     proves a real reference node serves them);
  4. SERVE the trained model through the 2-stage pipeline via the same CLI
     and config schema the reference uses, on a real PNG image, and check
     the pipeline prediction against the single-program forward.

Data: point --data-dir at standard CIFAR-10 binaries (data_batch_*.bin /
test_batch.bin) for the real dataset. Without it (this sandbox has no
network), a deterministic procedurally-generated stand-in dataset with the
same format/shapes is synthesized — learnable class structure, so training
demonstrably works (accuracy far above the 10% chance floor), while the
pipeline is byte-for-byte the one real data flows through.

Run:  python examples/train_cifar_serve.py --steps 300 --out-dir /tmp/cifar_run
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np


def synth_cifar(n: int, *, seed: int = 0):
    """Deterministic CIFAR-shaped dataset with learnable class structure:
    each class is a FIXED random 32x32x3 template (shared by every split —
    that's what makes train->test generalization possible) plus
    per-sample noise drawn from `seed`."""
    templates = np.random.default_rng(1234).integers(40, 216, (10, 32, 32, 3))
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, n).astype(np.uint8)
    noise = rng.normal(0.0, 40.0, (n, 32, 32, 3))
    images = np.clip(templates[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels


def ensure_data(data_dir: str | None, out_dir: str, *, n_train=4096, n_test=512):
    """Return (train_files, test_file); synthesize the stand-in set when no
    real CIFAR-10 binaries are supplied."""
    from dnn_tpu.data.cifar_binary import write_cifar_binary

    if data_dir:
        train = sorted(
            os.path.join(data_dir, f) for f in os.listdir(data_dir)
            if f.startswith("data_batch") and f.endswith(".bin")
        )
        test = os.path.join(data_dir, "test_batch.bin")
        if train and os.path.exists(test):
            return train, test
        raise FileNotFoundError(f"no CIFAR binaries under {data_dir}")

    os.makedirs(out_dir, exist_ok=True)
    train_path = os.path.join(out_dir, "synth_train.bin")
    test_path = os.path.join(out_dir, "synth_test.bin")
    if not (os.path.exists(train_path) and os.path.exists(test_path)):
        xi, yi = synth_cifar(n_train, seed=0)
        write_cifar_binary(train_path, xi, yi)
        xt, yt = synth_cifar(n_test, seed=1)
        write_cifar_binary(test_path, xt, yt)
    return [train_path], test_path


def train(train_files, *, steps: int, batch_size: int = 128, lr: float = 1e-3,
          seed: int = 0, log_every: int = 50):
    """Train the CIFAR CNN; returns (params, last_loss)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu.data import AsyncCifarLoader, prefetch_to_device
    from dnn_tpu.models import cifar
    from dnn_tpu.train import fit, make_train_step

    # native C++ background-thread decode when available (falls back to the
    # in-thread Python decoder transparently)
    loader = AsyncCifarLoader(train_files, batch_size, seed=seed)
    params = cifar.init(jax.random.PRNGKey(seed))

    def loss_fn(p, batch):
        x, y = batch
        probs = cifar.apply(p, x)  # reference semantics: softmax output
        logp = jnp.log(jnp.clip(probs, 1e-9))
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    opt = optax.adam(lr)
    raw_step = make_train_step(loss_fn, opt)

    def step_fn(state, batch):
        p, o = state
        p, o, loss = raw_step(p, o, batch)
        return (p, o), loss

    def on_step(s, loss):
        if log_every and s % log_every == 0:
            print(f"  step {s}/{steps}  loss {float(loss):.4f}")

    with loader:
        batches = prefetch_to_device(loader, size=2)
        (params, _), loss = fit(step_fn, (params, opt.init(params)), batches,
                                num_steps=steps, on_step=on_step)
    return params, float(loss)


def evaluate(params, test_file, *, batch_size: int = 256) -> float:
    import jax

    from dnn_tpu.data import CifarBinaryDataset
    from dnn_tpu.models import cifar

    ds = CifarBinaryDataset([test_file])
    apply_jit = jax.jit(cifar.apply)
    correct = total = 0
    for x, y in ds.batches(min(batch_size, len(ds)), shuffle=False, epochs=1,
                           drop_remainder=False):
        pred = np.argmax(np.asarray(apply_jit(params, x)), axis=1)
        correct += int((pred == y).sum())
        total += len(y)
    return correct / total


def export(params, out_dir: str):
    """Native .npz + reference-format .pth. Returns (npz_path, pth_path)."""
    from dnn_tpu.io.checkpoint import params_to_flat, save_npz
    from dnn_tpu.io.torch_export import cifar_state_dict_from_params, save_pth

    os.makedirs(out_dir, exist_ok=True)
    npz_path = os.path.join(out_dir, "cifar_cnn.npz")
    pth_path = os.path.join(out_dir, "cifar10_model.pth")
    save_npz(npz_path, params_to_flat(params))
    save_pth(pth_path, cifar_state_dict_from_params(params))
    return npz_path, pth_path


def serve_and_check(npz_path: str, out_dir: str, test_file: str) -> int:
    """Serve the trained checkpoint through the 2-stage pipeline CLI on a
    real PNG image; assert the pipeline prediction matches the
    single-program forward. Returns the predicted class."""
    import jax
    from PIL import Image

    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.data.cifar_binary import CifarBinaryDataset
    from dnn_tpu.models import cifar
    from dnn_tpu.node import main as node_main
    from dnn_tpu.runtime.engine import PipelineEngine

    # a real image file, through the same PIL path a user's photo takes
    ds = CifarBinaryDataset([test_file])
    recs = ds.decode([0])
    img_u8 = ((recs[0][0] * 0.5 + 0.5) * 255).clip(0, 255).astype(np.uint8)
    img_path = os.path.join(out_dir, "sample.png")
    Image.fromarray(img_u8).save(img_path)

    cfg = {
        "nodes": [
            {"id": "node0", "address": "127.0.0.1:51000", "part_index": 0},
            {"id": "node1", "address": "127.0.0.1:51001", "part_index": 1},
        ],
        "model_weights": npz_path,
        "num_parts": 2,
        "return_to_node_id": "node0",
    }
    cfg_path = os.path.join(out_dir, "config.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg, f, indent=2)

    rc = node_main(["--node_id", "node0", "--config", cfg_path,
                    "--input_image", img_path])
    assert rc == 0, "pipeline CLI failed"

    # cross-check: same image through the un-partitioned model
    engine = PipelineEngine(TopologyConfig.from_json(cfg_path))
    from dnn_tpu.io.preprocess import load_image_or_dummy

    x, used_dummy = load_image_or_dummy(img_path)
    assert not used_dummy
    direct = int(np.argmax(np.asarray(cifar.apply(engine.params, x))))
    pipeline_pred = engine.predict(x)
    assert pipeline_pred == direct, (pipeline_pred, direct)
    return pipeline_pred


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--data-dir", default=None,
                   help="directory with real CIFAR-10 binaries (optional)")
    p.add_argument("--out-dir", default="/tmp/cifar_run")
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=1e-3)
    args = p.parse_args(argv)

    import jax

    print(f"[1/4] data ({'real' if args.data_dir else 'synthesized'}), "
          f"backend={jax.default_backend()}")
    train_files, test_file = ensure_data(args.data_dir, args.out_dir)

    print(f"[2/4] training {args.steps} steps...")
    params, loss = train(train_files, steps=args.steps,
                         batch_size=args.batch_size, lr=args.lr)
    acc = evaluate(params, test_file)
    print(f"      final loss {loss:.4f}, test accuracy {acc:.1%} "
          f"(chance = 10.0%)")

    print("[3/4] exporting checkpoints...")
    npz_path, pth_path = export(params, args.out_dir)
    print(f"      native: {npz_path}\n      torch  : {pth_path} "
          "(loadable by an unmodified reference node)")

    print("[4/4] serving through the 2-stage pipeline CLI...")
    pred = serve_and_check(npz_path, args.out_dir, test_file)
    print(f"      pipeline prediction for sample.png: class {pred} "
          "(matches single-program forward)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
