"""Multi-LoRA end-to-end example: fine-tune N adapters on one base, serve
them all from ONE pool with per-request selection.

The reference has no fine-tuning and serves exactly one model per process
(/root/reference/node.py:294-325 loads a single .pth). This script runs
the modern multi-tenant loop the rebuild supports, TPU-first:

  1. INIT a small GPT base (random weights stand in for a pretrained
     checkpoint — no network in this sandbox);
  2. FINE-TUNE two LoRA adapters on two synthetic "tenant tasks" (task A:
     always continue with token sequence A; task B: with sequence B) —
     only the adapter trees train (`lora.make_lora_loss`), the base stays
     frozen;
  3. SAVE both adapters as npz artifacts (`lora.save_lora`) — the only
     thing a fine-tune ships;
  4. SERVE base + both adapters from one ContinuousBatcher
     (`lora_adapters=[...]`): requests pick an adapter per call, streams
     decode CONCURRENTLY in the same slot pool, and each adapted stream
     provably behaves like its tenant's fine-tune while the base stream
     stays untouched.

Run:  python examples/multi_adapter_serve.py
"""

from __future__ import annotations

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dnn_tpu import lora, train
from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]
PROMPT = np.array([11, 12, 13, 14], np.int32)


def tenant_batch(target_token: int, *, batch: int = 8, seed: int = 0):
    """A tenant's 'task': whatever the prompt, continue with its token."""
    rng = np.random.RandomState(seed)
    inp = rng.randint(0, CFG.vocab_size, (batch, 12)).astype(np.int32)
    tgt = np.full_like(inp, target_token)
    return jnp.asarray(inp), jnp.asarray(tgt)


def finetune_adapter(prepared, apply_fn, target_token: int, *, steps=60,
                     rank=8, seed=0):
    """LoRA-only training: the optimizer sees the adapter tree alone."""
    adapters = lora.init_lora(jax.random.PRNGKey(seed), prepared, rank=rank)

    def loss_fn(params, batch):
        inp, tgt = batch
        return train.cross_entropy(apply_fn(params, inp), tgt)

    lora_loss = lora.make_lora_loss(loss_fn, prepared)
    opt = optax.adamw(3e-3)
    step = train.make_train_step(lora_loss, opt)
    state = opt.init(adapters)
    for i in range(steps):
        adapters, state, loss = step(
            adapters, state, tenant_batch(target_token, seed=seed * 1000 + i))
    print(f"  tenant token {target_token}: final loss {float(loss):.4f}")
    return adapters


def main():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    apply_fn = gpt.make_apply_stacked(CFG)

    print("[1] fine-tuning two tenant adapters (base frozen)...")
    ad_a = finetune_adapter(prepared, apply_fn, target_token=42, seed=1)
    ad_b = finetune_adapter(prepared, apply_fn, target_token=99, seed=2)

    out_dir = tempfile.mkdtemp(prefix="multi_adapter_")
    pa, pb = os.path.join(out_dir, "a.npz"), os.path.join(out_dir, "b.npz")
    lora.save_lora(pa, ad_a)
    lora.save_lora(pb, ad_b)
    print(f"[2] adapters saved: {pa}, {pb}")

    loaded = [lora.load_lora(p)[0] for p in (pa, pb)]
    srv = ContinuousBatcher(CFG, prepared, slots=3, max_len=32,
                            prompt_pad=8, lora_adapters=loaded)
    r_a = srv.submit(PROMPT, max_new_tokens=6, adapter=0)
    r_b = srv.submit(PROMPT, max_new_tokens=6, adapter=1)
    r_base = srv.submit(PROMPT, max_new_tokens=6)
    res = srv.drain()
    print(f"[3] one pool, three tenants, same prompt {PROMPT.tolist()}:")
    print(f"    adapter A -> {res[r_a].tolist()}  (trained toward 42)")
    print(f"    adapter B -> {res[r_b].tolist()}  (trained toward 99)")
    print(f"    base      -> {res[r_base].tolist()}")

    assert (res[r_a] == 42).all(), "tenant A's fine-tune should dominate"
    assert (res[r_b] == 99).all(), "tenant B's fine-tune should dominate"
    assert not (res[r_base] == 42).any() and not (res[r_base] == 99).any(), \
        "the base stream must not inherit any tenant's tuning"
    print("[4] per-request isolation holds: each stream follows ITS adapter")


if __name__ == "__main__":
    main()
