"""caplens tests (ISSUE 20): the capacity observatory.

The acceptance contract this module pins: the what-if planner's
discrete-event replay is a hand-checkable golden under an injected
clock (1 shed-bound replica -> 0.50 availability, a warm pair -> 1.0)
and bit-identical across lens instances (same ring + reservoir + seed),
a cold replica prices its spawn->first-token debt into the verdict,
the demand window's arithmetic (rate, dispersion, change-point) is
exact on planted arrivals, the cold-start ledger attributes the
spawn->first-token wall into process-start/weight-load/compile/warmup
buckets off the child's boot gauges (with the settle_s deferral that
lets the fleet scrape flush the compile counter), every
wanted-replicas transition lands in the audit trail with its full
decision inputs, queued commits stay OUT of the planning reservoir,
the obs gate makes every producer a no-op, /capz serves JSON and
Prometheus text, the `python -m dnn_tpu.obs caplens --selftest` CLI
smoke passes — and the /fleetz wanted-replicas rollup is the explicit
MAX across stages with a per-stage column (the "first non-None"
regression this PR fixed)."""

import json
import subprocess
import sys
import urllib.request

import pytest

from dnn_tpu import obs
from dnn_tpu.obs.caplens import MIN_RING, CapLens, CapSLO


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _clock():
    t = [0.0]
    return t, (lambda: t[0])


def _shed_bound_lens(clock, **kw):
    """The selftest's golden regime: 1 slot, in-system bound 1,
    service 0.5 s, arrivals every 0.25 s — every other arrival finds
    the single slot busy and sheds, so plan(1) is exactly 0.50."""
    kw.setdefault("slots_per_replica", 1)
    kw.setdefault("max_inflight", 1)
    kw.setdefault("deadline_s", 2.0)
    kw.setdefault("window_s", 60.0)
    kw.setdefault("slo", CapSLO(availability=0.9))
    lens = CapLens(now=clock, **kw)
    for i in range(20):
        lens.on_arrival(8, scenario="gen", now=i * 0.25)
    for i in range(10):
        lens.on_commit("r0", role="both", tokens=4, wall_s=0.5,
                       inflight_at_dispatch=0, now=i * 0.5)
    return lens


# ----------------------------------------------------------------------
# the planner: replay goldens, determinism, cold-start debt
# ----------------------------------------------------------------------

def test_plan_golden_shed_bound():
    t, clock = _clock()
    lens = _shed_bound_lens(clock)
    p1 = lens.plan(1)
    # arrivals at 0, .25, .5, ...; service exactly .5: the in-flight
    # request finishes AT the next even arrival (active[0] <= t pops
    # it), so evens admit and odds shed — half and half, no queueing
    assert p1["availability"] == 0.5
    assert p1["shed_frac"] == 0.5
    assert p1["wait_p95_s"] == 0.0
    assert p1["ttft_p95_s"] == 0.5
    assert p1["deadline_frac"] == 0.0
    # a warm pair has a slot free at every arrival: nothing sheds
    p2 = lens.plan(2, warm=2)
    assert p2["availability"] == 1.0 and p2["shed_frac"] == 0.0


def test_plan_is_deterministic_across_instances():
    t1, c1 = _clock()
    t2, c2 = _clock()
    a, b = _shed_bound_lens(c1), _shed_bound_lens(c2)
    assert a.plan(1) == b.plan(1)
    assert a.plan(2, warm=2) == b.plan(2, warm=2)
    assert a.plan(3, warm=1) == b.plan(3, warm=1)


def test_plan_refuses_below_evidence_floor():
    t, clock = _clock()
    lens = CapLens(now=clock)
    for i in range(MIN_RING - 1):
        lens.on_arrival(4, now=float(i))
    lens.on_commit("r0", wall_s=0.1, now=1.0)
    assert lens.plan(1) is None
    # the caller's contract: no evidence -> defer to the v1 heuristic
    assert lens.wanted_replicas(n_live=1) is None


def test_cold_replica_pays_coldstart_debt():
    t, clock = _clock()
    lens = _shed_bound_lens(clock, coldstart_default_s=3.0)
    warm = lens.plan(2, warm=2)
    cold = lens.plan(2, warm=1)
    assert cold["cold"] == 1
    assert cold["coldstart_debt_s"] == 3.0
    # the cold replica's slot is unavailable for the first 3 s of the
    # 5 s trace: sheds resume there, availability must drop
    assert cold["availability"] < warm["availability"]


# ----------------------------------------------------------------------
# demand window arithmetic
# ----------------------------------------------------------------------

def test_demand_window_arithmetic():
    t, clock = _clock()
    lens = _shed_bound_lens(clock)
    t[0] = 20 * 0.25
    d = lens.demand()
    assert d["arrivals"] == 20 and d["arrivals_total"] == 20
    assert d["rate_hz"] == pytest.approx(20 / 60.0, abs=1e-4)
    assert d["prefill_tokens_per_s"] == pytest.approx(160 / 60.0,
                                                      abs=0.01)
    assert d["scenarios"] == {"gen": {"count": 20,
                                      "prefill_tokens": 160}}
    # evenly spaced arrivals: no change point, near-uniform buckets
    assert d["change_point"] is False
    assert d["index_of_dispersion"] is not None
    # arrivals older than window_s age out of the window (totals stay)
    t[0] = 100.0
    d2 = lens.demand()
    assert d2["arrivals"] == 0 and d2["arrivals_total"] == 20


def test_demand_change_point_fires_on_rate_shift():
    t, clock = _clock()
    lens = CapLens(now=clock, window_s=60.0)
    for i in range(5):                  # sparse early half
        lens.on_arrival(1, now=float(i))
    for i in range(30):                 # heavy late half
        lens.on_arrival(1, now=6.0 + i * 0.1)
    t[0] = 9.0
    d = lens.demand()
    assert d["change_point"] is True
    assert d["rate_ratio_recent"] > 2.0
    assert d["peak_to_mean"] > 1.0


# ----------------------------------------------------------------------
# cold-start ledger: bucket attribution off the boot gauges
# ----------------------------------------------------------------------

_SIGNALS = {"boot_imports_s": 3.0, "boot_weight_load_s": 1.0,
            "compile_seconds_total": 2.5,
            "boot_compile_preready_s": 0.5,
            "boot_ready_total_s": 4.5}


def test_coldstart_bucket_attribution():
    t, clock = _clock()
    lens = CapLens(now=clock, settle_s=1.0,
                   signals=lambda name: dict(_SIGNALS))
    lens.spawn_begin("r0", "both", now=0.0)
    lens.spawn_ready("r0", now=5.0)
    lens.on_commit("r0", wall_s=0.4, now=10.0)  # first token
    t[0] = 12.0  # past settle_s
    cs = lens.coldstart()
    assert cs["finalized"] == 1 and cs["pending"] == 0
    e = cs["entries"][0]
    assert e["total_s"] == 10.0
    assert e["spawn_to_ready_s"] == 5.0
    assert e["buckets"] == {"process_start_s": 3.0,
                            "weight_load_s": 1.0,
                            "compile_s": 2.5,
                            # total - ready_total - post-ready compile
                            # = 10 - 4.5 - (2.5 - 0.5)
                            "warmup_s": 3.5}
    assert e["coverage"] == 1.0
    assert lens.coldstart_delay_s() == 10.0  # the planner's p50 price
    kinds = [ev["kind"] for ev in lens.ledger.events()]
    assert kinds == ["spawn_begin", "spawn_ready", "coldstart"]


def test_coldstart_settle_defers_finalize():
    t, clock = _clock()
    lens = CapLens(now=clock, settle_s=1.0,
                   signals=lambda name: dict(_SIGNALS))
    lens.spawn_begin("r0", now=0.0)
    lens.on_commit("r0", wall_s=0.1, now=10.0)
    t[0] = 10.5  # first token seen, but the scrape hasn't settled
    assert lens.coldstart()["finalized"] == 0
    assert lens.coldstart()["pending"] == 1
    t[0] = 11.5
    assert lens.coldstart()["finalized"] == 1


def test_spawn_gone_abandons_unserved_spawn():
    t, clock = _clock()
    lens = CapLens(now=clock)
    lens.spawn_begin("r0", now=0.0)
    lens.spawn_gone("r0")
    t[0] = 100.0
    cs = lens.coldstart()
    assert cs["finalized"] == 0 and cs["pending"] == 0
    assert cs["spawns"] == 1
    assert [ev["kind"] for ev in lens.ledger.events()] \
        == ["spawn_begin", "spawn_abandoned"]


# ----------------------------------------------------------------------
# wanted_replicas: audit-trailed transitions, replan cache
# ----------------------------------------------------------------------

def test_wanted_replicas_audit_trail():
    t, clock = _clock()
    lens = _shed_bound_lens(clock)  # SLO avail 0.9: needs the pair
    w = lens.wanted_replicas(n_live=2)
    assert w == 2
    assert len(lens._audit) == 1
    a = lens._audit[-1]
    assert a["from"] is None and a["to"] == 2
    assert a["n_live"] == 2 and a["slo_unmet"] is False
    # the decision carries its full inputs: every candidate's plan
    # with the SLO verdict and margin, plus demand + capacity
    assert a["plans"][0]["n"] == 1
    assert a["plans"][0]["meets_slo"] is False
    assert a["plans"][1]["meets_slo"] is True
    assert "availability_margin" in a["plans"][0]
    assert a["demand"]["arrivals_total"] == 20
    # inside replan_interval_s the cached verdict answers: no new entry
    assert lens.wanted_replicas(n_live=2) == 2
    assert len(lens._audit) == 1
    # a stable verdict past the interval re-plans but does not re-audit
    t[0] = 10.0
    assert lens.wanted_replicas(n_live=2) == 2
    assert len(lens._audit) == 1
    kinds = [e["kind"] for e in lens.ledger.events(
        kind="caplens_decision")]
    assert kinds == ["caplens_decision"]


def test_queued_commit_stays_out_of_planning_reservoir():
    t, clock = _clock()
    lens = _shed_bound_lens(clock)
    p1 = lens.plan(1)
    # a commit whose wall includes replica-internal queueing (no free
    # slot at dispatch) must not poison the service sample the sim
    # draws from — the sim already simulates that queue
    lens.on_commit("r0", wall_s=3.0, inflight_at_dispatch=5, now=6.0)
    assert lens._queued_commits == 1
    assert lens.plan(1) == p1
    assert lens.capacity()["queued_commits_excluded"] == 1


# ----------------------------------------------------------------------
# gate, /capz endpoint, CLI
# ----------------------------------------------------------------------

def test_gate_off_records_nothing():
    t, clock = _clock()
    lens = CapLens(now=clock)
    obs.set_enabled(False)
    try:
        lens.on_arrival(8, scenario="gen")
        lens.on_shed("saturated")
        lens.on_commit("r0", tokens=4, wall_s=0.5)
        lens.spawn_begin("r0")
        lens.spawn_ready("r0")
        lens.spawn_gone("r0")
    finally:
        obs.set_enabled(True)
    assert lens.arrivals_total == 0 and lens.commits_total == 0
    assert lens.sheds_by_reason == {} and lens.spawns_total == 0
    assert not lens._pending and len(lens.ledger) == 0


def test_capz_endpoint_json_and_prom():
    t, clock = _clock()
    lens = _shed_bound_lens(clock)
    lens.wanted_replicas(n_live=2)
    srv = obs.serve_metrics(0, caplens=lens)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        r = urllib.request.urlopen(base + "/capz", timeout=10)
        assert r.headers["Content-Type"] == "application/json"
        z = json.load(r)
        assert z["demand"]["arrivals_total"] == 20
        assert z["wanted_replicas"] == 2
        assert z["plans"][0]["n"] == 1
        assert z["audit"][-1]["to"] == 2
        prom = urllib.request.urlopen(
            base + "/capz?format=prom", timeout=10).read().decode()
        assert "dnn_tpu_caplens_arrival_rate_hz" in prom
        assert "dnn_tpu_caplens_wanted_replicas 2.0" in prom
        assert 'dnn_tpu_caplens_plan_availability{n="2"} 1.0' in prom
        assert 'dnn_tpu_caplens_coldstart_coverage' in prom
    finally:
        srv.close()


def test_cli_selftest_smoke():
    r = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "caplens", "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "caplens selftest ok" in r.stdout


# ----------------------------------------------------------------------
# /fleetz wanted-replicas rollup (the satellite's regression)
# ----------------------------------------------------------------------

def test_fleetz_wanted_rollup_is_stage_max_with_column():
    # two routers wanting different counts + one stage with no gauge:
    # the rollup is the MAX (a multi-front-door fleet provisions for
    # its hungriest router, not whichever stage the dict yields first)
    # and the per-stage column keeps each verdict visible, omitting
    # gauge-less stages
    from dnn_tpu.obs.fleet import FleetCollector
    from dnn_tpu.obs.http import MetricsHTTPServer
    from dnn_tpu.utils.metrics import Metrics

    regs = {"ra": Metrics(), "rb": Metrics(), "plain": Metrics()}
    regs["ra"].set("dnn_tpu_wanted_replicas", 2.0)
    regs["rb"].set("dnn_tpu_wanted_replicas", 5.0)
    regs["plain"].set("serving.tokens_per_sec", 1.0)
    srvs = {k: MetricsHTTPServer(port=0, registry=v)
            for k, v in regs.items()}
    fc = None
    try:
        fc = FleetCollector(
            {k: f"http://127.0.0.1:{s.port}" for k, s in srvs.items()})
        fc.poll_once()
        z = fc.fleetz()
        assert z["fleet"]["wanted_replicas"] == 5.0
        assert z["fleet"]["wanted_replicas_by_stage"] \
            == {"ra": 2.0, "rb": 5.0}
        assert z["stages"]["ra"]["wanted_replicas"] == 2.0
        prom = fc.render_prom()
        assert 'dnn_tpu_fleet_stage_wanted_replicas{stage="ra"} 2' \
            in prom
        assert 'dnn_tpu_fleet_stage_wanted_replicas{stage="rb"} 5' \
            in prom
        assert 'stage="plain"} ' not in prom.split(
            "dnn_tpu_fleet_stage_wanted_replicas", 1)[1].split("#")[0]
    finally:
        if fc is not None:
            fc.close()
        for s in srvs.values():
            s.close()


# ----------------------------------------------------------------------
# lifecycle seams: the handles feed the lens + the flight ring
# ----------------------------------------------------------------------

def test_replica_handle_lifecycle_feeds_lens_and_flight():
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.obs import flight

    flight.recorder().clear()
    h = ReplicaHandle("r0", "127.0.0.1:1")
    rset = ReplicaSet([h], scrape=False)
    lens = CapLens()
    h.start()                 # idle -> warming (no supervisor: no-op)
    rset.attach_caplens(lens)  # backfills the spawn from the stamps
    h._mark_serving()
    assert "r0" in lens._pending
    assert lens._pending["r0"]["t_ready"] is not None
    ev = {e["kind"]: e for e in flight.recorder().events()}
    assert ev["replica_spawn"]["replica"] == "r0"
    # the ready event carries its DURATION (spawn->ready wall)
    assert ev["replica_ready"]["spawn_to_ready_s"] is not None
    assert ev["replica_ready"]["spawn_to_ready_s"] >= 0.0
    h._mark_dead("test")
    assert "r0" not in lens._pending  # unserved spawn abandoned
    ev = {e["kind"]: e for e in flight.recorder().events()}
    assert ev["replica_dead"]["alive_s"] is not None
