"""Speculative x bucketed decode composition (ISSUE 6).

Before this PR the two length-aware paths were disjoint: the bucket
ladder shrank decode bytes/step but SpeculativeBatcher rejected
`decode_buckets=`, so acceptance-weighted tokens/step and
bytes-proportional-to-live-context could not multiply. The composition's
correctness argument is the same bucket-view lemma PR 1 proved for the
dense step — a rung differs from the full allocation only in columns
beyond every row's band limit — applied to all three spec programs
(draft sync, draft propose, target verify), plus the +k scratch headroom
every grow must cover. This module pins:

  * greedy token identity: spec x bucketed == the PLAIN dense batcher
    (the spec parity contract), through bucket-edge crossings, with the
    ladder actually exercised (cache grew);
  * sampled-stream identity: spec x bucketed == spec unbucketed
    draw-for-draw (same rng discipline, mask-identical rungs);
  * draft pool lockstep: both caches sit on the same rung after a grow;
  * the paged pool stays un-composed: kv="paged" rejected, kv="auto"
    resolves dense.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher
from dnn_tpu.runtime.serving_spec import SpeculativeBatcher


@pytest.fixture(scope="module")
def models():
    cfg = gpt.GPTConfig(vocab_size=89, block_size=256, n_layer=2,
                        n_head=2, n_embd=32)
    d_cfg = gpt.GPTConfig(vocab_size=89, block_size=256, n_layer=1,
                          n_head=2, n_embd=16)
    key = jax.random.PRNGKey(0)
    prepared = gpt.prepare_stacked(gpt.init(key, cfg), cfg)
    d_prepared = gpt.prepare_stacked(
        gpt.init(jax.random.fold_in(key, 1), d_cfg), d_cfg)
    return cfg, prepared, d_cfg, d_prepared


PROMPT = (np.arange(1, 20) * 3) % 89


def test_spec_bucketed_greedy_parity_through_rungs(models):
    cfg, prepared, d_cfg, d_prepared = models
    ref = ContinuousBatcher(cfg, prepared, slots=2, max_len=192,
                            prompt_pad=16)
    r0 = ref.submit(PROMPT, max_new_tokens=120)
    t_ref = np.asarray(ref.drain()[r0])

    sp = SpeculativeBatcher(cfg, prepared, d_cfg, d_prepared, spec_k=3,
                            slots=2, max_len=192, prompt_pad=16,
                            decode_buckets=True)
    first_rung = sp._cache_len
    r1 = sp.submit(PROMPT, max_new_tokens=120)
    t_sp = np.asarray(sp.drain()[r1])
    np.testing.assert_array_equal(t_ref, t_sp)
    # the ladder was exercised: live positions crossed 64 and 128
    assert sp._buckets == (64, 128, 192)
    assert sp._cache_len > first_rung
    # the draft pool grew in lockstep (same rung as the target)
    d_len = jax.tree.leaves(sp.d_cache)[0].shape[3]
    assert d_len == sp._cache_len
    # speculation actually sped things up (something was accepted)
    assert sp.spec_accepted > 0


def test_spec_bucketed_matches_spec_unbucketed_sampled(models):
    cfg, prepared, d_cfg, d_prepared = models

    def run(**kw):
        sp = SpeculativeBatcher(cfg, prepared, d_cfg, d_prepared,
                                spec_k=2, slots=2, max_len=192,
                                prompt_pad=16, temperature=0.8,
                                top_k=11, **kw)
        rid = sp.submit(PROMPT, max_new_tokens=90, seed=7)
        return np.asarray(sp.drain()[rid])

    t_flat = run()
    t_buck = run(decode_buckets=True)
    # bucket rungs are attention-invisible, and the rng discipline is
    # shared — the SAMPLED stream must agree draw-for-draw
    np.testing.assert_array_equal(t_flat, t_buck)


def test_spec_bucketed_multi_slot_mixed_retirement(models):
    cfg, prepared, d_cfg, d_prepared = models
    sp = SpeculativeBatcher(cfg, prepared, d_cfg, d_prepared, spec_k=3,
                            slots=2, max_len=192, prompt_pad=16,
                            decode_buckets=True)
    ra = sp.submit(PROMPT, max_new_tokens=100)
    rb = sp.submit((PROMPT + 7) % 89, max_new_tokens=30)
    out = sp.drain()
    assert len(out[ra]) == 100 and len(out[rb]) == 30
    # each stream matches its solo run through the plain batcher
    for rid, prompt, budget in ((ra, PROMPT, 100),
                                (rb, (PROMPT + 7) % 89, 30)):
        ref = ContinuousBatcher(cfg, prepared, slots=1, max_len=192,
                                prompt_pad=16)
        rr = ref.submit(prompt, max_new_tokens=budget)
        np.testing.assert_array_equal(np.asarray(ref.drain()[rr]),
                                      np.asarray(out[rid]))


def test_spec_rejects_paged_resolves_auto_dense(models):
    cfg, prepared, d_cfg, d_prepared = models
    with pytest.raises(ValueError, match="paged"):
        SpeculativeBatcher(cfg, prepared, d_cfg, d_prepared,
                           slots=2, max_len=192, prompt_pad=16,
                           kv="paged")
    sp = SpeculativeBatcher(cfg, prepared, d_cfg, d_prepared,
                            slots=2, max_len=192, prompt_pad=16,
                            kv="auto")
    assert not sp._paged  # the serving default resolves dense here
