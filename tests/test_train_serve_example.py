"""CI-sized run of the flagship train-and-serve example
(examples/train_cifar_serve.py): the complete reference demo loop — train,
evaluate, export (native + torch .pth), serve through the pipeline CLI on a
real PNG — at a step count small enough for the CPU mesh."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))

import train_cifar_serve as ex  # noqa: E402


def test_synth_dataset_is_learnable_and_deterministic():
    xa, ya = ex.synth_cifar(64, seed=3)
    xb, yb = ex.synth_cifar(64, seed=3)
    np.testing.assert_array_equal(xa, xb)
    np.testing.assert_array_equal(ya, yb)
    # different split, same class structure: templates must be shared
    xt, yt = ex.synth_cifar(64, seed=4)
    assert not np.array_equal(ya, yt)
    cls_a = xa[ya == ya[0]].mean(axis=0)
    cls_t = xt[yt == ya[0]].mean(axis=0)
    assert np.abs(cls_a.astype(float) - cls_t.astype(float)).mean() < 25.0


def test_end_to_end_mini(tmp_path):
    out_dir = str(tmp_path)
    train_files, test_file = ex.ensure_data(None, out_dir, n_train=512, n_test=128)
    params, loss = ex.train(train_files, steps=60, batch_size=64, log_every=0)
    assert np.isfinite(loss)
    acc = ex.evaluate(params, test_file)
    assert acc > 0.5, f"mini training should clear chance by far, got {acc:.1%}"

    npz_path, pth_path = ex.export(params, out_dir)
    assert os.path.exists(npz_path) and os.path.exists(pth_path)

    pred = ex.serve_and_check(npz_path, out_dir, test_file)
    assert 0 <= pred < 10


def test_exported_pth_loads_in_torch(tmp_path):
    torch = pytest.importorskip("torch")
    train_files, test_file = ex.ensure_data(None, str(tmp_path), n_train=256, n_test=64)
    params, _ = ex.train(train_files, steps=10, batch_size=64, log_every=0)
    _, pth_path = ex.export(params, str(tmp_path))
    sd = torch.load(pth_path, map_location="cpu", weights_only=True)
    assert set(sd) == {
        "conv1.weight", "conv1.bias", "conv2.weight", "conv2.bias",
        "fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias",
    }
    assert sd["fc1.weight"].shape == (512, 4096)
