"""Qwen3 family: the LLaMA block with per-head q/k RMSNorm (qk_norm),
replacing Qwen2's projection biases.

The norms ride the one _qk_normed helper shared by every q/k projection
site (dense forward via _qkv_rope, batcher rows, verify rows), so all
runtime paths inherit them — pinned against HF Qwen3ForCausalLM and the
framework's own cross-path parity contracts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

CFG = llama.PRESETS["qwen3-test"]  # L=4, GQA 2:1, head_dim 32, qk_norm


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_structure():
    p = _params()
    attn = p["h_0"]["attn"]
    assert attn["q_norm"]["scale"].shape == (CFG.head_dim,)
    assert attn["k_norm"]["scale"].shape == (CFG.head_dim,)
    assert "bias" not in attn["q"]  # qk_norm replaces the biases


def test_hf_qwen3_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.Qwen3Config)
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    assert any(k.endswith("q_norm.weight") for k in sd)

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy cached decode == HF generate (q/k normed at every step's
    # positions, before RoPE)
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 10))
    n_new = 12
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 10:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_batcher_matches_solo():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(seed=3)
    prepared = gpt.prepare_stacked(p, CFG)
    prompts = [np.asarray([3, 1, 4, 1, 5]), np.asarray([9, 2, 6])]
    n_new = 7
    solo = llama.make_generate(CFG, max_new_tokens=n_new)
    want = [np.asarray(solo(prepared, jnp.asarray(pr[None]),
                            jax.random.PRNGKey(0)))[0] for pr in prompts]
    srv = ContinuousBatcher(CFG, prepared, slots=2,
                            max_len=CFG.block_size, prompt_pad=8,
                            family=llama.LlamaFamilyRows(CFG))
    rids = [srv.submit(pr, max_new_tokens=n_new) for pr in prompts]
    srv.drain()
    for rid, w in zip(rids, want):
        np.testing.assert_array_equal(srv.results[rid], w)


def test_torch_export_round_trips():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from dnn_tpu.io.torch_export import llama_state_dict_from_params

    p = _params(seed=4)
    sd = llama_state_dict_from_params(p)
    assert "model.layers.0.self_attn.q_norm.weight" in sd
    model = transformers.Qwen3ForCausalLM(
        llama.to_hf_config(CFG, attn_implementation="eager")).eval()
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd.items()}, strict=False)
    assert not unexpected, unexpected
    ids = np.random.RandomState(5).randint(0, CFG.vocab_size, (2, 10))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


def test_registry_registered():
    from dnn_tpu.registry import get_model

    spec = get_model("qwen3-8b")
    assert spec.config.qk_norm and spec.config.head_dim == 128
