"""Gemma family (1 and 2): the LLaMA block with (1+w) RMSNorm, GeGLU,
tied + sqrt(C)-scaled embeddings, decoupled head_dim — and, for Gemma-2,
post-branch norms, attention/final logit softcapping, query_pre_attn
scaling, and ALTERNATING local/global attention layers.

Every switch is a LlamaConfig field, so the whole serving/decode surface
(solo generate, batcher rows, partitions) inherits Gemma with no new
runtime code; these tests pin that against HF GemmaForCausalLM /
Gemma2ForCausalLM and the framework's own cross-path parity contracts.
The reference has no Gemma (its only LM is the GPT-2 wrapper family,
/root/reference/partitions/gpt_model_parts.py) — this widens the zoo
beyond it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

G1 = llama.PRESETS["gemma-test"]    # MQA, head_dim 32 != 64/4
G2 = llama.PRESETS["gemma2-test"]   # + post-norms, softcaps, alt window


def _params(cfg, seed=0):
    return llama.init(jax.random.PRNGKey(seed), cfg)


# ----------------------------------------------------------------------
# structure
# ----------------------------------------------------------------------

def test_init_structure():
    p1 = _params(G1)
    assert "lm_head" not in p1, "tied configs carry no lm_head leaf"
    assert "post_ln_1" not in p1["h_0"]
    p2 = _params(G2)
    assert "lm_head" not in p2
    assert set(p2["h_0"]) >= {"ln_1", "post_ln_1", "ln_2", "post_ln_2"}
    # head_dim decoupled from n_embd/n_head
    assert p1["h_0"]["attn"]["q"]["kernel"].shape == (
        G1.n_embd, G1.n_head * 32)
    assert p1["h_0"]["attn"]["k"]["kernel"].shape == (
        G1.n_embd, G1.n_kv_head * 32)


def test_every_switch_acts():
    """Each Gemma switch must change the logits of an otherwise-identical
    config (a silently-ignored flag would still pass structural tests)."""
    import dataclasses

    ids = np.random.RandomState(0).randint(0, G2.vocab_size, (1, 24))
    p = _params(G2, seed=3)

    def logits(cfg):
        return np.asarray(llama.make_apply(cfg)(p, jnp.asarray(ids)))

    base = logits(G2)
    # softcaps compare against a TIGHT cap (at 50/30 on random-init-scale
    # scores, cap*tanh(s/cap) is numerically ~identity — the off-vs-on
    # delta would drown in noise, a tight cap visibly saturates)
    for field, value in [("embed_scale", False), ("norm_plus_one", False),
                         ("query_scale", None), ("attn_softcap", 0.5),
                         ("final_softcap", 0.1), ("mlp_act", "silu"),
                         ("alt_window", False)]:
        changed = dataclasses.replace(G2, **{field: value})
        assert np.abs(logits(changed) - base).max() > 1e-6, field


# ----------------------------------------------------------------------
# HF parity
# ----------------------------------------------------------------------

def _hf_parity(cfg, hf_cls_name, prompt_len=10, n_new=10):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(cfg, attn_implementation="eager")
    assert type(hf_cfg).__name__ == hf_cls_name.replace("ForCausalLM",
                                                        "Config")
    torch.manual_seed(0)
    model = getattr(transformers, hf_cls_name)(hf_cfg).eval()
    assert hf_cfg.tie_word_embeddings, "premise: Gemma ties embeddings"
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(
        sd, post_norms=cfg.post_norms, tied_head="omit")
    assert "lm_head" not in params

    # full-sequence logits — long enough that Gemma-2's window (16) bands
    # the even layers while odd layers attend globally
    t = 3 * (cfg.sliding_window or 8) // 2
    ids = np.random.RandomState(1).randint(0, cfg.vocab_size, (2, t))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(cfg)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=3e-3, rtol=3e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy cached-decode trajectory matches transformers.generate
    prompt = np.random.RandomState(2).randint(0, cfg.vocab_size,
                                              (1, prompt_len))
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, prompt_len:]
    prepared = gpt.prepare_stacked(params, cfg)
    got_toks = np.asarray(llama.make_generate(cfg, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_hf_gemma1_parity():
    _hf_parity(G1, "GemmaForCausalLM")


def test_hf_gemma2_parity():
    """Pins the full Gemma-2 recipe — post-norms, both softcaps,
    query_pre_attn_scalar, tied scaled embeddings AND the alternating
    window pattern (even layers local, odd global) — against HF eager
    attention, including a prompt long enough to band the window."""
    _hf_parity(G2, "Gemma2ForCausalLM", prompt_len=24, n_new=10)


# ----------------------------------------------------------------------
# cross-path parity inside the framework
# ----------------------------------------------------------------------

def test_partition_parity_gemma1():
    p = _params(G1, seed=5)
    ids = np.random.RandomState(3).randint(0, G1.vocab_size, (2, 16))
    want = np.asarray(llama.make_apply(G1)(p, jnp.asarray(ids)))
    for parts in (2, 3):
        stages = llama.make_partition(G1)(parts)
        # last stage of a tied config must carry wte for the head
        assert "wte" in stages[-1].param_keys
        x = jnp.asarray(ids)
        for st in stages:
            x = st.apply(st.slice_params(p), x)
        np.testing.assert_allclose(np.asarray(x), want, atol=1e-5,
                                   rtol=1e-5)


def test_partition_parity_gemma2_alt_window():
    """Stage boundaries must slice the per-layer window array with the
    layer range — a stage starting at an odd layer still alternates
    correctly."""
    p = _params(G2, seed=6)
    ids = np.random.RandomState(4).randint(0, G2.vocab_size, (1, 24))
    want = np.asarray(llama.make_apply(G2)(p, jnp.asarray(ids)))
    stages = llama.make_partition(G2)(3)  # ranges split at odd offsets
    x = jnp.asarray(ids)
    for st in stages:
        x = st.apply(st.slice_params(p), x)
    np.testing.assert_allclose(np.asarray(x), want, atol=1e-5, rtol=1e-5)


def test_generate_matches_stepwise_dense_forward():
    """Greedy cached decode == argmax-stepping the stateless forward —
    the cache path's per-layer window masking must agree with the dense
    band mask on BOTH layer parities."""
    cfg = G2
    p = _params(cfg, seed=7)
    prepared = gpt.prepare_stacked(p, cfg)
    apply = llama.make_apply(cfg)
    prompt = np.random.RandomState(5).randint(0, cfg.vocab_size, (1, 20))
    n_new = 12  # crosses the window boundary (20 + 12 > 16)
    ids = list(prompt[0])
    for _ in range(n_new):
        logits = np.asarray(apply(p, jnp.asarray([ids])))
        ids.append(int(logits[0, -1].argmax()))
    want = np.asarray(ids[len(prompt[0]):])
    got = np.asarray(llama.make_generate(cfg, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_int8_cache_decode_gemma2():
    """The quantized cache composes with softcap + per-layer windows (the
    codec applies scales, then caps, then bands)."""
    cfg = G2
    p = _params(cfg, seed=8)
    prepared = gpt.prepare_stacked(p, cfg)
    prompt = np.random.RandomState(6).randint(0, cfg.vocab_size, (1, 12))
    f32 = np.asarray(llama.make_generate(cfg, max_new_tokens=8)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    q = np.asarray(llama.make_generate(cfg, max_new_tokens=8,
                                       kv_dtype="int8")(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    # int8 rounding may perturb late tokens; the head of the trajectory
    # must agree (same contract the LLaMA int8 tests pin)
    assert (f32[:4] == q[:4]).all()


def test_batcher_matches_solo_generate():
    """ContinuousBatcher greedy decode == solo make_generate for Gemma-2:
    per-slot positions, softcapped codec, per-layer windows."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = G2
    p = _params(cfg, seed=9)
    prepared = gpt.prepare_stacked(p, cfg)
    rs = np.random.RandomState(7)
    prompts = [rs.randint(0, cfg.vocab_size, (n,)) for n in (9, 14, 20)]
    n_new = 10

    solo = llama.make_generate(cfg, max_new_tokens=n_new)
    want = {}
    for i, pr in enumerate(prompts):
        want[i] = np.asarray(solo(prepared, jnp.asarray([pr]),
                                  jax.random.PRNGKey(0)))[0]

    b = ContinuousBatcher(cfg, prepared, slots=3, max_len=cfg.block_size,
                          prompt_pad=8, family=llama.LlamaFamilyRows(cfg))
    rids = [b.submit(np.asarray(pr), max_new_tokens=n_new)
            for pr in prompts]
    b.drain()
    for i, rid in enumerate(rids):
        np.testing.assert_array_equal(np.asarray(b.results[rid]), want[i])


def test_paged_pool_rejects_gemma2():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(G2, seed=1)
    prepared = gpt.prepare_stacked(p, G2)
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(G2, prepared, slots=2, max_len=64,
                          family=llama.LlamaFamilyRows(G2),
                          paged_blocks=8, block_len=8)


def test_pipeline_decode_rejects_alt_window():
    with pytest.raises(ValueError, match="alternating"):
        llama.LlamaPipelineFamily(G2)


def test_seq_paths_reject_softcap():
    import dataclasses

    from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh

    mesh = make_mesh({SEQ_AXIS: jax.device_count()})
    # windowless-but-softcapped variant hits the softcap check directly
    capped = dataclasses.replace(G2, sliding_window=None, alt_window=False)
    with pytest.raises(ValueError, match="softcap"):
        llama.make_apply_seq_parallel(capped, mesh)
    with pytest.raises(ValueError, match="softcap"):
        llama.make_generate_seq_sharded(capped, mesh, max_new_tokens=4)


def test_gemma1_pipeline_generate_parity():
    """Gemma-1 (uniform attention) rides the pipeline decode unchanged:
    token parity with the solo decoder over a 2-stage mesh."""
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh

    cfg = G1
    p = _params(cfg, seed=11)
    prepared = gpt.prepare_stacked(p, cfg)
    prompt = np.random.RandomState(8).randint(0, cfg.vocab_size, (1, 8))
    n_new = 8
    want = np.asarray(llama.make_generate(cfg, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))
    mesh = make_mesh({STAGE_AXIS: 2}, jax.devices()[:2])
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    stage_blocks, aux = prepare_pipeline_stacked(prepared, cfg, mesh)
    gen = llama.make_pipeline_generate(cfg, mesh, max_new_tokens=n_new)
    got = np.asarray(gen(stage_blocks, aux, jnp.asarray(prompt),
                         jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
