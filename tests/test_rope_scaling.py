"""Long-context RoPE scaling (linear position interpolation + NTK-aware
base stretch) for the LLaMA family.

Cross-checks: scale 1 is a bit-exact no-op; linear scaling matches
transformers' rope_scaling={"rope_type": "linear"} logits; the NTK form
matches an HF model whose theta is pre-multiplied by scale^(d/(d-2));
and the cached decode (the path serving actually runs) stays
token-identical to the dense forward under scaling — every RoPE site
goes through one table builder (llama._rope_tables).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

BASE = llama.PRESETS["llama-test"]


def _params(seed=0, cfg=BASE):
    return llama.init(jax.random.PRNGKey(seed), cfg)


def test_scale_one_is_identity():
    params = _params()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                             BASE.vocab_size)
    want = np.asarray(llama.make_apply(BASE)(params, ids))
    for kind in ("linear", "ntk"):
        cfg = dataclasses.replace(BASE, rope_scaling=kind, rope_scale=1.0)
        got = np.asarray(llama.make_apply(cfg)(params, ids))
        np.testing.assert_array_equal(got, want)


def test_unknown_scaling_rejected():
    cfg = dataclasses.replace(BASE, rope_scaling="yarn", rope_scale=2.0)
    with pytest.raises(ValueError, match="rope_scaling"):
        llama.make_apply(cfg)(_params(), jnp.zeros((1, 4), jnp.int32))
    bad = dataclasses.replace(BASE, rope_scaling="linear", rope_scale=0.5)
    with pytest.raises(ValueError, match="rope_scale"):
        llama.make_apply(bad)(_params(), jnp.zeros((1, 4), jnp.int32))
    # factor set but type forgotten — the likely long-context typo
    half = dataclasses.replace(BASE, rope_scale=4.0)
    with pytest.raises(ValueError, match="no effect"):
        llama.make_apply(half)(_params(), jnp.zeros((1, 4), jnp.int32))


@pytest.mark.parametrize("kind", ["linear", "ntk"])
def test_hf_parity_under_scaling(kind):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    # extended context: 2x the trained block size via scaling
    cfg = dataclasses.replace(BASE, block_size=BASE.block_size * 2,
                              rope_scaling=kind, rope_scale=2.0)
    hf_cfg = llama.to_hf_config(cfg, attn_implementation="eager")
    if kind == "linear":
        assert hf_cfg.rope_scaling["factor"] == 2.0
    else:
        assert hf_cfg.rope_theta > cfg.rope_theta  # pre-multiplied base
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    t = BASE.block_size + 16  # past the ORIGINAL context length
    ids = np.random.RandomState(1).randint(0, cfg.vocab_size, (2, t))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(cfg)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))


@pytest.mark.parametrize("kind", ["linear", "ntk"])
def test_cached_decode_matches_dense_under_scaling(kind):
    """Greedy cached decode past the original context == full dense
    recompute — the decode path's per-position tables scale exactly like
    the prefill's."""
    cfg = dataclasses.replace(BASE, block_size=BASE.block_size * 2,
                              rope_scaling=kind, rope_scale=2.0)
    params = _params(seed=3, cfg=cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    apply_fn = llama.make_apply(cfg)
    t = BASE.block_size - 2  # prompt near the original limit
    ids = jax.random.randint(jax.random.PRNGKey(4), (1, t), 0,
                             cfg.vocab_size)
    n_new = 8  # decode crosses the original block_size
    got = np.asarray(llama.make_generate(cfg, max_new_tokens=n_new)(
        prepared, ids, jax.random.PRNGKey(0)))
    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_batcher_scaled_matches_solo():
    """The batcher's per-slot rope (LlamaFamilyRows._block_rows) uses the
    same scaled tables as the solo decoder."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = dataclasses.replace(BASE, rope_scaling="linear", rope_scale=2.0)
    params = _params(seed=5, cfg=cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    prompt = np.array([5, 3, 7, 1, 2])
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=32,
                            prompt_pad=8, family=llama.LlamaFamilyRows(cfg))
    rid = srv.submit(prompt, max_new_tokens=6)
    got = srv.drain()[rid]
    want = np.asarray(llama.make_generate(cfg, max_new_tokens=6)(
        prepared, jnp.asarray(prompt, jnp.int32)[None, :],
        jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)
