"""min-p sampling and repetition penalty (serving sampler extras).

The reference has no sampling at all (argmax over one forward,
/root/reference/node.py:61); these are the modern serving knobs layered
onto the framework's samplers. Contracts: min-p restricts the support to
tokens within min_p x the top probability (sort-free threshold,
bit-identical to no-op when off); the repetition penalty follows HF/CTRL
semantics over each request's own tokens, tracked per slot; and every
knob composes with the pool's per-row mixing without changing any other
request's stream.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import (
    _sample,
    _sample_rows,
    apply_repetition_penalty,
    make_generate,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def _prompt(seed, n=6):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, CFG.vocab_size, dtype=jnp.int32))


# ----------------------------------------------------------------------
# op level
# ----------------------------------------------------------------------

def test_repetition_penalty_math():
    """HF semantics: positive seen logits divide, negative multiply,
    unseen untouched."""
    logits = jnp.asarray([[2.0, -1.0, 3.0, -4.0]])
    seen = jnp.asarray([[True, True, False, False]])
    out = np.asarray(apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out, [[1.0, -2.0, 3.0, -4.0]])


def test_min_p_restricts_support():
    """Every draw must come from tokens with prob >= min_p x max prob."""
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((1, 64)) * 3, jnp.float32)
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))[0]
    min_p = 0.2
    allowed = set(np.nonzero(probs >= min_p * probs.max())[0])
    assert 1 <= len(allowed) < 64  # the test must actually restrict
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(200, dtype=jnp.uint32))
    draws = jax.vmap(
        lambda k: _sample(logits, k, temperature=1.0, top_k=None,
                          min_p=min_p)[0])(keys)
    assert set(np.asarray(draws).tolist()) <= allowed


def test_tiny_min_p_is_identity():
    """A min_p below every relative probability must not perturb draws."""
    logits = jnp.asarray(
        np.random.default_rng(1).standard_normal((1, 64)), jnp.float32)
    k = jax.random.PRNGKey(3)
    a = _sample(logits, k, temperature=0.9, top_k=None)
    b = _sample(logits, k, temperature=0.9, top_k=None, min_p=1e-12)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_min_p_one_keeps_only_max_ties_in_both_paths():
    """The strictest legal setting (min_p=1.0) must behave identically in
    _sample and _sample_rows: only tokens tied with the max survive."""
    logits = jnp.asarray([[0.0, 5.0, 5.0, -2.0]], jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(50, dtype=jnp.uint32))
    solo = jax.vmap(lambda k: _sample(logits, k, temperature=1.0,
                                      top_k=None, min_p=1.0)[0])(keys)
    rows = jax.vmap(lambda k: _sample_rows(
        logits, k[None],
        temperature=jnp.ones((1,), jnp.float32),
        top_k=jnp.zeros((1,), jnp.int32),
        top_p=jnp.zeros((1,), jnp.float32),
        min_p=jnp.ones((1,), jnp.float32))[0])(keys)
    assert set(np.asarray(solo).tolist()) <= {1, 2}
    np.testing.assert_array_equal(np.asarray(solo), np.asarray(rows))


def test_sample_rows_min_p_matches_sample():
    """Per-row min_p reproduces the solo _sample draw for the same key,
    mixed with off rows in one call."""
    logits = jnp.asarray(
        np.random.default_rng(2).standard_normal((3, 128)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(3, dtype=jnp.uint32))
    got = np.asarray(_sample_rows(
        logits, keys,
        temperature=jnp.asarray([1.0, 1.0, 1.0], jnp.float32),
        top_k=jnp.zeros((3,), jnp.int32),
        top_p=jnp.zeros((3,), jnp.float32),
        min_p=jnp.asarray([0.3, 0.0, 0.05], jnp.float32)))
    for i, mp in enumerate((0.3, None, 0.05)):
        want = _sample(logits[i][None], keys[i], temperature=1.0,
                       top_k=None, min_p=mp)[0]
        assert got[i] == int(want), i


# ----------------------------------------------------------------------
# decode loops
# ----------------------------------------------------------------------

def test_greedy_repetition_penalty_suppresses_repeats():
    """With a heavy penalty a greedy stream cannot re-emit a token (its
    positive logit collapses); the unpenalized stream on the same weights
    repeats — the knob's observable purpose."""
    prepared = _prepared(seed=4)
    prompt = _prompt(5, n=4)
    n_new = 12
    plain = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    assert len(set(plain.tolist())) < n_new, (
        "test premise: the unpenalized greedy stream should repeat "
        "(pick another seed)")
    pen = np.asarray(make_generate(CFG, max_new_tokens=n_new,
                                   repetition_penalty=50.0)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    assert len(set(pen.tolist())) > len(set(plain.tolist()))


def test_batcher_matches_solo_with_penalty():
    """The batcher's per-slot seen-mask path == make_generate's carry
    path (two independent trackers, one definition), greedy."""
    prepared = _prepared(seed=6)
    prompt = _prompt(7, n=5)
    n_new = 10
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new,
                                    repetition_penalty=1.8)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                            prompt_pad=16)
    rid = srv.submit(prompt, max_new_tokens=n_new, repetition_penalty=1.8)
    np.testing.assert_array_equal(srv.drain()[rid], want)


def test_penalized_request_does_not_disturb_neighbors():
    """A penalty/min_p request next to a plain greedy one leaves the
    plain stream bit-identical to solo."""
    prepared = _prepared(seed=8)
    prompt = _prompt(9, n=5)
    n_new = 8
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    srv = ContinuousBatcher(CFG, prepared, slots=3, max_len=64,
                            prompt_pad=16)
    rid = srv.submit(prompt, max_new_tokens=n_new)
    srv.submit(_prompt(10), max_new_tokens=n_new, repetition_penalty=3.0,
               temperature=0.9, min_p=0.1, seed=5)
    np.testing.assert_array_equal(srv.drain()[rid], want)


def test_seeded_min_p_request_pool_independent():
    """A seeded sampled request with min_p + penalty reproduces its own
    stream regardless of pool contents."""
    prepared = _prepared(seed=11)
    prompt = _prompt(12, n=5)
    kw = dict(max_new_tokens=7, seed=13, temperature=0.9, min_p=0.15,
              repetition_penalty=1.4)
    srv_a = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    ra = srv_a.submit(prompt, **kw)
    alone = srv_a.drain()[ra]
    srv_b = ContinuousBatcher(CFG, prepared, slots=3, max_len=64)
    srv_b.submit(_prompt(14), max_new_tokens=9, temperature=1.2, seed=1)
    rb = srv_b.submit(prompt, **kw)
    srv_b.submit(_prompt(15), max_new_tokens=3)
    np.testing.assert_array_equal(alone, srv_b.drain()[rb])


def test_option_validation():
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=32)
    with pytest.raises(ValueError, match="min_p"):
        srv.submit(_prompt(0), max_new_tokens=2, min_p=1.5)
    with pytest.raises(ValueError, match="min_p"):
        make_generate(CFG, max_new_tokens=2, min_p=1.5)  # solo path too
    with pytest.raises(ValueError, match="repetition_penalty"):
        srv.submit(_prompt(0), max_new_tokens=2, repetition_penalty=0.0)
    with pytest.raises(ValueError, match="repetition_penalty"):
        make_generate(CFG, max_new_tokens=2, repetition_penalty=-1.0)


def test_speculative_rejects_extras():
    from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

    prepared = _prepared()
    srv = SpeculativeBatcher(CFG, prepared, CFG, prepared, slots=1,
                             max_len=32)
    with pytest.raises(ValueError, match="min_p"):
        srv.submit(_prompt(0), max_new_tokens=2, min_p=0.2)
    with pytest.raises(ValueError, match="repetition_penalty"):
        srv.submit(_prompt(0), max_new_tokens=2, repetition_penalty=2.0)


# ----------------------------------------------------------------------
# logit bias
# ----------------------------------------------------------------------

def test_logit_bias_bans_the_greedy_choice():
    """Banning the token greedy would pick forces the runner-up — in the
    solo decoder AND the batcher, identically."""
    prepared = _prepared(seed=20)
    prompt = _prompt(21, n=5)
    plain = np.asarray(make_generate(CFG, max_new_tokens=1)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    banned = int(plain[0])
    bias = {banned: -1e9}
    solo = np.asarray(make_generate(CFG, max_new_tokens=6,
                                    logit_bias=bias)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    assert solo[0] != banned
    assert banned not in solo.tolist()
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                            prompt_pad=16, allow_logit_bias=True)
    rid = srv.submit(prompt, max_new_tokens=6, logit_bias=bias)
    np.testing.assert_array_equal(srv.drain()[rid], solo)


def test_logit_bias_forces_a_token():
    """+big on one token makes every step emit it (greedy and sampled)."""
    prepared = _prepared(seed=22)
    prompt = _prompt(23, n=4)
    tok = 7
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                            prompt_pad=16, allow_logit_bias=True)
    r1 = srv.submit(prompt, max_new_tokens=5, logit_bias={tok: 1e9})
    r2 = srv.submit(prompt, max_new_tokens=5, temperature=1.0, seed=3,
                    logit_bias={tok: 1e9})
    res = srv.drain()
    assert res[r1].tolist() == [tok] * 5
    assert res[r2].tolist() == [tok] * 5


def test_logit_bias_does_not_disturb_neighbors():
    prepared = _prepared(seed=24)
    prompt = _prompt(25, n=5)
    want = np.asarray(make_generate(CFG, max_new_tokens=6)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                            prompt_pad=16, allow_logit_bias=True)
    rid = srv.submit(prompt, max_new_tokens=6)
    srv.submit(_prompt(26), max_new_tokens=6, logit_bias={3: 1e9})
    np.testing.assert_array_equal(srv.drain()[rid], want)


def test_logit_bias_validation():
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=32,
                            allow_logit_bias=True)
    with pytest.raises(ValueError, match="logit_bias"):
        srv.submit(_prompt(0), max_new_tokens=2,
                   logit_bias={CFG.vocab_size: -100.0})
    with pytest.raises(ValueError, match="not finite"):
        srv.submit(_prompt(0), max_new_tokens=2,
                   logit_bias={3: float("nan")})
    plain = ContinuousBatcher(CFG, prepared, slots=1, max_len=32)
    with pytest.raises(ValueError, match="allow_logit_bias"):
        plain.submit(_prompt(0), max_new_tokens=2, logit_bias={3: -1.0})
    # an EMPTY dict is a no-op, not an error — on both server kinds
    rid = plain.submit(_prompt(0), max_new_tokens=2, logit_bias={})
    assert len(plain.drain()[rid]) == 2
