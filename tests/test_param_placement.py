"""Per-stage param placement for the heterogeneous SPMD pipeline.

Round-1 weak spot #4: the lax.switch branches embedded every stage's
params, replicating all weights on all devices. Packed placement
(pack_stage_params) shards one (S, W) array over the stage axis instead —
each device's HBM holds only its own stage's (padded) weight vector."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
from dnn_tpu.parallel.pipeline import (
    _unpack_stage,
    pack_stage_params,
    spmd_pipeline,
)
from dnn_tpu.registry import get_model


def test_pack_unpack_roundtrip():
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    stages = spec.partition(4)
    sp = [s.slice_params(params) for s in stages]
    packed, metas = pack_stage_params(sp)
    assert packed.ndim == 2 and packed.shape[0] == 4
    for i, p in enumerate(sp):
        back = _unpack_stage(packed[i], metas[i])
        for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pack_rejects_integer_leaves():
    with pytest.raises(ValueError, match="float leaves only"):
        pack_stage_params([{"w": jnp.zeros((2,), jnp.int32)}])


def test_pack_carrier_keeps_uniform_dtype():
    """A bf16 model packs as bf16 — per-device HBM is the stage's true
    weight bytes, not a 2x f32 upcast."""
    sp = [{"w": jnp.full((3,), 1.5, jnp.bfloat16)},
          {"w": jnp.full((2,), -2.0, jnp.bfloat16)}]
    packed, metas = pack_stage_params(sp)
    assert packed.dtype == jnp.bfloat16
    back = _unpack_stage(jnp.asarray(packed[0]), metas[0])
    assert back["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(back["w"], np.float32),
                                  np.full((3,), 1.5, np.float32))


def test_pack_mixed_float_dtypes_use_f32_carrier():
    sp = [{"w": jnp.ones((2,), jnp.bfloat16), "b": jnp.ones((2,), jnp.float32)}]
    packed, metas = pack_stage_params(sp)
    assert packed.dtype == np.float32
    back = _unpack_stage(jnp.asarray(packed[0]), metas[0])
    assert back["w"].dtype == jnp.bfloat16 and back["b"].dtype == jnp.float32


def test_default_placement_works_with_traced_params():
    """jit/grad with stage params as ARGUMENTS (the round-1 calling
    pattern) must keep working under the new default placement: packing is
    impossible mid-trace, so spmd_pipeline falls back to replicated."""
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(4))
    stages = spec.partition(2)
    sp = [s.slice_params(params) for s in stages]
    mesh = Mesh(np.array(jax.devices()[:2]), (STAGE_AXIS,))
    x = jnp.asarray(spec.example_input(batch_size=4, rng=jax.random.PRNGKey(5)))
    fns = [s.apply for s in stages]
    out = jax.jit(
        lambda sp_, x_: spmd_pipeline(fns, sp_, x_, mesh=mesh, num_microbatches=2)
    )(sp, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(spec.apply(params, x)), atol=1e-5, rtol=1e-5
    )


def test_explicit_stage_with_traced_params_raises():
    """An explicit 'stage' request is never silently downgraded: traced
    params without packed= are an error pointing at the fix."""
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(6))
    stages = spec.partition(2)
    sp = [s.slice_params(params) for s in stages]
    mesh = Mesh(np.array(jax.devices()[:2]), (STAGE_AXIS,))
    x = jnp.asarray(spec.example_input(batch_size=4))
    fns = [s.apply for s in stages]
    with pytest.raises(ValueError, match="impossible mid-trace"):
        jax.jit(
            lambda sp_, x_: spmd_pipeline(
                fns, sp_, x_, mesh=mesh, num_microbatches=2,
                param_placement="stage",
            )
        )(sp, x)


def test_pack_rejects_lossy_f64_mix():
    sp = [{"w": np.ones((2,), np.float64), "b": np.ones((2,), np.float32)}]
    with pytest.raises(ValueError, match="truncate"):
        pack_stage_params(sp)


def test_cifar_4stage_per_device_weight_fraction():
    """The VERDICT's acceptance check: each device holds ~1/4 of the
    weights (one padded stage row), not the full model."""
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    stages = spec.partition(4)
    sp = [s.slice_params(params) for s in stages]
    mesh = make_mesh({STAGE_AXIS: 4}, jax.devices()[:4])

    packed, metas = pack_stage_params(sp)
    placed = jax.device_put(packed, NamedSharding(mesh, P(STAGE_AXIS)))
    total_bytes = placed.size * placed.dtype.itemsize
    for shard in placed.addressable_shards:
        shard_bytes = shard.data.size * shard.data.dtype.itemsize
        assert shard.data.shape[0] == 1          # exactly one stage row
        assert shard_bytes == total_bytes // 4   # ~1/4 of the packed weights

    # padding overhead is bounded: the packed total is < 4x the largest
    # stage but >= the true param bytes
    true_sizes = [sum(np.asarray(l).size for l in jax.tree.leaves(p)) for p in sp]
    assert placed.shape[1] == max(true_sizes)

    # and the packed pipeline still matches the full model
    x = jnp.asarray(spec.example_input(batch_size=8, rng=jax.random.PRNGKey(1)))
    out = spmd_pipeline(
        [s.apply for s in stages], sp, x, mesh=mesh, num_microbatches=2,
        packed=(placed, metas),
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(spec.apply(params, x)), atol=1e-5, rtol=1e-5
    )


@pytest.mark.parametrize("placement", ["stage", "replicated"])
def test_placements_agree(placement):
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(2))
    stages = spec.partition(2)
    sp = [s.slice_params(params) for s in stages]
    mesh = Mesh(np.array(jax.devices()[:2]), (STAGE_AXIS,))
    x = jnp.asarray(spec.example_input(batch_size=4, rng=jax.random.PRNGKey(3)))
    out = spmd_pipeline(
        [s.apply for s in stages], sp, x, mesh=mesh, num_microbatches=2,
        param_placement=placement,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(spec.apply(params, x)), atol=1e-5, rtol=1e-5
    )


def _engine_cfg(**over):
    from dnn_tpu.config import TopologyConfig

    d = {
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
        "num_parts": 4,
        "model": "cifar_cnn",
        "device_type": "cpu",
        "runtime": "spmd",
    }
    d.update(over)
    return TopologyConfig.from_dict(d)


def test_engine_spmd_uses_per_stage_placement():
    """With param_placement="stage" the engine's packed params ARE sharded
    P(stage): every device's addressable shard is exactly one stage row —
    not just output parity, the placement itself is asserted."""
    from dnn_tpu.runtime.engine import PipelineEngine

    eng = PipelineEngine(_engine_cfg(param_placement="stage"), rng_seed=0)
    assert eng.runtime == "spmd" and eng.param_placement == "stage"
    packed = eng._spmd_packed
    assert {s.data.shape[0] for s in packed.addressable_shards} == {1}
    assert packed.sharding.spec == P(STAGE_AXIS)
    x = np.asarray(eng.spec.example_input(batch_size=8))
    np.testing.assert_allclose(
        np.asarray(eng.run(x)), np.asarray(eng.spec.apply(eng.params, x)),
        atol=1e-5, rtol=1e-5,
    )


def test_engine_auto_placement_replicates_small_models():
    """auto -> replicated for models far below the HBM-savings threshold
    (CIFAR is ~9 MB): no packed array exists, parity still holds."""
    from dnn_tpu.runtime.engine import PipelineEngine

    eng = PipelineEngine(_engine_cfg(), rng_seed=0)
    assert eng.runtime == "spmd" and eng.param_placement == "replicated"
    assert not hasattr(eng, "_spmd_packed")
    x = np.asarray(eng.spec.example_input(batch_size=8))
    np.testing.assert_allclose(
        np.asarray(eng.run(x)), np.asarray(eng.spec.apply(eng.params, x)),
        atol=1e-5, rtol=1e-5,
    )


def test_engine_auto_placement_shards_big_models():
    """auto -> stage once total param bytes cross the threshold."""
    from dnn_tpu.runtime.engine import PipelineEngine

    eng = PipelineEngine(_engine_cfg(), rng_seed=0)
    big = jax.tree.map(lambda l: l, eng._stage_params)  # shallow copy
    big[0]["pad"] = {"kernel": jnp.zeros((64 * 1024 * 1024 // 4,), jnp.float32)}
    eng._stage_params = big
    assert eng._resolve_param_placement() == "stage"
