"""Observability v2 tests (ISSUE 4): flight recorder, on-demand device
profiling, memory watermarks, hung-device watchdog.

The acceptance contract this module pins: /statusz reports `wedged`
(and /healthz degrades to 503) within one watchdog period when the
device probe is stubbed to hang, WHILE the serving loop keeps answering
CPU-path requests; a deadline-missed request's /debugz dump contains
its trace id and the surrounding event window; POST /profilez on a live
LMServer produces a Perfetto-loadable capture containing the new
layer/stage annotations; /metrics and /profilez survive concurrent
scraping under load — plus the unit contracts underneath: flight-ring
overflow/ordering, crash-dump excepthook (in a subprocess), paged-pool
watermark arithmetic, memory gauges, and the deprecated
utils.tracing shim honoring the obs gate."""

import gzip
import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.obs.flight import FlightRecorder
from dnn_tpu.obs.watchdog import STATE_VALUES, Watchdog


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------

def test_flight_ring_overflow_and_ordering_golden():
    fr = FlightRecorder(capacity=4)
    for i in range(7):
        fr.record("ev", i=i)
    evs = fr.events()
    # bounded ring: newest 4 survive, in order, seq strictly increasing
    assert [e["i"] for e in evs] == [3, 4, 5, 6]
    assert [e["seq"] for e in evs] == [4, 5, 6, 7]
    assert all(evs[k]["ts"] <= evs[k + 1]["ts"] for k in range(3))
    # jsonl: one valid object per line, schema keys present
    lines = [json.loads(ln) for ln in fr.jsonl().splitlines()]
    assert len(lines) == 4
    for d in lines:
        assert {"seq", "ts", "kind", "i"} <= set(d)


def test_flight_filters_and_window():
    fr = FlightRecorder(capacity=64)
    fr.record("admit", rid=1)
    miss = fr.record("deadline_miss", trace_id="abcd", rid=1)
    fr.record("retire", rid=2)
    assert [e["kind"] for e in fr.events(kind="deadline_miss")] == \
        ["deadline_miss"]
    assert fr.events(trace_id="abcd")[0]["seq"] == miss["seq"]
    assert len(fr.events(last=2)) == 2
    win = fr.window(miss["ts"], before_s=60, after_s=60)
    assert len(win) == 3  # the miss plus its surrounding events


def test_flight_record_respects_gate():
    fr = obs.flight.recorder()
    obs.set_enabled(False)
    try:
        n = len(fr)
        assert obs.flight.record("nope") is None
        assert len(fr) == n
    finally:
        obs.set_enabled(True)
    assert obs.flight.record("yep") is not None


def test_flight_cli_selftest_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "flight", "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "flight selftest ok" in out.stdout


def test_crash_dump_excepthook_in_subprocess(tmp_path):
    # a subprocess, because the hook fires on process-level unhandled
    # exceptions — exactly what a test must not raise in-process
    code = f"""
import sys
from dnn_tpu import obs
d = obs.flight.install_crash_dump({str(tmp_path)!r})
assert d == {str(tmp_path)!r}
obs.flight.record("admit", rid=1)
obs.flight.record("retire", rid=1, reason="length")
raise RuntimeError("synthetic crash for the flight recorder")
"""
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode != 0
    dumps = list(tmp_path.glob("flight-crash-*.jsonl"))
    assert len(dumps) == 1, out.stderr
    events = [json.loads(ln) for ln in
              dumps[0].read_text().splitlines()]
    kinds = [e["kind"] for e in events]
    assert kinds[:2] == ["admit", "retire"]  # the pre-crash window rides
    crash = events[-1]
    assert crash["kind"] == "crash"
    assert crash["exc_type"] == "RuntimeError"
    assert "synthetic crash" in crash["message"]
    assert "Traceback" in crash["traceback"]
    # the original traceback still reached stderr (hooks chain, not mask)
    assert "synthetic crash" in out.stderr


# ----------------------------------------------------------------------
# paged-pool watermark arithmetic
# ----------------------------------------------------------------------

def test_block_allocator_watermark_arithmetic():
    from dnn_tpu.runtime.paged_kvcache import BlockAllocator

    a = BlockAllocator(8)  # 7 allocatable (block 0 reserved)
    assert (a.n_used, a.n_free, a.high_water) == (0, 7, 0)
    b1 = a.alloc(3)
    assert (a.n_used, a.n_free, a.high_water) == (3, 4, 3)
    b2 = a.alloc(2)
    assert (a.n_used, a.n_free, a.high_water) == (5, 2, 5)
    a.free(b1)
    # high water survives the release — the point of a watermark
    assert (a.n_used, a.n_free, a.high_water) == (2, 5, 5)
    b3 = a.alloc(1)
    assert (a.n_used, a.high_water) == (3, 5)  # below HW: no move
    a.free(b2)
    a.free(b3)
    assert (a.n_used, a.n_free, a.high_water) == (0, 7, 5)
    # invariant everywhere: used + free == n_blocks - 1
    assert a.n_used + a.n_free == 7
    # refcounted sharing counts as use until the LAST holder frees
    b4 = a.alloc(2)
    a.ref(b4)
    a.free(b4)
    assert a.n_used == 2
    a.free(b4)
    assert a.n_used == 0


def test_paged_pool_gauges_export(tiny_gpt):
    from dnn_tpu.runtime.serving import ContinuousBatcher
    from dnn_tpu.utils.metrics import default_metrics

    cfg, prepared = tiny_gpt
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=16, paged_blocks=12, block_len=16)
    srv.submit(np.arange(1, 9), 4)
    srv.drain()
    snap = default_metrics.snapshot()["gauges"]
    assert snap["serving.paged_blocks_high_water"] >= 1
    assert snap["serving.paged_blocks_used"] == 0  # retired -> freed
    assert snap["serving.paged_blocks_free"] == 11
    assert snap["serving.kv_live_positions_high_water"] >= 9
    assert snap["serving.active_slots_high_water"] >= 1


# ----------------------------------------------------------------------
# memory gauges
# ----------------------------------------------------------------------

def test_memory_gauges_install_and_render():
    from dnn_tpu.obs.mem import install_memory_gauges, rss_bytes
    from dnn_tpu.utils.metrics import Metrics, render_prometheus

    assert rss_bytes() > 1e6  # this process surely exceeds a megabyte
    reg = Metrics()
    registered = install_memory_gauges(reg)
    assert "process_resident_bytes" in registered
    body = render_prometheus(reg)
    line = next(ln for ln in body.splitlines()
                if ln.startswith("process_resident_bytes"))
    assert float(line.split()[-1]) > 1e6
    # idempotent per registry object
    assert install_memory_gauges(reg) == []


# ----------------------------------------------------------------------
# watchdog
# ----------------------------------------------------------------------

def test_watchdog_wedged_on_hanging_probe_within_one_period():
    def hang_probe(deadline_s):
        time.sleep(deadline_s + 60)

    wd = Watchdog(period_s=0.3, probe_deadline_s=0.2,
                  device_probe=hang_probe, registry=None)
    wd.start()
    try:
        deadline = time.monotonic() + 0.2 + 2.0 + 2.0  # deadline+join slack
        while time.monotonic() < deadline and wd.state() != "wedged":
            time.sleep(0.05)
        assert wd.state() == "wedged"
        st = wd.status()
        assert st["components"]["device"]["state"] == "wedged"
        assert "deadline" in st["components"]["device"]["detail"]
        # the firing landed in the flight ring
        fired = [e for e in obs.flight.recorder().events(kind="watchdog")
                 if e.get("component") == "device" and
                 e.get("state") == "wedged"]
        assert fired
    finally:
        wd.close()


def test_watchdog_ok_probe_and_heartbeat_staleness():
    wd = Watchdog(period_s=0.2, probe_deadline_s=5.0,
                  device_probe=lambda d: (True, "ok"),
                  heartbeat_stale_s=0.3)
    wd.start()
    try:
        time.sleep(0.4)
        assert wd.state() == "ok"  # probe ok, no heartbeat expected yet
        wd.beat()
        assert wd.status()["components"]["decode_heartbeat"]["state"] == "ok"
        time.sleep(0.5)  # beat goes stale BEFORE any step completed:
        st = wd.status()  # warmup grace — the first step's cold-chip
        # compile blocks the loop for minutes legitimately, so this is
        # degraded (visible), not wedged (503 -> orchestrator evicts a
        # healthy warming server)
        assert st["components"]["decode_heartbeat"]["state"] == "degraded"
        assert st["state"] == "degraded"
        wd.beat()
        wd.step_done()  # a step completed: staleness now means wedged
        time.sleep(0.5)
        st = wd.status()
        assert st["components"]["decode_heartbeat"]["state"] == "wedged"
        assert st["state"] == "wedged"
        wd.beat()  # recovery
        assert wd.status()["state"] == "ok"
    finally:
        wd.close()


def test_watchdog_degraded_on_fast_probe_error_and_gauge():
    from dnn_tpu.utils.metrics import Metrics

    reg = Metrics()  # private registry: gauge assertions stay isolated
    wd = Watchdog(period_s=0.2, probe_deadline_s=5.0,
                  device_probe=lambda d: (False, "probe exited rc=1"),
                  registry=reg)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and wd.state() != "degraded":
            time.sleep(0.05)
        assert wd.state() == "degraded"
        assert reg.snapshot()["gauges"][
            "dnn_tpu_watchdog_state"] == STATE_VALUES["degraded"]
    finally:
        wd.close()


def test_subprocess_device_probe_real_and_bounded():
    from dnn_tpu.obs.watchdog import subprocess_device_probe

    ok, detail, timed_out = subprocess_device_probe(deadline_s=120.0)
    assert ok and not timed_out, detail  # the CPU backend answers


def test_subprocess_device_probe_platform_pinned():
    # the LMServer wiring probes the SERVER's backend, not whatever a
    # fresh child resolves by default (a cpu-substrate daemon must not
    # queue behind a device plugin it never uses)
    from dnn_tpu.obs.watchdog import subprocess_device_probe

    ok, detail, timed_out = subprocess_device_probe(deadline_s=120.0,
                                                    platform="cpu")
    assert ok and not timed_out, detail


# ----------------------------------------------------------------------
# LMServer integration: statusz/healthz/debugz/profilez
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_gpt():
    import jax

    from dnn_tpu.models import gpt

    cfg = gpt.GPTConfig(block_size=64, vocab_size=64, n_layer=2, n_head=2,
                        n_embd=32)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


@pytest.fixture(scope="module")
def lm_v2_server(tiny_gpt, tmp_path_factory):
    import os

    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    # route crash dumps / profile spool somewhere disposable
    os.environ["DNN_TPU_OBS_DIR"] = str(
        tmp_path_factory.mktemp("obs_spool"))
    cfg, prepared = tiny_gpt

    def hang_probe(deadline_s):
        time.sleep(deadline_s + 60)

    wd = Watchdog(period_s=0.3, probe_deadline_s=0.2,
                  device_probe=hang_probe)
    t, stop = start_lm_server_in_background(
        cfg, prepared, port=59561, slots=2, max_len=64, prompt_pad=16,
        default_max_new=8, request_timeout=60.0, metrics_port=0,
        watchdog=wd)
    yield stop.servicer
    stop()
    os.environ.pop("DNN_TPU_OBS_DIR", None)


def _get(url, timeout=30):
    return urllib.request.urlopen(url, timeout=timeout)


def test_statusz_wedged_while_serving_answers(lm_v2_server):
    from dnn_tpu.comm.client import NodeClient

    base = f"http://127.0.0.1:{lm_v2_server.metrics_server.port}"
    # within one watchdog period (+ probe deadline + thread-join slack)
    deadline = time.monotonic() + 0.2 + 2.0 + 3.0
    state = None
    while time.monotonic() < deadline:
        state = json.load(_get(base + "/statusz"))
        if state["state"] == "wedged":
            break
        time.sleep(0.05)
    assert state["state"] == "wedged", state
    assert state["components"]["device"]["state"] == "wedged"
    # /healthz degrades to 503 "wedged"
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(base + "/healthz")
    assert ei.value.code == 503
    assert ei.value.read().decode().strip() == "wedged"
    # ...while the serving loop keeps answering CPU-path requests
    c = NodeClient("127.0.0.1:59561")
    toks = c.generate([1, 2, 3, 4], max_new_tokens=6, seed=0)
    c.close()
    assert len(toks) == 6
    # the worker's own heartbeat stays fresh (it is not the wedged part)
    assert state["components"]["decode_heartbeat"]["state"] == "ok"
    # and the watchdog gauge rides the /metrics scrape
    body = _get(base + "/metrics").read().decode()
    line = next(ln for ln in body.splitlines()
                if ln.startswith("dnn_tpu_watchdog_state"))
    assert float(line.split()[-1]) == STATE_VALUES["wedged"]


def test_deadline_miss_lands_in_debugz_with_trace_id(lm_v2_server):
    from dnn_tpu.comm.client import NodeClient

    base = f"http://127.0.0.1:{lm_v2_server.metrics_server.port}"
    c = NodeClient("127.0.0.1:59561")
    with obs.span("client.doomed") as root:
        # force the miss by shrinking the SERVER deadline under the
        # request (5 ms cannot cover a 55-token decode even warm);
        # DEADLINE_EXCEEDED is deliberately non-retryable client-side
        lm_v2_server.request_timeout = 0.005
        try:
            with pytest.raises(Exception) as ei:
                c.generate([1, 2, 3], max_new_tokens=55, seed=1,
                           timeout=30.0)
        finally:
            lm_v2_server.request_timeout = 60.0
    c.close()
    assert "DEADLINE" in str(ei.value).upper() or \
        "exceeded" in str(ei.value)
    # the dump: deadline_miss event carrying this request's trace id,
    # with the surrounding event window (admissions etc.) around it
    body = _get(base + "/debugz").read().decode()
    events = [json.loads(ln) for ln in body.splitlines()]
    misses = [e for e in events if e["kind"] == "deadline_miss"
              and e.get("trace_id") == root.trace_id]
    assert misses, [e["kind"] for e in events]
    fr = obs.flight.recorder()
    win = fr.window(misses[-1]["ts"], before_s=120, after_s=5)
    assert any(e["kind"] == "admit" for e in win)
    # filtered fetch matches the CLI's ?trace= path
    filt = _get(base + f"/debugz?trace={root.trace_id}").read().decode()
    assert all(json.loads(ln)["trace_id"] == root.trace_id
               for ln in filt.splitlines())


def test_profilez_auto_trigger_captures_annotated_step(lm_v2_server):
    import urllib.parse

    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.obs.profile import trace_files

    base = f"http://127.0.0.1:{lm_v2_server.metrics_server.port}"
    # arm: threshold 0 ms -> the first step breaches, the NEXT one is
    # captured (exactly one step: the capture stays small enough that
    # the trace-viewer JSON exporter's 1M-event cap cannot drop the
    # annotation events)
    req = urllib.request.Request(
        base + "/profilez?auto=1&threshold_ms=0", method="POST")
    armed = json.load(urllib.request.urlopen(req, timeout=30))
    assert armed["armed"]["threshold_ms"] == 0
    c = NodeClient("127.0.0.1:59561")
    toks = c.generate([1, 2, 3, 4], max_new_tokens=10, seed=2)
    c.close()
    assert len(toks) == 10
    # the capture landed in the spool and is disarmed now
    deadline = time.monotonic() + 30
    caps = []
    while time.monotonic() < deadline and not caps:
        status = json.load(_get(base + "/profilez"))
        caps = status["captures"]
        time.sleep(0.1)
    assert caps, "auto-trigger produced no capture"
    assert status["armed"] is None
    tf = trace_files(caps[-1])
    assert tf, f"no trace.json.gz under {caps[-1]}"
    raw = gzip.open(tf[0]).read().decode(errors="replace")
    assert "serving.decode_step" in raw  # the new annotation, in Perfetto
    events = [e for e in json.loads(raw)["traceEvents"]
              if e.get("name") == "serving.decode_step"]
    assert events and all(e.get("ph") == "X" for e in events)


def test_concurrent_metrics_and_profilez_scrape_under_load(lm_v2_server):
    from dnn_tpu.comm.client import NodeClient

    base = f"http://127.0.0.1:{lm_v2_server.metrics_server.port}"
    errors = []
    stop = threading.Event()

    def load():
        c = NodeClient("127.0.0.1:59561")
        while not stop.is_set():
            c.generate([1, 2, 3], max_new_tokens=8, seed=3)
        c.close()

    def scrape():
        try:
            while not stop.is_set():
                body = _get(base + "/metrics").read().decode()
                assert "serving_decode_steps_total" in body
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=load),
               threading.Thread(target=scrape),
               threading.Thread(target=scrape)]
    for t in threads:
        t.start()
    try:
        # two on-demand captures racing the scrapes and each other: the
        # loser of the race gets 409 (ProfilerBusy), never corruption
        results = []

        def post():
            req = urllib.request.Request(base + "/profilez?ms=150",
                                         method="POST")
            try:
                results.append(
                    json.load(urllib.request.urlopen(req, timeout=60)))
            except urllib.error.HTTPError as e:
                results.append(e.code)

        p1, p2 = threading.Thread(target=post), threading.Thread(target=post)
        p1.start(), p2.start()
        p1.join(60), p2.join(60)
    finally:
        stop.set()
        for t in threads:
            t.join(30)
    assert not errors, errors
    oks = [r for r in results if isinstance(r, dict)]
    assert len(oks) >= 1  # at least one capture succeeded
    assert all(r == 409 for r in results if not isinstance(r, dict))
    for r in oks:
        assert r["trace_files"], r  # Perfetto artifact exists


def test_statusz_without_watchdog_reports_worker(tiny_gpt):
    from dnn_tpu.runtime.lm_server import LMServer

    cfg, prepared = tiny_gpt
    srv = LMServer(cfg, prepared, slots=1, max_len=32, prompt_pad=16,
                   metrics_port=0)
    try:
        base = f"http://127.0.0.1:{srv.metrics_server.port}"
        st = json.load(_get(base + "/statusz"))
        assert st["state"] == "ok"
        assert st["components"]["worker"]["state"] == "ok"
        assert _get(base + "/healthz").status == 200
    finally:
        srv.close()


# ----------------------------------------------------------------------
# tracing shim + gate
# ----------------------------------------------------------------------

def test_tracing_shim_is_the_obs_annotation():
    from dnn_tpu.obs import profile
    from dnn_tpu.utils import tracing

    assert tracing.span is profile.annotation
    assert tracing.step_span is profile.step_annotation
    # the gate: off -> the hot-path ctx is the shared nullcontext
    obs.set_enabled(False)
    try:
        assert profile.annotation_ctx("x") is profile._NULL_CTX
        with tracing.span("gated"):
            pass  # still a working context manager
    finally:
        obs.set_enabled(True)


def test_profiler_busy_is_exclusive():
    from dnn_tpu.obs import profile

    with profile._capture_lock:
        with pytest.raises(profile.ProfilerBusy):
            profile.capture(1, capture_root="/tmp/never")


def test_legacy_trace_to_still_annotates(monkeypatch):
    # the deprecated trace_to + span pattern must keep producing
    # annotated captures: trace_to marks the capture as recording so
    # annotation_ctx's hot-path gate (which otherwise only opens during
    # obs-driven captures) emits real TraceAnnotations
    import jax

    from dnn_tpu.obs import profile
    from dnn_tpu.utils import tracing

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    assert not profile.capturing()
    with tracing.trace_to("/tmp/never-written"):
        assert profile.capturing()
        ctx = profile.annotation_ctx("legacy-span")
        assert ctx is not profile._NULL_CTX
        with ctx:
            pass
    assert not profile.capturing()
    assert profile.annotation_ctx("after") is profile._NULL_CTX


def test_serve_metrics_is_the_full_v2_surface():
    # the public helper must not drift behind the endpoints the real
    # servers expose: it installs memory gauges and serves the whole
    # surface (LMServer and serve_stage construct through it)
    srv = obs.serve_metrics(0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        for path in ("/metrics", "/debugz", "/statusz", "/healthz"):
            with urllib.request.urlopen(base + path, timeout=5) as r:
                assert r.status == 200, path
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert b"process_resident_bytes" in r.read()
    finally:
        srv.close()


def test_memory_gauges_reinstall_after_registry_clear():
    # regression (ISSUE 10 tier-1 find): install -> registry.clear()
    # (a test/bench leg resetting series) -> any later server's
    # install must RE-register, not trust the per-registry id marker —
    # the latched marker left every later /metrics scrape without
    # host/device memory series, a deterministic cross-module suite
    # failure (LMServer installed, a transport test cleared, this
    # module's surface test scraped)
    from dnn_tpu.obs.mem import install_memory_gauges

    m = obs.metrics()
    assert m is not None
    install_memory_gauges(m)
    assert "process_resident_bytes" in m.gauges
    m.clear()
    assert "process_resident_bytes" not in m.gauges
    install_memory_gauges(m)  # must self-heal past the id marker
    assert "process_resident_bytes" in m.gauges


def test_pool_exhausted_episode_reopens_after_cancel_frees_blocks(tiny_gpt):
    # the episode latch dedupes per-step retries, but a shortage whose
    # held request is cancelled (never re-admitted) must not suppress
    # the NEXT episode: returning blocks to the pool ends the episode
    from dnn_tpu.runtime.paged_kvcache import InsufficientBlocks
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg, prepared = tiny_gpt
    srv = ContinuousBatcher(cfg, prepared, slots=3, max_len=64,
                            prompt_pad=16, paged_blocks=5, block_len=16)

    def n_exhausted():
        return sum(1 for e in obs.flight.recorder().events()
                   if e["kind"] == "pool_exhausted")

    base = n_exhausted()
    srv.submit(np.arange(1, 9), 24)            # 32 pos -> 2 of 4 blocks
    rid_small = srv.submit(np.arange(1, 9), 4)  # 12 pos -> 1 block
    with pytest.raises(InsufficientBlocks):     # needs 2, 1 free
        srv.submit(np.arange(1, 9), 24)
    assert n_exhausted() == base + 1
    with pytest.raises(InsufficientBlocks):     # retry: same episode
        srv.submit(np.arange(1, 9), 24)
    assert n_exhausted() == base + 1
    assert srv.cancel(rid_small)                # blocks return -> episode over
    with pytest.raises(InsufficientBlocks):     # needs 3, 2 free: NEW episode
        srv.submit(np.arange(1, 9), 40)
    assert n_exhausted() == base + 2


def test_watchdog_classifies_structurally_not_by_detail_text():
    # hung-vs-failed is the probe's structured timed_out flag, never a
    # substring sniff of the free-text detail: a FAST failure whose
    # message happens to contain "timeout" is degraded (the backend
    # answered), and a reported child timeout is wedged regardless of
    # its wording
    wd = Watchdog(period_s=0.2, probe_deadline_s=5.0,
                  device_probe=lambda d: (
                      False, "rpc timeout contacting coordinator"),
                  registry=None)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and wd.state() == "ok":
            time.sleep(0.05)
        assert wd.state() == "degraded"
    finally:
        wd.close()

    wd = Watchdog(period_s=0.2, probe_deadline_s=5.0,
                  device_probe=lambda d: (False, "chip stuck", True),
                  registry=None)
    wd.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and wd.state() != "wedged":
            time.sleep(0.05)
        assert wd.state() == "wedged"
    finally:
        wd.close()
