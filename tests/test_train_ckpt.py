"""Checkpoint save/resume tests (the capability half the reference lacks —
SURVEY §5 "Checkpoint / resume: LOAD-ONLY"). Runs on the virtual 8-device
CPU mesh from conftest.py so the sharded-resume test exercises real
NamedShardings.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.io.train_ckpt import (
    cleanup_old_checkpoints,
    latest_checkpoint,
    restore_train_state,
    save_train_state,
)
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import make_mesh, DATA_AXIS, MODEL_AXIS

CFG = gpt.PRESETS["gpt2-test"]


def _assert_trees_equal(a, b):
    jax.tree.map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x), np.asarray(y)),
        a, b,
    )


def test_roundtrip_params_and_opt_state(tmp_path):
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    opt = optax.adam(1e-3)
    state = (params, opt.init(params))

    save_train_state(str(tmp_path), 7, state)
    fresh = (gpt.init(jax.random.PRNGKey(1), CFG), opt.init(params))
    restored, step = restore_train_state(str(tmp_path), like=fresh)
    assert step == 7
    _assert_trees_equal(restored, state)


def test_roundtrip_bfloat16_leaves(tmp_path):
    state = {"w": jnp.ones((4, 4), jnp.bfloat16) * 1.5, "step": jnp.int32(3)}
    save_train_state(str(tmp_path), 1, state)
    restored, _ = restore_train_state(str(tmp_path), like=state)
    assert restored["w"].dtype == jnp.bfloat16
    _assert_trees_equal(restored, state)


def test_latest_and_cleanup(tmp_path):
    state = {"w": jnp.zeros((2,))}
    for s in (10, 20, 30, 40):
        save_train_state(str(tmp_path), s, state)
    path, step = latest_checkpoint(str(tmp_path))
    assert step == 40 and path.endswith("step_00000040.npz")
    removed = cleanup_old_checkpoints(str(tmp_path), keep=2)
    assert removed == 4  # 2 checkpoints x (npz + manifest)
    steps = sorted(
        int(f[5:13]) for f in os.listdir(tmp_path) if f.endswith(".npz")
    )
    assert steps == [30, 40]


def test_cleanup_removes_debris_and_keeps_complete(tmp_path):
    """Incomplete checkpoints must not count toward `keep`, and both debris
    shapes (npz without manifest, manifest without npz) are swept."""
    state = {"w": jnp.zeros((2,))}
    save_train_state(str(tmp_path), 10, state)
    (tmp_path / "step_00000020.npz").write_bytes(b"junk")  # npz, no manifest
    (tmp_path / "step_00000030.npz.manifest.json").write_text("{}")  # no npz
    removed = cleanup_old_checkpoints(str(tmp_path), keep=1)
    assert removed == 2
    assert sorted(os.listdir(tmp_path)) == [
        "step_00000010.npz", "step_00000010.npz.manifest.json"
    ]
    assert latest_checkpoint(str(tmp_path))[1] == 10


def test_overwrite_same_step(tmp_path):
    """Re-saving an existing step (restarted run re-reaching a boundary)
    replaces it; restore sees the new payload."""
    save_train_state(str(tmp_path), 5, {"w": jnp.zeros((3,))})
    save_train_state(str(tmp_path), 5, {"w": jnp.ones((3,))})
    restored, step = restore_train_state(str(tmp_path), like={"w": jnp.zeros((3,))})
    assert step == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(3))
    # no temp debris left behind
    assert sorted(os.listdir(tmp_path)) == [
        "step_00000005.npz", "step_00000005.npz.manifest.json"
    ]


def test_compressed_bf16_checkpoint(tmp_path):
    """compress_bf16 halves f32 leaf bytes; restore upcasts to the template
    dtype within bf16 precision. int leaves pass through untouched."""
    state = {
        "w": jnp.asarray(np.random.default_rng(0).standard_normal((64, 64)),
                         jnp.float32),
        "step": jnp.int32(9),
    }
    save_train_state(str(tmp_path), 1, state, compress_bf16=True)
    restored, _ = restore_train_state(str(tmp_path), like=state)
    assert restored["w"].dtype == np.float32
    assert int(restored["step"]) == 9
    np.testing.assert_allclose(
        np.asarray(restored["w"]), np.asarray(state["w"]), rtol=1e-2, atol=1e-2
    )
    # and it really is smaller than the uncompressed save
    import os as _os

    full_dir = tmp_path / "full"
    save_train_state(str(full_dir), 1, state)
    small = _os.path.getsize(tmp_path / "step_00000001.npz")
    big = _os.path.getsize(full_dir / "step_00000001.npz")
    assert small < 0.6 * big


def test_latest_skips_manifestless_debris(tmp_path):
    """A crash can leave an npz without its manifest; resume must fall back
    to the previous complete checkpoint instead of dying on the orphan."""
    state = {"w": jnp.zeros((2,))}
    save_train_state(str(tmp_path), 10, state)
    # simulate a kill between the manifest and npz writes of step 20
    (tmp_path / "step_00000020.npz").write_bytes(b"not a checkpoint")
    path, step = latest_checkpoint(str(tmp_path))
    assert step == 10
    restored, s = restore_train_state(str(tmp_path), like=state)
    assert s == 10


def test_restore_rejects_shape_mismatch(tmp_path):
    save_train_state(str(tmp_path), 1, {"w": jnp.zeros((2, 2))})
    with pytest.raises(ValueError, match="shape mismatch"):
        restore_train_state(str(tmp_path), like={"w": jnp.zeros((3, 3))})


def test_restore_rejects_missing_leaf(tmp_path):
    save_train_state(str(tmp_path), 1, {"w": jnp.zeros((2,))})
    with pytest.raises(KeyError, match="missing leaf"):
        restore_train_state(
            str(tmp_path), like={"w": jnp.zeros((2,)), "b": jnp.zeros((2,))}
        )


def test_sharded_state_resumes_with_sharding(tmp_path):
    """A tp-sharded train state round-trips and lands back on the mesh."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    params, specs = train.init_sharded(
        lambda rng: gpt.init(rng, CFG), jax.random.PRNGKey(0), mesh
    )
    save_train_state(str(tmp_path), 5, params)

    template, _ = train.init_sharded(
        lambda rng: gpt.init(rng, CFG), jax.random.PRNGKey(9), mesh
    )
    restored, step = restore_train_state(str(tmp_path), like=template)
    assert step == 5
    qkv = restored["h_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == specs["h_0"]["attn"]["qkv"]["kernel"]
    _assert_trees_equal(restored, params)


def test_fit_resume_matches_uninterrupted():
    """fit() interrupted at step 3 + resume == fit() straight through."""
    import tempfile

    apply_fn = gpt.make_apply(CFG)
    opt = optax.sgd(1e-2)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    raw_step = train.make_train_step(loss_fn, opt)

    def step_fn(state, batch):
        p, s = state
        p, s, l = raw_step(p, s, batch)
        return (p, s), l

    def batches():
        k = jax.random.PRNGKey(42)
        while True:
            k, sub = jax.random.split(k)
            yield jax.random.randint(sub, (4, 17), 0, CFG.vocab_size)

    params = gpt.init(jax.random.PRNGKey(0), CFG)
    init_state = (params, opt.init(params))

    # straight through, 6 steps
    ref_state, _ = train.fit(step_fn, init_state, batches(), num_steps=6)

    with tempfile.TemporaryDirectory() as ckpt_dir:
        # interrupted: run 3 steps (checkpointing every step), then resume
        # with a FRESH deterministic generator — fit's default
        # advance_batches=True must line the data back up with the step
        train.fit(
            step_fn, init_state, batches(), num_steps=3,
            ckpt_dir=ckpt_dir, ckpt_every=1,
        )
        resumed, start = train.resume_or_init(ckpt_dir, init_state)
        assert start == 3
        final, _ = train.fit(
            step_fn, resumed, batches(), num_steps=6, start_step=start,
        )

    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        final, ref_state,
    )


# ----------------------------------------------------------------------
# async checkpointing
# ----------------------------------------------------------------------

def test_async_save_snapshot_semantics(tmp_path):
    """The write captures the state AT save() time: later updates (or
    donation invalidating the device buffers) cannot leak in, and the
    restored tree equals the snapshot bit-for-bit."""
    from dnn_tpu.io.train_ckpt import AsyncCheckpointer

    state0 = {"w": jnp.arange(8.0), "step": jnp.int32(0)}
    with AsyncCheckpointer() as ck:
        ck.save(str(tmp_path), 1, state0)
        # the caller immediately moves on (as a train loop would)
        state1 = jax.tree.map(lambda x: x + 100, state0)
        ck.save(str(tmp_path), 2, state1)
        ck.wait()
        got1, s1 = restore_train_state(str(tmp_path), state0, step=1)
        got2, s2 = restore_train_state(str(tmp_path), state0, step=2)
    _assert_trees_equal(got1, state0)
    _assert_trees_equal(got2, state1)
    path, step = latest_checkpoint(str(tmp_path))
    assert step == 2


def test_async_error_surfaces_on_wait(tmp_path):
    """A writer-side failure must raise in the caller's loop, not vanish
    in the background thread."""
    from dnn_tpu.io.train_ckpt import AsyncCheckpointer

    blocker = tmp_path / "not_a_dir"
    blocker.write_text("file where the ckpt dir should go")
    ck = AsyncCheckpointer()
    ck.save(str(blocker), 1, {"w": jnp.ones((2,))})
    with pytest.raises(RuntimeError, match="async checkpoint"):
        ck.wait()
    # the checkpointer recovers: a good save afterwards works
    ck.save(str(tmp_path), 2, {"w": jnp.ones((2,))})
    ck.close()
    assert latest_checkpoint(str(tmp_path))[1] == 2


def test_async_close_is_idempotent_and_rejects_after(tmp_path):
    from dnn_tpu.io.train_ckpt import AsyncCheckpointer

    ck = AsyncCheckpointer()
    ck.save(str(tmp_path), 5, {"w": jnp.zeros((3,))})
    ck.close()
    ck.close()
    with pytest.raises(RuntimeError, match="closed"):
        ck.save(str(tmp_path), 6, {"w": jnp.zeros((3,))})
    assert latest_checkpoint(str(tmp_path))[1] == 5


def test_async_save_copies_numpy_leaves(tmp_path):
    """Host-side (numpy) leaves must be COPIED at save() time — an
    in-place mutation after save() cannot leak into the checkpoint."""
    from dnn_tpu.io.train_ckpt import AsyncCheckpointer

    w = np.arange(6.0)
    with AsyncCheckpointer() as ck:
        ck.save(str(tmp_path), 1, {"w": w})
        w[:] = -1.0  # in-place, after save
        ck.wait()
    got, _ = restore_train_state(str(tmp_path), {"w": np.zeros(6)}, step=1)
    np.testing.assert_array_equal(got["w"], np.arange(6.0))
