"""Continuous-batching server: parity with solo decode, slot isolation,
mid-flight entry/exit, EOS retirement, pool reuse.

The contract: a request's token stream is identical to a solo batch-1
`make_generate` run of the same prompt — whatever else shares the pool,
whenever it joined. That is what makes continuous batching a pure
throughput feature rather than a semantics change."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def setup():
    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    return cfg, prepared


def _solo(cfg, prepared, prompt, n):
    fn = make_generate(cfg, max_new_tokens=n)
    out = fn(prepared, jnp.asarray(prompt, jnp.int32)[None, :], jax.random.PRNGKey(9))
    return np.asarray(out)[0]


def test_single_request_matches_solo(setup):
    cfg, prepared = setup
    prompt = np.arange(1, 9) % cfg.vocab_size
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=cfg.block_size,
                            prompt_pad=16)
    rid = srv.submit(prompt, max_new_tokens=10)
    res = srv.drain()
    np.testing.assert_array_equal(res[rid], _solo(cfg, prepared, prompt, 10))


def test_concurrent_requests_are_isolated(setup):
    """Different prompts/lengths share the pool; each equals its solo run."""
    cfg, prepared = setup
    p1 = (np.arange(1, 7) * 3) % cfg.vocab_size
    p2 = (np.arange(1, 12) * 5) % cfg.vocab_size
    srv = ContinuousBatcher(cfg, prepared, slots=3, max_len=cfg.block_size,
                            prompt_pad=16)
    r1 = srv.submit(p1, max_new_tokens=8)
    r2 = srv.submit(p2, max_new_tokens=12)
    res = srv.drain()
    np.testing.assert_array_equal(res[r1], _solo(cfg, prepared, p1, 8))
    np.testing.assert_array_equal(res[r2], _solo(cfg, prepared, p2, 12))


def test_midflight_entry_and_slot_reuse(setup):
    """A request joining mid-decode doesn't disturb running ones, and a
    retired slot serves a new request correctly (stale cache never leaks)."""
    cfg, prepared = setup
    p1 = (np.arange(1, 10) * 7) % cfg.vocab_size
    p2 = (np.arange(1, 5) * 11) % cfg.vocab_size
    p3 = (np.arange(1, 8) * 13) % cfg.vocab_size
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=cfg.block_size,
                            prompt_pad=16)
    r1 = srv.submit(p1, max_new_tokens=12)
    for _ in range(3):
        srv.step()
    r2 = srv.submit(p2, max_new_tokens=4)  # joins mid-flight
    while srv.free_slots() == 0:
        srv.step()
    r3 = srv.submit(p3, max_new_tokens=6)  # reuses r2's slot
    res = srv.drain()
    np.testing.assert_array_equal(res[r1], _solo(cfg, prepared, p1, 12))
    np.testing.assert_array_equal(res[r2], _solo(cfg, prepared, p2, 4))
    np.testing.assert_array_equal(res[r3], _solo(cfg, prepared, p3, 6))


def test_eos_retires_early(setup):
    """EOS mid-decode truncates the stream and frees the slot. Greedy
    streams of the tiny random model collapse to one repeated token, so
    sample with temperature: two servers with identical seeds produce
    identical streams, and the one with eos_id set stops at its first
    occurrence."""
    cfg, prepared = setup
    prompt = np.arange(1, 6)

    def run(eos_id):
        srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=cfg.block_size,
                                prompt_pad=16, temperature=1.0, seed=42,
                                eos_id=eos_id)
        rid = srv.submit(prompt, max_new_tokens=16)
        return srv.drain()[rid]

    full = run(eos_id=None)
    assert len(full) == 16
    # first token value whose first occurrence is mid-stream
    first_at = {}
    for i, t in enumerate(full):
        first_at.setdefault(int(t), i)
    eos, idx = next(((t, i) for t, i in first_at.items() if i >= 1), (None, None))
    assert eos is not None, "sampled stream should vary"
    trunc = run(eos_id=eos)
    assert len(trunc) == idx + 1 and trunc[-1] == eos
    np.testing.assert_array_equal(trunc, full[: idx + 1])


def test_sampled_stream_isolated_from_pool(setup):
    """A seeded sampled request emits the same tokens whether it runs
    alone or joins a busy pool mid-flight — the rng-isolation contract."""
    cfg, prepared = setup
    p1 = np.arange(1, 9)
    other = (np.arange(1, 6) * 7) % cfg.vocab_size

    def run_alone():
        srv = ContinuousBatcher(cfg, prepared, slots=3, max_len=cfg.block_size,
                                prompt_pad=16, temperature=1.0, seed=5)
        rid = srv.submit(p1, max_new_tokens=10, seed=123)
        return srv.drain()[rid]

    def run_busy():
        srv = ContinuousBatcher(cfg, prepared, slots=3, max_len=cfg.block_size,
                                prompt_pad=16, temperature=1.0, seed=5)
        srv.submit(other, max_new_tokens=8)   # different rid ordering
        srv.step()
        srv.step()
        rid = srv.submit(p1, max_new_tokens=10, seed=123)
        return srv.drain()[rid]

    np.testing.assert_array_equal(run_alone(), run_busy())


def test_pool_full_raises(setup):
    cfg, prepared = setup
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=cfg.block_size,
                            prompt_pad=8)
    srv.submit(np.arange(1, 4), max_new_tokens=8)
    with pytest.raises(RuntimeError, match="no free slot"):
        srv.submit(np.arange(1, 4), max_new_tokens=8)


def test_budget_validation(setup):
    cfg, prepared = setup
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=32, prompt_pad=8)
    with pytest.raises(ValueError, match="exceeds max_len"):
        srv.submit(np.arange(1, 8), max_new_tokens=30)
    with pytest.raises(ValueError, match="at least one token"):
        srv.submit(np.array([], np.int32), max_new_tokens=4)
    # > prompt_pad is no longer an error: it prefills in chunks
    rid = srv.submit(np.arange(1, 12) % cfg.vocab_size, max_new_tokens=4)
    assert rid in srv.drain()


def test_one_prefill_one_decode_program(setup):
    """The batcher's compile story: ONE prefill-chunk program, ONE finish
    program, ONE decode program — across mixed prompt lengths (including
    multi-chunk prompts longer than prompt_pad), slots, and chunk counts.
    Positions/slots enter as traced scalars, so no combination may
    retrace — this pins the "three compiled programs" claim in the
    module docstring."""
    cfg, prepared = setup
    srv = ContinuousBatcher(cfg, prepared, slots=4, max_len=64, prompt_pad=16)
    for plen in (3, 12, 20, 37):  # 1-chunk, 1-chunk, 2-chunk, 3-chunk
        srv.submit(np.arange(1, plen + 1) % cfg.vocab_size, max_new_tokens=4)
    srv.drain()
    assert srv._prefill_chunk._cache_size() == 1, (
        f"prefill chunk compiled {srv._prefill_chunk._cache_size()}x")
    assert srv._prefill_finish._cache_size() == 1
    assert srv._decode._cache_size() == 1


def test_long_prompt_chunked_prefill_matches_solo(setup):
    """A prompt longer than prompt_pad prefills in chunks and still
    reproduces the solo batch-1 decode token-for-token."""
    cfg, prepared = setup
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64, prompt_pad=8)
    prompt = (np.arange(1, 22) * 3) % cfg.vocab_size  # 21 tokens = 3 chunks
    rid = srv.submit(prompt, max_new_tokens=6)
    got = srv.drain()[rid]
    want = np.asarray(_solo(cfg, prepared, prompt, 6))
    np.testing.assert_array_equal(got, want)


def test_chunked_prefill_non_divisible_max_len(setup):
    """Regression (review repro): max_len not a multiple of prompt_pad —
    the tail chunk must not have its cache write clamped back onto real
    prompt positions. 17-token prompt, prompt_pad=8, max_len=20."""
    cfg, prepared = setup
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=20, prompt_pad=8)
    prompt = (np.arange(1, 18) * 5) % cfg.vocab_size  # 17 tokens, 3 chunks
    rid = srv.submit(prompt, max_new_tokens=3)
    got = srv.drain()[rid]
    want = np.asarray(_solo(cfg, prepared, prompt, 3))
    np.testing.assert_array_equal(got, want)
