"""LM serving daemon tests: the gRPC edge on top of the continuous batcher.

The reference's serving process answers one CNN forward per SendTensor
(/root/reference/node.py:35-105); the LM daemon answers generation — same
wire protocol, prompt ids in, generated tokens out, concurrent requests
sharing the decode pool. Parity oracle is the solo KV-cache decoder."""

import threading
import time

import jax
import numpy as np
import pytest

from dnn_tpu.comm.client import NodeClient
from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.lm_server import (
    parse_gen_options,
    start_lm_server_in_background,
)

CFG = gpt.PRESETS["gpt2-test"]
PORT = 59261


@pytest.fixture(scope="module")
def lm_server():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    t, stop = start_lm_server_in_background(
        CFG, prepared, port=PORT, slots=3, max_len=64, prompt_pad=16,
        default_max_new=8)
    yield prepared
    stop()


def test_parse_gen_options():
    assert parse_gen_options("gen:12:7", 32) == (12, 7, {})
    assert parse_gen_options("gen:12", 32) == (12, None, {})
    assert parse_gen_options("gen", 32) == (32, None, {})
    assert parse_gen_options("", 32) == (32, None, {})
    assert parse_gen_options("whatever:junk:x", 32) == (32, None, {})
    assert parse_gen_options("gen:0", 32) == (1, None, {})  # floored at 1
    # named per-request sampling overrides, any position after the prefix
    assert parse_gen_options("gen:12:7:t=0.9:k=40:p=0.95", 32) == (
        12, 7, {"temperature": 0.9, "top_k": 40, "top_p": 0.95})
    assert parse_gen_options("gen:t=1.5", 32) == (
        32, None, {"temperature": 1.5})
    assert parse_gen_options("gen:12:t=0.5:99", 32) == (
        12, 99, {"temperature": 0.5})  # positional continues past named
    assert parse_gen_options("gen:t=bogus:x=1", 32) == (32, None, {})
    # per-request LoRA adapter selection (multi-adapter serving)
    assert parse_gen_options("gen:8:a=1", 32) == (8, None, {"adapter": 1})
    # logit bias pairs ride "~" inside one segment (":" separates segments)
    assert parse_gen_options("gen:8:b=5~-100,7~2.5", 32) == (
        8, None, {"logit_bias": {5: -100.0, 7: 2.5}})
    assert parse_gen_options("gen:8:b=garbage", 32) == (8, None, {})
    # only the literal 'gen' prefix carries options: a foreign client's
    # tracing id must NOT be reinterpreted as a token budget
    assert parse_gen_options("req:1234", 32) == (32, None, {})
    assert parse_gen_options("cifar_pipe_2node_001", 32) == (32, None, {})


def test_health_and_pool_stats(lm_server):
    c = NodeClient(f"127.0.0.1:{PORT}")
    assert c.health_check()
    assert "pool" in c.send_message("tester", "stats")
    c.close()


def test_generate_matches_solo_decode(lm_server):
    prepared = lm_server
    prompt = np.array([5, 3, 7, 1, 2], np.int32)
    n_new = 6
    c = NodeClient(f"127.0.0.1:{PORT}")
    got = c.generate(prompt, max_new_tokens=n_new)
    c.close()
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, prompt[None, :], jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_per_request_sampling_over_the_wire(lm_server):
    """temperature/top_k/top_p ride the request_id; a seeded sampled
    request over gRPC equals the same request submitted to a local
    batcher directly."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    prepared = lm_server
    prompt = np.array([5, 3, 7, 1, 2], np.int32)
    c = NodeClient(f"127.0.0.1:{PORT}")
    got = c.generate(prompt, max_new_tokens=6, seed=17, temperature=0.8,
                     top_k=9, top_p=0.9)
    c.close()
    # server fixture: slots=3, max_len=64, seed default 0
    local = ContinuousBatcher(CFG, prepared, slots=3, max_len=64,
                              prompt_pad=16)
    rid = local.submit(prompt, 6, seed=17, temperature=0.8, top_k=9,
                       top_p=0.9)
    want = local.drain()[rid]
    np.testing.assert_array_equal(got, want)


def test_concurrent_requests_batch_together(lm_server):
    """More concurrent callers than slots: all must finish, each with its
    solo-decode tokens (pool isolation), exercising queue + slot reuse."""
    prepared = lm_server
    prompts = [np.array(p, np.int32) for p in
               ([5, 3, 7], [2, 2, 9, 4], [1], [8, 6, 5, 4, 3], [11, 12])]
    n_new = 5
    results = [None] * len(prompts)
    errors = []

    def call(i):
        try:
            c = NodeClient(f"127.0.0.1:{PORT}")
            results[i] = c.generate(prompts[i], max_new_tokens=n_new)
            c.close()
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errors.append((i, e))

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, f"concurrent generate failed: {errors}"

    solo = make_generate(CFG, max_new_tokens=n_new)
    for i, p in enumerate(prompts):
        want = np.asarray(solo(prepared, p[None, :], jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[i], want)


def test_bad_prompt_rejected(lm_server):
    import grpc

    c = NodeClient(f"127.0.0.1:{PORT}")
    with pytest.raises((grpc.RpcError, RuntimeError)):
        # prompt + budget exceeding max_len=64 -> INVALID_ARGUMENT
        # (prompts longer than prompt_pad alone are fine: chunked prefill)
        c.generate(np.arange(70, dtype=np.int32) % 256, max_new_tokens=4)
    with pytest.raises((grpc.RpcError, RuntimeError)):
        # float payload -> INVALID_ARGUMENT (not silently truncated)
        c.send_tensor(np.zeros(4, np.float32), request_id="gen:4")
    c.close()


def test_compile_cache_guard_soak():
    """Soak across the compile-cache guard boundary: with a budget of 1
    the worker clears ALL XLA caches at every idle point, so each
    request round recompiles the three programs — the server must keep
    producing identical (seeded) results through repeated
    clear+recompile cycles. This is the bounded form of the suite-scale
    pathology (utils/xla_cache.py): a week-long daemon periodically
    dropping caches must behave exactly like one that never did."""
    from dnn_tpu.runtime.lm_server import _BatcherWorker
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    b = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                          prompt_pad=8)
    w = _BatcherWorker(b, compile_cache_budget=1)
    w.start()
    try:
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        want = w.submit(prompt, 6, 7).result(timeout=120)
        for _ in range(3):
            # idle gap so the worker reaches its safe boundary and the
            # budget-1 guard fires before the next admit
            time.sleep(0.3)
            got = w.submit(prompt, 6, 7).result(timeout=120)
            np.testing.assert_array_equal(got, want)
        assert w.cache_guard.clears >= 1, \
            "guard never fired despite budget=1"
    finally:
        w.stop(drain=False)


def test_compile_cache_guard_off_by_default_budget():
    """A steady server (three compiled programs) must never trip the
    default budget — the guard costs nothing until the pathology-shaped
    workload appears."""
    from dnn_tpu.runtime.lm_server import _BatcherWorker
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    b = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                          prompt_pad=8)
    w = _BatcherWorker(b)  # default budget
    w.start()
    try:
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        w.submit(prompt, 4, 7).result(timeout=120)
        time.sleep(0.3)
        w.submit(prompt, 4, 7).result(timeout=120)
        assert w.cache_guard.clears == 0
    finally:
        w.stop(drain=False)
