"""KV-cache generation tests.

Key invariant: incremental decode through the cache must produce exactly
the tokens that repeated full-sequence forwards (the reference's only mode,
gpt_model_parts.py:13-50) would produce greedily."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import forward_with_cache, init_cache, make_generate

CFG = gpt.PRESETS["gpt2-test"]  # block_size=64, vocab=256, L=4, H=4, C=64


def _prepared(seed=0):
    params = gpt.init(jax.random.PRNGKey(seed), CFG)
    return params, gpt.prepare_stacked(params, CFG)


def test_prefill_logits_match_full_forward():
    params, prepared = _prepared()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    cache = init_cache(CFG, 2, 32)
    logits_cache, cache = forward_with_cache(prepared, ids, cache, 0, cfg=CFG)
    logits_full = gpt.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_cache), np.asarray(logits_full), atol=2e-4
    )


def test_incremental_decode_matches_full_recompute():
    params, prepared = _prepared()
    apply_fn = gpt.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG.vocab_size)
    n_new = 6

    gen = make_generate(CFG, max_new_tokens=n_new, temperature=0.0)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    # oracle: greedy via repeated full forwards
    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    want = np.stack(want, axis=1)
    np.testing.assert_array_equal(got, want)


def test_generate_single_token():
    _, prepared = _prepared()
    ids = jnp.zeros((1, 4), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=1, temperature=0.0)
    out = gen(prepared, ids, jax.random.PRNGKey(0))
    assert out.shape == (1, 1)


def test_generate_sampling_is_reproducible_and_in_range():
    _, prepared = _prepared()
    ids = jnp.zeros((2, 4), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=5, temperature=0.8, top_k=20)
    a = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    b = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    c = np.asarray(gen(prepared, ids, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 5)
    assert (a >= 0).all() and (a < CFG.vocab_size).all()
    assert not np.array_equal(a, c)  # different seed, different stream


def test_generate_rejects_overlong():
    _, prepared = _prepared()
    ids = jnp.zeros((1, 60), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=10, temperature=0.0)
    try:
        gen(prepared, ids, jax.random.PRNGKey(0))
        raised = False
    except ValueError as e:
        raised = "block_size" in str(e)
    assert raised
