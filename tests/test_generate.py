"""KV-cache generation tests.

Key invariant: incremental decode through the cache must produce exactly
the tokens that repeated full-sequence forwards (the reference's only mode,
gpt_model_parts.py:13-50) would produce greedily."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import forward_with_cache, init_cache, make_generate

CFG = gpt.PRESETS["gpt2-test"]  # block_size=64, vocab=256, L=4, H=4, C=64


def _prepared(seed=0):
    params = gpt.init(jax.random.PRNGKey(seed), CFG)
    return params, gpt.prepare_stacked(params, CFG)


def test_prefill_logits_match_full_forward():
    params, prepared = _prepared()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    cache = init_cache(CFG, 2, 32)
    logits_cache, cache = forward_with_cache(prepared, ids, cache, 0, cfg=CFG)
    logits_full = gpt.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_cache), np.asarray(logits_full), atol=2e-4
    )


def test_incremental_decode_matches_full_recompute():
    params, prepared = _prepared()
    apply_fn = gpt.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG.vocab_size)
    n_new = 6

    gen = make_generate(CFG, max_new_tokens=n_new, temperature=0.0)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    # oracle: greedy via repeated full forwards
    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    want = np.stack(want, axis=1)
    np.testing.assert_array_equal(got, want)


def test_generate_single_token():
    _, prepared = _prepared()
    ids = jnp.zeros((1, 4), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=1, temperature=0.0)
    out = gen(prepared, ids, jax.random.PRNGKey(0))
    assert out.shape == (1, 1)


def test_generate_sampling_is_reproducible_and_in_range():
    _, prepared = _prepared()
    ids = jnp.zeros((2, 4), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=5, temperature=0.8, top_k=20)
    a = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    b = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    c = np.asarray(gen(prepared, ids, jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 5)
    assert (a >= 0).all() and (a < CFG.vocab_size).all()
    assert not np.array_equal(a, c)  # different seed, different stream


def test_generate_rejects_overlong():
    _, prepared = _prepared()
    ids = jnp.zeros((1, 60), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=10, temperature=0.0)
    try:
        gen(prepared, ids, jax.random.PRNGKey(0))
        raised = False
    except ValueError as e:
        raised = "block_size" in str(e)
    assert raised


def test_top_p_sampling():
    """Nucleus sampling: with a known distribution, top_p must restrict
    draws to the smallest prefix reaching the mass — and compose with
    temperature/top_k without shape tricks."""
    from dnn_tpu.runtime.generate import _sample

    # hand-built logits: probs ~ [0.5, 0.3, 0.1, 0.06, 0.04]
    p = np.array([0.5, 0.3, 0.1, 0.06, 0.04])
    logits = jnp.asarray(np.log(p)[None, :], jnp.float32)
    draws = []
    for i in range(300):
        draws.append(int(_sample(logits, jax.random.PRNGKey(i),
                                 temperature=1.0, top_k=None, top_p=0.75)[0]))
    seen = set(draws)
    # nucleus at 0.75: keep {0 (0.5), 1 (cum-before 0.5 < .75)}; token 2's
    # mass-before is 0.8 >= .75 -> excluded
    assert seen <= {0, 1}, seen
    assert 0 in seen and 1 in seen
    # top-1-always-kept guard: tiny p still samples something valid
    t = int(_sample(logits, jax.random.PRNGKey(0), temperature=1.0,
                    top_k=None, top_p=1e-6)[0])
    assert t == 0
    # greedy ignores top_p entirely
    g = _sample(logits, jax.random.PRNGKey(0), temperature=0.0,
                top_k=None, top_p=0.5)
    assert int(g[0]) == 0


def test_top_p_prefilter_matches_full_vocab_filter():
    """The static top-k prefilter (TOP_P_PREFILTER_K candidates ranked
    instead of a full-vocab sort) must be DISTRIBUTION-IDENTICAL to the
    full filter whenever the nucleus fits inside k — proven the strong
    way: same filtered logits -> same categorical draw per key."""
    from dnn_tpu.runtime.generate import (
        _NEG_BIG,
        _sample,
        TOP_P_PREFILTER_K,
    )

    rng = np.random.default_rng(0)
    V = 4096  # > TOP_P_PREFILTER_K so the prefilter actually engages
    # peaked logits (trained-LM-like): top-256 holds essentially all mass
    logits_np = (7.0 * rng.standard_normal((3, V))).astype(np.float32)
    probs = np.exp(logits_np - logits_np.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    top_mass = np.sort(probs, -1)[:, ::-1][:, :TOP_P_PREFILTER_K].sum(-1)
    assert (top_mass > 0.999).all(), "fixture must keep nucleus inside k"

    for p in (0.1, 0.5, 0.9, 0.99):
        # reference: the full-vocab sort filter, in numpy
        order = np.argsort(-logits_np, axis=-1)
        sp = np.take_along_axis(probs, order, axis=-1)
        cum = np.cumsum(sp, axis=-1)
        keep = (cum - sp) < p
        n_keep = np.maximum(keep.sum(-1), 1)
        thresh = np.take_along_axis(
            np.take_along_axis(logits_np, order, -1), (n_keep - 1)[:, None], -1)
        ref_filtered = np.where(logits_np < thresh, _NEG_BIG, logits_np)

        for i in range(20):
            key = jax.random.PRNGKey(i)
            want = np.asarray(jax.random.categorical(
                key, jnp.asarray(ref_filtered), axis=-1))
            got = np.asarray(_sample(jnp.asarray(logits_np), key,
                                     temperature=1.0, top_k=None, top_p=p))
            np.testing.assert_array_equal(got, want)


def test_top_p_prefilter_overflow_truncates_to_top_k():
    """When the nucleus would exceed TOP_P_PREFILTER_K tokens (near-flat
    logits, p -> 1), the prefilter truncates to the k best — a strictly
    tighter cut, so every draw still comes from the top-k set."""
    from dnn_tpu.runtime.generate import _sample, TOP_P_PREFILTER_K

    rng = np.random.default_rng(1)
    V = 2048
    logits_np = (0.01 * rng.standard_normal((1, V))).astype(np.float32)
    top_set = set(np.argsort(-logits_np[0])[:TOP_P_PREFILTER_K].tolist())
    for i in range(50):
        t = int(_sample(jnp.asarray(logits_np), jax.random.PRNGKey(i),
                        temperature=1.0, top_k=None, top_p=0.999)[0])
        assert t in top_set


def test_generate_with_top_p_runs_and_reproduces():
    _, prepared = _prepared()
    ids = jnp.zeros((2, 4), jnp.int32)
    gen = make_generate(CFG, max_new_tokens=5, temperature=0.8, top_p=0.9)
    a = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    b = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < CFG.vocab_size).all()
