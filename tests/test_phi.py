"""Phi family: the parallel-residual block (Phi-2 shape) on the LLaMA
machinery — biased LayerNorms, partial rotary, plain gelu MLP, biases on
every projection.

The switches ride the same one-definition helpers every other family
uses (_norm, _rope_apply, _branches_residual), so the dense forward,
cached decode, batcher rows, and partitions inherit them with no
per-path plumbing — pinned here against HF PhiForCausalLM and the
framework's own cross-path parity contracts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

CFG = llama.PRESETS["phi-test"]  # L=4, H=4 (MHA), C=64, rotary 8 of 16


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_structure():
    p = _params()
    blk = p["h_0"]
    assert "ln_2" not in blk, "parallel block has ONE norm"
    assert "bias" in blk["ln_1"] and "bias" in p["ln_f"]  # LayerNorm
    assert "gate" not in blk["mlp"], "plain MLP: fc1/fc2 only"
    for k in ("up", "down"):
        assert "bias" in blk["mlp"][k], k
    assert "bias" in blk["attn"]["o"] and "bias" in p["lm_head"]
    assert CFG.rotary_dim == 8 and CFG.head_dim == 16


def test_config_validation():
    import dataclasses

    with pytest.raises(ValueError, match="incompatible"):
        dataclasses.replace(CFG, post_norms=True)
    with pytest.raises(ValueError, match="rotary_dim"):
        dataclasses.replace(CFG, rotary_dim=7)  # odd
    with pytest.raises(ValueError, match="rotary_dim"):
        dataclasses.replace(CFG, rotary_dim=32)  # > head_dim


def test_partial_rotary_leaves_tail_dims_unrotated():
    """The pass-through half is the whole point of partial rotary: a
    position change must not touch dims >= rotary_dim of q/k."""
    p = _params()
    bp = gpt.prepare_stacked(p, CFG)["blocks"]
    blk = jax.tree.map(lambda a: a[0], bp)
    h = jax.random.normal(jax.random.PRNGKey(1), (1, 1, CFG.n_embd))
    q0, k0, _ = llama._qkv_rope(blk, h, jnp.asarray([0]), cfg=CFG,
                                compute_dtype=None)
    q9, k9, _ = llama._qkv_rope(blk, h, jnp.asarray([9]), cfg=CFG,
                                compute_dtype=None)
    d = CFG.rotary_dim
    assert not np.allclose(np.asarray(q0)[..., :d], np.asarray(q9)[..., :d])
    np.testing.assert_array_equal(np.asarray(q0)[..., d:],
                                  np.asarray(q9)[..., d:])
    np.testing.assert_array_equal(np.asarray(k0)[..., d:],
                                  np.asarray(k9)[..., d:])


def test_hf_phi_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.PhiConfig)
    assert hf_cfg.partial_rotary_factor == 0.5
    torch.manual_seed(0)
    model = transformers.PhiForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    assert any(k.endswith("self_attn.dense.bias") for k in sd)

    from dnn_tpu.io.checkpoint import phi_params_from_state_dict

    params = phi_params_from_state_dict(sd)
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy-generate parity: the cached decode (partial rotary at
    # cache positions, parallel residual per step) matches HF generate
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 10))
    n_new = 12
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 10:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_generate_matches_stepwise_forward():
    p = _params(seed=3)
    prepared = gpt.prepare_stacked(p, CFG)
    apply = llama.make_apply(CFG)
    prompt = np.random.RandomState(4).randint(0, CFG.vocab_size, (1, 8))
    ids = list(prompt[0])
    for _ in range(8):
        logits = np.asarray(apply(p, jnp.asarray([ids])))
        ids.append(int(logits[0, -1].argmax()))
    want = np.asarray(ids[8:])
    got = np.asarray(llama.make_generate(CFG, max_new_tokens=8)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_batcher_matches_solo():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(seed=5)
    prepared = gpt.prepare_stacked(p, CFG)
    prompts = [np.asarray([3, 1, 4, 1, 5]), np.asarray([9, 2, 6])]
    n_new = 7
    solo = llama.make_generate(CFG, max_new_tokens=n_new)
    want = [np.asarray(solo(prepared, jnp.asarray(pr[None]),
                            jax.random.PRNGKey(0)))[0] for pr in prompts]
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=CFG.block_size,
                            prompt_pad=8,
                            family=llama.LlamaFamilyRows(CFG))
    rids = [srv.submit(pr, max_new_tokens=n_new) for pr in prompts]
    srv.drain()
    for rid, w in zip(rids, want):
        np.testing.assert_array_equal(srv.results[rid], w)


def test_pipeline_decode_matches_solo():
    """The parallel block + partial rotary ride the generic pipeline
    decode (stage-ring ppermute, per-stage cache shards) unchanged —
    token parity with the solo decoder on the 4-stage mesh."""
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    mesh = make_mesh({STAGE_AXIS: 4}, jax.devices()[:4])
    p = _params(seed=6)
    prepared = gpt.prepare_stacked(p, CFG)
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    prompt = np.random.RandomState(7).randint(0, CFG.vocab_size, (2, 5))
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=6)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(1)))
    got = np.asarray(llama.make_pipeline_generate(
        CFG, mesh, max_new_tokens=6)(
        stage_blocks, aux, jnp.asarray(prompt), jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(got, want)


def test_torch_export_round_trips_to_hf():
    """Fine-tune-and-hand-back: framework Phi params export to an HF
    PhiForCausalLM state dict that loads cleanly and reproduces this
    framework's logits."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from dnn_tpu.io.torch_export import llama_state_dict_from_params

    p = _params(seed=7)
    sd = llama_state_dict_from_params(p)  # auto-detects the Phi layout
    assert "model.layers.0.self_attn.dense.weight" in sd
    assert "model.final_layernorm.bias" in sd and "lm_head.bias" in sd
    model = transformers.PhiForCausalLM(
        llama.to_hf_config(CFG, attn_implementation="eager")).eval()
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd.items()}, strict=False)
    assert not unexpected, unexpected
    assert all("rotary_emb" in m for m in missing), missing  # buffers
    ids = np.random.RandomState(8).randint(0, CFG.vocab_size, (2, 10))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


def test_registry_and_partition_compose():
    from dnn_tpu.registry import get_model

    spec = get_model("phi-test")
    params = spec.init(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randint(0, CFG.vocab_size, (2, 8))
    full = np.asarray(spec.apply(params, jnp.asarray(x)))
    stages = spec.partition(2)
    h = jnp.asarray(x)
    for st in stages:
        h = st.apply(st.slice_params(params), h)
    np.testing.assert_allclose(np.asarray(h), full, atol=1e-4, rtol=1e-4)
