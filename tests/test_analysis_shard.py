"""Fixture suite for the sharding-safety analyzer (analysis/shardcheck).

One known-bad snippet per SHD AST rule (must be flagged) and a known-good
twin (must not be); buggy-variant PROGRAM fixtures through the audit's
own helpers (a declared-sharded leaf compiled replicated FAILS the
memory bill; branches that psum over different mesh axes FAIL the
mesh-axis-aware PRG001; a contract/lowering mismatch FAILS SHD009; an
un-aliased sharded donation reads 0 in the compiled alias table); the
real-program goldens on HEAD (the zero1 bill, donation coverage, and
sharding census, pinned); and the CLI/SARIF exit-code contract.
"""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dnn_tpu.analysis.lint import lint_source

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rules_of(src):
    return sorted({f.rule for f in lint_source(textwrap.dedent(src), "t")})


# ----------------------------------------------------------------------
# AST rule fixtures: (known-bad, known-good twin)
# ----------------------------------------------------------------------

SHD_FIXTURES = {
    "SHD001": (
        """
        import jax
        def shards_per_replica():
            return len(jax.devices()) // 2
        """,
        """
        import jax
        def has_pair():
            # a COMPARISON on the count is a capability check, not a
            # baked topology assumption
            return len(jax.devices()) >= 2
        """,
    ),
    "SHD002": (
        """
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        def build(devs):
            mesh = Mesh(np.array(devs), ("data", "model"))
            return mesh, P("dta", None)
        """,
        """
        import numpy as np
        from jax.sharding import Mesh, PartitionSpec as P
        def build(devs):
            mesh = Mesh(np.array(devs), ("data", "model"))
            return mesh, P("data", None)
        """,
    ),
    "SHD003": (
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def body(x):
            return x * 2.0
        def build(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P())
        """,
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def body(x):
            return jax.lax.psum(x, "data")
        def build(mesh):
            # replicated output EARNED by a psum reduction
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P())
        """,
    ),
    "SHD004": (
        """
        import jax
        import numpy as np
        from jax.sharding import PartitionSpec as P
        def log_stats(x):
            return np.asarray(x).mean()
        def body(x):
            log_stats(x)
            return x * 2.0
        def build(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P("data"))
        """,
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        def log_stats(x):
            return jnp.mean(x)
        def body(x):
            log_stats(x)
            return x * 2.0
        def build(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P("data"))
        """,
    ),
    "SHD005": (
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def body(x):
            key = jax.random.PRNGKey(0)
            noise = jax.random.normal(key, x.shape)
            return x + noise
        def build(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P("data"))
        """,
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def body(x):
            key = jax.random.PRNGKey(0)
            key = jax.random.fold_in(key, jax.lax.axis_index("data"))
            noise = jax.random.normal(key, x.shape)
            return x + noise
        def build(mesh):
            return jax.shard_map(body, mesh=mesh,
                                 in_specs=P("data"), out_specs=P("data"))
        """,
    ),
    "SHD006": (
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def _step(w, x):
            return (x,)
        def build():
            return jax.jit(_step, donate_argnums=(0,),
                           in_shardings=(P("model", None), P()),
                           out_shardings=(P(),))
        """,
        """
        import jax
        from jax.sharding import PartitionSpec as P
        def _step(w, x):
            return (w * 0.9,)
        def build():
            return jax.jit(_step, donate_argnums=(0,),
                           in_shardings=(P("model", None), P()),
                           out_shardings=(P("model", None),))
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(SHD_FIXTURES))
def test_shd_fixture_pair(rule):
    bad, good = SHD_FIXTURES[rule]
    assert rule in rules_of(bad), f"{rule} must flag its bad fixture"
    assert rule not in rules_of(good), f"{rule} must pass its good twin"


def test_shd001_comparison_is_not_arithmetic():
    """program.py:~600's `len(jax.devices()) >= 2` shape — a capability
    check — must stay quiet; only arithmetic with an int literal fires."""
    assert "SHD001" not in rules_of("""
        import jax
        ok = len(jax.devices()) >= 2
        also_ok = jax.device_count() == 8
        """)
    assert "SHD001" in rules_of("""
        import jax
        n = jax.device_count() * 4
        """)


def test_shd002_silent_without_mesh_declaration():
    """Modules that never declare a Mesh (the whole package: axis names
    flow from parallel/mesh.py constants) get no axis-literal policing —
    the rule is module-scoped by design."""
    assert "SHD002" not in rules_of("""
        from jax.sharding import PartitionSpec as P
        spec = P("anything_goes")
        """)


def test_shd003_pjit_inference_not_flagged():
    """jit/pjit with sharded in_shardings and OMITTED out_shardings is
    fine — GSPMD propagates; only shard_map's undeclared outputs fire."""
    assert "SHD003" not in rules_of("""
        import jax
        from jax.sharding import PartitionSpec as P
        def f(x):
            return x * 2.0
        g = jax.jit(f, in_shardings=(P("data"),))
        """)


# ----------------------------------------------------------------------
# sharding-contract API
# ----------------------------------------------------------------------

def test_contract_registry():
    from dnn_tpu.analysis.shardcheck import contract_names, get_contract

    names = contract_names()
    for expected in ("train.gpt_dp_tp.params", "train.llama_dp_tp.params",
                     "train.zero1.opt_state",
                     "pipeline.stacked_param_placement"):
        assert expected in names
    specs = get_contract("pipeline.stacked_param_placement")(
        {"w": jax.ShapeDtypeStruct((2, 4, 4), jnp.float32)})
    assert specs == {"w": P("stage")}


# ----------------------------------------------------------------------
# program-audit helpers on buggy-variant fixtures
# ----------------------------------------------------------------------

def _mesh_dm():
    from dnn_tpu.parallel.mesh import make_mesh

    return make_mesh({"data": 2, "model": 2})


def test_memory_bill_replicated_leaf_fails():
    """The ISSUE 17 acceptance fixture: a leaf DECLARED sharded that the
    program lowers replicated fails the per-shard memory bill (SHD008)
    — the accidentally-replicated weight tree of 2004.13336, on paper."""
    from dnn_tpu.analysis.shardcheck import memory_bill

    mesh = _mesh_dm()
    shapes = {"w": jax.ShapeDtypeStruct((8, 16), jnp.float32)}
    declared = {"w": P(None, "model")}

    # healthy: compiled with the declared sharding — bill balances
    sharded_aval = {"w": jax.ShapeDtypeStruct(
        (8, 16), jnp.float32,
        sharding=NamedSharding(mesh, P(None, "model")))}
    comp = jax.jit(lambda p: p).lower(sharded_aval).compile()
    rep, findings = memory_bill(shapes, declared,
                                comp.input_shardings[0][0], mesh,
                                where="fixture")
    assert findings == [] and rep["mismatches"] == []
    assert rep["actual_per_device_bytes"] == rep["global_bytes"] // 2

    # buggy: same declaration, program compiled fully replicated
    repl_aval = {"w": jax.ShapeDtypeStruct(
        (8, 16), jnp.float32, sharding=NamedSharding(mesh, P()))}
    comp = jax.jit(lambda p: p).lower(repl_aval).compile()
    rep, findings = memory_bill(shapes, declared,
                                comp.input_shardings[0][0], mesh,
                                where="fixture")
    assert any(f.rule == "SHD008" for f in findings)
    assert "REPLICATED" in findings[0].message
    assert rep["mismatches"][0]["actual_bytes"] == \
        rep["mismatches"][0]["global_bytes"]


def test_contract_mismatch_fails():
    """An implementation whose out_shardings drift from the declared
    contract fails SHD009 on the compiled output shardings. (A
    with_sharding_constraint on a pass-through is NOT enough to drift:
    GSPMD re-propagates the input sharding over the intermediate
    constraint — the check watches what the program FINALLY commits.)"""
    from dnn_tpu.analysis.shardcheck import contract_findings

    mesh = _mesh_dm()
    shapes = {"w": jax.ShapeDtypeStruct((8, 16), jnp.float32)}
    declared = {"w": P(None, "model")}
    aval = {"w": jax.ShapeDtypeStruct(
        (8, 16), jnp.float32,
        sharding=NamedSharding(mesh, P(None, "model")))}

    def step(p):
        return jax.tree.map(lambda x: x * 0.9, p)

    drifted = jax.jit(  # silently re-replicates the declared-sharded leaf
        step, out_shardings={"w": NamedSharding(mesh, P())})
    comp = drifted.lower(aval).compile()
    findings = contract_findings("fixture.params", declared,
                                 comp.output_shardings, shapes, mesh,
                                 where="fixture")
    assert any(f.rule == "SHD009" for f in findings)
    assert "fixture.params" in findings[0].message

    faithful = jax.jit(
        step, out_shardings={"w": NamedSharding(mesh, P(None, "model"))})
    comp = faithful.lower(aval).compile()
    assert contract_findings("fixture.params", declared,
                             comp.output_shardings, shapes, mesh,
                             where="fixture") == []


def test_allocation_sized_collective_flagged():
    """SHD007's optimized-HLO walk: a collective whose result reaches the
    tree-size threshold fires; leaf-sized gathers (healthy zero1) don't."""
    from dnn_tpu.analysis.shardcheck import collective_allocation_findings

    tree_bytes = 4 * 1024 * 32  # a 128 kB f32 weight tree
    healthy = (
        "  %ag = f32[64,32]{1,0} all-gather(f32[16,32]{1,0} %p), "
        "dimensions={0}\n")
    rep, findings = collective_allocation_findings(
        healthy, tree_bytes, where="fixture")
    assert findings == [] and rep["collectives"] == 1

    repaired = (
        "  %ag = f32[1024,32]{1,0} all-gather(f32[256,32]{1,0} %p), "
        "dimensions={0}\n")
    rep, findings = collective_allocation_findings(
        repaired, tree_bytes, where="fixture")
    assert any(f.rule == "SHD007" for f in findings)
    assert rep["largest_frac"] == 1.0


def test_prg001_axis_aware():
    """The ISSUE 17 dropped-psum fixture: two branches agreeing on the
    primitive NAME but reducing over different mesh axes fail the
    mesh-axis-aware PRG001 (the name-level signature cannot see this)."""
    from dnn_tpu.analysis.program import (
        axis_collective_signature,
        check_branch_collectives,
        collective_signature,
    )

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))

    def body(x):
        return lax.cond(lax.axis_index("a") == 0,
                        lambda v: lax.psum(v, "a"),
                        lambda v: lax.psum(v, "b"), x)

    f = jax.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    closed = jax.make_jaxpr(f)(jnp.ones((4,)))
    # the name-level signature sees only "psum" — blind to the split
    assert set(collective_signature(closed)) == {"psum"}
    findings = check_branch_collectives(closed, "fixture")
    assert any(f.rule == "PRG001" for f in findings)
    assert any("@a" in s for s in axis_collective_signature(closed))

    def matched(x):
        return lax.cond(lax.axis_index("a") == 0,
                        lambda v: lax.psum(2 * v, "a"),
                        lambda v: lax.psum(v, "a"), x)

    g = jax.shard_map(matched, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    assert check_branch_collectives(
        jax.make_jaxpr(g)(jnp.ones((4,))), "fixture") == []


def test_unaliased_sharded_donation_detected():
    """A sharded donated buffer whose output cannot alias reads ZERO in
    the compiled input_output_alias table (the count the zero1 audit
    gates on); a faithful donating update reads full coverage."""
    import warnings

    from dnn_tpu.utils.hlo_audit import count_aliased_compiled, lowered_text

    mesh = _mesh_dm()
    sh = NamedSharding(mesh, P("data"))
    w = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sh)

    def update(buf):
        return buf * 0.9

    text = lowered_text(update, w, donate_argnums=(0,), optimize=True)
    assert count_aliased_compiled(text) == 1

    def shrink(buf):  # output shape can never alias the donated input
        return buf[:1]

    with warnings.catch_warnings():
        warnings.simplefilter("ignore", UserWarning)
        text = lowered_text(shrink, w, donate_argnums=(0,), optimize=True)
    assert count_aliased_compiled(text) == 0


def test_sharding_aware_census():
    """PRG004's census keys on declared shardings too: identical avals
    under different NamedShardings are different compiled programs."""
    from dnn_tpu.analysis.program import recompile_census

    mesh = _mesh_dm()
    shard = jax.ShapeDtypeStruct(
        (8, 16), jnp.float32, sharding=NamedSharding(mesh, P("data")))
    repl = jax.ShapeDtypeStruct(
        (8, 16), jnp.float32, sharding=NamedSharding(mesh, P()))
    plain = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    rep = recompile_census([(shard,), (repl,), (shard,), (plain,)],
                           bound=2, where="fixture")
    assert rep["programs"] == 3
    assert any(f.rule == "PRG004" for f in rep["findings"])


# ----------------------------------------------------------------------
# real-program goldens on HEAD
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def shard_audit():
    from dnn_tpu.analysis.shardcheck import run_shard_audit

    return run_shard_audit()


def test_audit_clean_on_head(shard_audit):
    rep, findings = shard_audit
    assert findings == []


def test_zero1_bill_golden(shard_audit):
    """The acceptance golden: the zero1 step's per-shard bytes match the
    declared PartitionSpecs exactly — params sliced to 40448 B/device of
    a 126464 B tree on the {data:2, model:4} mesh, adam moments sliced
    by the same specs plus the ZeRO-1 data axis."""
    rep, _ = shard_audit
    bill = rep["zero1"]["bill"]
    assert bill["params"]["mismatches"] == []
    assert bill["params"]["expected_per_device_bytes"] == \
        bill["params"]["actual_per_device_bytes"] == 40448
    assert bill["params"]["global_bytes"] == 126464
    assert bill["opt_state"]["mismatches"] == []
    assert bill["opt_state"]["actual_per_device_bytes"] == \
        bill["opt_state"]["expected_per_device_bytes"]
    # the sharded state is a fraction of the replicated tree — the ZeRO
    # memory win the bill certifies
    assert bill["opt_state"]["actual_per_device_bytes"] < \
        bill["opt_state"]["global_bytes"] / 2


def test_zero1_donation_and_census_golden(shard_audit):
    rep, _ = shard_audit
    don = rep["zero1"]["donation"]
    assert don["aliased"] == don["expected"] == 88
    census = rep["zero1"]["sharding_census"]
    assert census["programs"] == 2 and census["bound"] == 2


def test_zero1_collectives_leaf_sized(shard_audit):
    """Healthy zero1 all-gathers LEAF-sized updates (observed max ~6% of
    the tree) — far under the 25% accidental-replication threshold."""
    rep, _ = shard_audit
    col = rep["zero1"]["collectives"]
    assert 0 < col["largest_frac"] < col["threshold_frac"]
    assert rep["llama_dp_tp"]["collectives"]["largest_frac"] < 0.25


def test_stacked_pipeline_and_moe_goldens(shard_audit):
    rep, _ = shard_audit
    pl = rep["pipeline_stacked"]
    assert pl["bill"]["stacked"]["mismatches"] == []
    # each device holds exactly its 1/S stage slice
    assert pl["bill"]["stacked"]["actual_per_device_bytes"] == \
        pl["bill"]["stacked"]["global_bytes"] // 2
    assert rep["moe_ep"]["collective_signature"] == \
        ["all_to_all@expert", "all_to_all@expert"]


def test_program_censuses_pinned():
    """Satellite: the mesh/pipeline/transport program counts are pinned
    (PRG004) — the sharded serving PR can't silently multiply
    compilations per rung."""
    from dnn_tpu.analysis.program import (
        audit_pipeline_programs,
        audit_transport_programs,
    )

    pipe = audit_pipeline_programs()
    assert pipe.get("skipped") is None
    assert pipe["findings"] == []
    assert pipe["step_census"]["programs"] == 1
    tp = audit_transport_programs()
    assert tp.get("skipped") is None
    assert tp["findings"] == []
    assert tp["hop_census"]["programs"] == 1


# ----------------------------------------------------------------------
# CLI gate + SARIF
# ----------------------------------------------------------------------

@pytest.mark.parametrize("rule", sorted(SHD_FIXTURES))
def test_cli_nonzero_per_shd_rule(rule, tmp_path):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / f"inject_{rule.lower()}.py"
    bad.write_text(textwrap.dedent(SHD_FIXTURES[rule][0]))
    assert main([str(bad), "--no-program", "--no-baseline"]) == 1
    good = tmp_path / f"clean_{rule.lower()}.py"
    good.write_text(textwrap.dedent(SHD_FIXTURES[rule][1]))
    assert main([str(good), "--no-program", "--no-baseline"]) == 0


def test_sarif_carries_shd_findings(tmp_path, capsys):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / "user_mesh_code.py"
    bad.write_text(textwrap.dedent(SHD_FIXTURES["SHD001"][0]))
    rc = main([str(bad), "--no-program", "--no-baseline",
               "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert any(r["ruleId"] == "SHD001" and r["level"] == "error"
               for r in results)
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert "SHD001" in rules


def test_shd_rules_registered():
    from dnn_tpu.analysis.findings import RULES

    for n in range(1, 10):
        assert f"SHD00{n}" in RULES
