"""Per-request serving options: sampling parameters, stop sequences,
finish reasons, logprobs.

The reference's serving story is one stateless forward per request
(/root/reference/node.py:137-200) — none of these exist there. The tests
pin the contract that makes per-request options safe in a POOL: a request
samples exactly what it would in a single-request server (the per-row
sampler reproduces the uniform-parameter path draw-for-draw), and the
host-side features (stop, reasons, logprobs) never disturb neighbors."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import _sample, _sample_rows, make_generate
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.GPTConfig(block_size=96, vocab_size=128, n_layer=2, n_head=4,
                    n_embd=64)


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def _prompt(seed, n=8):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, CFG.vocab_size, dtype=jnp.int32))


# ----------------------------------------------------------------------
# the sampler itself
# ----------------------------------------------------------------------

def test_sample_rows_matches_sample_draw_for_draw():
    """Uniform parameters + the same per-row keys -> _sample_rows
    reproduces the pool's vmapped _sample exactly (greedy and sampled,
    with and without each filter)."""
    logits = jnp.asarray(
        np.random.default_rng(0).standard_normal((5, 128)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(5, dtype=jnp.uint32))
    for t, k, p in ((0.0, None, None), (0.8, None, None), (1.0, 7, None),
                    (0.9, None, 0.85), (1.1, 11, 0.7)):
        if t == 0.0:
            want = _sample(logits, keys[0], temperature=0.0, top_k=k,
                           top_p=p)
        else:
            want = jax.vmap(
                lambda lg, kk: _sample(lg[None, :], kk, temperature=t,
                                       top_k=k, top_p=p)[0]
            )(logits, keys)
        got = _sample_rows(
            logits, keys,
            temperature=jnp.full((5,), t, jnp.float32),
            top_k=jnp.full((5,), k or 0, jnp.int32),
            top_p=jnp.full((5,), p or 0.0, jnp.float32))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sample_rows_mixes_parameters_per_row():
    """Each row follows ITS OWN parameters: greedy rows equal argmax while
    sampled rows equal their solo draw, in the same call."""
    logits = jnp.asarray(
        np.random.default_rng(1).standard_normal((4, 128)) * 3, jnp.float32)
    keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(4, dtype=jnp.uint32))
    got = np.asarray(_sample_rows(
        logits, keys,
        temperature=jnp.asarray([0.0, 0.9, 0.0, 1.2], jnp.float32),
        top_k=jnp.asarray([0, 5, 0, 0], jnp.int32),
        top_p=jnp.asarray([0.0, 0.0, 0.0, 0.9], jnp.float32)))
    assert got[0] == int(jnp.argmax(logits[0]))
    assert got[2] == int(jnp.argmax(logits[2]))
    want1 = _sample(logits[1][None], keys[1], temperature=0.9, top_k=5)[0]
    want3 = _sample(logits[3][None], keys[3], temperature=1.2, top_k=None,
                    top_p=0.9)[0]
    assert got[1] == int(want1) and got[3] == int(want3)


# ----------------------------------------------------------------------
# the pool
# ----------------------------------------------------------------------

def test_mixed_pool_greedy_matches_solo_generate():
    """A greedy request decoding NEXT TO a sampled request produces the
    same tokens as solo make_generate."""
    prepared = _prepared()
    prompt = _prompt(1)
    n_new = 6
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]

    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64)
    rid_g = srv.submit(prompt, max_new_tokens=n_new)  # server default greedy
    srv.submit(_prompt(2), max_new_tokens=n_new, temperature=0.9,
               top_k=20, seed=7)
    out = srv.drain()
    np.testing.assert_array_equal(out[rid_g], want)
    assert srv.finish_reasons[rid_g] == "length"


def test_seeded_sampled_request_pool_independent_with_overrides():
    """A sampled request with per-request overrides reproduces its own
    token stream regardless of what shares the pool."""
    prepared = _prepared(3)
    prompt = _prompt(4)
    kw = dict(max_new_tokens=7, seed=11, temperature=0.8, top_k=12,
              top_p=0.95)

    srv_a = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    ra = srv_a.submit(prompt, **kw)
    alone = srv_a.drain()[ra]

    srv_b = ContinuousBatcher(CFG, prepared, slots=3, max_len=64)
    srv_b.submit(_prompt(5), max_new_tokens=9, temperature=1.3, seed=1)
    rb = srv_b.submit(prompt, **kw)
    srv_b.submit(_prompt(6), max_new_tokens=3)
    crowded = srv_b.drain()[rb]
    np.testing.assert_array_equal(alone, crowded)


def test_per_request_overrides_server_defaults():
    """Server-default sampled pool; one request overrides to greedy."""
    prepared = _prepared()
    prompt = _prompt(1)
    want = np.asarray(make_generate(CFG, max_new_tokens=5)(
        prepared, jnp.asarray(prompt)[None], jax.random.PRNGKey(0)))[0]
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            temperature=1.0, top_k=10)
    rid = srv.submit(prompt, max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(srv.drain()[rid], want)


def test_stop_sequence_trims_and_reports():
    """Learn a (seeded, sampled) continuation, then stop on one of its
    bigrams: the result ends just before the EARLIEST match and the reason
    is 'stop'."""
    prepared = _prepared()
    prompt = _prompt(1)
    kw = dict(max_new_tokens=8, seed=5, temperature=1.0)
    srv0 = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid0 = srv0.submit(prompt, **kw)
    full = srv0.drain()[rid0]
    stop = full[3:5]
    # earliest end position whose tail matches the bigram (a degenerate
    # stream may repeat it before position 4)
    first_end = next(i for i in range(1, len(full))
                     if (full[i - 1:i + 1] == stop).all())

    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid = srv.submit(prompt, stop=[stop], **kw)
    got = srv.drain()[rid]
    np.testing.assert_array_equal(got, full[:first_end - 1])
    assert srv.finish_reasons[rid] == "stop"


def test_stop_on_first_token_yields_empty_result():
    prepared = _prepared()
    prompt = _prompt(1)
    srv0 = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid0 = srv0.submit(prompt, max_new_tokens=3)
    full = srv0.drain()[rid0]

    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid = srv.submit(prompt, max_new_tokens=3, stop=[full[:1]])
    got = srv.drain()[rid]
    assert len(got) == 0 and srv.finish_reasons[rid] == "stop"


def test_eos_reason_and_cancel_reason():
    prepared = _prepared()
    prompt = _prompt(1)
    srv0 = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid0 = srv0.submit(prompt, max_new_tokens=4)
    full = srv0.drain()[rid0]

    eos = int(full[1])
    first_eos = next(i for i, t in enumerate(full) if int(t) == eos)
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64, eos_id=eos)
    rid = srv.submit(prompt, max_new_tokens=8)
    assert srv.drain()[rid].tolist() == full[:first_eos + 1].tolist()
    assert srv.finish_reasons[rid] == "eos"

    srv2 = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid2 = srv2.submit(prompt, max_new_tokens=8)
    assert srv2.cancel(rid2)
    assert srv2.finish_reasons[rid2] == "cancelled"


def test_logprobs_recorded_for_greedy():
    """Greedy + logprobs: the chosen token IS the top-1 alternative, its
    logprob matches, rows are one per emitted token."""
    prepared = _prepared()
    prompt = _prompt(1)
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            logprobs_k=4)
    rid = srv.submit(prompt, max_new_tokens=5, logprobs=True)
    toks = srv.drain()[rid]
    lp = srv.token_logprobs[rid]
    assert lp["chosen"].shape == (5,)
    assert lp["top_ids"].shape == (5, 4) and lp["top_logprobs"].shape == (5, 4)
    np.testing.assert_array_equal(lp["top_ids"][:, 0], toks)
    np.testing.assert_allclose(lp["chosen"], lp["top_logprobs"][:, 0],
                               rtol=1e-6)
    assert (lp["chosen"] <= 0).all()
    # descending alternatives
    assert (np.diff(lp["top_logprobs"], axis=1) <= 1e-6).all()


def test_logprobs_server_tokens_unchanged():
    """Compiling the logprobs outputs must not perturb decode itself."""
    prepared = _prepared()
    prompt = _prompt(1)
    plain = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    rid_p = plain.submit(prompt, max_new_tokens=6)
    want = plain.drain()[rid_p]
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            logprobs_k=2)
    rid_s = srv.submit(prompt, max_new_tokens=6)
    got = srv.drain()[rid_s]
    np.testing.assert_array_equal(got, want)


def test_option_validation():
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64)
    with pytest.raises(ValueError, match="logprobs"):
        srv.submit(_prompt(1), max_new_tokens=2, logprobs=True)
    with pytest.raises(ValueError, match="temperature"):
        srv.submit(_prompt(1), max_new_tokens=2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_p"):
        srv.submit(_prompt(1), max_new_tokens=2, top_p=1.5)
    with pytest.raises(ValueError, match="stop"):
        srv.submit(_prompt(1), max_new_tokens=2, stop=[[]])
    assert srv.free_slots() == 1  # failed submits must not leak slots
