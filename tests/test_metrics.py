"""Observability tests: metrics counters/percentiles, tracing spans, and
the engine benchmark path (SURVEY §5 — all absent from the reference)."""

import json

import jax
import numpy as np
import pytest

from dnn_tpu.utils import tracing
from dnn_tpu.utils.metrics import LatencyReservoir, Metrics, Throughput, percentile


def test_percentile_nearest_rank():
    vals = [float(i) for i in range(101)]  # 0..100
    assert percentile(vals, 50) == 50.0
    assert percentile(vals, 0) == 0.0
    assert percentile(vals, 100) == 100.0
    with pytest.raises(ValueError):
        percentile([], 50)


def test_reservoir_sliding_window():
    r = LatencyReservoir(capacity=10)
    for i in range(25):
        r.record(float(i))
    assert r.count == 25
    q = r.quantiles()
    assert set(q) == {"p50", "p90", "p99"}
    assert all(v >= 10.0 for v in q.values())  # early samples evicted


def test_metrics_snapshot_and_json():
    m = Metrics()
    m.inc("requests")
    m.inc("requests", 2)
    m.set("stages", 4)
    m.observe("hop", 0.001)
    m.observe("hop", 0.003)
    snap = m.snapshot()
    assert snap["counters"]["requests"] == 3
    assert snap["gauges"]["stages"] == 4
    assert snap["latency"]["hop"]["count"] == 2
    assert json.loads(m.json_line()) == snap


def test_metrics_timer():
    m = Metrics()
    with m.timer("op"):
        pass
    assert m.snapshot()["latency"]["op"]["count"] == 1


def test_throughput():
    t = Throughput()
    assert t.per_sec == 0.0
    t.add(100)
    t.add(100)
    assert t.per_sec > 0


def test_tracing_spans_are_safe():
    with tracing.span("unit-test-span"):
        pass
    with tracing.step_span(3):
        pass
    out, dt = tracing.timed_blocked(jax.jit(lambda x: x * 2), np.ones(4))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones(4))
    assert dt >= 0


def test_engine_benchmark_relay_and_spmd():
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    x = np.zeros((4, 32, 32, 3), np.float32)
    for runtime in ("relay", "spmd"):
        cfg = TopologyConfig.from_dict({
            "num_parts": 2, "model": "cifar_cnn", "device_type": "cpu",
            "runtime": runtime, "microbatches": 2,
        })
        eng = PipelineEngine(cfg)
        res = eng.benchmark(x, iters=3, warmup=1)
        assert res["items_per_sec"] > 0
        assert res["step_latency_p50_s"] > 0
        assert res["runtime"] == runtime
        if runtime == "relay":
            # slope-based estimate jitters to 0 on CPU, clamped non-negative
            assert res["inter_stage_hop_p50_s"] >= 0
