"""Qwen2 family: the LLaMA block with q/k/v projection biases.

The bias rides as a plain "bias" leaf that ops.nn.linear applies
wherever the kernel goes, so every runtime (stateless forward, cached
decode, batcher rows, partitions) inherits it with no per-path plumbing
— these tests pin that claim against HF Qwen2ForCausalLM and the
framework's own cross-path parity contracts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

CFG = llama.PRESETS["qwen2-test"]  # L=4, H=4, KV=2, C=64, V=256, biased


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_init_carries_qkv_biases_only():
    p = _params()
    blk = p["h_0"]
    for k in ("q", "k", "v"):
        assert "bias" in blk["attn"][k], k
    assert "bias" not in blk["attn"]["o"]
    for k in ("gate", "up", "down"):
        assert "bias" not in blk["mlp"][k], k


def test_hf_qwen2_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.Qwen2Config)
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    assert any(k.endswith("q_proj.bias") for k in sd), "premise: biased ckpt"

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy-generate parity: the cached decode trajectory (bias applied
    # at every step's projections) matches transformers' generate
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 10))
    n_new = 12
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 10:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_biases_change_the_output():
    """The bias leaves must actually act (a silently-dropped bias would
    still pass structural checks)."""
    p = _params(seed=1)
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0,
                             CFG.vocab_size)
    base = np.asarray(llama.make_apply(CFG)(p, ids))
    bumped = jax.tree_util.tree_map_with_path(
        lambda path, x: x + 0.5 if "bias" in str(path[-1]) else x, p)
    moved = np.asarray(llama.make_apply(CFG)(bumped, ids))
    assert np.abs(base - moved).max() > 0


def test_incremental_decode_matches_full_recompute():
    params = _params(seed=3)
    prepared = gpt.prepare_stacked(params, CFG)
    apply_fn = llama.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 8), 0,
                             CFG.vocab_size)
    n_new = 6
    got = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, ids, jax.random.PRNGKey(0)))
    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_partition_composes_to_full_model():
    params = _params(seed=5)
    ids = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0,
                             CFG.vocab_size)
    want = np.asarray(llama.make_apply(CFG)(params, ids))
    x = ids
    for st in llama.make_partition(CFG)(2):
        x = st.apply(st.slice_params(params), x)
    np.testing.assert_allclose(np.asarray(x), want, atol=1e-4, rtol=1e-4)


def test_batcher_matches_solo_decode():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = _params(seed=7)
    prepared = gpt.prepare_stacked(params, CFG)
    prompt = np.array([5, 3, 7, 1, 2])
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=32,
                            prompt_pad=8, family=llama.LlamaFamilyRows(CFG))
    rid = srv.submit(prompt, max_new_tokens=6)
    got = srv.drain()[rid]
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=6)(
        prepared, jnp.asarray(prompt, jnp.int32)[None, :],
        jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_qwen2_preset_registered():
    from dnn_tpu.registry import get_model

    spec = get_model("qwen2-7b")
    assert spec.config.attn_bias
    assert spec.config.n_kv_head == 4
