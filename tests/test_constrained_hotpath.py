"""ISSUE 16 — constrained decoding joins the interleaved/overlap hot
path: the grammar DFA walk is a DEVICE-side operation (an int32
transition-table pool next to the mask pool, the state advance folded
into the decode/mixed/fused-finish programs as donated per-slot carried
state), and the composition rejections that pinned constraints to
convoy admission are gone.

The load-bearing contracts:

  * constrained token parity: a grammar-constrained population served
    through the MIXED program (and with overlap=True on top) produces
    token streams IDENTICAL to the convoy path — greedy and sampled
    draw-for-draw, across dense/paged/bucketed pools, for requests
    admitted mid-decode, across bucket-rung crossings, and with several
    grammars resident in the pool at once;
  * EOS legality is in-program: with an eos_id configured, accept-state
    mask rows admit EOS on device and the retired body full-matches;
  * overlap ordering: the one-step pipeline's commit discipline holds
    with a constraint live, and retirement resets the slot's device DFA
    row to the unconstrained zero row;
  * prefix-cache adoption installs the correct device DFA state (the
    grammar constrains GENERATED tokens — an adopted prompt prefix
    leaves the walk at its post-first-token state);
  * speculative serving still rejects constraints LOUD (the k-token
    verify cannot gate per-token masks);
  * the transition pool evicts LRU-unreferenced entries next to the
    mask pool, and uploaded rows carry GLOBAL (offset-rebased)
    coordinates.
"""

import re as pyre

import numpy as np
import pytest

import jax

from dnn_tpu.models import gpt
from dnn_tpu.runtime.constrain import TokenConstraint, byte_vocab
from dnn_tpu.runtime.serving import ContinuousBatcher
from dnn_tpu.runtime.serving_spec import SpeculativeBatcher


@pytest.fixture(scope="module")
def model():
    cfg = gpt.GPTConfig(block_size=64, vocab_size=64, n_layer=2,
                        n_head=2, n_embd=32)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


# grammars over single-byte tokens that exist in the tiny vocab
# (digits are bytes 48-57 < 64); compiled once — the pool keys by id()
VOCAB = byte_vocab(64)
DIGITS = TokenConstraint.from_regex(r"[0-9]+", VOCAB)
EVENS = TokenConstraint.from_regex(r"[02468]{3}", VOCAB)
ODDS = TokenConstraint.from_regex(r"[13579]+", VOCAB)


def _serve(cfg, prepared, submits, **kw):
    """Run a submission schedule (list of (prompt, max_new, opts,
    steps_before)) through a constrained-capable batcher; returns
    ([tokens...], batcher)."""
    kw.setdefault("slots", 3)
    kw.setdefault("constraint_rows", 16)
    srv = ContinuousBatcher(cfg, prepared, max_len=64, prompt_pad=8,
                            allow_constraints=True, **kw)
    rids = []
    for prompt, max_new, opts, steps_before in submits:
        for _ in range(steps_before):
            srv.step()
        rids.append(srv.submit(np.asarray(prompt, np.int32), max_new,
                               **opts))
    srv.drain()
    return [srv.results[r].tolist() for r in rids], srv


# greedy + sampled constrained requests, an unconstrained rider, and a
# mid-decode admission under a SECOND grammar — the population every
# parity leg below replays
SCHEDULE = [
    (range(1, 10), 8, {"seed": 0, "constraint": DIGITS}, 0),
    (range(2, 8), 8, {"seed": 1, "temperature": 0.9, "top_k": 5,
                      "constraint": DIGITS}, 0),
    # admitted mid-decode into the free third slot, a SECOND grammar
    # resident alongside; [02468]{3} retires via c_done at 3 tokens,
    # under budget — the constraint-finish on the hot path
    (range(1, 6), 6, {"seed": 2, "temperature": 1.1,
                      "constraint": EVENS}, 3),
    # unconstrained rider admitted once slots have freed (20 steps
    # covers the interleaved path's deferred-commit lag too)
    (range(3, 12), 6, {"seed": 3}, 20),
]


@pytest.mark.parametrize("pool_kw", [
    {},  # dense
    {"kv": "paged", "block_len": 8},
    {"decode_buckets": True},
])
def test_constrained_mixed_parity(model, pool_kw):
    """mixed == convoy == mixed+overlap, token for token, with the
    grammar walk live — the composition this PR lifted the rejections
    for."""
    cfg, prepared = model
    base, _ = _serve(cfg, prepared, SCHEDULE, **pool_kw)
    mixed, srv = _serve(cfg, prepared, SCHEDULE,
                        prefill_chunk_tokens=8, **pool_kw)
    assert mixed == base
    both, _ = _serve(cfg, prepared, SCHEDULE, prefill_chunk_tokens=8,
                     overlap=True, **pool_kw)
    assert both == base
    assert srv._ilv and srv._mixed is not None
    # every constrained stream full-matches its grammar
    for toks, cons in ((base[0], r"[0-9]+"), (base[1], r"[0-9]+"),
                       (base[2], r"[02468]{1,3}")):
        assert pyre.fullmatch(cons.encode(),
                              bytes(int(t) for t in toks)), toks


def test_constrained_bucket_rung_crossing(model):
    """A constrained decode that crosses bucket rungs keeps parity: the
    carried crow state survives the cache-view re-bucketing."""
    cfg, prepared = model
    # prompt 8 + 40 new tokens walks the bucketed cache across rungs
    sched = [(range(1, 9), 40,
              {"seed": 7, "temperature": 1.0, "constraint": DIGITS}, 0),
             (range(2, 7), 12, {"seed": 8, "constraint": DIGITS}, 2)]
    base, _ = _serve(cfg, prepared, sched, decode_buckets=True)
    both, _ = _serve(cfg, prepared, sched, decode_buckets=True,
                     prefill_chunk_tokens=8, overlap=True)
    assert both == base
    assert pyre.fullmatch(rb"[0-9]+", bytes(int(t) for t in base[0]))


def test_eos_at_accept_state_on_device(model):
    """EOS legality rides the mask row (dead/accept-state rows are in
    the pool): with eos configured, a sampled EOS only ever lands at an
    accepting state, and the hot path agrees with convoy exactly."""
    cfg, prepared = model
    grammar = r"[0-9]{2,6}"
    c = TokenConstraint.from_regex(grammar, VOCAB)
    sched = [(range(1, 8), 10,
              {"seed": s, "temperature": 1.0, "constraint": c}, 0)
             for s in range(3)]
    base, bsrv = _serve(cfg, prepared, sched, eos_id=0)
    both, hsrv = _serve(cfg, prepared, sched, eos_id=0,
                        prefill_chunk_tokens=8, overlap=True)
    assert both == base
    for rid in range(3):
        assert hsrv.finish_reasons[rid] == bsrv.finish_reasons[rid]
        body = bytes(int(t) for t in base[rid] if t != 0)
        assert pyre.fullmatch(grammar.encode(), body), (body, rid)
        assert hsrv.finish_reasons[rid] in ("eos", "constraint", "length")


def test_overlap_ordering_with_constraint_live(model):
    """The double buffer's one-step-pipeline contract holds with a
    grammar walking on device, and retirement resets the slot's DFA
    row on the POST-step buffer (no stale state leaks into the next
    admission)."""
    cfg, prepared = model
    kw = dict(slots=2, max_len=64, prompt_pad=8, allow_constraints=True,
              constraint_rows=16)
    srv = ContinuousBatcher(cfg, prepared, overlap=True, **kw)
    ref = ContinuousBatcher(cfg, prepared, **kw)
    r = srv.submit(np.arange(1, 10), 6, seed=0, constraint=DIGITS)
    ref.submit(np.arange(1, 10), 6, seed=0, constraint=DIGITS)
    out1 = srv.step()      # dispatches step 0, pipeline filling
    assert out1 == {}
    assert srv._inflight is not None
    out2 = srv.step()      # dispatches step 1, commits step 0
    ref1 = ref.step()
    assert out2 == ref1    # exactly step 0's tokens, one call later
    srv.drain()
    ref.drain()
    assert srv._inflight is None
    assert srv.results[r].tolist() == ref.results[0].tolist()
    # retirement landed the zero-row reset on the carried device state
    assert int(np.asarray(srv._crow)[0]) == 0


def test_prefix_cache_adoption_installs_dfa_state(model):
    """A prefix-cache hit adopts cached K/V rows but the grammar
    constrains GENERATED tokens: the device row must hold the
    post-first-token walk state, and the hit stream must equal the
    cold one."""
    cfg, prepared = model
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, allow_constraints=True,
                            constraint_rows=16, prefix_cache=4)
    prompt = np.arange(1, 17)  # two full prompt_pad chunks -> cacheable
    r0 = srv.submit(prompt, 6, seed=5, constraint=DIGITS)
    srv.drain()
    hits0 = srv.prefix_hits
    r1 = srv.submit(prompt, 6, seed=5, constraint=DIGITS)
    assert srv.prefix_hits == hits0 + 1, "second submit must hit"
    slot = next(i for i, q in enumerate(srv._slot_req)
                if q is not None and q["rid"] == r1)
    req = srv._slot_req[slot]
    off = srv._ctab_entries[id(DIGITS)]["off"]
    # the device row is the GLOBAL post-first-token state of the walk
    assert int(np.asarray(srv._crow)[slot]) == off + req["c_state"]
    srv.drain()
    assert srv.results[r1].tolist() == srv.results[r0].tolist()


def test_speculative_rejection_still_loud(model):
    """The k-token verify cannot gate per-token masks: speculative
    serving keeps its LOUD construction-time rejection."""
    cfg, prepared = model
    with pytest.raises(ValueError, match="constraint"):
        SpeculativeBatcher(cfg, prepared, cfg, prepared, spec_k=2,
                           slots=2, max_len=64, prompt_pad=8,
                           allow_constraints=True)


def test_transition_pool_lru_eviction_golden(model):
    """The transition pool shares the mask pool's allocator: an
    unreferenced LRU entry is evicted to make room, rows upload in
    GLOBAL coordinates (local next-state + offset), and row 0 stays the
    all-zero unconstrained self-loop."""
    cfg, prepared = model
    # pool sized so DIGITS (2 states) + EVENS (4 states) fit but a
    # third grammar forces an eviction: 1 reserved + 7 allocatable
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, allow_constraints=True,
                            constraint_rows=8)
    assert not np.asarray(srv._ctrans[0]).any(), "row 0 = self-loop"
    off_d = srv._ctab_register(DIGITS)
    off_e = srv._ctab_register(EVENS)
    # global-coordinate golden: uploaded rows == local table + offset
    want = DIGITS.trans_table(srv.eos_id) + np.int32(off_d)
    got = np.asarray(srv._ctrans[off_d:off_d + want.shape[0]])
    np.testing.assert_array_equal(got, want)
    # DIGITS retires (refs -> 0) and stays cached; EVENS stays live
    srv._ctab_release(DIGITS)
    assert srv._ctab_entries[id(DIGITS)]["refs"] == 0
    assert srv._ctab_entries[id(EVENS)]["refs"] == 1
    # ODDS (same 2-state shape) needs DIGITS' gap -> the unreferenced
    # LRU entry is evicted, the live one survives
    off_p = srv._ctab_register(ODDS)
    assert id(DIGITS) not in srv._ctab_entries
    assert id(EVENS) in srv._ctab_entries
    want_p = ODDS.trans_table(srv.eos_id) + np.int32(off_p)
    got_p = np.asarray(srv._ctrans[off_p:off_p + want_p.shape[0]])
    np.testing.assert_array_equal(got_p, want_p)
    # a live entry can NEVER be evicted: exhaust the pool while EVENS
    # and PAIRS hold references
    big = TokenConstraint.from_regex(r"[0-9]{1,5}", VOCAB)
    if big.table.shape[0] <= srv._ctab_rows - 1:
        with pytest.raises(ValueError, match="exhausted"):
            srv._ctab_register(big)


def test_constrained_slots_gauge(model):
    """The StepClock's `constrained_slots` gauge tracks live grammar
    admissions (up at submit, down at retire) — the /stepz receipt that
    constrained traffic actually rode a measured run."""
    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import StepClock

    cfg, prepared = model
    was = obs.enabled()
    obs.set_enabled(True)
    try:
        srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                                prompt_pad=8, allow_constraints=True,
                                constraint_rows=16)
        clock = StepClock().install()
        srv.step_clock = clock
        srv.submit(np.arange(1, 9), 4, seed=0, constraint=DIGITS)
        assert clock.constrained_slots == 1
        assert clock.summary()["constrained_slots"] == 1
        srv.submit(np.arange(2, 9), 4, seed=1)  # unconstrained: no bump
        assert clock.constrained_slots == 1
        srv.drain()
        assert clock.constrained_slots == 0
        assert "constrained_slots" in clock.render_prom()
    finally:
        obs.set_enabled(was)
