"""Per-token streaming + cancellation tests for the LM daemon.

The reference's only RPC shape is unary SendTensor (node_service.proto:7);
GenerateStream is the serving capability beyond it: tokens stream as they
commit, and a client that disconnects mid-decode frees its slot at the
next step boundary instead of decoding on to its budget."""

import time

import jax
import numpy as np
import pytest

from dnn_tpu.comm.client import NodeClient
from dnn_tpu.models import gpt
from dnn_tpu.runtime.lm_server import _BatcherWorker, start_lm_server_in_background
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def test_stream_matches_unary_generate():
    """Streamed tokens, in order, equal the unary result for the same
    (seeded) request — same batcher, same rng convention."""
    port = 59331
    t, stop = start_lm_server_in_background(
        CFG, _prepared(), port=port, slots=2, max_len=48, prompt_pad=8,
        default_max_new=8)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        prompt = np.array([3, 1, 4, 1, 5], np.int32)
        want = c.generate(prompt, max_new_tokens=8, seed=7)
        got = list(c.generate_stream(prompt, max_new_tokens=8, seed=7))
        assert got == [int(x) for x in want]
        c.close()
    finally:
        stop()


def test_text_stream_matches_one_shot_decode():
    """generate_text_stream: the concatenated UTF-8-safe chunks equal
    the one-shot decode of the same token stream byte-for-byte (the
    detokenizer holds split multi-byte pieces until complete)."""
    from dnn_tpu.io.tokenizer import ByteTokenizer

    port = 59336
    tok = ByteTokenizer(CFG.vocab_size)
    t, stop = start_lm_server_in_background(
        CFG, _prepared(), port=port, slots=2, max_len=64, prompt_pad=8,
        default_max_new=8, tokenizer=tok, temperature=1.0)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        prompt = "héllo 🙂"
        ids = list(c.generate_stream(np.asarray(tok.encode(prompt),
                                                np.int32),
                                     max_new_tokens=24, seed=11))
        text = "".join(c.generate_text_stream(prompt, tok,
                                              max_new_tokens=24, seed=11))
        assert text == tok.decode(ids)  # same seed -> same stream
        c.close()
    finally:
        stop()


def test_stream_tokens_arrive_incrementally():
    """The stream is really per-token: the first token arrives well before
    the full generation completes (not one buffered burst at the end)."""
    import threading

    port = 59332
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=1), port=port, slots=1,
        max_len=CFG.block_size, prompt_pad=8, default_max_new=4)
    try:
        # slow the live batcher's steps so per-token arrival is measurable
        # on any machine (the tiny model otherwise decodes its budget in
        # milliseconds and the timing assertion goes flaky)
        workers = [th for th in threading.enumerate()
                   if th.name == "lm-batcher"]
        assert workers, "no lm-batcher thread found"
        b = workers[-1].batcher
        real_step = b.step
        step_gap = 0.03

        def slow_step():
            time.sleep(step_gap)
            return real_step()

        b.step = slow_step
        c = NodeClient(f"127.0.0.1:{port}")
        prompt = np.array([1, 2, 3], np.int32)
        stamps = []
        for tok in c.generate_stream(prompt, max_new_tokens=40):
            stamps.append(time.monotonic())
        assert len(stamps) == 40
        # per-token streaming: arrivals must SPAN the slowed decode (a
        # buffered-burst implementation would deliver all 40 in one gap)
        assert (stamps[-1] - stamps[0]) > 10 * step_gap, \
            "all tokens arrived in a burst"
        c.close()
    finally:
        stop()


def test_cancel_mid_decode_frees_slot():
    """slots=1 + a long-budget stream: breaking out of the stream cancels
    the RPC; the slot must re-enter the free pool so a second request is
    served promptly instead of waiting out the first's budget."""
    port = 59333
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=2), port=port, slots=1,
        max_len=CFG.block_size, prompt_pad=8, default_max_new=4)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        prompt = np.array([1, 2, 3], np.int32)
        # consume 3 tokens of a 55-token budget, then abandon the stream
        got = []
        for tok in c.generate_stream(prompt, max_new_tokens=55):
            got.append(tok)
            if len(got) == 3:
                break  # generator close -> RPC cancel
        assert len(got) == 3

        # the slot must free (poll the stats endpoint over the same wire)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            stats = c.send_message("test", "!stats")
            if "0/1 slots active" in stats:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"slot never freed: {stats}")

        # and a new request is served to completion
        t0 = time.monotonic()
        out = c.generate(prompt, max_new_tokens=5)
        assert out.shape == (5,)
        assert time.monotonic() - t0 < 30
        c.close()
    finally:
        stop()


def test_worker_level_cancel_event():
    """Direct worker test: setting cancel_evt retires the slot at the next
    boundary and resolves the future cancelled."""
    import threading

    srv = ContinuousBatcher(CFG, _prepared(seed=3), slots=1,
                            max_len=CFG.block_size, prompt_pad=8)
    # the tiny test model decodes its whole budget in well under a second —
    # slow each step so the cancel demonstrably lands MID-decode
    real_step = srv.step

    def slow_step():
        time.sleep(0.05)
        return real_step()

    srv.step = slow_step
    worker = _BatcherWorker(srv)
    worker.start()
    evt = threading.Event()
    toks = []
    fut = worker.submit(np.array([1, 2, 3], np.int32), 60, None,
                        on_token=toks.append, cancel_evt=evt)
    # let a few tokens stream, then cancel
    deadline = time.monotonic() + 30
    while len(toks) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(toks) >= 3, "no tokens streamed"
    evt.set()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if fut.cancelled() and srv.free_slots() == 1:
            break
        time.sleep(0.05)
    assert fut.cancelled(), "future not cancelled"
    assert srv.free_slots() == 1, "slot not freed"
    # pool still serves new work after the cancel
    fut2 = worker.submit(np.array([4, 5], np.int32), 3, None)
    assert fut2.result(timeout=60).shape == (3,)
    worker.stop(drain=False)
    worker.join(timeout=10)


def test_stage_server_reports_unimplemented_for_stream():
    """Stage servers don't serve GenerateStream — a caller gets a clean
    UNIMPLEMENTED, not a hang."""
    import grpc

    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = {
        "nodes": [{"id": "n1", "address": "127.0.0.1:59334", "part_index": 0}],
        "model": "mlp", "model_weights": None, "num_parts": 1,
        "device_type": "cpu",
    }
    from dnn_tpu.comm.service import start_stage_server_in_background

    engine = PipelineEngine(TopologyConfig.from_dict(cfg))
    t, stop = start_stage_server_in_background(engine, "n1", port=59334)
    try:
        c = NodeClient("127.0.0.1:59334")
        with pytest.raises(grpc.RpcError) as ei:
            list(c.generate_stream(np.array([1], np.int32), max_new_tokens=2))
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        c.close()
    finally:
        stop()
