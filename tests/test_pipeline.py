"""Pipeline runtimes on the 8-device virtual CPU mesh: real shard_map +
ppermute collectives, verified bit-for-bit against the single-device model.

This is the test the reference never had (SURVEY §4): its only correctness
signal was eyeballing printed shapes across N terminals.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.models import gpt
from dnn_tpu.parallel import (
    RelayExecutor,
    make_mesh,
    split_microbatches,
    merge_microbatches,
    spmd_pipeline,
)
from dnn_tpu.parallel.pipeline import spmd_pipeline_stacked


@pytest.fixture(scope="module")
def cifar_setup():
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    x = spec.example_input(batch_size=8, rng=jax.random.PRNGKey(1))
    return spec, params, x


# ----------------------------------------------------------------------
# relay executor
# ----------------------------------------------------------------------

@pytest.mark.parametrize("num_parts", [2, 4])
def test_relay_matches_full_model(cifar_setup, num_parts):
    spec, params, x = cifar_setup
    stages = spec.partition(num_parts)
    ex = RelayExecutor(
        [s.apply for s in stages], [s.slice_params(params) for s in stages]
    )
    y = ex(x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(spec.apply(params, x)), atol=1e-6, rtol=1e-6
    )


def test_relay_stage_devices_distinct(cifar_setup):
    """Each stage must actually live on its own device (the reference's
    one-part-per-machine placement, config.json:3-14)."""
    spec, params, _ = cifar_setup
    stages = spec.partition(4)
    ex = RelayExecutor(
        [s.apply for s in stages], [s.slice_params(params) for s in stages]
    )
    assert len({str(d) for d in ex.devices}) == 4
    for p, d in zip(ex.stage_params, ex.devices):
        leaf = jax.tree.leaves(p)[0]
        assert leaf.devices() == {d}


def test_relay_timings(cifar_setup):
    spec, params, x = cifar_setup
    stages = spec.partition(2)
    ex = RelayExecutor([s.apply for s in stages], [s.slice_params(params) for s in stages])
    ex(x, record_timings=True)
    # one compute sample per stage
    assert ex.last_stage_times is not None and len(ex.last_stage_times) == 2
    assert all(t > 0 for t in ex.last_stage_times)
    # 2 stages -> 1 inter-stage hop (stage 0's host ingress excluded);
    # slope-based measurement jitters to 0 on CPU, clamped non-negative
    hops = ex.measure_hop_latency(x)
    assert len(hops) == 1 and hops[0] >= 0.0
    # non-timed runs reset the records
    ex(x)
    assert ex.last_stage_times is None


# ----------------------------------------------------------------------
# SPMD pipeline (shard_map + ppermute)
# ----------------------------------------------------------------------

@pytest.mark.parametrize("num_parts,microbatches", [(2, 1), (2, 4), (4, 1), (4, 4), (4, 8)])
def test_spmd_pipeline_cifar(cifar_setup, num_parts, microbatches):
    spec, params, x = cifar_setup
    stages = spec.partition(num_parts)
    mesh = make_mesh({"stage": num_parts})
    y = spmd_pipeline(
        [s.apply for s in stages],
        [s.slice_params(params) for s in stages],
        x,
        mesh=mesh,
        num_microbatches=microbatches,
    )
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(spec.apply(params, x)), atol=1e-5, rtol=1e-5
    )


def test_spmd_pipeline_gpt_end_to_end():
    """GPT through the heterogeneous pipeline: int token microbatches in,
    logits out, embed/blocks/head split across 4 stages."""
    spec = get_model("gpt2-test")
    cfg = spec.config
    params = spec.init(jax.random.PRNGKey(0))
    ids = spec.example_input(batch_size=4, seq_len=16, rng=jax.random.PRNGKey(1))
    stages = spec.partition(4)
    mesh = make_mesh({"stage": 4})
    y = spmd_pipeline(
        [s.apply for s in stages],
        [s.slice_params(params) for s in stages],
        ids,
        mesh=mesh,
        num_microbatches=2,
    )
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(spec.apply(params, ids)), atol=1e-4, rtol=1e-4
    )


def test_spmd_pipeline_stacked_gpt_blocks():
    """Homogeneous block-stack pipeline: params sharded one-stage-per-device
    (P('stage')), activations hopping by ppermute."""
    cfg = gpt.PRESETS["gpt2-test"]  # 4 layers
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 16, cfg.n_embd))

    stacked = gpt.stack_blocks(params, range(cfg.n_layer))
    mesh = make_mesh({"stage": cfg.n_layer})

    def block_fn(p, h):
        return gpt.block_apply(p, h, cfg=cfg)

    y = spmd_pipeline_stacked(
        block_fn, stacked, x, mesh=mesh, num_microbatches=4
    )

    ref = x
    for i in range(cfg.n_layer):
        ref = gpt.block_apply(params[f"h_{i}"], ref, cfg=cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_spmd_pipeline_wrong_mesh_size(cifar_setup):
    spec, params, x = cifar_setup
    stages = spec.partition(2)
    mesh = make_mesh({"stage": 4})
    with pytest.raises(ValueError, match="one device per stage"):
        spmd_pipeline(
            [s.apply for s in stages],
            [s.slice_params(params) for s in stages],
            x,
            mesh=mesh,
        )


def test_microbatch_split_merge():
    x = jnp.arange(24).reshape(12, 2)
    mb = split_microbatches(x, 4)
    assert mb.shape == (4, 3, 2)
    np.testing.assert_array_equal(np.asarray(merge_microbatches(mb)), np.asarray(x))
    with pytest.raises(ValueError, match="not divisible"):
        split_microbatches(x, 5)
