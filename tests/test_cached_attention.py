"""Pallas cached-attention kernel tests (serving decode/prefill hot loop).

The kernel's distinguishing features over ops/pallas/flash_attention.py —
RUNTIME position limits (one compiled program for every chunk start and
slot position) and fused int8-cache dequant — are exercised in Pallas
interpreter mode so CPU CI runs the real kernel logic, then integrated
through the full decode loop (make_generate / ContinuousBatcher with
attn_kernel="interpret") with token parity against the einsum path."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.ops.pallas.cached_attention import (
    cached_attention,
    decode_attention,
    reference_cached_attention,
    reference_decode_attention,
)

RNG = np.random.default_rng(0)


def _rand(shape, dtype=jnp.float32):
    return jnp.asarray(RNG.standard_normal(shape), dtype)


def test_kernel_decode_float_and_bf16():
    B, H, S, D = 3, 4, 256, 64
    q = _rand((B, H, 1, D))
    k, v = _rand((B, H, S, D)), _rand((B, H, S, D))
    pos = jnp.asarray([5, 130, 255], jnp.int32)  # incl. first/last block
    for cast in (jnp.float32, jnp.bfloat16):
        want = reference_cached_attention(q, k.astype(cast), v.astype(cast), pos)
        got = cached_attention(q, k.astype(cast), v.astype(cast), pos,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_kernel_decode_int8_scales():
    B, H, S, D = 2, 4, 256, 64
    q = _rand((B, H, 1, D))
    kq = jnp.asarray(RNG.integers(-127, 128, (B, H, S, D)), jnp.int8)
    vq = jnp.asarray(RNG.integers(-127, 128, (B, H, S, D)), jnp.int8)
    ks = jnp.asarray(RNG.uniform(0.005, 0.02, (B, H, S)), jnp.float32)
    vs = jnp.asarray(RNG.uniform(0.005, 0.02, (B, H, S)), jnp.float32)
    pos = jnp.asarray([7, 200], jnp.int32)
    want = reference_cached_attention(q, kq, vq, pos, ks=ks, vs=vs)
    got = cached_attention(q, kq, vq, pos, ks=ks, vs=vs, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_kernel_prefill_chunk_at_dynamic_start():
    """The flash-can't-do-this case: a (T) query block whose absolute start
    is a runtime value — same compiled kernel for chunk 0 and chunk N."""
    B, H, S, D, T = 2, 4, 256, 64, 128
    q = _rand((B, H, T, D))
    k, v = _rand((B, H, S, D)), _rand((B, H, S, D))
    for start in (0, 128):
        pos = jnp.full((B,), start, jnp.int32)
        want = reference_cached_attention(q, k, v, pos)
        got = cached_attention(q, k, v, pos, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_kernel_nontiling_falls_back():
    B, H, S, D = 2, 2, 100, 64  # S % 128 != 0
    q = _rand((B, H, 1, D))
    k, v = _rand((B, H, S, D)), _rand((B, H, S, D))
    pos = jnp.asarray([5, 99], jnp.int32)
    got = cached_attention(q, k, v, pos)  # silently reference
    want = reference_cached_attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


# ----------------------------------------------------------------------
# decode-specialized kernel (heads folded into one program per slot;
# clamped index map skips dead cache blocks)
# ----------------------------------------------------------------------


def test_decode_kernel_r1_positions_span_blocks():
    """R=1 (plain MHA decode rows) at positions inside the first block,
    mid-buffer, and the last column — incl. limits that leave most blocks
    dead (the clamped index map must not corrupt the live prefix)."""
    B, H, S, D = 4, 4, 512, 64
    q = _rand((B, H, 1, D))
    k, v = _rand((B, H, S, D)), _rand((B, H, S, D))
    pos = jnp.asarray([3, 127, 128, 511], jnp.int32)
    for cast in (jnp.float32, jnp.bfloat16):
        want = reference_decode_attention(q, k.astype(cast), v.astype(cast),
                                          pos)
        got = decode_attention(q, k.astype(cast), v.astype(cast), pos,
                               interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_decode_kernel_gqa_rows_share_limit():
    """R=G>1 (the LLaMA GQA fold): every group row of a slot shares the
    slot's limit — the case the general kernel's +row contract excludes."""
    B, KV, G, S, D = 2, 2, 4, 256, 64
    q = _rand((B, KV, G, D))
    k, v = _rand((B, KV, S, D)), _rand((B, KV, S, D))
    pos = jnp.asarray([9, 255], jnp.int32)
    want = reference_decode_attention(q, k, v, pos)
    got = decode_attention(q, k, v, pos, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_decode_kernel_int8_scales():
    B, H, S, D = 2, 4, 256, 64
    q = _rand((B, H, 1, D))
    kq = jnp.asarray(RNG.integers(-127, 128, (B, H, S, D)), jnp.int8)
    vq = jnp.asarray(RNG.integers(-127, 128, (B, H, S, D)), jnp.int8)
    ks = jnp.asarray(RNG.uniform(0.005, 0.02, (B, H, S)), jnp.float32)
    vs = jnp.asarray(RNG.uniform(0.005, 0.02, (B, H, S)), jnp.float32)
    pos = jnp.asarray([7, 200], jnp.int32)
    want = reference_decode_attention(q, kq, vq, pos, ks=ks, vs=vs)
    got = decode_attention(q, kq, vq, pos, ks=ks, vs=vs, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_decode_kernel_block_size_fallback():
    """S=128 engages the 128 block; S=96 doesn't tile -> reference path."""
    B, H, D = 2, 2, 64
    for S in (128, 96):
        q = _rand((B, H, 1, D))
        k, v = _rand((B, H, S, D)), _rand((B, H, S, D))
        pos = jnp.asarray([5, S - 1], jnp.int32)
        want = reference_decode_attention(q, k, v, pos)
        got = decode_attention(q, k, v, pos,
                               interpret=True if S == 128 else None)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_llama_generate_with_kernel_matches_einsum():
    """LLaMA solo decode (GQA fold through attend_rows) with
    attn_kernel='interpret': greedy tokens equal the einsum path. Cache
    length 120+8=128 tiles the kernel's 128 block so decode steps really
    run it (llama-test's block_size=64 cache would silently fall back)."""
    from dnn_tpu.models import llama

    cfg = llama.LlamaConfig(block_size=256, vocab_size=256, n_layer=2,
                            n_head=4, n_kv_head=2, n_embd=64, d_ff=128)
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(0), cfg), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 120), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    want = llama.make_generate(cfg, max_new_tokens=8)(
        prepared, prompt, jax.random.PRNGKey(2))
    got = llama.make_generate(cfg, max_new_tokens=8,
                              attn_kernel="interpret")(
        prepared, prompt, jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_llama_batcher_with_kernel_matches_einsum():
    """LlamaFamilyRows(attn_kernel='interpret') through the
    ContinuousBatcher: R=G decode rows hit the decode kernel; tokens equal
    the plain batcher."""
    from dnn_tpu.models import llama
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = llama.LlamaConfig(block_size=256, vocab_size=256, n_layer=2,
                            n_head=4, n_kv_head=2, n_embd=64, d_ff=128)
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(3), cfg), cfg)
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(4), (100,), 0, cfg.vocab_size, dtype=jnp.int32))

    def run(**kw):
        # max_len 128 tiles the decode kernel's 128 block
        srv = ContinuousBatcher(
            cfg, prepared, slots=2, max_len=128, prompt_pad=128,
            family=llama.LlamaFamilyRows(cfg, **kw))
        rid = srv.submit(prompt, max_new_tokens=6)
        return srv.drain()[rid]

    np.testing.assert_array_equal(run(attn_kernel="interpret"), run())


# ----------------------------------------------------------------------
# integration: the real kernel inside the full decode loop
# ----------------------------------------------------------------------

KCFG = gpt.GPTConfig(block_size=128, vocab_size=128, n_layer=2, n_head=4,
                     n_embd=64)


def _kprepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), KCFG), KCFG)


def test_generate_with_kernel_matches_einsum_path():
    """make_generate(attn_kernel='interpret') greedy tokens == the einsum
    decode on the same weights/prompt (prefill T=120 tiles the S=128 cache,
    decode runs T=1 rows)."""
    from dnn_tpu.runtime.generate import make_generate

    prepared = _kprepared()
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 120), 0,
                                KCFG.vocab_size, dtype=jnp.int32)
    want = make_generate(KCFG, max_new_tokens=8)(
        prepared, prompt, jax.random.PRNGKey(2))
    got = make_generate(KCFG, max_new_tokens=8, attn_kernel="interpret")(
        prepared, prompt, jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_batcher_with_kernel_matches_einsum_batcher():
    """ContinuousBatcher(attn_kernel='interpret'): chunked prefill AND
    per-row decode run the kernel; greedy results equal the plain batcher."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    prepared = _kprepared(seed=3)
    prompts = [np.asarray(jax.random.randint(
        jax.random.PRNGKey(10 + i), (100 + i,), 0, KCFG.vocab_size,
        dtype=jnp.int32)) for i in range(2)]

    def run(**kw):
        srv = ContinuousBatcher(KCFG, prepared, slots=2, max_len=128,
                                prompt_pad=128, **kw)
        rids = [srv.submit(p, max_new_tokens=6) for p in prompts]
        out = srv.drain()
        return [out[r] for r in rids]

    want = run()
    got = run(attn_kernel="interpret")
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_batcher_with_kernel_int8_cache():
    """int8 cache + kernel: the fused-dequant path through the live pool;
    tokens equal the einsum int8 batcher (identical quantization math)."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    prepared = _kprepared(seed=4)
    prompt = np.asarray(jax.random.randint(
        jax.random.PRNGKey(20), (64,), 0, KCFG.vocab_size, dtype=jnp.int32))

    def run(**kw):
        srv = ContinuousBatcher(KCFG, prepared, slots=1, max_len=128,
                                prompt_pad=128, kv_dtype="int8", **kw)
        rid = srv.submit(prompt, max_new_tokens=5)
        return srv.drain()[rid]

    np.testing.assert_array_equal(run(attn_kernel="interpret"), run())
