"""Pins for run_all.py's per-config orchestration: the append-only row
store, crash-resume semantics, and the device-config registry — the
machinery that guarantees one wedging config can no longer cost the
benchmark table's tail (VERDICT r4 weak #2)."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "_run_all_state_mod", os.path.join(REPO, "benchmarks", "run_all.py"))
run_all = importlib.util.module_from_spec(spec)
sys.modules["_run_all_state_mod"] = run_all
spec.loader.exec_module(run_all)


def test_registry_names_unique_and_ordered():
    names = [n for n, _, _ in run_all.DEVICE_CONFIGS]
    assert len(names) == len(set(names))
    # the serving tail that crashed in round 4 must be present
    for required in ("gpt2_decode_matrix", "gpt2_decode_attnkernel",
                     "gpt2_decode_top_p_tax", "gpt2_serving_e2e",
                     "gpt2_serving_constrained_tax", "mixtral_decode",
                     "speculative_decode", "embeddings_throughput",
                     "beam_vs_greedy"):
        assert required in names, required


def test_state_persists_rows_immediately(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    # rows are on disk BEFORE the config is marked done — a kill between
    # the two must not lose the measurement
    lines = [json.loads(l) for l in open(path)]
    assert lines[-1]["_row"] == {"config": "a", "value": 1}
    st.mark_done("device:a", "ok")


def test_state_resume_skips_completed(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    st.add_rows("device:b", [{"config": "b", "value": 2}])
    # no done marker for b: the run died mid-config

    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "ok"}
    # a's row survives; b's partial row is there too (salvage), but b is
    # NOT done, so the orchestrator will re-run it
    assert {"config": "a", "value": 1} in st2.all_rows()
    assert "device:b" not in st2.done


def test_state_fresh_run_truncates(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    st2 = run_all._State(path=path, resume=False)  # no --resume
    assert st2.done == {} and st2.all_rows() == []


def test_state_resume_retries_failed_configs(tmp_path):
    """A config that failed last run must be RETRIED on --resume (that
    is the point of resuming past a wedger), and its superseded salvage
    rows must not duplicate in the final table."""
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "metric": "failed",
                              "value": "timeout"}])
    st.mark_done("device:a", "failed")

    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "failed"}
    st2.reset("device:a")  # what the orchestrator does before retrying
    assert "device:a" not in st2.done and st2.all_rows() == []
    st2.add_rows("device:a", [{"config": "a", "value": 7}])
    st2.mark_done("device:a", "ok")

    st3 = run_all._State(path=path, resume=True)
    assert st3.done == {"device:a": "ok"}
    assert st3.all_rows() == [{"config": "a", "value": 7}]


def test_state_tolerates_torn_final_line(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    with open(path, "a") as f:
        f.write('{"_cfg": "device:b", "_row": {"conf')  # SIGKILL mid-write
    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "ok"}
    assert len(st2.all_rows()) == 1


def test_seed_state_carries_device_rows_with_provenance(tmp_path):
    # an off-chip host seeds resume state from a committed RESULTS.md:
    # on-chip device rows ride along provenance-stamped, their configs
    # marked ok (so --resume skips re-measuring them on the wrong
    # substrate), while cpu-mesh rows are dropped for a fresh re-run
    results = tmp_path / "RESULTS.md"
    results.write_text(
        "# Benchmark results (measured)\n\n"
        "Generated at commit `abc1234` on 2026-07-31 08:09 UTC; "
        "device-section platform: tpu.\n\n"
        "| config | metric | value | mfu | platform | details |\n"
        "|---|---|---|---|---|---|\n"
        "| gpt2_fwd | tokens_per_sec | 454770.9 | 61.4% | tpu | batch=8 |\n"
        "| gpt2_train_step | tokens_per_sec | 87266.2 | 35.3% | tpu | |\n"
        "| cifar_2stage_pipeline | images_per_sec | 21.0 | — | cpu-mesh | |\n")
    state_path = str(tmp_path / "rows.jsonl")
    n = run_all.seed_state_from_results(str(results), state_path)
    assert n == 2  # the cpu-mesh row is NOT carried
    st = run_all._State(path=state_path, resume=True)
    # gpt2_fwd rows come from the gpt_fwd config (multi-row mapping)
    assert st.done == {"device:gpt_fwd": "ok",
                       "device:gpt2_train_step": "ok"}
    rows = st.all_rows()
    assert [r["config"] for r in rows] == ["gpt2_fwd", "gpt2_train_step"]
    for r in rows:
        assert r["provenance"] == "abc1234 2026-07-31 08:09 UTC"
        assert r["platform"] == "tpu"
    assert rows[0]["mfu"] == 0.614


def test_seed_state_drops_markers_and_cpu_rows_keeps_provenance(tmp_path):
    """Re-seeding from a RESULTS.md that was ITSELF produced by a
    carried refresh must not (a) freeze failed/skipped marker rows as
    'ok' — their configs must retry, (b) carry cpu-substrate rows this
    host can re-measure, or (c) restamp an already-carried row with the
    newer header commit (old numbers masquerading as fresh, details
    nesting one level per cycle)."""
    results = tmp_path / "RESULTS.md"
    results.write_text(
        "# Benchmark results (measured)\n\n"
        "Generated at commit `def5678` on 2026-08-03 15:10 UTC; "
        "device-section platform: cpu, tpu.\n\n"
        "| config | metric | value | mfu | platform | details |\n"
        "|---|---|---|---|---|---|\n"
        "| gpt2_fwd | tokens_per_sec | 454770.9 | 61.4% | tpu | "
        "provenance=abc1234 2026-07-31 08:09 UTC, details=batch=8 |\n"
        "| gpt2_decode_matrix | failed | timeout | — | meta | note=x |\n"
        "| device_section | truncated | True | — | meta | note=z |\n"
        "| mixtral_decode | skipped | tpu_only | — | cpu | note=y |\n"
        "| obs_overhead | overhead_pct | 0.95 | — | cpu | ok=True |\n")
    state_path = str(tmp_path / "rows.jsonl")
    n = run_all.seed_state_from_results(str(results), state_path)
    assert n == 1  # only the on-chip measurement is carried
    st = run_all._State(path=state_path, resume=True)
    # failed / skipped / cpu configs are NOT done: --resume re-runs them
    assert st.done == {"device:gpt_fwd": "ok"}
    (row,) = st.all_rows()
    # the ORIGINAL stamp survives the second carry, un-nested
    assert row["provenance"] == "abc1234 2026-07-31 08:09 UTC"
    assert row["details"] == "batch=8"


def test_seed_state_maps_decode_matrix_rows_to_their_config(tmp_path):
    # gpt2_decode_matrix emits five gpt2_decode_w_* rows; seeding from a
    # TPU table must map them back to the config and mark it ok, or an
    # off-chip --resume re-runs the matrix on CPU and the table renders
    # the same row names on two substrates
    results = tmp_path / "RESULTS.md"
    results.write_text(
        "# Benchmark results (measured)\n\n"
        "Generated at commit `abc1234` on 2026-07-31 08:09 UTC; "
        "device-section platform: tpu.\n\n"
        "| config | metric | value | mfu | platform | details |\n"
        "|---|---|---|---|---|---|\n"
        "| gpt2_decode_w_f32_kv_f32 | tokens_per_sec | 9714.3 | — | tpu "
        "| batch=8 |\n"
        "| gpt2_decode_w_int4_kv_int8 | tokens_per_sec | 20512.8 | — | "
        "tpu | batch=8 |\n")
    state_path = str(tmp_path / "rows.jsonl")
    assert run_all.seed_state_from_results(str(results), state_path) == 2
    st = run_all._State(path=state_path, resume=True)
    assert st.done == {"device:gpt2_decode_matrix": "ok"}
    assert [r["config"] for r in st.all_rows()] == [
        "gpt2_decode_w_f32_kv_f32", "gpt2_decode_w_int4_kv_int8"]
