"""Pins for run_all.py's per-config orchestration: the append-only row
store, crash-resume semantics, and the device-config registry — the
machinery that guarantees one wedging config can no longer cost the
benchmark table's tail (VERDICT r4 weak #2)."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "_run_all_state_mod", os.path.join(REPO, "benchmarks", "run_all.py"))
run_all = importlib.util.module_from_spec(spec)
sys.modules["_run_all_state_mod"] = run_all
spec.loader.exec_module(run_all)


def test_registry_names_unique_and_ordered():
    names = [n for n, _, _ in run_all.DEVICE_CONFIGS]
    assert len(names) == len(set(names))
    # the serving tail that crashed in round 4 must be present
    for required in ("gpt2_decode_matrix", "gpt2_decode_attnkernel",
                     "gpt2_decode_top_p_tax", "gpt2_serving_e2e",
                     "gpt2_serving_constrained_tax", "mixtral_decode",
                     "speculative_decode", "embeddings_throughput",
                     "beam_vs_greedy"):
        assert required in names, required


def test_state_persists_rows_immediately(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    # rows are on disk BEFORE the config is marked done — a kill between
    # the two must not lose the measurement
    lines = [json.loads(l) for l in open(path)]
    assert lines[-1]["_row"] == {"config": "a", "value": 1}
    st.mark_done("device:a", "ok")


def test_state_resume_skips_completed(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    st.add_rows("device:b", [{"config": "b", "value": 2}])
    # no done marker for b: the run died mid-config

    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "ok"}
    # a's row survives; b's partial row is there too (salvage), but b is
    # NOT done, so the orchestrator will re-run it
    assert {"config": "a", "value": 1} in st2.all_rows()
    assert "device:b" not in st2.done


def test_state_fresh_run_truncates(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    st2 = run_all._State(path=path, resume=False)  # no --resume
    assert st2.done == {} and st2.all_rows() == []


def test_state_resume_retries_failed_configs(tmp_path):
    """A config that failed last run must be RETRIED on --resume (that
    is the point of resuming past a wedger), and its superseded salvage
    rows must not duplicate in the final table."""
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "metric": "failed",
                              "value": "timeout"}])
    st.mark_done("device:a", "failed")

    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "failed"}
    st2.reset("device:a")  # what the orchestrator does before retrying
    assert "device:a" not in st2.done and st2.all_rows() == []
    st2.add_rows("device:a", [{"config": "a", "value": 7}])
    st2.mark_done("device:a", "ok")

    st3 = run_all._State(path=path, resume=True)
    assert st3.done == {"device:a": "ok"}
    assert st3.all_rows() == [{"config": "a", "value": 7}]


def test_state_tolerates_torn_final_line(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    st = run_all._State(path=path, resume=False)
    st.add_rows("device:a", [{"config": "a", "value": 1}])
    st.mark_done("device:a", "ok")
    with open(path, "a") as f:
        f.write('{"_cfg": "device:b", "_row": {"conf')  # SIGKILL mid-write
    st2 = run_all._State(path=path, resume=True)
    assert st2.done == {"device:a": "ok"}
    assert len(st2.all_rows()) == 1
