"""Sequence-sharded KV-cache decode tests (long-context serving bridge).

The parity contract: token-for-token equal to the single-device decoder
while each device's cache slice holds only ceil(S_max/n) positions —
i.e. the total context genuinely exceeds any one shard's cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.generate_seq import make_generate_seq_sharded

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


@pytest.mark.parametrize("n", [2, 4])
def test_seq_sharded_greedy_matches_single_device(n, devices):
    mesh = make_mesh({SEQ_AXIS: n}, devices[:n])
    prepared = _prepared()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, CFG.vocab_size)
    n_new = 10  # context 20 > per-device slice of 20/n
    gen = make_generate_seq_sharded(CFG, mesh, max_new_tokens=n_new)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_seq_sharded_sampled_matches_single_device(devices):
    """Same rng split sequence + exact distributed softmax -> sampled
    streams agree draw-for-draw, not just in distribution."""
    mesh = make_mesh({SEQ_AXIS: 4}, devices[:4])
    prepared = _prepared(seed=2)
    ids = jax.random.randint(jax.random.PRNGKey(3), (1, 12), 0, CFG.vocab_size)
    gen = make_generate_seq_sharded(
        CFG, mesh, max_new_tokens=8, temperature=0.9, top_k=40)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(7)))
    want = np.asarray(make_generate(
        CFG, max_new_tokens=8, temperature=0.9, top_k=40)(
        prepared, ids, jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(got, want)


def test_seq_sharded_uneven_context(devices):
    """s_max not divisible by n: ceil-sized slices, tail shard half empty."""
    mesh = make_mesh({SEQ_AXIS: 4}, devices[:4])
    prepared = _prepared(seed=4)
    ids = jax.random.randint(jax.random.PRNGKey(5), (1, 7), 0, CFG.vocab_size)
    n_new = 6  # s_max = 13 -> sd = 4, last shard holds 1 real position
    gen = make_generate_seq_sharded(CFG, mesh, max_new_tokens=n_new)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate(CFG, max_new_tokens=n_new)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_seq_sharded_rejects_overlong(devices):
    mesh = make_mesh({SEQ_AXIS: 2}, devices[:2])
    prepared = _prepared()
    gen = make_generate_seq_sharded(CFG, mesh, max_new_tokens=60)
    with pytest.raises(ValueError, match="block_size"):
        gen(prepared, jnp.zeros((1, 10), jnp.int32), jax.random.PRNGKey(0))


@pytest.mark.parametrize("n", [2, 4])
def test_llama_seq_sharded_matches_solo(n, devices):
    """LLaMA sequence-sharded decode (KV-head-width position shards, GQA
    fold over the distributed softmax) == the solo LLaMA decoder."""
    from dnn_tpu.models import llama

    lcfg = llama.PRESETS["llama-test"]
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(40), lcfg), lcfg)
    mesh = make_mesh({SEQ_AXIS: n}, devices[:n])
    ids = jax.random.randint(jax.random.PRNGKey(41), (2, 9), 0,
                             lcfg.vocab_size)
    n_new = 7  # context 16: shards of 8 (n=2) / 4 (n=4), both exact
    gen = llama.make_generate_seq_sharded(
        lcfg, mesh, max_new_tokens=n_new, temperature=0.9, top_k=40)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(5)))
    want = np.asarray(llama.make_generate(
        lcfg, max_new_tokens=n_new, temperature=0.9, top_k=40)(
        prepared, ids, jax.random.PRNGKey(5)))
    np.testing.assert_array_equal(got, want)


def test_llama_seq_sharded_ragged_tail(devices):
    from dnn_tpu.models import llama

    lcfg = llama.PRESETS["llama-test"]
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(42), lcfg), lcfg)
    mesh = make_mesh({SEQ_AXIS: 4}, devices[:4])
    ids = jax.random.randint(jax.random.PRNGKey(43), (1, 7), 0,
                             lcfg.vocab_size)
    gen = llama.make_generate_seq_sharded(lcfg, mesh, max_new_tokens=6)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))
    want = np.asarray(llama.make_generate(lcfg, max_new_tokens=6)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
