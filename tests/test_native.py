"""Native codec tests: the compiled C++ path and the pure-Python fallback
must be bit-identical, and the wire layer must reject corrupt payloads.
(The reference has no native layer and no integrity checking — SURVEY §2
"100% Python", §5 "no endianness/alignment handling".)
"""

import shutil

import numpy as np
import pytest

from dnn_tpu import native


@pytest.mark.skipif(shutil.which("g++") is None, reason="no C++ toolchain")
def test_native_builds_here():
    """Where a compiler exists the compiled path must actually be used —
    a silent fallback would invalidate the perf claims. (Hosts without g++
    run the bit-identical Python fallback by design.)"""
    assert native.native_available()


# Known-answer tests: RFC 3720 CRC32C vectors.
@pytest.mark.parametrize(
    "data,want",
    [
        (b"", 0x00000000),
        (b"a", 0xC1D04330),
        (b"123456789", 0xE3069283),
        (bytes(32), 0x8A9136AA),
        (bytes(range(32)), 0x46DD794E),
    ],
)
def test_crc32c_known_answers(data, want):
    assert native.crc32c(data) == want


def test_crc32c_native_matches_python_fallback():
    rng = np.random.default_rng(0)
    for n in (0, 1, 7, 8, 9, 63, 64, 65, 1000, 4096, 100_000):
        buf = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
        native_crc = native.crc32c(buf)
        # force the fallback path
        table = native._py_table()
        crc = 0xFFFFFFFF
        for b in buf:
            crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
        assert native_crc == (~crc) & 0xFFFFFFFF


def test_crc32c_seed_chaining():
    buf = b"hello, pipeline world"
    whole = native.crc32c(buf)
    part = native.crc32c(buf[7:], seed=native.crc32c(buf[:7]))
    assert whole == part


def test_crc32c_unaligned_offsets():
    """slice-by-8 has an alignment prologue; exercise every phase."""
    base = np.frombuffer(bytes(range(256)) * 4, dtype=np.uint8)
    want = [native.crc32c(base[off:].tobytes()) for off in range(9)]
    got = [native.crc32c(base[off:]) for off in range(9)]
    assert want == got


def test_bf16_roundtrip_exact():
    import ml_dtypes

    rng = np.random.default_rng(1)
    x = rng.standard_normal((137,)).astype(ml_dtypes.bfloat16)
    f32 = native.bf16_to_f32(x)
    assert f32.dtype == np.float32
    np.testing.assert_array_equal(f32, x.astype(np.float32))
    back = native.f32_to_bf16(f32)
    np.testing.assert_array_equal(back.view(np.uint16), x.view(np.uint16))


def test_f32_to_bf16_matches_ml_dtypes_rounding():
    """Round-to-nearest-even must match ml_dtypes (== XLA) bit-for-bit,
    including ties, subnormals, infinities."""
    import ml_dtypes

    rng = np.random.default_rng(2)
    cases = np.concatenate([
        rng.standard_normal(10_000).astype(np.float32),
        rng.standard_normal(1000).astype(np.float32) * 1e30,
        rng.standard_normal(1000).astype(np.float32) * 1e-30,
        np.array([0.0, -0.0, np.inf, -np.inf, 1.0, -1.0,
                  3.0000001, 0.1, 65504.0], np.float32),
        # tie cases: exactly halfway between bf16 neighbors
        np.array([1.00390625, 1.01171875], np.float32),
    ])
    ours = native.f32_to_bf16(cases).view(np.uint16)
    ref = cases.astype(ml_dtypes.bfloat16).view(np.uint16)
    np.testing.assert_array_equal(ours, ref)


def test_f32_to_bf16_nan_stays_nan():
    out = native.f32_to_bf16(np.array([np.nan, -np.nan], np.float32))
    assert np.isnan(out.astype(np.float32)).all()


def test_wire_rejects_corrupt_payload():
    from dnn_tpu.comm import wire_pb2 as pb
    from dnn_tpu.comm.service import _tensor_arr, _tensor_msg

    msg = _tensor_msg(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert msg.HasField("crc32c")
    # round-trips clean
    np.testing.assert_array_equal(
        _tensor_arr(msg), np.arange(12, dtype=np.float32).reshape(3, 4)
    )
    # flip one payload byte -> must be detected
    data = bytearray(msg.tensor_data)
    data[5] ^= 0x01
    bad = pb.Tensor(
        tensor_data=bytes(data), shape=msg.shape, dtype=msg.dtype, crc32c=msg.crc32c
    )
    with pytest.raises(ValueError, match="corrupt"):
        _tensor_arr(bad)


def test_wire_accepts_reference_peer_without_crc():
    """A reference node.py peer sends no crc32c field; we must still decode
    (wire compat, SURVEY C3)."""
    from dnn_tpu.comm import wire_pb2 as pb
    from dnn_tpu.comm.service import _tensor_arr

    arr = np.ones((2, 2), np.float32)
    msg = pb.Tensor(tensor_data=arr.tobytes(), shape=[2, 2], dtype="float32")
    np.testing.assert_array_equal(_tensor_arr(msg), arr)
