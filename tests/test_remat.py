"""Rematerialization (jax.checkpoint) support: gradients must be identical
with and without remat — remat trades recompute for memory, never numerics.
(The reference has no training at all, let alone memory management —
SURVEY §5; remat is the TPU-native HBM lever.)"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dnn_tpu import train
from dnn_tpu.models import gpt

CFG = gpt.PRESETS["gpt2-test"]


def test_remat_forward_identical():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab_size)
    base = gpt.make_apply(CFG)(params, ids)
    rem = gpt.make_apply(CFG, remat=True)(params, ids)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(rem))


def test_remat_gradients_identical():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, CFG.vocab_size)

    def loss(apply_fn):
        return lambda p: train.next_token_loss(apply_fn, p, tokens)

    g_base = jax.grad(loss(gpt.make_apply(CFG)))(params)
    g_rem = jax.grad(loss(gpt.make_apply(CFG, remat=True)))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        g_base, g_rem,
    )


def test_remat_trains():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    opt = optax.adam(1e-3)
    apply_fn = gpt.make_apply(CFG, remat=True)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    step = train.make_train_step(loss_fn, opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0, CFG.vocab_size)
    p, s = params, opt.init(params)
    losses = []
    for _ in range(4):
        p, s, l = step(p, s, tokens)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_flash_auto_routing(monkeypatch):
    """use_flash='auto' must stay on the XLA path below the threshold and
    route through the flash kernel at/above it. Spy on the kernel entry
    (its CPU fallback is numerically identical, so outputs can't
    distinguish the paths — the routing decision itself is the subject)."""
    import importlib

    from dnn_tpu.ops import attention as attn_mod

    # the package __init__ re-exports the function under the same name, so
    # fetch the submodule explicitly
    fa_mod = importlib.import_module("dnn_tpu.ops.pallas.flash_attention")

    calls = []
    real_flash = fa_mod.flash_attention

    def spy(*args, **kwargs):
        calls.append(1)
        return real_flash(*args, **kwargs)

    monkeypatch.setattr(fa_mod, "flash_attention", spy)
    monkeypatch.setattr(attn_mod, "FLASH_AUTO_THRESHOLD", 16)

    params = gpt.init(jax.random.PRNGKey(0), CFG)
    below = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, CFG.vocab_size)
    at = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, CFG.vocab_size)

    gpt.make_apply(CFG, use_flash="auto")(params, below)
    assert not calls, "flash engaged below threshold"
    out_auto = gpt.make_apply(CFG, use_flash="auto")(params, at)
    assert calls, "flash not engaged at threshold"
    # and the routed result still matches the XLA path numerically
    out_base = gpt.make_apply(CFG)(params, at)
    np.testing.assert_allclose(
        np.asarray(out_auto), np.asarray(out_base), atol=2e-4, rtol=2e-4
    )
