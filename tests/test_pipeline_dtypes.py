"""Dtype-aware pipeline ring buffers.

Round-1 weak spot: the SPMD hop buffer was always f32 — bf16 pipelines
paid 2x the ICI bytes per ppermute hop, and integer inputs relied on the
unchecked "ints < 2^24 are exact in f32" trick. Now single-dtype pipelines
carry their native dtype and mixed pipelines bitcast ints into the f32
carrier (exact over the full int32 range)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import STAGE_AXIS
from dnn_tpu.parallel.pipeline import (
    _buffer_dtype,
    spmd_pipeline,
    spmd_pipeline_stacked,
)

CFG = gpt.PRESETS["gpt2-test"]


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), (STAGE_AXIS,))


def _ppermute_dtypes(jaxpr):
    """All dtypes flowing through ppermute ops, recursively (descends into
    shard_map/scan/pjit sub-jaxprs wherever they hide in eqn params)."""
    def sub_jaxprs(obj):
        if hasattr(obj, "eqns"):
            yield obj
        elif hasattr(obj, "jaxpr"):
            yield obj.jaxpr
        elif isinstance(obj, (list, tuple)):
            for o in obj:
                yield from sub_jaxprs(o)

    out = []
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "ppermute":
            out.extend(v.aval.dtype for v in eqn.invars)
        for param in eqn.params.values():
            for sj in sub_jaxprs(param):
                out.extend(_ppermute_dtypes(sj))
    return out


def test_buffer_dtype_selection():
    assert _buffer_dtype([jnp.bfloat16]) == jnp.bfloat16
    assert _buffer_dtype([jnp.int32]) == jnp.int32
    assert _buffer_dtype([jnp.float32, jnp.bfloat16]) == jnp.float32
    assert _buffer_dtype([jnp.int32, jnp.float32]) == jnp.float32
    with pytest.raises(ValueError, match="int32"):
        _buffer_dtype([jnp.int64, jnp.float32])


def test_stacked_pipeline_hops_ride_bf16():
    """With bf16 activations, every ppermute on the ring must carry bf16 —
    half the ICI bytes of the old always-f32 buffer."""
    mesh = _mesh(4)
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    stacks = jax.tree.map(
        lambda p: p.reshape(4, 1, *p.shape[1:]), prepared["blocks"]
    )

    def block_fn(bp, h):
        return gpt.blocks_scan(bp, h, cfg=CFG, compute_dtype=jnp.bfloat16)

    def run(stacked, x):
        return spmd_pipeline_stacked(block_fn, stacked, x, mesh=mesh,
                                     num_microbatches=2)

    x = jnp.ones((4, 8, CFG.n_embd), jnp.bfloat16)
    dtypes = _ppermute_dtypes(jax.make_jaxpr(run)(stacks, x).jaxpr)
    assert dtypes, "no ppermute found in the pipeline jaxpr"
    assert all(d == jnp.bfloat16 for d in dtypes), dtypes
    out = run(stacks, x)
    assert out.dtype == jnp.bfloat16


@pytest.mark.parametrize("compute_dtype", [None, jnp.bfloat16])
def test_stacked_parity_both_dtypes(compute_dtype):
    """Pipeline output must equal the single-device blocks_scan in both
    dtypes (the native-dtype ring changes bytes moved, not math)."""
    mesh = _mesh(4)
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    stacks = jax.tree.map(
        lambda p: p.reshape(4, 1, *p.shape[1:]), prepared["blocks"]
    )
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, CFG.n_embd))
    if compute_dtype is not None:
        x = x.astype(compute_dtype)

    def block_fn(bp, h):
        return gpt.blocks_scan(bp, h, cfg=CFG, compute_dtype=compute_dtype)

    got = spmd_pipeline_stacked(block_fn, stacks, x, mesh=mesh,
                                num_microbatches=2)
    want = gpt.blocks_scan(prepared["blocks"], x, cfg=CFG,
                           compute_dtype=compute_dtype)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_heterogeneous_int_payload_exact_beyond_2p24():
    """Integer payloads on the mixed-dtype ring must survive bit-exactly —
    including values far above 2^24, where a value-level f32 cast would
    corrupt them."""
    mesh = _mesh(2)
    big = np.array([[1, 2 ** 24 + 1], [2 ** 31 - 5, 7]], np.int32)

    def stage0(params, ids):  # int -> int (rides the ring to stage 1)
        return ids + params

    def stage1(params, ids):  # int -> float
        return ids.astype(jnp.float64).astype(jnp.float32) * params

    # integer stage params -> the packed (float) placement doesn't apply;
    # replicated placement also exercises the non-packed branch path
    out = spmd_pipeline(
        [stage0, stage1], [jnp.int32(1), jnp.float32(1.0)],
        jnp.asarray(big), mesh=mesh, num_microbatches=2,
        param_placement="replicated",
    )
    expect = (big.astype(np.int64) + 1).astype(np.float64).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_heterogeneous_gpt_parity_bf16():
    """GPT partition stages (ids in, bf16 compute) through the mixed ring
    match the composed stages exactly."""
    mesh = _mesh(4)
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    stages = gpt.make_partition(CFG, compute_dtype=jnp.bfloat16)(4)
    sp = [s.slice_params(params) for s in stages]
    fns = [s.apply for s in stages]
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, 16), 0, CFG.vocab_size,
                             dtype=jnp.int32)
    got = spmd_pipeline(fns, sp, ids, mesh=mesh, num_microbatches=2)
    want = ids
    for fn, p in zip(fns, sp):
        want = fn(p, want)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_integer_final_output_uses_native_out_buffer():
    """An integer-producing final stage (e.g. argmax serving) must come
    back exact: the out buffer is the final dtype itself and its psum is
    integer arithmetic."""
    mesh = _mesh(2)

    def stage0(params, x):
        return x * params

    def stage1(params, x):
        return jnp.argmax(x, axis=-1).astype(jnp.int32) + (2 ** 24 + 3)

    x = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    out = spmd_pipeline(
        [stage0, stage1], [jnp.float32(2.0), None], x,
        mesh=mesh, num_microbatches=2,
    )
    want = np.argmax(np.asarray(x) * 2.0, axis=-1).astype(np.int32) + (2 ** 24 + 3)
    assert out.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out), want)
