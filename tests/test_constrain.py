"""Constrained (structured) decoding: the regex->DFA->token-mask stack
(runtime/constrain.py) and its continuous-batcher integration.

The engine is cross-checked against Python's `re` on the shared subset,
then driven end-to-end: every constrained completion must FULL-MATCH its
grammar, greedy decoding must pick the argmax AMONG allowed tokens, and
"JSON mode" output must json.loads. The reference framework has no
decode loop at all (node.py:137-200) — this is serving surface built
beyond it.
"""

import json
import re as pyre

import jax
import numpy as np
import pytest

from dnn_tpu.runtime import constrain
from dnn_tpu.runtime.constrain import (
    TokenConstraint,
    byte_vocab,
    compile_regex,
    json_regex,
    match,
)

# ----------------------------------------------------------------------
# regex engine vs Python re (shared subset, full-match semantics)
# ----------------------------------------------------------------------

CASES = [
    (r"abc", ["abc"], ["ab", "abcd", ""]),
    (r"a*b+c?", ["b", "aab", "aabbc"], ["a", "c", "bcc"]),
    (r"[a-f0-9]{2,4}", ["ab", "12ef", "0f0"], ["a", "abcde", "gh"]),
    (r"(ab|cd)*", ["", "ab", "abcdab"], ["a", "abc"]),
    (r"-?(0|[1-9][0-9]*)(\.[0-9]+)?", ["0", "-42", "3.14"],
     ["00", "1.", "-", "+1"]),
    (r"[^xyz]+", ["abc", "123"], ["", "axb"]),
    (r"\d{3}-\d{4}", ["555-1234"], ["5551234", "55-1234"]),
    (r"\w+@\w+\.(com|org)", ["a_1@b.com", "x@y.org"], ["a@b.net", "@b.com"]),
    (r"a.c", ["abc", "a0c"], ["ac", "a\nc"]),
    (r"(x|y){2}z?", ["xy", "yxz"], ["x", "xyzz"]),
    (r"\{\"k\": [0-9]+\}", ['{"k": 7}', '{"k": 42}'], ['{"k": }', "{k: 1}"]),
    (r"a{2,}", ["aa", "aaaa"], ["a", ""]),
    (r"colou?r", ["color", "colour"], ["colouur"]),
]


@pytest.mark.parametrize("pattern,good,bad", CASES)
def test_engine_matches_python_re(pattern, good, bad):
    dfa = compile_regex(pattern)
    for s in good:
        assert pyre.fullmatch(pattern, s), f"test premise: {s!r}"
        assert match(dfa, s.encode()), f"{pattern!r} should accept {s!r}"
    for s in bad:
        assert not pyre.fullmatch(pattern, s), f"test premise: {s!r}"
        assert not match(dfa, s.encode()), f"{pattern!r} should reject {s!r}"


def test_engine_randomized_against_re():
    """Fuzz short strings over a tiny alphabet against Python re for a
    few patterns — the systematic check the hand cases can't cover."""
    rs = np.random.RandomState(0)
    for pattern in [r"a*b|c", r"(ab?)+", r"[ab]{1,3}c*", r"a(b|c){2}d?"]:
        dfa = compile_regex(pattern)
        for _ in range(300):
            n = rs.randint(0, 6)
            s = "".join(rs.choice(list("abcd")) for _ in range(n))
            assert bool(pyre.fullmatch(pattern, s)) == match(
                dfa, s.encode()), (pattern, s)


def test_token_table_multibyte_tokens():
    """BPE-style multi-byte tokens walk the DFA atomically: a token is
    allowed iff its WHOLE byte string survives."""
    vocab = [b"a", b"b", b"ab", b"abc", b"c", b""]
    c = TokenConstraint.from_regex(r"ab*c", vocab)
    s = c.start
    allowed = c.allowed[s]
    assert allowed[0] and allowed[2] and allowed[3]   # a, ab, abc
    assert not allowed[1] and not allowed[4]           # b, c can't start
    assert not allowed[5], "empty-byte tokens are always banned"
    s_a = c.advance(s, 0)
    assert c.advance(s_a, 1) >= 0      # b continues
    s_abc = c.advance(s, 3)
    assert c.is_accepting(s_abc)
    # 'abc' consumed the closing c: under ab*c no byte may follow, so no
    # token can continue from this state
    assert not c.has_continuation(s_abc)


def test_json_regex_accepts_real_json():
    dfa = compile_regex(json_regex(max_depth=2))
    good = [
        42, -3.5, True, None, "hi there", [1, 2, 3],
        {"a": 1, "b": "x"}, {"outer": [1, "two", None]},
        [], {},
    ]
    for obj in good:
        s = json.dumps(obj)
        assert match(dfa, s.encode()), s
    for s in ['{"a": }', "[1,, 2]", "tru", '"unterminated', "01"]:
        assert not match(dfa, s.encode()), s
    # depth 3 exceeds the expansion budget — rejected by construction
    assert not match(dfa, json.dumps([[[1]]]).encode())


# ----------------------------------------------------------------------
# batcher integration (byte-level vocab: llama-test has V=256)
# ----------------------------------------------------------------------

from dnn_tpu.models import gpt, llama  # noqa: E402

CFG = llama.PRESETS["llama-test"]


def _batcher(**kw):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = llama.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    kw.setdefault("slots", 2)
    return ContinuousBatcher(
        CFG, prepared, max_len=CFG.block_size, prompt_pad=8,
        family=llama.LlamaFamilyRows(CFG), allow_constraints=True, **kw)


def test_constrained_output_matches_grammar_sampled():
    srv = _batcher(temperature=1.0, slots=3)
    pattern = r"[ab]{5}"
    c = TokenConstraint.from_regex(pattern, byte_vocab(CFG.vocab_size))
    rids = [srv.submit(np.asarray([65, 66, 67]), max_new_tokens=32,
                       seed=s, constraint=c) for s in (1, 2, 3)]
    # one compiled constraint object serves many concurrent requests
    srv.drain()
    for rid in rids:
        toks = srv.results[rid]
        text = bytes(int(t) for t in toks)
        assert pyre.fullmatch(pattern.encode(), text), text
        assert srv.finish_reasons[rid] == "constraint"


def test_constrained_greedy_is_argmax_over_allowed():
    """Greedy + constraint == restrict-then-argmax of the unconstrained
    distribution (constraints must not perturb allowed logits)."""
    srv = _batcher()
    c = TokenConstraint.from_regex(r"[qz]+", byte_vocab(CFG.vocab_size))
    prompt = np.asarray([1, 2, 3, 4])
    rid = srv.submit(prompt, max_new_tokens=4, constraint=c)

    srv2 = _batcher(logprobs_k=8)
    rid2 = srv2.submit(prompt, max_new_tokens=4, logprobs=True)
    srv.drain()
    srv2.drain()
    got = srv.results[rid]
    assert all(int(t) in (ord("q"), ord("z")) for t in got)
    # cross-check first step against the unconstrained top-k record:
    # among {q, z}, the constrained pick is the higher-logprob one
    lp = srv2.token_logprobs[rid2]
    ids0 = list(lp["top_ids"][0] if lp["top_ids"].ndim == 2
                else lp["top_ids"][0])
    if ord("q") in ids0 and ord("z") in ids0:
        want = (ord("q") if ids0.index(ord("q")) < ids0.index(ord("z"))
                else ord("z"))
        assert int(got[0]) == want


def test_json_mode_end_to_end():
    """A bounded JSON grammar forces a parseable object from a RANDOM
    model under sampling — the 'JSON mode' aha in one test."""
    srv = _batcher(temperature=1.0)
    # no leading zeros: [0-9]{1,3} admits "002", which regex-matches but
    # is not a legal JSON number — the constraint engine faithfully
    # produced it and json.loads rightly refused (the old failure)
    pattern = r"\{\"k\": (true|false|0|[1-9][0-9]{0,2})\}"
    c = TokenConstraint.from_regex(pattern, byte_vocab(CFG.vocab_size))
    rid = srv.submit(np.asarray([10, 20]), max_new_tokens=24, seed=7,
                     constraint=c)
    srv.drain()
    text = bytes(int(t) for t in srv.results[rid]).decode()
    obj = json.loads(text)
    assert set(obj) == {"k"}
    assert srv.finish_reasons[rid] == "constraint"


def test_eos_only_in_accepting_states():
    """With an eos_id configured, open-ended grammars stop via a real
    sampled EOS — and the emitted prefix is a complete match."""
    eos = 0
    srv = _batcher(temperature=1.0, eos_id=eos, slots=4)
    pattern = r"[xy]{2,6}"
    c = TokenConstraint.from_regex(pattern, byte_vocab(CFG.vocab_size))
    rids = [srv.submit(np.asarray([5, 6]), max_new_tokens=10, seed=s,
                       constraint=c) for s in range(4)]
    srv.drain()
    for rid in rids:
        toks = [int(t) for t in srv.results[rid]]
        reason = srv.finish_reasons[rid]
        body = bytes(t for t in toks if t != eos)
        assert pyre.fullmatch(pattern.encode(), body), (body, reason)
        assert reason in ("eos", "constraint"), reason


def test_constraint_requires_capability_and_matching_vocab():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = llama.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            prompt_pad=8,
                            family=llama.LlamaFamilyRows(CFG))
    c = TokenConstraint.from_regex(r"a+", byte_vocab(CFG.vocab_size))
    with pytest.raises(ValueError, match="allow_constraints"):
        srv.submit(np.asarray([1]), max_new_tokens=4, constraint=c)

    srv2 = _batcher()
    bad = TokenConstraint.from_regex(r"a+", byte_vocab(128))
    with pytest.raises(ValueError, match="vocab"):
        srv2.submit(np.asarray([1]), max_new_tokens=4, constraint=bad)


def test_constraint_rejects_grammar_relevant_eos():
    """An eos_id that aliases bytes the grammar can consume must be
    rejected at submit — mask_row's eos override would otherwise ban a
    required token (and an emitted one would retire as 'eos' mid-match)."""
    srv = _batcher(eos_id=ord("x"))
    c = TokenConstraint.from_regex(r"[xy]{3}", byte_vocab(CFG.vocab_size))
    with pytest.raises(ValueError, match="eos"):
        srv.submit(np.asarray([1, 2]), max_new_tokens=5, constraint=c)


def test_constraint_accepts_eos_aliased_only_in_unreachable_states():
    """BPE-style multi-byte tokens jump over byte-DFA states; eos bytes
    consumable ONLY in those token-unreachable states must not trip the
    submit guard (regression pin for the reachable-quantified check —
    reverting to `allowed[:, eos_id].any()` breaks this)."""
    vocab = [b"ab", b"b"] + [b""] * (CFG.vocab_size - 2)
    c = TokenConstraint.from_regex(r"ab", vocab)
    # the post-'a' byte state exists (it consumes b"b", token 1) but no
    # token walk from start lands on it — token b"ab" jumps over it
    unreachable = ~c.reachable
    assert c.allowed[unreachable, 1].any()
    assert not c.allowed[c.reachable, 1].any()
    srv = _batcher(eos_id=1)
    rid = srv.submit(np.asarray([3, 4]), max_new_tokens=4, constraint=c)
    srv.drain()
    toks = [int(t) for t in srv.results[rid]]
    assert [t for t in toks if t != 1] == [0]  # b"ab" (eos may trail)
    assert srv.finish_reasons[rid] in ("eos", "constraint")


def test_constraint_composes_with_user_logit_bias():
    """logit_bias steers WITHIN the grammar: banning 'a' under [ab]{3}
    yields bbb."""
    srv = _batcher(allow_logit_bias=True, temperature=1.0)
    c = TokenConstraint.from_regex(r"[ab]{3}", byte_vocab(CFG.vocab_size))
    rid = srv.submit(np.asarray([9]), max_new_tokens=8, seed=1,
                     constraint=c, logit_bias={ord("a"): -100.0})
    srv.drain()
    assert bytes(int(t) for t in srv.results[rid]) == b"bbb"


def test_empty_string_grammar_serves_empty_match():
    """A grammar matching ONLY the empty string is legal when eos can
    express it: the first sample is forced to eos and the request
    retires with a valid empty match. Without an eos there is no way to
    express it — rejected."""
    c = TokenConstraint.from_regex(r"", byte_vocab(CFG.vocab_size))
    assert not c.allowed[c.start].any() and c.is_accepting(c.start)
    srv = _batcher(eos_id=0)
    rid = srv.submit(np.asarray([5]), max_new_tokens=4, constraint=c)
    srv.drain()
    assert [t for t in srv.results[rid] if t != 0] == []
    assert srv.finish_reasons[rid] == "eos"

    srv2 = _batcher(eos_id=None)
    with pytest.raises(ValueError, match="no first token"):
        srv2.submit(np.asarray([5]), max_new_tokens=4, constraint=c)


def test_constraint_table_pool_hit_refcount_eviction():
    """The device mask pool uploads each grammar ONCE (pool hit on
    resubmit), keeps unreferenced entries cached, and evicts them LRU
    when space runs out."""
    srv = _batcher(constraint_rows=12)
    v = byte_vocab(CFG.vocab_size)
    c1 = TokenConstraint.from_regex(r"[ab]{3}", v)
    n1 = c1.table.shape[0]
    rid = srv.submit(np.asarray([1]), max_new_tokens=8, constraint=c1)
    assert len(srv._ctab_entries) == 1
    e1 = srv._ctab_entries[id(c1)]
    assert e1["refs"] == 1 and e1["n"] == n1 and e1["off"] >= 1
    srv.drain()
    assert e1["refs"] == 0  # retired; entry stays cached
    assert srv.finish_reasons[rid] == "constraint"

    srv.submit(np.asarray([1]), max_new_tokens=8, constraint=c1)
    assert len(srv._ctab_entries) == 1 and e1["refs"] == 1  # pool hit
    srv.drain()

    # fill the pool with fresh grammars until c1's entry must evict
    fillers = [TokenConstraint.from_regex(r"[cd]{%d}" % k, v)
               for k in (3, 4)]
    for f in fillers:
        srv.submit(np.asarray([1]), max_new_tokens=10, constraint=f)
        srv.drain()
    assert id(c1) not in srv._ctab_entries, "LRU entry should have evicted"


def test_constraint_pool_rejects_oversized_and_exhausted():
    srv = _batcher(constraint_rows=8)
    v = byte_vocab(CFG.vocab_size)
    big = TokenConstraint.from_regex(r"[ab]{20}", v)
    assert big.table.shape[0] > 7
    with pytest.raises(ValueError, match="constraint_rows"):
        srv.submit(np.asarray([1]), max_new_tokens=4, constraint=big)

    # two LIVE grammars that cannot coexist in an 8-row pool: the second
    # submit must fail loudly (no unreferenced entry to evict)
    c1 = TokenConstraint.from_regex(r"[ab]{4}", v)
    c2 = TokenConstraint.from_regex(r"[cd]{4}", v)
    assert c1.table.shape[0] + c2.table.shape[0] > 7
    srv.submit(np.asarray([1]), max_new_tokens=8, constraint=c1)  # live
    with pytest.raises(ValueError, match="exhausted"):
        srv.submit(np.asarray([2]), max_new_tokens=8, constraint=c2)
    srv.drain()


def test_constraints_need_no_bias_buffer():
    """Device-resident tables removed the constraint path's dependence
    on the (slots, V) bias buffer: an allow_constraints-only server
    keeps the zero-width buffer (memory win) and the per-slot state
    vector mirrors the host DFA walk."""
    srv = _batcher(slots=2)
    assert srv._bias.shape == (2, 0)
    c = TokenConstraint.from_regex(r"[ab]{4}", byte_vocab(CFG.vocab_size))
    srv.submit(np.asarray([1]), max_new_tokens=2, constraint=c)
    srv.step()
    off = srv._ctab_entries[id(c)]["off"]
    req = srv._slot_req[0]
    if req is not None:  # still live: device row tracks the host state
        assert int(np.asarray(srv._crow)[0]) == off + req["c_state"]
    srv.drain()
    assert int(np.asarray(srv._crow)[0]) == 0  # released back to the zero row


def test_choice_constraint_picks_exactly_one_label():
    """The enum/classifier pattern: output is VERBATIM one of the
    options, across several sampled requests."""
    from dnn_tpu.runtime.constrain import choice_regex, regex_escape

    options = ["positive", "negative", "neutral(ish)"]  # metachars too
    pattern = choice_regex(options)
    dfa = compile_regex(pattern)
    for o in options:
        assert match(dfa, o.encode())
    assert not match(dfa, b"positiv")
    assert not match(dfa, b"neutralXishX"), "metachars match literally"
    assert pyre.fullmatch(pyre.escape("a.b{c"),
                          "a.b{c") and match(
        compile_regex(regex_escape("a.b{c")), b"a.b{c")

    srv = _batcher(temperature=1.0, slots=3)
    c = TokenConstraint.from_regex(pattern, byte_vocab(CFG.vocab_size))
    rids = [srv.submit(np.asarray([11, 12]), max_new_tokens=32, seed=s,
                       constraint=c) for s in (1, 2, 3)]
    srv.drain()
    for rid in rids:
        text = bytes(int(t) for t in srv.results[rid]).decode()
        assert text in options, text
        assert srv.finish_reasons[rid] == "constraint"


def test_lm_server_json_mode_wiring():
    """The daemon's ':j=DEPTH' gen option: parse -> compile-once
    constraint over the tokenizer's byte vocab -> constrained submit
    through the worker; output json.loads."""
    from dnn_tpu.io.tokenizer import ByteTokenizer
    from dnn_tpu.runtime.lm_server import LMServer, parse_gen_options

    mx, seed, opts = parse_gen_options("gen:40:7:j=1", 32)
    assert (mx, seed, opts) == (40, 7, {"json_depth": 1})

    params = llama.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    srv = LMServer(CFG, prepared, tokenizer=ByteTokenizer(CFG.vocab_size),
                   slots=2, max_len=CFG.block_size, prompt_pad=8,
                   family=llama.LlamaFamilyRows(CFG),
                   allow_constraints=True, temperature=1.0)
    try:
        assert srv.json_constraint(0) is srv.json_constraint(0), "cached"
        with pytest.raises(ValueError, match="depth"):
            srv.json_constraint(9)
        fut = srv.worker.submit(np.asarray([3, 4, 5], np.int32), 40, 7,
                                opts={"constraint": srv.json_constraint(0)})
        toks = fut.result(timeout=120)
        json.loads(bytes(int(t) for t in toks).decode())
    finally:
        srv.close()

    # a server whose tokenizer has no byte map cannot serve JSON mode
    srv2 = LMServer(CFG, prepared, tokenizer=None, slots=1, max_len=32,
                    prompt_pad=8, family=llama.LlamaFamilyRows(CFG))
    try:
        assert srv2.json_constraint(1) is None
    finally:
        srv2.close()


def test_hf_vocab_bytes_sentencepiece_convention():
    """Convention is detected ONCE per vocab: a SentencePiece piece made
    of alias-alphabet chars ('é') must yield its UTF-8 bytes, not the
    Latin-1 byte the BPE alias table would give; '<0xNN>' pieces are raw
    bytes; padding ids beyond the tokenizer map to b""."""
    from dnn_tpu.io.tokenizer import hf_vocab_bytes

    class FakeSP:
        all_special_tokens = ["<s>"]

        @staticmethod
        def get_vocab():
            return {"<s>": 0, "▁caf": 1, "é": 2, "<0x0A>": 3, "hello": 4}

    vb = hf_vocab_bytes(FakeSP())
    assert vb[0] == b""                       # special: banned
    assert vb[1] == " caf".encode()
    assert vb[2] == "é".encode("utf-8")       # b'\xc3\xa9', NOT b'\xe9'
    assert vb[3] == b"\n"
    assert vb[4] == b"hello"
    vb2 = hf_vocab_bytes(FakeSP(), vocab_size=10)
    assert len(vb2) == 10 and vb2[9] == b""   # padded embedding table


def test_hf_vocab_bytes_real_bpe_constrained_decode():
    """Constrained decoding over a REAL byte-level BPE vocabulary
    (multi-byte tokens), not just the byte tokenizer: hf_vocab_bytes
    inverts the GPT-2 alias alphabet, and a grammar holds token streams
    whose tokens span several grammar bytes at once."""
    import dataclasses

    tokenizers = pytest.importorskip("tokenizers")
    transformers = pytest.importorskip("transformers")

    from dnn_tpu.io.tokenizer import hf_vocab_bytes

    bpe = tokenizers.implementations.ByteLevelBPETokenizer()
    corpus = (['{"name": "value", "count": 123, "flag": true}'] * 40
              + ["hello world, plain text with spaces"] * 40)
    bpe.train_from_iterator(corpus, vocab_size=300, min_frequency=1)
    fast = transformers.PreTrainedTokenizerFast(
        tokenizer_object=bpe._tokenizer)
    vb = hf_vocab_bytes(fast)

    # THE invariant constraints rely on: concatenating a real encoding's
    # token bytes reproduces the text's utf-8 bytes exactly
    for text in ['{"count": 42}', "hello world", '{"flag": true}']:
        ids = fast.encode(text)
        assert b"".join(vb[i] for i in ids) == text.encode(), text

    V = len(vb)
    cfg = dataclasses.replace(CFG, vocab_size=V)
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = llama.init(jax.random.PRNGKey(3), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=cfg.block_size,
                            prompt_pad=8, family=llama.LlamaFamilyRows(cfg),
                            allow_constraints=True, temperature=1.0)
    c = TokenConstraint.from_regex(r"\{\"count\": [0-9]{1,3}\}", vb)
    # multi-byte tokens must be usable: the grammar's fixed prefix
    # ('{"count": ') is in-corpus, so merged tokens cover it
    assert any(len(vb[t]) > 1 and c.allowed[:, t].any() for t in range(V))
    rid = srv.submit(np.asarray(fast.encode("hello world")),
                     max_new_tokens=32, seed=5, constraint=c)
    srv.drain()
    text = b"".join(vb[int(t)] for t in srv.results[rid]).decode()
    obj = json.loads(text)
    assert set(obj) == {"count"}
    assert srv.finish_reasons[rid] == "constraint"


def test_speculative_batcher_rejects_constraints():
    """The speculative batcher commits multiple tokens per step — it
    rejects allow_constraints at CONSTRUCTION (before allocating the
    device mask pool it could never use), and constraint= submits on an
    unconstrained instance fail with the capability error."""
    from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

    cfg = gpt.PRESETS["gpt2-test"]
    rng = jax.random.PRNGKey(0)
    params = gpt.init(rng, cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    with pytest.raises(ValueError, match="allow_constraints"):
        SpeculativeBatcher(cfg, prepared, cfg, prepared, spec_k=2,
                           slots=1, max_len=32, prompt_pad=8,
                           allow_constraints=True)
    srv = SpeculativeBatcher(cfg, prepared, cfg, prepared, spec_k=2,
                             slots=1, max_len=32, prompt_pad=8)
    c = TokenConstraint.from_regex(r"a+", byte_vocab(cfg.vocab_size))
    with pytest.raises(ValueError, match="constraint"):
        srv.submit(np.asarray([1, 2, 3]), max_new_tokens=4, constraint=c)
