"""Topology config: reference-schema compatibility and validation
(the reference validates manually with exit(1) per field, node.py:222-277)."""

import json

import pytest

from dnn_tpu.config import TopologyConfig


REFERENCE_STYLE = {
    # exactly the reference's schema (config.json:1-18)
    "nodes": [
        {"id": "node1", "address": "192.168.1.101:50051", "part_index": 0},
        {"id": "node2", "address": "192.168.1.120:50051", "part_index": 1},
    ],
    "model_weights": "./cifar10_model.pth",
    "num_parts": 2,
    "return_to_node_id": "node1",
}


def test_reference_config_parses():
    cfg = TopologyConfig.from_dict(REFERENCE_STYLE)
    assert cfg.num_parts == 2
    assert cfg.model == "cifar_cnn"  # the reference's only wired family
    assert cfg.node_by_id("node2").part_index == 1
    assert cfg.node_by_part(0).id == "node1"
    assert cfg.nodes[0].port == 50051


def test_next_and_return_resolution():
    cfg = TopologyConfig.from_dict(REFERENCE_STYLE)
    n1, n2 = cfg.node_by_id("node1"), cfg.node_by_id("node2")
    assert cfg.next_node(n1).id == "node2"  # node.py:262-271
    assert cfg.next_node(n2) is None
    assert cfg.return_node().id == "node1"  # node.py:272-277


def test_arbitrary_num_parts_allowed():
    """The reference hard-exits unless num_parts == 2 (node.py:246-248);
    the rebuild accepts any coverage-complete topology."""
    d = {
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(5)],
        "num_parts": 5,
    }
    assert TopologyConfig.from_dict(d).num_parts == 5


@pytest.mark.parametrize(
    "mutate,match",
    [
        (lambda d: d["nodes"].pop(), "cover exactly"),
        (lambda d: d["nodes"][0].update(part_index=1), "cover exactly"),
        (lambda d: d["nodes"][1].update(id="node1"), "duplicate"),
        (lambda d: d.update(return_to_node_id="ghost"), "not among"),
        (lambda d: d.update(runtime="mpi"), "runtime"),
        (lambda d: d.update(microbatches=-1), "microbatches"),
    ],
)
def test_validation_errors(mutate, match):
    d = json.loads(json.dumps(REFERENCE_STYLE))
    mutate(d)
    with pytest.raises(ValueError, match=match):
        TopologyConfig.from_dict(d)


def test_bad_address_port():
    cfg = TopologyConfig.from_dict(REFERENCE_STYLE)
    bad = cfg.nodes[0].__class__(id="x", part_index=0, address="nocolonhere")
    with pytest.raises(ValueError, match="Invalid address"):
        _ = bad.port


def test_repo_example_configs_parse():
    for p in ("configs/cifar_2stage.json", "configs/gpt2_8stage.json"):
        cfg = TopologyConfig.from_json(p)
        assert cfg.num_parts == len(cfg.nodes)
