"""Tokenizer + text-serving tests: the SendMessage RPC (dead code in the
reference, node.py:111-113) serving prompt text -> generated text through
the tokenizer-equipped LM daemon."""

import jax
import numpy as np
import pytest

from dnn_tpu.comm.client import NodeClient
from dnn_tpu.io.tokenizer import ByteTokenizer
from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.lm_server import start_lm_server_in_background

CFG = gpt.PRESETS["gpt2-test"]  # vocab 256: bytes fit exactly


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(256)
    for s in ("hello", "héllo wörld", "", "a\nb\tc", "🙂"):
        assert tok.decode(tok.encode(s)) == s
    # out-of-range ids degrade to replacement bytes, never raise
    assert isinstance(ByteTokenizer(300, offset=2).decode([0, 1, 299]), str)
    with pytest.raises(ValueError, match="vocab_size"):
        ByteTokenizer(100)


def test_text_endpoint_matches_id_endpoint():
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), CFG), CFG)
    tok = ByteTokenizer(CFG.vocab_size)
    port = 59321
    t, stop = start_lm_server_in_background(
        CFG, prepared, port=port, slots=2, max_len=64, prompt_pad=16,
        default_max_new=6, tokenizer=tok)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        # stats path still reachable
        assert "pool" in c.send_message("anyone", "!stats")

        prompt = "hello"
        text = c.generate_text(prompt, max_new_tokens=6)
        # oracle: tokenize -> id-endpoint semantics -> detokenize
        ids = np.asarray(tok.encode(prompt), np.int32)
        want_ids = np.asarray(make_generate(CFG, max_new_tokens=6)(
            prepared, ids[None, :], jax.random.PRNGKey(0)))[0]
        assert text == tok.decode([int(i) for i in want_ids])
        c.close()
    finally:
        stop()


def test_text_endpoint_without_tokenizer_gives_stats():
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(1), CFG), CFG)
    port = 59322
    t, stop = start_lm_server_in_background(
        CFG, prepared, port=port, slots=1, max_len=32, prompt_pad=8)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        assert "pool" in c.send_message("gen:4", "some prompt")
        c.close()
    finally:
        stop()


def test_out_of_range_ids_become_replacement_char():
    tok = ByteTokenizer(300, offset=2)
    s = tok.decode([0, 1, 299, 2 + ord("a")])
    assert s == "���a"
