"""Tokenizer + text-serving tests: the SendMessage RPC (dead code in the
reference, node.py:111-113) serving prompt text -> generated text through
the tokenizer-equipped LM daemon."""

import jax
import numpy as np
import pytest

from dnn_tpu.comm.client import NodeClient
from dnn_tpu.io.tokenizer import ByteTokenizer
from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.lm_server import start_lm_server_in_background

CFG = gpt.PRESETS["gpt2-test"]  # vocab 256: bytes fit exactly


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(256)
    for s in ("hello", "héllo wörld", "", "a\nb\tc", "🙂"):
        assert tok.decode(tok.encode(s)) == s
    # out-of-range ids degrade to replacement bytes, never raise
    assert isinstance(ByteTokenizer(300, offset=2).decode([0, 1, 299]), str)
    with pytest.raises(ValueError, match="vocab_size"):
        ByteTokenizer(100)


def test_streaming_detok_byte_exact():
    """Byte streamer: pushing one id at a time yields exactly the
    one-shot decode, and a multi-byte char split across pushes never
    surfaces as partial garbage."""
    from dnn_tpu.io.tokenizer import stream_detokenizer

    tok = ByteTokenizer(300, offset=2)
    text = "héllo wörld 🙂 ∑x"
    ids = tok.encode(text)
    det = stream_detokenizer(tok)
    chunks = [det.push(i) for i in ids]
    assert "".join(chunks) + det.flush() == tok.decode(ids) == text
    # mid-emoji pushes emit nothing (the 4-byte char is held complete)
    e_ids = tok.encode("🙂")
    det2 = stream_detokenizer(tok)
    assert [det2.push(i) for i in e_ids[:-1]] == ["", "", ""]
    assert det2.push(e_ids[-1]) == "🙂"
    # out-of-range ids degrade to U+FFFD exactly as decode() does
    det3 = stream_detokenizer(tok)
    bad = [0, 1, 299]
    assert "".join(det3.push(i) for i in bad) + det3.flush() \
        == tok.decode(bad)


def test_streaming_detok_generic_multibyte_pieces():
    """The decode-diff streamer holds back BPE pieces that END mid
    -character: a vocab whose tokens split an emoji's bytes across two
    pieces still streams byte-identically to the one-shot decode."""
    from dnn_tpu.io.tokenizer import StreamingDetokenizer

    pieces = [b"a", b"\xf0\x9f", b"\x98\x80", b" ok", b"\xc3"]

    class _Toy:
        @staticmethod
        def decode(ids):
            return b"".join(pieces[i] for i in ids).decode(
                "utf-8", errors="replace")

    det = StreamingDetokenizer(_Toy())
    assert det.push(0) == "a"
    assert det.push(1) == ""        # partial emoji held
    assert det.push(2) == "😀"      # completed
    assert det.push(3) == " ok"
    assert det.push(4) == ""        # dangling lead byte
    assert det.flush() == "�"       # never completed -> replacement
    ids = [0, 1, 2, 3, 4]
    det2 = StreamingDetokenizer(_Toy())
    assert "".join(det2.push(i) for i in ids) + det2.flush() \
        == _Toy.decode(ids)


def test_streaming_detok_non_monotone_never_duplicates():
    """A decode that REWRITES earlier text (HF cleanup collapsing
    'word ' + '.' -> 'word.') cannot stream exactly; the streamer must
    detect it, never duplicate already-emitted characters, and converge
    via flush()."""
    from dnn_tpu.io.tokenizer import StreamingDetokenizer

    class _Cleanup:  # piece 0 = "word ", piece 1 = "." with cleanup
        @staticmethod
        def decode(ids):
            raw = "".join(["word ", "."][i] for i in ids)
            return raw.replace(" .", ".")

    det = StreamingDetokenizer(_Cleanup())
    out = det.push(0)          # "word "
    out += det.push(1)         # decode shrank to "word." — held
    out += det.flush()
    assert "word" in out and out.count("word") == 1
    assert out.endswith(".")


def test_text_endpoint_matches_id_endpoint():
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), CFG), CFG)
    tok = ByteTokenizer(CFG.vocab_size)
    port = 59321
    t, stop = start_lm_server_in_background(
        CFG, prepared, port=port, slots=2, max_len=64, prompt_pad=16,
        default_max_new=6, tokenizer=tok)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        # stats path still reachable
        assert "pool" in c.send_message("anyone", "!stats")

        prompt = "hello"
        text = c.generate_text(prompt, max_new_tokens=6)
        # oracle: tokenize -> id-endpoint semantics -> detokenize
        ids = np.asarray(tok.encode(prompt), np.int32)
        want_ids = np.asarray(make_generate(CFG, max_new_tokens=6)(
            prepared, ids[None, :], jax.random.PRNGKey(0)))[0]
        assert text == tok.decode([int(i) for i in want_ids])
        c.close()
    finally:
        stop()


def test_text_endpoint_without_tokenizer_gives_stats():
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(1), CFG), CFG)
    port = 59322
    t, stop = start_lm_server_in_background(
        CFG, prepared, port=port, slots=1, max_len=32, prompt_pad=8)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        assert "pool" in c.send_message("gen:4", "some prompt")
        c.close()
    finally:
        stop()


def test_out_of_range_ids_become_replacement_char():
    tok = ByteTokenizer(300, offset=2)
    s = tok.decode([0, 1, 299, 2 + ord("a")])
    assert s == "���a"
