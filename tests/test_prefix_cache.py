"""Prefix-cache tests: requests sharing a prompt prefix must reuse cached
K/V chunks instead of re-prefilling them — with token output IDENTICAL to
the uncached batcher (the reuse is a pure work-savings, never a numerics
change), and the three-program compile contract intact."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]  # block_size=64
P_PAD = 8


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def _prompt(prefix_tokens, suffix_tokens):
    return np.concatenate([prefix_tokens, suffix_tokens]).astype(np.int32)


PREFIX = np.arange(1, 17, dtype=np.int32)          # 16 tokens = 2 full chunks
SUF_A = np.array([21, 22, 23], np.int32)
SUF_B = np.array([31, 32, 33, 34], np.int32)


def test_shared_prefix_parity_and_chunk_savings():
    prepared = _prepared()

    def run(cache_entries):
        srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                                prompt_pad=P_PAD,
                                prefix_cache=cache_entries)
        r1 = srv.submit(_prompt(PREFIX, SUF_A), max_new_tokens=6)
        chunks_first = srv.prefill_chunks_run
        r2 = srv.submit(_prompt(PREFIX, SUF_B), max_new_tokens=6)
        chunks_second = srv.prefill_chunks_run - chunks_first
        out = srv.drain()
        return out[r1], out[r2], chunks_first, chunks_second, srv

    a0, b0, c1_off, c2_off, _ = run(0)
    a1, b1, c1_on, c2_on, srv = run(8)

    # parity: cached == uncached, token for token
    np.testing.assert_array_equal(a1, a0)
    np.testing.assert_array_equal(b1, b0)

    # measured prefill-work drop: request 2 shares 2 full chunks with
    # request 1 and must re-run only its tail chunk
    assert c1_on == c1_off == 3   # 19 tokens / pad 8 -> 3 chunks
    assert c2_off == 3            # uncached: full re-prefill
    assert c2_on == 1, f"expected 1 chunk after prefix hit, ran {c2_on}"
    assert srv.prefix_hits == 1


def test_identical_full_chunk_prompt_runs_zero_chunks():
    """A prompt that is exactly N full chunks, submitted twice: the second
    submission reuses everything including the first-token logits."""
    prepared = _prepared(seed=1)
    prompt = np.arange(1, 17, dtype=np.int32)  # exactly 2 chunks
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                            prompt_pad=P_PAD, prefix_cache=8)
    r1 = srv.submit(prompt, max_new_tokens=5)
    n1 = srv.prefill_chunks_run
    r2 = srv.submit(prompt, max_new_tokens=5)
    n2 = srv.prefill_chunks_run - n1
    out = srv.drain()
    assert n1 == 2 and n2 == 0
    np.testing.assert_array_equal(out[r1], out[r2])  # greedy determinism

    # uncached oracle for absolute correctness
    ref = ContinuousBatcher(CFG, prepared, slots=1, max_len=48,
                            prompt_pad=P_PAD)
    rr = ref.submit(prompt, max_new_tokens=5)
    np.testing.assert_array_equal(out[r1], ref.drain()[rr])


def test_prefix_cache_with_int8_cache():
    """The int8 codec's row pytree (k/v/ks/vs) caches and copies the same
    way; parity against the uncached int8 batcher."""
    prepared = _prepared(seed=2)
    prompt = _prompt(PREFIX, SUF_A)

    def run(**kw):
        srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                                prompt_pad=P_PAD, kv_dtype="int8", **kw)
        a = srv.submit(prompt, max_new_tokens=4)
        b = srv.submit(prompt, max_new_tokens=4)
        out = srv.drain()
        return out[a], out[b]

    (a0, b0), (a1, b1) = run(), run(prefix_cache=4)
    np.testing.assert_array_equal(a1, a0)
    np.testing.assert_array_equal(b1, b0)


def test_lru_eviction():
    prepared = _prepared(seed=3)
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=48,
                            prompt_pad=P_PAD, prefix_cache=1)
    p1 = np.arange(1, 9, dtype=np.int32)    # 1 full chunk
    p2 = np.arange(50, 58, dtype=np.int32)  # different chunk
    srv.submit(p1, max_new_tokens=2)
    srv.drain()
    srv.submit(p2, max_new_tokens=2)        # evicts p1 (capacity 1)
    srv.drain()
    n = srv.prefill_chunks_run
    srv.submit(p1, max_new_tokens=2)        # p1 must re-run its chunk
    srv.drain()
    assert srv.prefill_chunks_run - n == 1
    assert srv.prefix_hits == 0


def test_compile_count_unchanged():
    """The prefix cache must not add compiled programs: chunk, finish and
    decode each stay at ONE jit cache entry through mixed cached/uncached
    traffic (incl. the whole-prompt-cached logits rebuild)."""
    prepared = _prepared(seed=4)
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                            prompt_pad=P_PAD, prefix_cache=8)
    full = np.arange(1, 17, dtype=np.int32)       # exact chunks
    tailed = _prompt(PREFIX, SUF_B)               # padded tail
    for p in (full, full, tailed, tailed):
        srv.submit(p, max_new_tokens=3)
        srv.drain()
    assert srv._prefill_chunk._cache_size() == 1
    assert srv._prefill_finish._cache_size() == 1
    assert srv._decode._cache_size() == 1
