"""Determinism + purity audits over the parallel runtimes (SURVEY §5
'Race detection: ABSENT' -> the rebuild's collective-order and
donation/aliasing checks). Runs on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import (
    DATA_AXIS, MODEL_AXIS, SEQ_AXIS, STAGE_AXIS, make_mesh,
)
from dnn_tpu.parallel.pipeline import spmd_pipeline, spmd_pipeline_stacked
from dnn_tpu.registry import get_model
from dnn_tpu.utils.audit import (
    assert_deterministic, assert_deterministic_and_pure, assert_pure,
)

CFG = gpt.PRESETS["gpt2-test"]


def test_audit_catches_mutation():
    """The purity check itself must work: a mutating fn is flagged."""
    buf = np.zeros(4)

    def mutator(x):
        x[0] = 1.0  # numpy input mutated in place
        return x.sum()

    with pytest.raises(AssertionError, match="mutated"):
        assert_pure(mutator, buf)


def test_audit_catches_nondeterminism():
    state = {"n": 0}

    def impure(x):
        state["n"] += 1
        return x + state["n"]

    with pytest.raises(AssertionError, match="differs"):
        assert_deterministic(impure, jnp.zeros(3))


def test_spmd_pipeline_deterministic_and_pure():
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    stages = spec.partition(4)
    mesh = make_mesh({STAGE_AXIS: 4}, jax.devices()[:4])
    x = jnp.asarray(spec.example_input(batch_size=8))
    sfns = [st.apply for st in stages]
    sparams = [st.slice_params(params) for st in stages]

    def run(xx):
        return spmd_pipeline(sfns, sparams, xx, mesh=mesh, num_microbatches=2)

    assert_deterministic_and_pure(run, x)


def test_stacked_pipeline_deterministic_and_pure():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    mesh = make_mesh({STAGE_AXIS: 4}, jax.devices()[:4])
    stacked = gpt.stack_blocks(params, range(CFG.n_layer))
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, CFG.vocab_size)

    def run(ids_in):
        x = gpt.embed(aux, ids_in, cfg=CFG)
        h = spmd_pipeline_stacked(
            lambda bp, a: gpt.block_apply(bp, a, cfg=CFG),
            stacked, x, mesh=mesh, num_microbatches=2,
        )
        return gpt.head(aux, h.astype(jnp.float32), cfg=CFG)

    assert_deterministic_and_pure(run, ids)


def test_sharded_train_step_deterministic():
    """dp x tp gradients all-reduce over 'data' — reduction order must be
    fixed: repeated steps from identical state match bit-for-bit."""
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    apply_fn = gpt.make_apply(CFG)
    opt = optax.sgd(1e-2)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    params, specs = train.init_sharded(
        lambda rng: gpt.init(rng, CFG), jax.random.PRNGKey(0), mesh
    )
    step = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    opt_state = opt.init(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0, CFG.vocab_size)

    def run(p, s, t):
        p2, s2, l = step(p, s, t)
        return p2, l

    assert_deterministic(run, params, opt_state, tokens)


def test_ring_attention_deterministic():
    from dnn_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    b, h, s, d = 2, 4, 64, 16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (b, h, s, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))

    def run(q_, k_, v_):
        return ring_attention(q_, k_, v_, mesh=mesh, causal=True)

    assert_deterministic_and_pure(run, q, k, v)
