"""Gradient accumulation: microbatched steps == the full-batch step.

With a uniform-mean loss (cross_entropy, no ignore_index) and equal-size
microbatches, mean-of-microbatch-grads IS the full-batch grad, so the
accumulated step must match the plain step to fp tolerance — params,
opt state, and loss alike.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt

CFG = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=4,
                    n_embd=32)


@pytest.fixture(scope="module")
def setup():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    apply_fn = gpt.make_apply(CFG)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    return params, tokens, loss_fn


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_matches_full_batch(setup, accum):
    params, tokens, loss_fn = setup
    opt = optax.adamw(1e-3)
    full = train.make_train_step(loss_fn, opt)
    acc = train.make_train_step(loss_fn, opt, accum_steps=accum)

    p1, s1, l1 = full(params, opt.init(params), tokens)
    p2, s2, l2 = acc(params, opt.init(params), tokens)
    np.testing.assert_allclose(float(l2), float(l1), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-6)


def test_indivisible_batch_raises(setup):
    params, tokens, loss_fn = setup
    opt = optax.sgd(1e-2)
    step = train.make_train_step(loss_fn, opt, accum_steps=3)
    with pytest.raises(ValueError, match="not divisible"):
        step(params, opt.init(params), tokens)  # 8 % 3 != 0


def test_rejects_bad_accum(setup):
    _, _, loss_fn = setup
    with pytest.raises(ValueError, match="accum_steps"):
        train.make_train_step(loss_fn, optax.sgd(1e-2), accum_steps=0)
