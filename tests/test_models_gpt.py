"""GPT-2 family: shapes, partition parity, scan-vs-loop equivalence, and
cross-framework numerical parity against HuggingFace GPT-2 (random-init,
no network needed)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.models import gpt


@pytest.fixture(scope="module")
def gpt_setup():
    spec = get_model("gpt2-test")
    params = spec.init(jax.random.PRNGKey(0))
    x = spec.example_input(batch_size=2, seq_len=16, rng=jax.random.PRNGKey(1))
    return spec, params, x


def test_forward_shape(gpt_setup):
    spec, params, x = gpt_setup
    logits = spec.apply(params, x)
    assert logits.shape == (2, 16, spec.config.vocab_size)


def test_bf16_logits_close_to_f32(gpt_setup):
    """logits_dtype=bf16 (the serving/bench configuration) is the f32
    forward rounded on the way out: accumulation stays f32, so values
    differ only by final-rounding (~0.4% relative for bf16)."""
    spec, params, x = gpt_setup
    cfg = spec.config
    prepared = gpt.prepare_stacked(params, cfg)
    y32 = np.asarray(gpt.make_apply_stacked(cfg)(prepared, x), np.float32)
    y16 = gpt.make_apply_stacked(cfg, logits_dtype=jnp.bfloat16)(prepared, x)
    assert y16.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y16, np.float32), y32,
                               rtol=8e-3, atol=8e-3)


@pytest.mark.parametrize("num_parts", [1, 2, 3, 4])
def test_partition_parity(gpt_setup, num_parts):
    """Composed stage pipeline == full model (the reference's implied
    ModelPart0 -> Intermediate -> Final composition invariant,
    gpt_model_parts.py:6-50)."""
    spec, params, x = gpt_setup
    stages = spec.partition(num_parts)
    h = x
    for st in stages:
        h = st.apply(st.slice_params(params), h)
    np.testing.assert_allclose(
        np.asarray(h), np.asarray(spec.apply(params, x)), atol=1e-5, rtol=1e-5
    )


def test_stage_param_ownership(gpt_setup):
    spec, params, _ = gpt_setup
    stages = spec.partition(3)
    assert "wte" in stages[0].param_keys and "wpe" in stages[0].param_keys
    assert "ln_f" in stages[-1].param_keys and "lm_head" in stages[-1].param_keys
    all_keys = [k for s in stages for k in s.param_keys]
    assert sorted(all_keys) == sorted(params.keys())


def test_layer_ranges():
    assert gpt.layer_ranges(12, 2) == [(0, 6), (6, 12)]
    assert gpt.layer_ranges(12, 8) == [
        (0, 2), (2, 4), (4, 6), (6, 8), (8, 9), (9, 10), (10, 11), (11, 12)
    ]
    with pytest.raises(ValueError):
        gpt.layer_ranges(4, 5)


def test_block_size_guard(gpt_setup):
    """T > block_size must fail, like the reference's assert
    (gpt_model_parts.py:15)."""
    spec, params, _ = gpt_setup
    too_long = jnp.zeros((1, spec.config.block_size + 1), jnp.int32)
    with pytest.raises(ValueError, match="block_size"):
        spec.apply(params, too_long)


def test_scan_matches_python_loop(gpt_setup):
    spec, params, x = gpt_setup
    cfg = spec.config
    h = gpt.embed(params, x, cfg=cfg)
    looped = h
    for i in range(cfg.n_layer):
        looped = gpt.block_apply(params[f"h_{i}"], looped, cfg=cfg)
    scanned = gpt.blocks_scan(gpt.stack_blocks(params, range(cfg.n_layer)), h, cfg=cfg)
    np.testing.assert_allclose(np.asarray(scanned), np.asarray(looped), atol=1e-5)


def test_hf_gpt2_numerical_parity():
    """Random-init HF GPT-2 (tiny config, built locally — no downloads) vs
    our functional GPT with converted weights."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()

    from dnn_tpu.io.checkpoint import gpt_params_from_state_dict

    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    params = gpt_params_from_state_dict(sd)

    cfg = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=2, n_embd=32)
    apply = gpt.make_apply(cfg)

    ids = np.array([[3, 17, 9, 100, 42, 7]], dtype=np.int64)
    with torch.no_grad():
        ref_logits = hf(torch.from_numpy(ids)).logits.numpy()
    ours = np.asarray(apply(params, jnp.asarray(ids, jnp.int32)))
    # fp32 accumulation-order noise (oneDNN vs XLA CPU); per-block divergence
    # is ~1e-6 once both sides run true f32 matmuls.
    np.testing.assert_allclose(ours, ref_logits, atol=1e-4, rtol=1e-4)
