"""Sequence-parallel GPT forward (ring attention inside the model).

The reference's long-context story is a hard assert (T <= block_size,
gpt_model_parts.py:15); this path shards T over the "seq" mesh axis. The
invariant: sequence-parallel forward == single-device forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh

CFG = gpt.PRESETS["gpt2-test"]


@pytest.fixture(scope="module")
def prepared():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    return params, gpt.prepare_stacked(params, CFG)


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_seq_parallel_matches_full(prepared, n_shards):
    params, prep = prepared
    mesh = make_mesh({SEQ_AXIS: n_shards}, jax.devices()[:n_shards])
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (2, 32), 0, CFG.vocab_size, dtype=jnp.int32
    )
    want = np.asarray(gpt.make_apply(CFG)(params, ids))
    got = np.asarray(gpt.make_apply_seq_parallel(CFG, mesh)(prep, ids))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_seq_parallel_positions_are_global(prepared):
    """Shard i must embed positions [i*T/n, (i+1)*T/n) — a local arange
    would silently reuse positions 0..T/n-1 on every shard. Catch it by
    comparing against the full model on an input where position matters
    (all-identical tokens: only wpe distinguishes positions)."""
    params, prep = prepared
    mesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    ids = jnp.full((1, 16), 7, jnp.int32)
    want = np.asarray(gpt.make_apply(CFG)(params, ids))
    got = np.asarray(gpt.make_apply_seq_parallel(CFG, mesh)(prep, ids))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)
    # sanity: rows differ across positions (wpe engaged)
    assert np.abs(want[0, 0] - want[0, -1]).max() > 1e-3


def test_seq_parallel_rejects_indivisible(prepared):
    _, prep = prepared
    mesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    ids = jnp.zeros((1, 18), jnp.int32)
    with pytest.raises(ValueError, match="not divisible"):
        gpt.make_apply_seq_parallel(CFG, mesh)(prep, ids)


def test_seq_parallel_respects_block_size(prepared):
    _, prep = prepared
    mesh = make_mesh({SEQ_AXIS: 2}, jax.devices()[:2])
    ids = jnp.zeros((1, CFG.block_size + 2), jnp.int32)
    with pytest.raises(ValueError, match="block_size"):
        gpt.make_apply_seq_parallel(CFG, mesh)(prep, ids)


def test_seq_parallel_bf16(prepared):
    params, prep = prepared
    mesh = make_mesh({SEQ_AXIS: 4}, jax.devices()[:4])
    ids = jax.random.randint(
        jax.random.PRNGKey(2), (2, 32), 0, CFG.vocab_size, dtype=jnp.int32
    )
    want = np.asarray(
        gpt.make_apply(CFG, compute_dtype=jnp.bfloat16)(params, ids)
    )
    got = np.asarray(
        gpt.make_apply_seq_parallel(CFG, mesh, compute_dtype=jnp.bfloat16)(prep, ids)
    )
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, atol=0.15, rtol=0.1)
