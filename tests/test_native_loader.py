"""Native async loader: bit-parity with the Python decoder, epoch
coverage under shuffle, bounded-queue liveness, clean shutdown, and the
Python fallback path."""

import numpy as np
import pytest

from dnn_tpu.data.async_loader import AsyncCifarLoader
from dnn_tpu.data.cifar_binary import CifarBinaryDataset, write_cifar_binary


@pytest.fixture(scope="module")
def cifar_file(tmp_path_factory):
    rng = np.random.default_rng(0)
    n = 64
    imgs = rng.integers(0, 256, (n, 32, 32, 3), dtype=np.uint8)
    labels = rng.integers(0, 10, (n,), dtype=np.uint8)
    path = tmp_path_factory.mktemp("cifar") / "batch.bin"
    write_cifar_binary(str(path), imgs, labels)
    return str(path), n


def test_native_builds(cifar_file):
    from dnn_tpu import native

    # g++ is baked into this image; if this fails the loader silently
    # degraded, and the perf claim is void — surface that loudly.
    assert native.loader_available(), "native loader failed to build"


def test_ordered_batches_bitwise_match_python(cifar_file):
    path, n = cifar_file
    bs = 16
    with AsyncCifarLoader([path], bs, shuffle=False) as loader:
        assert loader.native
        py = CifarBinaryDataset([path]).batches(bs, shuffle=False, epochs=None)
        for _ in range(2 * (n // bs) + 1):  # across an epoch boundary
            ni, nl = next(loader)
            pi, pl = next(py)
            np.testing.assert_array_equal(nl, pl)
            np.testing.assert_array_equal(ni, pi)  # incl. normalize op order


def test_shuffled_epoch_covers_dataset(cifar_file):
    path, n = cifar_file
    bs = 16
    with AsyncCifarLoader([path], bs, shuffle=True, seed=7) as loader:
        assert loader.native
        labels_seen = []
        first_epoch = []
        for _ in range(n // bs):
            imgs, labels = next(loader)
            assert imgs.shape == (bs, 32, 32, 3) and imgs.dtype == np.float32
            assert imgs.min() >= -1.0 and imgs.max() <= 1.0
            first_epoch.append(labels)
        # one epoch = every record exactly once: label MULTISET matches
        ref_labels = CifarBinaryDataset([path]).decode(np.arange(n))[1]
        np.testing.assert_array_equal(
            np.sort(np.concatenate(first_epoch)), np.sort(ref_labels)
        )
        # and the permutation actually shuffles
        ordered = CifarBinaryDataset([path]).decode(np.arange(bs))[1]
        assert not np.array_equal(first_epoch[0], ordered)
        labels_seen.extend(first_epoch)


def test_two_loaders_same_seed_agree(cifar_file):
    path, _ = cifar_file
    with AsyncCifarLoader([path], 8, shuffle=True, seed=3) as a, \
            AsyncCifarLoader([path], 8, shuffle=True, seed=3) as b:
        for _ in range(5):
            ia, la = next(a)
            ib, lb = next(b)
            np.testing.assert_array_equal(la, lb)
            np.testing.assert_array_equal(ia, ib)


def test_close_then_next_raises(cifar_file):
    path, _ = cifar_file
    loader = AsyncCifarLoader([path], 8, shuffle=False)
    was_native = loader.native
    loader.close()
    if was_native:
        with pytest.raises(RuntimeError):
            next(loader)


def test_fallback_when_native_unavailable(cifar_file, monkeypatch):
    from dnn_tpu import native

    path, n = cifar_file
    monkeypatch.setattr(native, "loader_lib", lambda: None)
    with AsyncCifarLoader([path], 8, shuffle=False) as loader:
        assert not loader.native
        imgs, labels = next(loader)
        pi, pl = next(CifarBinaryDataset([path]).batches(8, shuffle=False))
        np.testing.assert_array_equal(imgs, pi)
        np.testing.assert_array_equal(labels, pl)


def test_batch_size_validation(cifar_file):
    path, n = cifar_file
    with pytest.raises(ValueError):
        AsyncCifarLoader([path], n + 1)


def test_queue_depth_validation(cifar_file):
    path, _ = cifar_file
    for bad in (0, -1):
        with pytest.raises(ValueError, match="queue_depth"):
            AsyncCifarLoader([path], 8, queue_depth=bad)
