"""Fleet observability tests (dnn_tpu/obs/fleet.py + obs/goodput.py).

The acceptance contract this module pins (ISSUE 5): a FleetCollector
over two REAL in-process stage HTTP endpoints produces (a) a merged
/fleetz JSON with worst-of health and per-stage tables, (b) a clock-
offset estimate that recovers ±500 ms of injected skew within 10%, and
(c) ONE stitched cross-host Perfetto trace with per-request critical-
path/bubble attribution — plus live MFU/MBU gauges whose values match
hand-computed utils/flops.py estimates within 5%, SLO burn-rate gauges
that fire a flight event on induced TTFT breaches, the content-type /
?format= contracts on /statusz /debugz /fleetz, the DNN_TPU_LOG=json
structured-log mode with trace-id injection, and the
`python -m dnn_tpu.obs fleet --selftest` CLI smoke tier-1 invokes."""

import io
import json
import logging
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.obs import trace as obs_trace
from dnn_tpu.obs.fleet import (
    FleetCollector,
    critical_path,
    estimate_offsets,
    parse_prometheus,
    stitch_spans,
)
from dnn_tpu.obs.goodput import GoodputTracker, SLOConfig, model_cost
from dnn_tpu.utils.metrics import Metrics, labeled, render_prometheus


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _mk_span(col, trace_id, span_id, parent_id, name, ts, dur, **attrs):
    """Plant a finished span with a CONTROLLED wall-clock timestamp in a
    collector (skew injection needs exact ts; the public API stamps
    perf_counter)."""
    s = obs_trace.Span(name, trace_id, span_id, parent_id, attrs)
    s.t0 = ts - obs_trace._EPOCH0
    s.dur = dur
    s._done = True
    col.add(s)
    return s


def _get(url):
    return urllib.request.urlopen(url, timeout=10)


# ----------------------------------------------------------------------
# prometheus text parsing (the poller's half of render_prometheus)
# ----------------------------------------------------------------------

def test_parse_prometheus_roundtrip():
    from dnn_tpu.obs.fleet import _Samples

    m = Metrics()
    m.set("serving.tokens_per_sec", 42.5)
    m.inc(labeled("serving.requests_total", outcome="eos"), 5)
    m.inc(labeled("serving.requests_total", outcome="length"), 3)
    m.observe("serving.ttft_seconds", 0.01)
    m.observe("serving.ttft_seconds", 0.03)
    m.observe_hist(labeled("comm.rpc_latency_seconds", role="server"),
                   0.03, buckets=(0.01, 0.05, 0.1))
    s = _Samples(parse_prometheus(render_prometheus(m)))
    assert s.get("serving_tokens_per_sec") == 42.5
    assert s.get("serving_requests_total", outcome="eos") == 5
    assert s.sum("serving_requests_total") == 8
    assert s.get("serving_ttft_seconds", quantile="0.5") == 0.01
    # histogram_quantile interpolates inside the winning bucket
    q = s.hist_quantile("comm_rpc_latency_seconds", 0.5)
    assert 0.01 < q <= 0.05
    assert s.get("nope_total") is None and s.sum("nope_total") is None


def test_parse_prometheus_tolerates_garbage():
    p = parse_prometheus("# HELP x\nnot a line !!!\nok_total 3\n"
                         'lab{a="b"} bogusvalue\n')
    assert p["samples"] == [("ok_total", {}, 3.0)]


# ----------------------------------------------------------------------
# clock-offset estimation
# ----------------------------------------------------------------------

@pytest.mark.parametrize("skew", [0.5, -0.5])
def test_clock_offset_recovers_injected_skew(skew):
    now = 1000.0
    client = {"trace_id": "t", "span_id": "c1", "parent_id": None,
              "name": "rpc.forward", "ts": now, "dur": 0.1, "tid": 1,
              "attrs": {"cs": now, "cr": now + 0.1}}
    server = {"trace_id": "t", "span_id": "s1", "parent_id": "c1",
              "name": "stage.request", "ts": now + 0.02 + skew,
              "dur": 0.06, "tid": 2, "attrs": {"stage": "B"}}
    offs = estimate_offsets({"A": [client], "B": [server]})
    assert offs["A"] == 0.0
    assert abs(offs["B"] - skew) < 0.1 * abs(skew)  # ±500 ms within 10%


def test_clock_offset_chains_through_pipeline_and_falls_back():
    """A->B->C: C never talks to A directly; its offset must chain
    through B. The B->C client span has no cs/cr attrs (an older build)
    — the estimator falls back to the span's own ts/dur window."""
    now = 2000.0
    a_client = {"trace_id": "t", "span_id": "ab", "parent_id": None,
                "name": "rpc.SendTensor", "ts": now, "dur": 0.1,
                "tid": 1, "attrs": {"cs": now, "cr": now + 0.1}}
    b_server = {"trace_id": "t", "span_id": "b1", "parent_id": "ab",
                "name": "stage.request", "ts": now + 0.025 + 0.2,
                "dur": 0.05, "tid": 1, "attrs": {"stage": "B"}}
    b_client = {"trace_id": "t", "span_id": "bc", "parent_id": "b1",
                "name": "rpc.forward", "ts": now + 0.03 + 0.2,
                "dur": 0.04, "tid": 1, "attrs": {}}  # no cs/cr
    c_server = {"trace_id": "t", "span_id": "c1", "parent_id": "bc",
                "name": "stage.request", "ts": now + 0.04 + 0.2 - 0.3,
                "dur": 0.02, "tid": 1, "attrs": {"stage": "C"}}
    offs = estimate_offsets({"A": [a_client],
                             "B": [b_server, b_client],
                             "C": [c_server]})
    assert abs(offs["B"] - 0.2) < 0.02
    # C = B's offset + (C rel B) = 0.2 + (-0.3) = -0.1
    assert abs(offs["C"] - (-0.1)) < 0.05


# ----------------------------------------------------------------------
# critical path / bubble golden
# ----------------------------------------------------------------------

def _golden_tree():
    # 10 ms request; stage work covers [0,3] [4,7] [7,10] ms -> exactly
    # one 1 ms bubble between stage0 and stage1
    return [
        {"span_id": "r", "parent_id": None, "name": "request",
         "ts": 0.0, "dur": 0.010, "attrs": {}},
        {"span_id": "a", "parent_id": "r", "name": "stage.compute",
         "ts": 0.0, "dur": 0.003, "attrs": {"stage": "s0"}},
        {"span_id": "b", "parent_id": "r", "name": "stage.compute",
         "ts": 0.004, "dur": 0.003, "attrs": {"stage": "s1"}},
        {"span_id": "c", "parent_id": "r", "name": "stage.compute",
         "ts": 0.007, "dur": 0.003, "attrs": {"stage": "s2"}},
    ]


def test_critical_path_golden_three_stages():
    rep = critical_path(_golden_tree())
    assert rep["total_s"] == pytest.approx(0.010)
    assert rep["work_s"] == pytest.approx(0.009)
    assert rep["bubble_s"] == pytest.approx(0.001)
    assert rep["bubble_fraction"] == pytest.approx(0.1)
    assert [p["stage"] for p in rep["path"]] == ["s0", "s1", "s2"]
    assert rep["path"][1]["enter_s"] == pytest.approx(0.004)
    assert rep["per_stage_busy_s"] == {
        "s0": pytest.approx(0.003), "s1": pytest.approx(0.003),
        "s2": pytest.approx(0.003)}


def test_critical_path_overlap_picks_furthest_reaching():
    # two overlapping leaves: the one reaching furthest gates progress
    spans = [
        {"span_id": "r", "parent_id": None, "name": "request",
         "ts": 0.0, "dur": 0.010, "attrs": {}},
        {"span_id": "a", "parent_id": "r", "name": "short",
         "ts": 0.0, "dur": 0.004, "attrs": {"stage": "x"}},
        {"span_id": "b", "parent_id": "r", "name": "long",
         "ts": 0.001, "dur": 0.009, "attrs": {"stage": "y"}},
    ]
    rep = critical_path(spans)
    assert rep["bubble_fraction"] == pytest.approx(0.0)
    assert rep["path"][-1]["name"] == "long"
    assert rep["path"][-1]["exit_s"] == pytest.approx(0.010)


def test_critical_path_queue_wait_is_bubble():
    """queue_wait is a leaf by construction but measures WAITING — its
    cover must read as bubble, or an overloaded server looks
    bubble-free."""
    spans = [
        {"span_id": "r", "parent_id": None, "name": "request",
         "ts": 0.0, "dur": 0.010, "attrs": {}},
        {"span_id": "q", "parent_id": "r", "name": "queue_wait",
         "ts": 0.0, "dur": 0.006, "attrs": {}},
        {"span_id": "w", "parent_id": "r", "name": "decode",
         "ts": 0.006, "dur": 0.004, "attrs": {"stage": "lm"}},
    ]
    rep = critical_path(spans)
    assert rep["bubble_fraction"] == pytest.approx(0.6)
    assert [p["name"] for p in rep["path"]] == ["decode"]


def test_critical_path_empty_and_leafless():
    assert critical_path([])["bubble_fraction"] == 0.0
    solo = critical_path([{"span_id": "r", "parent_id": None,
                           "name": "request", "ts": 0.0, "dur": 0.01,
                           "attrs": {}}])
    assert solo["bubble_fraction"] == pytest.approx(0.0)


def test_stitch_dedups_and_tracks_per_stage():
    now = time.time()
    a = {"trace_id": "t", "span_id": "c1", "parent_id": None,
         "name": "rpc.forward", "ts": now, "dur": 0.1, "tid": 1,
         "attrs": {"cs": now, "cr": now + 0.1}}
    b = {"trace_id": "t", "span_id": "s1", "parent_id": "c1",
         "name": "stage.request", "ts": now + 0.55, "dur": 0.06,
         "tid": 2, "attrs": {"stage": "B"}}
    # duplicated span dicts (overlapping ring polls) must stitch once
    ct = stitch_spans({"A": [a, dict(a)], "B": [b, dict(b)]})
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 2
    assert {e["args"]["stage"] for e in xs} == {"A", "B"}
    assert len({e["pid"] for e in xs}) == 2  # one process track each
    names = [e for e in ct["traceEvents"]
             if e.get("name") == "process_name"]
    assert len(names) == 2
    # offset applied: the corrected server span nests inside the client
    by = {e["name"]: e for e in xs}
    c, s = by["rpc.forward"], by["stage.request"]
    assert c["ts"] - 1 <= s["ts"] and \
        s["ts"] + s["dur"] <= c["ts"] + c["dur"] + 1


# ----------------------------------------------------------------------
# merged /fleetz over two real in-process endpoints
# ----------------------------------------------------------------------

@pytest.fixture()
def two_stage_fleet():
    from dnn_tpu.obs.http import MetricsHTTPServer

    regA, regB = Metrics(), Metrics()
    regA.set("serving.tokens_per_sec", 10.0)
    regA.set("dnn_tpu_mfu", 0.25)
    regA.observe("serving.ttft_seconds", 0.02)
    regB.set("serving.tokens_per_sec", 5.0)
    colA, colB = obs.TraceCollector(), obs.TraceCollector()
    now = time.time()
    _mk_span(colA, "t1", "c1", None, "rpc.forward", now, 0.10,
             cs=now, cr=now + 0.10)
    _mk_span(colB, "t1", "s1", "c1", "stage.request",
             now + 0.02 + 0.5, 0.06, stage="node2")
    sA = MetricsHTTPServer(port=0, registry=regA, collector=colA,
                           healthy=lambda: True)
    sB = MetricsHTTPServer(
        port=0, registry=regB, collector=colB,
        status=lambda: {"state": "degraded",
                        "components": {"worker": {"state": "degraded",
                                                  "detail": "t"}}})
    fc = FleetCollector({"node1": f"http://127.0.0.1:{sA.port}",
                         "node2": f"http://127.0.0.1:{sB.port}"})
    fc.poll_once()
    yield fc
    fc.close()
    sA.close()
    sB.close()


def test_fleetz_rollup_worst_of_and_tables(two_stage_fleet):
    z = two_stage_fleet.fleetz()
    assert z["state"] == "degraded"  # worst-of across stages
    assert z["stages"]["node1"]["state"] == "ok"
    assert z["stages"]["node2"]["state"] == "degraded"
    assert z["stages"]["node1"]["tokens_per_sec"] == 10.0
    assert z["stages"]["node1"]["mfu"] == 0.25
    assert z["stages"]["node1"]["ttft_p50_ms"] == pytest.approx(20.0)
    assert z["fleet"]["tokens_per_sec"] == 15.0  # fleet total
    assert z["fleet"]["stages_ok"] == 1
    assert abs(z["clock_offsets_s"]["node2"] - 0.5) < 0.05
    assert "t1" in z["trace_ids"]
    # watchdog-shaped status: fleet /healthz degrades with the worst stage
    st = two_stage_fleet.status()
    assert st["state"] == "degraded"
    assert set(st["components"]) == {"node1", "node2"}


def test_fleetz_unreachable_stage_is_wedged_health():
    fc = FleetCollector({"gone": "http://127.0.0.1:9"},  # discard port
                        timeout_s=0.5)
    fc.poll_once()
    z = fc.fleetz()
    assert z["stages"]["gone"]["state"] == "unreachable"
    assert fc.status()["state"] == "wedged"  # the pipeline IS down
    fc.close()


def test_fleetz_endpoint_formats(two_stage_fleet):
    from dnn_tpu.obs.http import MetricsHTTPServer

    srv = MetricsHTTPServer(port=0, fleet=two_stage_fleet)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        r = _get(base + "/fleetz")
        assert r.headers["Content-Type"] == "application/json"
        z = json.load(r)
        assert z["state"] == "degraded"
        prom = _get(base + "/fleetz?format=prom")
        assert prom.headers["Content-Type"].startswith("text/plain")
        body = prom.read().decode()
        assert "dnn_tpu_fleet_state 1" in body
        assert 'dnn_tpu_fleet_stage_up{stage="node1"} 1' in body
        ct = json.load(_get(base + "/fleetz?format=trace&id=t1"))
        assert len([e for e in ct["traceEvents"]
                    if e.get("ph") == "X"]) == 2
        rep = _get(base + "/fleetz?format=report").read().decode()
        assert "fleet state: degraded" in rep
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base + "/fleetz?format=nope")
        assert ei.value.code == 400
        # /healthz rides the fleet's worst-of (degraded -> still 200)
        assert _get(base + "/healthz").read().decode().strip() \
            == "degraded"
    finally:
        srv.close()


def test_fleetz_404_without_collector():
    from dnn_tpu.obs.http import MetricsHTTPServer

    srv = MetricsHTTPServer(port=0, registry=Metrics(),
                            collector=obs.TraceCollector())
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{srv.port}/fleetz")
        assert ei.value.code == 404
    finally:
        srv.close()


def test_request_report_cross_host(two_stage_fleet):
    rep = two_stage_fleet.request_report("t1")
    assert rep["trace_id"] == "t1" and rep["spans"] == 2
    # the server span is the only leaf; with offsets corrected it
    # covers 60 of the client's 100 ms -> bubble 40%
    assert rep["bubble_fraction"] == pytest.approx(0.4, abs=0.05)
    assert rep["per_stage_busy_s"].keys() == {"node2"}


# ----------------------------------------------------------------------
# /statusz /debugz content-type + ?format= regression (satellite)
# ----------------------------------------------------------------------

def test_statusz_debugz_content_types_and_formats():
    from dnn_tpu.obs.flight import FlightRecorder
    from dnn_tpu.obs.http import MetricsHTTPServer

    fr = FlightRecorder(capacity=16)
    fr.record("probe", i=1)
    reg = Metrics()
    reg.inc("x_total", 1)
    srv = MetricsHTTPServer(port=0, registry=reg,
                            collector=obs.TraceCollector(),
                            healthy=lambda: True, flight=fr)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        st = _get(base + "/statusz")
        assert st.headers["Content-Type"] == "application/json"
        assert json.load(st)["state"] == "ok"
        prom = _get(base + "/statusz?format=prom")
        assert prom.headers["Content-Type"].startswith("text/plain")
        assert "dnn_tpu_status_state 0" in prom.read().decode()
        db = _get(base + "/debugz")
        assert db.headers["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in
                 db.read().decode().splitlines()]
        assert lines and lines[-1]["kind"] == "probe"
        dbj = _get(base + "/debugz?format=json")
        assert dbj.headers["Content-Type"] == "application/json"
        evs = json.load(dbj)  # a PROPER JSON array — no sniffing
        assert isinstance(evs, list) and evs[-1]["kind"] == "probe"
        for path in ("/debugz?format=nope", "/statusz?format=nope"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base + path)
            assert ei.value.code == 400
        # ?format=prom passthrough on /metrics: query params are
        # ignored, the scrape is identical
        assert _get(base + "/metrics?format=prom").read() == \
            _get(base + "/metrics").read()
    finally:
        srv.close()


# ----------------------------------------------------------------------
# goodput: MFU/MBU arithmetic + SLO burn rate (obs/goodput.py)
# ----------------------------------------------------------------------

def test_mfu_mbu_match_hand_computed_flops():
    from dnn_tpu.models import gpt
    from dnn_tpu.utils import flops as F

    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=4,
                        n_head=4, n_embd=256)
    PEAK_F, PEAK_B = 1e12, 1e10
    clock = [0.0]
    tr = GoodputTracker(model_cost(cfg), peak_flops=PEAK_F,
                        peak_bytes=PEAK_B, window_s=60.0,
                        now=lambda: clock[0])
    clock[0] = 1.0
    tr.on_prefill(16)
    tr.on_decode_step(4, live_positions=128)  # 4 tokens, mean ctx 32
    clock[0] = 2.0  # window denominator: min(60, lifetime=2 s)

    cost = model_cost(cfg)
    hand_flops = (F.gpt_forward_flops(cfg, 1, 16)
                  + 4 * F.gpt_decode_token_flops(cfg, 32))
    hand_bytes = (2 * cost.weight_bytes  # prefill + one decode step
                  + (16 + 128) * F.kv_bytes_per_pos(cfg))
    assert tr.mfu() == pytest.approx(hand_flops / 2.0 / PEAK_F,
                                     rel=0.05)
    assert tr.mbu() == pytest.approx(hand_bytes / 2.0 / PEAK_B,
                                     rel=0.05)
    assert tr.tokens_per_sec() == pytest.approx(5 / 2.0, rel=0.05)
    assert tr.mfu() > 0 and tr.mbu() > 0  # nonzero on a CPU host


def test_goodput_gauges_on_real_batcher(tmp_path):
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher
    from dnn_tpu.utils.metrics import default_metrics

    cfg = gpt.GPTConfig(block_size=64, vocab_size=64, n_layer=2,
                        n_head=2, n_embd=32)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=16)
    tr = GoodputTracker(model_cost(cfg, prepared), peak_flops=1e12,
                        peak_bytes=1e10).install()
    srv.goodput = tr
    srv.submit(np.arange(1, 9), max_new_tokens=6)
    srv.submit(np.arange(1, 5), max_new_tokens=6)
    srv.drain()
    assert tr.mfu() > 0 and tr.mbu() > 0
    assert tr.tokens_per_sec() > 0
    # the scrape path reads the SAME values through the registry
    text = render_prometheus(default_metrics)
    mfu_line = [ln for ln in text.splitlines()
                if ln.startswith("dnn_tpu_mfu ")]
    assert mfu_line and float(mfu_line[0].split()[1]) > 0
    # sanity: achieved flops reconcile with the token count (2 prompts
    # prefilled + 12 tokens total; every event charged > linear cost)
    min_per_tok = tr.cost.flops_per_token(0)
    assert tr.achieved_flops_per_sec() * 60 >= 0  # window is live
    assert tr._flops._items >= 10 * min_per_tok


def test_slo_burn_rate_and_breach_flight_event():
    from dnn_tpu.obs import flight as obs_flight

    clock = [0.0]
    tr = GoodputTracker(
        model_cost(__import__("dnn_tpu.models.gpt",
                              fromlist=["gpt"]).GPTConfig(
            block_size=32, vocab_size=64, n_layer=1, n_head=1,
            n_embd=16)),
        peak_flops=1.0, peak_bytes=1.0,
        slo=SLOConfig(ttft_s=0.1, availability=0.999, target=0.9,
                      window_s=60.0),
        now=lambda: clock[0])
    ring = obs_flight.recorder()
    before = len(ring.events(kind="slo_breach"))
    # 10% budget (target=0.9): 4 good + 1 bad = 20% bad -> burn 2.0
    for s in (0.01, 0.01, 0.01, 0.01, 0.5):
        tr.on_ttft(s)
    rates = tr.burn_rates()
    assert rates["ttft"] == pytest.approx(2.0)
    events = ring.events(kind="slo_breach")
    assert len(events) == before + 1  # latched: ONE event per episode
    tr.on_ttft(0.5)
    assert len(ring.events(kind="slo_breach")) == before + 1
    # recovery clears the latch; the next episode fires again
    for _ in range(200):
        tr.on_ttft(0.01)
    assert tr.burn_rates()["ttft"] <= 1.0
    for _ in range(60):
        tr.on_ttft(0.5)
    assert len(ring.events(kind="slo_breach")) == before + 2
    # availability objective: failures burn 1000x faster than the
    # three-nines budget admits
    tr.on_outcome(True)
    tr.on_outcome(False)
    assert tr.burn_rates()["availability"] > 100


def test_budget_window_buckets_evict_and_stay_exact():
    """Per-second bucket storage: burn arithmetic stays exact inside the
    window, expired seconds fall out with their counts, and memory is
    bounded by seconds, not events."""
    from dnn_tpu.obs.goodput import _BudgetWindow

    clock = [0.0]
    w = _BudgetWindow(0.1, window_s=10.0, now=lambda: clock[0])
    for _ in range(1000):  # 1000 events, ONE bucket
        w.add(False)
    w.add(True)
    assert len(w._buckets) == 1
    assert w.burn_rate() == pytest.approx((1 / 1001) / 0.1)
    clock[0] = 5.0
    w.add(True)  # second bucket
    assert w.burn_rate() == pytest.approx((2 / 1002) / 0.1)
    clock[0] = 12.0  # the t=0 bucket (1001 events) expires
    assert w.burn_rate() == pytest.approx((1 / 1) / 0.1)
    assert len(w._buckets) == 1
    clock[0] = 100.0  # everything expires
    assert w.burn_rate() == 0.0
    assert w._buckets == {} and w._n == 0 and w._bad == 0


def test_peak_env_overrides_degrade_on_garbage(monkeypatch):
    """DNN_TPU_PEAK_FLOPS=0 or garbage must read as 'unknown', not crash
    every MFU consumer (the degrade-don't-crash env-knob rule)."""
    from dnn_tpu.utils import flops as F

    monkeypatch.setenv("DNN_TPU_PEAK_FLOPS", "not a number")
    assert F.device_peak_flops() is None  # cpu host, table miss
    monkeypatch.setenv("DNN_TPU_PEAK_FLOPS", "0")
    assert F.device_peak_flops() is None
    monkeypatch.setenv("DNN_TPU_PEAK_HBM_BW", "-5")
    assert F.device_peak_hbm_bw() is None
    monkeypatch.setenv("DNN_TPU_PEAK_FLOPS", "1.25e11")
    assert F.device_peak_flops() == 1.25e11


def test_fleetz_not_yet_polled_reads_degraded():
    """Before the first poll completes, /fleetz and status() must agree:
    degraded (no evidence), not unreachable/wedged — a scrape racing
    start() must not page."""
    fc = FleetCollector({"slow": "http://127.0.0.1:9"}, timeout_s=0.5)
    try:  # NOTE: no poll_once()
        z = fc.fleetz()
        assert z["stages"]["slow"]["state"] == "degraded"
        assert z["stages"]["slow"]["error"] == "not polled yet"
        assert fc.status()["state"] == "degraded"
        assert "dnn_tpu_fleet_stage_state{stage=\"slow\"} 1" \
            in fc.render_prom()
    finally:
        fc.close()


def test_worker_death_burns_availability_budget():
    """Error-path failures (worker death failing every pending future,
    and fast-fails after it) must count against the availability SLO —
    the objective exists precisely to page on that outage, and the
    retirement path (_obs_retire) never sees these requests."""
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.lm_server import _BatcherWorker
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                        n_head=1, n_embd=16)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=32,
                            prompt_pad=8)
    srv.step = lambda: (_ for _ in ()).throw(
        RuntimeError("injected device fault"))
    worker = _BatcherWorker(srv)
    tr = GoodputTracker(model_cost(cfg), peak_flops=1.0, peak_bytes=1.0,
                        slo=SLOConfig(availability=0.999))
    worker.goodput = tr
    worker.start()
    fut = worker.submit(np.array([1, 2, 3], np.int32), 4, None)
    with pytest.raises(RuntimeError):
        fut.result(timeout=60)
    worker.join(timeout=10)
    assert tr.burn_rates()["availability"] > 100  # outage burns hard
    fut2 = worker.submit(np.array([1, 2], np.int32), 4, None)  # fast-fail
    with pytest.raises(RuntimeError):
        fut2.result(timeout=5)
    w = tr._slo_windows["availability"]
    assert w._n == 2 and w._bad == 2


def test_lm_server_autobuilds_goodput_with_slo():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.lm_server import LMServer

    cfg = gpt.GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                        n_head=1, n_embd=16)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = LMServer(cfg, prepared, slots=1, max_len=32, prompt_pad=8,
                   slo=SLOConfig(ttft_s=30.0))
    try:
        assert srv.goodput is not None
        assert srv.batcher.goodput is srv.goodput
        assert srv.worker.goodput is srv.goodput
        assert "ttft" in srv.goodput._slo_windows
        # exact weight bytes from the real prepared tree
        real = float(sum(x.size * x.dtype.itemsize
                         for x in jax.tree_util.tree_leaves(prepared)))
        assert srv.goodput.cost.weight_bytes == pytest.approx(real)
    finally:
        srv.close()


def test_lm_server_goodput_prices_kv_at_cache_dtype():
    """Regression: without an explicit kv_dtype the batcher stores its
    cache at compute_dtype (serving.py) — the auto-built goodput tracker
    must price KV bytes at the SAME width, not default to f32 (a bf16
    server's MBU would read 2x high)."""
    import jax
    import jax.numpy as jnp

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.lm_server import LMServer
    from dnn_tpu.utils import flops as F

    cfg = gpt.GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                        n_head=1, n_embd=16)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = LMServer(cfg, prepared, slots=1, max_len=32, prompt_pad=8,
                   compute_dtype=jnp.bfloat16)
    try:
        assert srv.batcher.cache["k"].dtype == jnp.bfloat16
        assert srv.goodput.cost.kv_bytes_per_pos == pytest.approx(
            F.kv_bytes_per_pos(cfg, kv_bytes=2))
    finally:
        srv.close()


def test_targets_from_config_rejects_duplicate_urls():
    """A same-host pipeline config + one shared metrics port derives the
    SAME URL for every node — one endpoint polled under N names, the
    rest silently never. Must refuse, not double-count."""
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.obs.fleet import targets_from_config

    cfg = TopologyConfig.from_dict({
        "nodes": [
            {"id": "node1", "address": "127.0.0.1:50051",
             "part_index": 0},
            {"id": "node2", "address": "127.0.0.1:50052",
             "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
    })
    with pytest.raises(ValueError, match="duplicate obs URLs"):
        targets_from_config(cfg, 9100)
    cfg2 = TopologyConfig.from_dict({
        "nodes": [
            {"id": "node1", "address": "10.0.0.1:50051",
             "part_index": 0},
            {"id": "node2", "address": "10.0.0.2:50051",
             "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
    })
    assert targets_from_config(cfg2, 9100) == {
        "node1": "http://10.0.0.1:9100",
        "node2": "http://10.0.0.2:9100"}


# ----------------------------------------------------------------------
# structured JSON logs with trace-id injection (satellite)
# ----------------------------------------------------------------------

def test_json_log_mode_injects_trace_id():
    from dnn_tpu.utils.logging import setup_logging

    buf = io.StringIO()
    setup_logging("INFO", node_id="node1", stream=buf, fmt="json")
    log = logging.getLogger("dnn_tpu.test_fleet")
    try:
        with obs.span("request", kind="logtest") as sp:
            log.info("inside %d", 7)
        log.info("outside")
    finally:
        setup_logging("INFO", stream=io.StringIO())  # detach buf
    lines = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    assert lines[0]["msg"] == "inside 7"
    assert lines[0]["node_id"] == "node1"
    assert lines[0]["trace_id"] == sp.trace_id  # correlates with traces
    assert lines[0]["level"] == "INFO"
    assert "trace_id" not in lines[1]


def test_text_log_mode_unchanged_by_default(monkeypatch):
    from dnn_tpu.utils.logging import setup_logging

    monkeypatch.delenv("DNN_TPU_LOG", raising=False)
    buf = io.StringIO()
    setup_logging("INFO", node_id="n2", stream=buf)
    logging.getLogger("dnn_tpu.test_fleet").info("plain line")
    setup_logging("INFO", stream=io.StringIO())
    assert "INFO dnn_tpu.test_fleet: [n2] plain line" in buf.getvalue()


# ----------------------------------------------------------------------
# e2e: a REAL 2-stage pipeline request, stitched across endpoints
# ----------------------------------------------------------------------

def test_e2e_two_stage_request_stitched_with_bubble():
    """The acceptance path: run one real request through two in-process
    gRPC stage servers, partition the spans by owning stage onto two
    real HTTP endpoints (as two hosts' collectors would hold them),
    fleet-poll both, and verify ONE stitched Perfetto trace with
    critical-path/bubble attribution."""
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.comm.service import start_stage_server_in_background
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.obs.http import MetricsHTTPServer
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict({
        "nodes": [
            {"id": "node1", "address": "127.0.0.1:59371",
             "part_index": 0},
            {"id": "node2", "address": "127.0.0.1:59372",
             "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
    })
    engine = PipelineEngine(cfg)
    t1, stop1 = start_stage_server_in_background(engine, "node1")
    t2, stop2 = start_stage_server_in_background(engine, "node2")
    try:
        x = np.asarray(engine.spec.example_input(batch_size=1))
        c = NodeClient(cfg.node_by_id("node1").address)
        with obs.span("client.request") as root:
            status, result = c.send_tensor(x, request_id="fleet_e2e_1")
        c.close()
    finally:
        stop1()
        stop2()
    assert result is not None
    spans = obs.collector().spans(root.trace_id)
    assert len(spans) == 7  # client + rpc + 2x(request, compute) + fwd
    # the client rpc span carries the clock-offset sampling fields
    rpc = [s for s in spans if s.name == "rpc.SendTensor"][0]
    assert rpc.attrs["cr"] >= rpc.attrs["cs"] > 0
    fwd = [s for s in spans if s.name == "rpc.forward"][0]
    assert fwd.attrs["cr"] >= fwd.attrs["cs"] > 0

    # partition by owning process, exactly as each host's collector
    # would hold them (all three run in this test process, so the
    # shared collector held the union)
    def owner(s):
        st = s.attrs.get("stage")
        if st:
            return st
        if "part" in s.attrs:  # stage.compute carries part=, not stage=
            return f"node{s.attrs['part'] + 1}"
        if s.name == "rpc.forward":
            return "node1"  # node1's relay client span
        return "client"

    cols = {k: obs.TraceCollector() for k in ("client", "node1",
                                              "node2")}
    for s in spans:
        cols[owner(s)].add(s)
    servers = {k: MetricsHTTPServer(port=0, registry=Metrics(),
                                    collector=col)
               for k, col in cols.items()}
    try:
        fc = FleetCollector({k: f"http://127.0.0.1:{srv.port}"
                             for k, srv in servers.items()})
        fc.poll_once()
        ct = fc.stitch(root.trace_id)
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 7  # ONE trace across three "hosts"
        assert {e["args"]["stage"] for e in xs} == {"client", "node1",
                                                    "node2"}
        rep = fc.request_report(root.trace_id)
        assert rep["spans"] == 7
        assert 0.0 <= rep["bubble_fraction"] < 1.0
        busy = rep["per_stage_busy_s"]
        assert "node1" in busy and "node2" in busy
        assert rep["path"], rep  # a non-empty critical path
        # same-process clocks: estimated offsets must be ~zero (no
        # false skew invented when there is none)
        for off in fc.offsets().values():
            assert abs(off) < 0.05
        fc.close()
    finally:
        for srv in servers.values():
            srv.close()


# ----------------------------------------------------------------------
# CLI smoke (tier-1 wired via conftest _MODULE_COST_S)
# ----------------------------------------------------------------------

def test_fleet_cli_selftest_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "fleet", "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "fleet selftest ok" in out.stdout


def test_fleet_cli_one_shot_report(tmp_path):
    from dnn_tpu.obs.http import MetricsHTTPServer

    reg = Metrics()
    reg.set("serving.tokens_per_sec", 3.0)
    col = obs.TraceCollector()
    now = time.time()
    _mk_span(col, "tr9", "r1", None, "request", now, 0.05)
    _mk_span(col, "tr9", "w1", "r1", "stage.compute", now + 0.01, 0.03,
             stage="s0")
    srv = MetricsHTTPServer(port=0, registry=reg, collector=col,
                            healthy=lambda: True)
    out_path = tmp_path / "stitched.json"
    try:
        out = subprocess.run(
            [sys.executable, "-m", "dnn_tpu.obs", "fleet",
             "--targets", f"http://127.0.0.1:{srv.port}",
             "--out", str(out_path)],
            capture_output=True, text=True, timeout=120)
    finally:
        srv.close()
    assert out.returncode == 0, out.stderr
    assert "fleet state: ok" in out.stdout
    assert "bubble" in out.stdout
    ct = json.loads(out_path.read_text())
    assert [e for e in ct["traceEvents"] if e.get("ph") == "X"]
