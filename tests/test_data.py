"""Data-package tests: package importability, loader sampling bounds, and
host→device prefetch. The reference has no input pipeline at all (SURVEY
§5) — these cover the rebuild's training-side loaders end to end."""

import numpy as np
import pytest

import dnn_tpu.data  # the package import itself is under test
from dnn_tpu.data import CifarBinaryDataset, TokenDataset, prefetch_to_device
from dnn_tpu.data.cifar_binary import write_cifar_binary
from dnn_tpu.data.tokens import write_tokens


def test_package_exports_resolve():
    for name in dnn_tpu.data.__all__:
        assert getattr(dnn_tpu.data, name) is not None


def test_token_dataset_minimal_length_sampling(tmp_path):
    # len(tokens) == seq_len + 1: exactly one valid window; previously this
    # raised ValueError('high <= 0') from rng.integers(0, 0).
    path = str(tmp_path / "toks.bin")
    write_tokens(path, np.arange(9))
    ds = TokenDataset(path)
    rng = np.random.default_rng(0)
    batch = ds.sample(rng, 4, seq_len=8)
    assert batch.shape == (4, 9)
    np.testing.assert_array_equal(batch, np.tile(np.arange(9), (4, 1)))


def test_token_dataset_last_window_reachable(tmp_path):
    # The final valid start offset (len - seq_len - 1) must be sampleable.
    path = str(tmp_path / "toks.bin")
    write_tokens(path, np.arange(12))
    ds = TokenDataset(path)
    rng = np.random.default_rng(0)
    seq_len = 4
    starts = set()
    for _ in range(200):
        batch = ds.sample(rng, 8, seq_len)
        starts.update(int(b[0]) for b in batch)
    assert max(starts) == len(ds) - seq_len - 1
    assert min(starts) == 0


def test_prefetch_to_device_order_and_placement(tmp_path):
    import jax

    path = str(tmp_path / "cifar.bin")
    rng = np.random.default_rng(0)
    write_cifar_binary(
        path,
        rng.integers(0, 256, (32, 32, 32, 3), dtype=np.uint8),
        rng.integers(0, 10, 32, dtype=np.uint8),
    )
    ds = CifarBinaryDataset(path)
    host = list(ds.batches(8, shuffle=False, epochs=1))
    dev = list(prefetch_to_device(ds.batches(8, shuffle=False, epochs=1), size=3))
    assert len(dev) == len(host) == 4
    for (hx, hy), (dx, dy) in zip(host, dev):
        assert isinstance(dx, jax.Array) and isinstance(dy, jax.Array)
        np.testing.assert_array_equal(np.asarray(dx), hx)
        np.testing.assert_array_equal(np.asarray(dy), hy)


def test_prefetch_with_sharding(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    path = str(tmp_path / "toks.bin")
    write_tokens(path, np.arange(4096) % 1000)
    ds = TokenDataset(path)
    mesh = Mesh(np.array(jax.devices()[:8]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    it = prefetch_to_device(ds.batches(8, 16, seed=0), size=2, sharding=sharding)
    batch = next(it)
    assert batch.shape == (8, 17)
    assert batch.sharding.is_equivalent_to(sharding, batch.ndim)
    np.testing.assert_array_equal(
        np.asarray(batch),
        ds.sample(np.random.default_rng(0), 8, 16),
    )


def test_prefetch_shorter_than_queue():
    out = list(prefetch_to_device(iter([np.ones(3)]), size=4))
    assert len(out) == 1
    with pytest.raises(ValueError):
        next(prefetch_to_device(iter([]), size=0))
