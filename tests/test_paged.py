"""Paged KV cache (runtime/paged_kvcache.py) through the continuous
batcher: token parity with the dense cache, admission by actual length,
block recycling, and the validation surface.

The reference framework has no KV cache at all (each request is one
stateless forward, /root/reference/node.py:45-105); the dense batcher is
therefore the parity oracle here, and the paged pool's claim — the same
HBM serves MORE concurrent requests when lengths are mixed — is asserted
directly on the allocator."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.paged_kvcache import BlockAllocator
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.GPTConfig(block_size=96, vocab_size=128, n_layer=2, n_head=4,
                    n_embd=64)
BP = 16  # block_len


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def _prompt(seed, n=8):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, CFG.vocab_size, dtype=jnp.int32))


def _mk(prepared, *, paged, slots=4, blocks=32, **kw):
    extra = dict(paged_blocks=blocks, block_len=BP) if paged else {}
    return ContinuousBatcher(CFG, prepared, slots=slots, max_len=64,
                             prompt_pad=16, **extra, **kw)


def test_paged_matches_dense_tokens():
    """Mixed-length greedy + seeded-sampled requests: the paged pool
    produces token-for-token the dense batcher's results."""
    prepared = _prepared()
    reqs = [
        (_prompt(1, 5), dict(max_new_tokens=7)),
        (_prompt(2, 20), dict(max_new_tokens=9, seed=3, temperature=0.9,
                              top_k=11)),
        (_prompt(3, 33), dict(max_new_tokens=4)),
        (_prompt(4, 16), dict(max_new_tokens=12, seed=8, temperature=1.1,
                              top_p=0.9)),
    ]

    def run(paged):
        srv = _mk(prepared, paged=paged)
        rids = [srv.submit(p, **kw) for p, kw in reqs]
        out = srv.drain()
        return [out[r] for r in rids]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_paged_mid_flight_admission_matches_dense():
    """A request admitted while others are mid-decode lands on recycled
    state and still matches dense (same interleaving on both sides)."""
    prepared = _prepared(1)

    def run(paged):
        srv = _mk(prepared, paged=paged, slots=2)
        r1 = srv.submit(_prompt(5, 10), max_new_tokens=8)
        r2 = srv.submit(_prompt(6, 4), max_new_tokens=3)
        for _ in range(3):
            srv.step()   # r2 retires (budget 3) mid-flight
        r3 = srv.submit(_prompt(7, 18), max_new_tokens=6)  # reuses r2's slot
        out = srv.drain()
        return [out[r] for r in (r1, r2, r3)]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_admission_by_actual_length_beats_per_slot_reservation():
    """A pool holding 2 full-length requests' worth of blocks admits 4
    short requests CONCURRENTLY (the dense design reserves max_len per
    slot — 4 slots would cost 4 x 64 positions; the pool serves them in
    2 x 64)."""
    prepared = _prepared()
    # 9 blocks: 1 reserved junk + 8 usable = 2 x ceil(64/16) full-length
    srv = _mk(prepared, paged=True, slots=4, blocks=9)
    rids = [srv.submit(_prompt(10 + i, 8), max_new_tokens=8)
            for i in range(4)]  # each: ceil(16/16) = 1 block
    assert srv.n_active == 4  # all four decode concurrently
    assert srv._allocator.n_free == 4
    out = srv.drain()
    assert all(len(out[r]) == 8 for r in rids)
    # all blocks returned on retirement
    assert srv._allocator.n_free == 8


def test_block_exhaustion_rejects_then_recovers():
    prepared = _prepared()
    srv = _mk(prepared, paged=True, slots=4, blocks=9)
    # one full-length request: 48 prompt + 16 new = 64 -> 4 blocks
    r1 = srv.submit(_prompt(20, 48), max_new_tokens=16)
    srv.submit(_prompt(21, 48), max_new_tokens=16)
    with pytest.raises(RuntimeError, match="insufficient free cache blocks"):
        srv.submit(_prompt(22, 48), max_new_tokens=16)
    assert srv.n_active == 2  # the failed submit leaked no slot
    srv.drain()
    # blocks recycled: the same request now admits
    r3 = srv.submit(_prompt(22, 48), max_new_tokens=16)
    assert len(srv.drain()[r3]) == 16


def test_recycled_blocks_are_clean_for_tokens():
    """Round N+1 on recycled (dirty) blocks equals a fresh server — junk
    beyond each slot's length is never attended."""
    prepared = _prepared(2)
    srv = _mk(prepared, paged=True, slots=2, blocks=9)
    for _ in range(3):  # three generations of block reuse
        rid = srv.submit(_prompt(30, 40), max_new_tokens=10)
        got = srv.drain()[rid]
    fresh = _mk(prepared, paged=True, slots=2, blocks=9)
    rid_f = fresh.submit(_prompt(30, 40), max_new_tokens=10)
    np.testing.assert_array_equal(got, fresh.drain()[rid_f])


def test_paged_validation():
    prepared = _prepared()
    with pytest.raises(ValueError, match="tile block_len"):
        ContinuousBatcher(CFG, prepared, slots=2, max_len=60,
                          prompt_pad=16, paged_blocks=8, block_len=16)


def test_paged_int8_matches_dense_int8():
    """int8 paged pool (quantized K/V blocks + per-position scale blocks):
    the quantization math is the dense Int8KV's row recipe on both paths,
    so tokens match the dense int8 batcher exactly — including through a
    shared-prefix hit (scale blocks shared alongside)."""
    prepared = _prepared()
    prompt = _prompt(90, 32)

    def run(paged):
        extra = dict(paged_blocks=20, block_len=16) if paged else {}
        srv = ContinuousBatcher(CFG, prepared, slots=3, max_len=64,
                                prompt_pad=16, kv_dtype="int8",
                                prefix_cache=4, **extra)
        r1 = srv.submit(prompt, max_new_tokens=7)
        r2 = srv.submit(prompt, max_new_tokens=9, seed=5,
                        temperature=0.9, top_k=13)  # prefix hit
        r3 = srv.submit(_prompt(91, 10), max_new_tokens=5)
        out = srv.drain()
        return [out[r] for r in (r1, r2, r3)]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_paged_llama_gqa_matches_dense():
    """The LLaMA family through the paged pool: the pool stores KV heads
    (GQA width — family.kv_heads) and the folded-group attend rides the
    same gather; tokens equal the dense LLaMA batcher."""
    from dnn_tpu.models import llama

    lcfg = llama.PRESETS["llama-test"]
    lprep = gpt.prepare_stacked(llama.init(jax.random.PRNGKey(0), lcfg),
                                lcfg)

    def run(paged):
        extra = dict(paged_blocks=12, block_len=16) if paged else {}
        srv = ContinuousBatcher(
            lcfg, lprep, slots=2, max_len=64, prompt_pad=16,
            family=llama.LlamaFamilyRows(lcfg), **extra)
        r1 = srv.submit(_prompt(70, 12) % lcfg.vocab_size,
                        max_new_tokens=6)
        r2 = srv.submit(_prompt(71, 30) % lcfg.vocab_size,
                        max_new_tokens=8, seed=4, temperature=0.9,
                        top_k=7)
        out = srv.drain()
        return [out[r] for r in (r1, r2)]

    # the paged pool really is KV-head narrow
    from dnn_tpu.runtime.paged_kvcache import init_paged_cache
    pool = init_paged_cache(lcfg, 2, 64, n_blocks=12, block_len=16,
                            kv_heads=lcfg.n_kv_head)
    assert pool["k"].shape[2] == lcfg.n_kv_head

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_worker_holds_back_on_block_exhaustion():
    """The LM daemon worker must treat a transiently full pool as
    back-pressure — the request waits for a retirement — not as a hard
    failure handed to the caller."""
    from dnn_tpu.runtime.lm_server import _BatcherWorker

    prepared = _prepared()
    srv = _mk(prepared, paged=True, slots=4, blocks=9)
    w = _BatcherWorker(srv)
    w.start()
    try:
        # two full-length requests exhaust the 8 usable blocks; the third
        # must WAIT (not fail) and complete once one of them retires
        futs = [w.submit(_prompt(40 + i, 48), 16, None) for i in range(3)]
        outs = [f.result(timeout=120) for f in futs]
        assert all(len(o) == 16 for o in outs)
    finally:
        w.stop(drain=False)
        w.join(timeout=10)


def test_never_fitting_request_fails_fast():
    """A request larger than the whole pool must raise (ValueError), not
    wait forever."""
    prepared = _prepared()
    srv = _mk(prepared, paged=True, slots=2, blocks=3)  # 2 usable blocks
    with pytest.raises(ValueError, match="blocks"):
        srv.submit(_prompt(50, 48), max_new_tokens=16)  # needs 4


def test_claim_and_cancel_release_bookkeeping():
    prepared = _prepared()
    srv = _mk(prepared, paged=False, slots=2)
    rid = srv.submit(_prompt(60, 8), max_new_tokens=3)
    srv.drain()
    toks, reason, lps = srv.claim(rid)
    assert len(toks) == 3 and reason == "length" and lps is None
    assert rid not in srv.results and rid not in srv.finish_reasons
    with pytest.raises(KeyError):
        srv.claim(rid)

    # claim on a cancelled-while-live rid yields the cancelled record
    rid2 = srv.submit(_prompt(61, 8), max_new_tokens=8)
    assert srv.cancel(rid2)
    toks2, reason2, _ = srv.claim(rid2)
    assert toks2 is None and reason2 == "cancelled"
    assert rid2 not in srv.finish_reasons

    # cancel on a finished-unclaimed rid drops the whole record
    rid3 = srv.submit(_prompt(62, 8), max_new_tokens=2)
    srv.drain()
    assert srv.cancel(rid3)
    assert rid3 not in srv.results and rid3 not in srv.finish_reasons


def test_paged_prefix_sharing_copy_free():
    """Prefix cache in paged mode shares BLOCKS by refcount instead of
    copying rows: a second request with the same prompt allocates only
    its tail, prefill skips the shared chunks, and tokens match the dense
    prefix-cache server."""
    prepared = _prepared()
    prompt = _prompt(80, 32)  # 2 full chunks (pad 16) -> 2 shared blocks
    tail_a = np.concatenate([prompt, _prompt(81, 3)])

    def run(paged):
        extra = dict(paged_blocks=20, block_len=16) if paged else {}
        srv = ContinuousBatcher(CFG, prepared, slots=4, max_len=64,
                                prompt_pad=16, prefix_cache=8, **extra)
        r1 = srv.submit(prompt, max_new_tokens=6)
        chunks_after_first = srv.prefill_chunks_run
        r2 = srv.submit(prompt, max_new_tokens=9, seed=2, temperature=0.8)
        r3 = srv.submit(tail_a, max_new_tokens=5)
        out = srv.drain()
        return ([out[r] for r in (r1, r2, r3)], srv.prefix_hits,
                srv.prefill_chunks_run - chunks_after_first, srv)

    (toks_p, hits_p, extra_chunks_p, srv_p) = run(True)
    (toks_d, hits_d, extra_chunks_d, _) = run(False)
    for a, b in zip(toks_p, toks_d):
        np.testing.assert_array_equal(a, b)
    assert hits_p == hits_d == 2          # r2 whole-prompt, r3 partial
    assert extra_chunks_p == extra_chunks_d == 1  # only r3's tail chunk


def test_paged_prefix_block_accounting():
    """The memory claim, measured on the allocator: a same-prompt second
    request consumes ONLY its tail block; after both retire, just the
    entry-pinned prefix blocks stay out of the free list."""
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=4, max_len=64,
                            prompt_pad=16, prefix_cache=8,
                            paged_blocks=20, block_len=16)
    prompt = _prompt(82, 32)          # needs 2 blocks; +16 new -> 3 total
    assert srv._allocator.n_free == 19
    r1 = srv.submit(prompt, max_new_tokens=16)
    assert srv._allocator.n_free == 16            # 3 allocated
    r2 = srv.submit(prompt, max_new_tokens=16)    # whole-prefix hit
    assert srv._allocator.n_free == 15            # tail block ONLY
    srv.drain()
    # slots returned their references; the two prefix entries (1-chunk and
    # 2-chunk) still pin the 2 distinct prefix blocks
    assert srv._allocator.n_free == 17
    # hit entries survive retirement: a third request still shares
    r3 = srv.submit(prompt, max_new_tokens=16)
    assert srv._allocator.n_free == 16
    out = srv.drain()
    assert len(out[r3]) == 16


def test_paged_prefix_eviction_under_sharing():
    """Evicting an entry whose blocks a live slot still uses must not
    recycle those blocks until the slot retires — and tokens stay
    correct throughout."""
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=4, max_len=64,
                            prompt_pad=16, prefix_cache=1,  # tiny LRU
                            paged_blocks=24, block_len=16)
    p1 = _prompt(83, 16)
    r1 = srv.submit(p1, max_new_tokens=12)        # entry for p1 parked
    free_mid = srv._allocator.n_free
    # a different prompt's entry evicts p1's (cap 1) while r1 is LIVE
    r2 = srv.submit(_prompt(84, 16), max_new_tokens=12)
    # the eviction dropped the ENTRY's reference only: r1 still holds its
    # prefix block, so the only free-list movement is r2's 2 new blocks —
    # a buggy evict that recycled the shared block would show free_mid - 1
    assert srv._allocator.n_free == free_mid - 2
    out = srv.drain()
    assert len(out[r1]) == 12 and len(out[r2]) == 12
    # after retirement: only the surviving entry's 1 block stays pinned
    assert srv._allocator.n_free == 22


def test_entry_pinned_blocks_evict_instead_of_wedging():
    """Prefix entries pin blocks after their requests retire; a new novel
    request must EVICT entries to fit rather than raise forever (the
    livelock: entries only evicted on insertion, insertion needs a
    successful prefill)."""
    prepared = _prepared()
    srv = ContinuousBatcher(CFG, prepared, slots=4, max_len=64,
                            prompt_pad=16, prefix_cache=8,
                            paged_blocks=8, block_len=16)  # 7 allocatable
    # three distinct 2-chunk prompts, drained: entries pin 2 blocks each
    for s in (100, 101, 102):
        rid = srv.submit(_prompt(s, 32), max_new_tokens=16)
        srv.drain()
    assert srv._allocator.n_free <= 1  # nearly everything entry-pinned
    # a novel request needing 3 blocks must evict its way in
    rid = srv.submit(_prompt(103, 32), max_new_tokens=16)
    assert len(srv.drain()[rid]) == 16


def test_allocator_atomic_free():
    from dnn_tpu.runtime.paged_kvcache import BlockAllocator

    a = BlockAllocator(6)
    got = a.alloc(3)
    with pytest.raises(ValueError):
        a.free([got[0], 0])          # bad id mid-list...
    assert a.n_free == 2             # ...must not half-free
    with pytest.raises(ValueError):
        a.free([got[0], got[0]])     # duplicate beyond refcount
    assert a.n_free == 2
    a.free(got)
    assert a.n_free == 5


def test_allocator_contract():
    a = BlockAllocator(5)
    assert a.n_free == 4  # block 0 reserved
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.alloc(2) is None  # only 1 left
    a.free(got)
    assert a.n_free == 4
    with pytest.raises(ValueError):
        a.free([0])


def test_paged_windowed_matches_dense_windowed():
    """Sliding-window families on the paged pool: PagedKV band-masks and
    the batcher reclaims rolled-out blocks mid-request — tokens must
    equal the dense windowed batcher's across streams several windows
    long (the wrap is exercised: window 16 < prompt+new)."""
    from dnn_tpu.models import llama

    lcfg = llama.LlamaConfig(block_size=96, vocab_size=256, n_layer=2,
                             n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                             sliding_window=16)
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(3), lcfg), lcfg)
    prompts = [_prompt(11, n=24), _prompt(12, n=5)]
    n_new = 40  # stream runs 4x past the window

    outs = {}
    for paged in (False, True):
        extra = dict(paged_blocks=24, block_len=16) if paged else {}
        srv = ContinuousBatcher(lcfg, prepared, slots=2, max_len=96,
                                prompt_pad=16,
                                family=llama.LlamaFamilyRows(lcfg),
                                **extra)
        rids = [srv.submit(p % lcfg.vocab_size, max_new_tokens=n_new)
                for p in prompts]
        srv.drain()
        outs[paged] = [srv.results[r] for r in rids]
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)


def test_paged_windowed_reclaims_rolled_blocks():
    """The pool form of the rolling cache's win: a long windowed stream
    frees its fully-rolled-out blocks MID-REQUEST — the allocator's free
    count grows past its post-prefill level while the request is still
    decoding, and the freed capacity admits another request a causal
    pool could not hold."""
    from dnn_tpu.models import llama

    lcfg = llama.LlamaConfig(block_size=160, vocab_size=256, n_layer=2,
                             n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                             sliding_window=16)
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(4), lcfg), lcfg)
    srv = ContinuousBatcher(lcfg, prepared, slots=2, max_len=160,
                            prompt_pad=16,
                            family=llama.LlamaFamilyRows(lcfg),
                            paged_blocks=16, block_len=16)
    # 64-token prompt + 64 new = 8 blocks reserved at admission
    rid = srv.submit(_prompt(13, n=64) % lcfg.vocab_size,
                     max_new_tokens=64)
    free_after_prefill = srv._allocator.n_free
    req = srv._slot_req[0]
    # the prompt already rolled blocks out at install: positions <=
    # 63-16 are dead -> 3 full blocks freed immediately
    assert req["freed"] == 3
    for _ in range(40):
        srv.step()
    assert srv._slot_req[0] is not None, "request should still be live"
    assert srv._allocator.n_free > free_after_prefill
    assert srv._slot_req[0]["freed"] > 3
    srv.drain()
    # retirement must not double-free the reclaimed prefix
    assert srv._allocator.n_free == srv._allocator.n_blocks - 1


def test_paged_windowed_rejects_prefix_cache_and_altwindow():
    from dnn_tpu.models import llama

    lcfg = llama.LlamaConfig(block_size=96, vocab_size=256, n_layer=2,
                             n_head=4, n_kv_head=2, n_embd=64, d_ff=128,
                             sliding_window=16)
    prepared = gpt.prepare_stacked(
        llama.init(jax.random.PRNGKey(5), lcfg), lcfg)
    with pytest.raises(ValueError, match="prefix"):
        ContinuousBatcher(lcfg, prepared, slots=2, max_len=96,
                          prompt_pad=16,
                          family=llama.LlamaFamilyRows(lcfg),
                          paged_blocks=16, block_len=16, prefix_cache=2)
    g2 = llama.PRESETS["gemma2-test"]
    g2p = gpt.prepare_stacked(llama.init(jax.random.PRNGKey(6), g2), g2)
    with pytest.raises(ValueError, match="alternating"):
        ContinuousBatcher(g2, g2p, slots=2, max_len=64, prompt_pad=16,
                          family=llama.LlamaFamilyRows(g2),
                          paged_blocks=16, block_len=16)
