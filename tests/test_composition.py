"""Runtime-feature composition tests — the support matrix in README
("Runtime feature composition") is backed row-by-row by this file.

The interesting compositions:
  * speculative x quantized DRAFT: the rejection-sampling construction
    makes greedy output depend ONLY on the target — ANY draft (including
    an int8-quantized one, the natural choice: the draft is pure
    overhead) must leave greedy output identical to target-only decode;
  * speculative x quantized TARGET: spec decode on a quantized target
    equals plain decode on the same quantized target;
  * batcher x int8 weights x int8 KV cache: the pool's per-row cache
    codec quantizes each row exactly like the solo decoder's, so a
    greedy slot still reproduces the solo run token-for-token.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.quant import quantize_gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.serving import ContinuousBatcher
from dnn_tpu.runtime.speculative import make_speculative_generate

CFG = gpt.PRESETS["gpt2-test"]
D_CFG = gpt.GPTConfig(block_size=64, vocab_size=256, n_layer=1, n_head=2,
                      n_embd=32)


def _pair(seed=0):
    tp = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)
    dp = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed + 1), D_CFG), D_CFG)
    return tp, dp


def test_speculative_with_int8_draft_keeps_target_greedy():
    tp, dp = _pair()
    dq = quantize_gpt(dp)  # quantized draft: cheaper proposals, same output
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, CFG.vocab_size)
    n = 12
    spec = make_speculative_generate(CFG, D_CFG, max_new_tokens=n, k=4)
    got = np.asarray(spec(tp, dq, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate(CFG, max_new_tokens=n)(
        tp, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_speculative_with_int8_target_matches_plain_int8_decode():
    tp, dp = _pair(seed=3)
    tq = quantize_gpt(tp)
    ids = jax.random.randint(jax.random.PRNGKey(4), (1, 8), 0, CFG.vocab_size)
    n = 10
    spec = make_speculative_generate(CFG, D_CFG, max_new_tokens=n, k=3)
    got = np.asarray(spec(tq, dp, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate(CFG, max_new_tokens=n)(
        tq, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_batcher_int8_weights_and_cache_matches_solo():
    tp, _ = _pair(seed=5)
    tq = quantize_gpt(tp)
    prompts = [np.array([5, 3, 7, 1]), np.array([9, 8, 2])]
    n = 6
    srv = ContinuousBatcher(CFG, tq, slots=2, max_len=32, prompt_pad=8,
                            kv_dtype="int8")
    rids = [srv.submit(p, max_new_tokens=n) for p in prompts]
    results = srv.drain()

    solo = make_generate(CFG, max_new_tokens=n, kv_dtype="int8")
    for rid, p in zip(rids, prompts):
        want = np.asarray(solo(tq, jnp.asarray(p, jnp.int32)[None, :],
                               jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[rid], want)


def test_batcher_bf16_cache_matches_solo():
    tp, _ = _pair(seed=7)
    prompt = np.array([4, 5, 6, 7, 8])
    n = 6
    srv = ContinuousBatcher(CFG, tp, slots=2, max_len=32, prompt_pad=8,
                            kv_dtype=jnp.bfloat16)
    rid = srv.submit(prompt, max_new_tokens=n)
    got = srv.drain()[rid]
    want = np.asarray(make_generate(CFG, max_new_tokens=n, kv_dtype=jnp.bfloat16)(
        tp, jnp.asarray(prompt, jnp.int32)[None, :], jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)
