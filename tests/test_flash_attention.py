"""Pallas flash attention vs the jnp reference (kernel run in interpret mode
on the CPU backend; on TPU the same code path compiles for real)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.ops.pallas.flash_attention import flash_attention, reference_attention


def _qkv(b=2, h=2, t=256, d=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, h, t, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128, interpret=True)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_fallback_on_untileable_shapes():
    """T not divisible by the block size must silently use the reference
    path (the use_flash=True 'always safe' contract)."""
    q, k, v = _qkv(t=100)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_attention_use_flash_flag():
    """causal_self_attention(use_flash=True) must work on any backend."""
    from dnn_tpu.ops.attention import causal_self_attention

    c, n_head = 32, 2
    k_qkv, k_proj = jax.random.split(jax.random.PRNGKey(1))
    params = {
        "qkv": {"kernel": jax.random.normal(k_qkv, (c, 3 * c)) * 0.05, "bias": jnp.zeros((3 * c,))},
        "proj": {"kernel": jax.random.normal(k_proj, (c, c)) * 0.05, "bias": jnp.zeros((c,))},
    }
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 16, c))
    y_flash = causal_self_attention(params, x, n_head=n_head, use_flash=True)
    y_ref = causal_self_attention(params, x, n_head=n_head, use_flash=False)
    np.testing.assert_allclose(np.asarray(y_flash), np.asarray(y_ref), atol=1e-5, rtol=1e-5)


def test_gpt_compute_dtype_bf16():
    """compute_dtype=bf16 must actually change matmul dtype (and stay close
    to the f32 result)."""
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32)
    f32 = gpt.make_apply(cfg)(params, ids)
    bf16 = gpt.make_apply(cfg, compute_dtype=jnp.bfloat16)(params, ids)
    assert bf16.dtype == jnp.float32  # head always produces f32 logits
    diff = np.abs(np.asarray(f32) - np.asarray(bf16)).max()
    assert 0 < diff < 0.15, f"bf16 path diff {diff} (0 means bf16 never engaged)"


def test_stacked_apply_matches_per_layer():
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32)
    prepared = gpt.prepare_stacked(params, cfg)
    np.testing.assert_array_equal(
        np.asarray(gpt.make_apply_stacked(cfg)(prepared, ids)),
        np.asarray(gpt.make_apply(cfg)(params, ids)),
    )


def test_flash_decode_shapes_bottom_right_mask():
    """T != S causal (KV-cache decode): kernel must match the reference's
    bottom-right-aligned mask (tril k=S-T)."""
    b, h, d = 1, 2, 32
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(kq, (b, h, 128, d))
    k = jax.random.normal(kk, (b, h, 256, d))
    v = jax.random.normal(kv, (b, h, 256, d))
    out = flash_attention(q, k, v, causal=True, block_q=128, interpret=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal,t,s", [
    (True, 256, 256), (False, 256, 256), (True, 128, 256),
])
def test_flash_backward_matches_reference(causal, t, s):
    """jax.grad through the Pallas kernel (custom_vjp recompute backward)
    must match grads through the jnp reference — dq, dk, and dv, including
    the bottom-right-aligned (KV-cache) mask when S > T."""
    b, h, d = 2, 2, 64
    kq, kk, kv, kw = jax.random.split(jax.random.PRNGKey(7), 4)
    q = jax.random.normal(kq, (b, h, t, d))
    k = jax.random.normal(kk, (b, h, s, d))
    v = jax.random.normal(kv, (b, h, s, d))
    w = jax.random.normal(kw, (b, h, t, d))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, interpret=True) * w)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=causal) * w)

    np.testing.assert_allclose(loss_flash(q, k, v), loss_ref(q, k, v), rtol=1e-4)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=2e-4, rtol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_grad_through_use_flash_apply():
    """Training with use_flash=True must differentiate end to end (weak #1
    of the round-1 review: pallas_call alone has no autodiff rule)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.train import next_token_loss

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    apply_fn = gpt.make_apply(cfg, use_flash=True, remat=True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0, cfg.vocab_size,
                                jnp.int32)
    loss, grads = jax.value_and_grad(
        lambda p: next_token_loss(apply_fn, p, tokens)
    )(params)
    assert jnp.isfinite(loss)
    flat = jax.tree.leaves(grads)
    assert all(jnp.isfinite(g).all() for g in flat)
    assert any(float(jnp.abs(g).max()) > 0 for g in flat)


def test_long_context_preset_reaches_flash_auto():
    """gpt2-4k exists so `use_flash='auto'` can actually engage (all the
    classic presets cap block_size at 1024, below FLASH_AUTO_THRESHOLD)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.ops.attention import FLASH_AUTO_THRESHOLD

    assert "gpt2-4k" in gpt.PRESETS
    assert gpt.PRESETS["gpt2-4k"].block_size >= FLASH_AUTO_THRESHOLD


def test_partition_compute_dtype_matches_full_model():
    """Pipeline stages with compute_dtype=bf16 must match the full-model
    bf16 path (the review-found silent-f32 regression)."""
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size, jnp.int32)
    full = gpt.make_apply(cfg, compute_dtype=jnp.bfloat16)(params, ids)
    h = ids
    for st in gpt.make_partition(cfg, compute_dtype=jnp.bfloat16)(2):
        h = st.apply(st.slice_params(params), h)
    np.testing.assert_allclose(np.asarray(h), np.asarray(full), atol=1e-5, rtol=1e-5)
