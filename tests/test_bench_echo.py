"""Round-trip pin between run_all.write_results_md and bench.py's
stale-TPU echo parser: the echo scrapes RESULTS.md, so any format drift
in the writer must break THIS test, not silently return None and ship a
perf-blind round (the exact failure the echo exists to prevent)."""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


bench = _load("_bench_echo_bench", "bench.py")
run_all = _load("_bench_echo_run_all", os.path.join("benchmarks",
                                                    "run_all.py"))


def test_echo_round_trips_write_results_md(tmp_path):
    rows = [
        {"config": "cifar_cnn_fwd", "metric": "images_per_sec",
         "value": 100.0, "platform": "tpu", "batch": 1024},
        {"config": "gpt2_fwd", "metric": "tokens_per_sec",
         "value": 454770.9, "mfu": 0.614, "platform": "tpu",
         "batch": 8, "seq": 512},
    ]
    path = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(path))

    ref = bench._last_good_tpu_reference(str(path))
    assert ref is not None, "echo parser lost the writer's format"
    assert ref["value"] == 454770.9
    assert ref["mfu"] == 0.614
    assert ref["commit"]  # provenance stamp present
    assert "NOT measured this run" in ref["note"]


def test_echo_uses_carried_row_provenance(tmp_path):
    """After an off-chip refresh the table HEADER carries the refresh
    commit while a carried tpu row names its own measurement vintage in
    a provenance= detail — the echo must attribute the number to the
    commit where it was MEASURED, not the one that re-rendered the
    table."""
    rows = [
        {"config": "gpt2_fwd", "metric": "tokens_per_sec",
         "value": 454770.9, "mfu": 0.614, "platform": "tpu",
         "provenance": "abc1234 2026-07-31 08:09 UTC",
         "details": "batch=8, seq=512"},
    ]
    path = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(path))
    ref = bench._last_good_tpu_reference(str(path))
    assert ref is not None
    assert ref["commit"] == "abc1234"
    assert ref["date"] == "2026-07-31 08:09 UTC"


def test_echo_refuses_cpu_only_tables(tmp_path):
    """A table whose device section ran on CPU must NOT be echoed as a
    TPU reference."""
    rows = [{"config": "gpt2_fwd", "metric": "tokens_per_sec",
             "value": 1234.5, "platform": "cpu", "batch": 8, "seq": 512}]
    path = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(path))
    assert bench._last_good_tpu_reference(str(path)) is None


def test_previous_round_ratio_both_formats(tmp_path):
    """The drift echo reads the LATEST BENCH_r*.json whether the row is
    top-level or embedded in the driver's captured "tail" text."""
    import json

    (tmp_path / "BENCH_r03.json").write_text(
        json.dumps({"metric": "m", "vs_baseline": 0.97}))
    assert bench._previous_round_ratio(str(tmp_path)) == {
        "round": 3, "vs_baseline": 0.97, "metric": "m"}
    tail = ("noise line\n"
            + json.dumps({"metric": "m2", "vs_baseline": 0.84}) + "\n")
    (tmp_path / "BENCH_r05.json").write_text(
        json.dumps({"n": 5, "rc": 0, "tail": tail}))
    got = bench._previous_round_ratio(str(tmp_path))
    assert got == {"round": 5, "vs_baseline": 0.84, "metric": "m2"}
    # unparseable latest round -> None, never a crash
    (tmp_path / "BENCH_r06.json").write_text("{broken")
    assert bench._previous_round_ratio(str(tmp_path)) is None


def test_sync_readme_round_trip(tmp_path):
    """README's perf table regenerates from RESULTS.md between the
    markers, stamped with the bench commit and a staleness warning when
    HEAD differs."""
    rows = [{"config": "gpt2_fwd", "metric": "tokens_per_sec",
             "value": 454770.9, "mfu": 0.614, "platform": "tpu",
             "batch": 8, "seq": 512}]
    results = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(results))
    # force a stale stamp so the warning branch is exercised
    text = results.read_text()
    import re

    results.write_text(re.sub(r"commit `[^`]+`", "commit `0000000`", text))
    readme = tmp_path / "README.md"
    readme.write_text("intro\n\n" + run_all.README_BEGIN + "\nstale\n"
                      + run_all.README_END + "\n\nfooter\n")
    run_all.sync_readme(results_path=str(results), readme_path=str(readme))
    out = readme.read_text()
    assert "intro" in out and "footer" in out and "stale" not in out
    assert "Measured at commit `0000000`" in out
    assert "Staleness warning" in out
    assert "| gpt2_fwd | tokens_per_sec | 454770.9 |" in out
    # markers survive, so the next sync still finds its section
    assert run_all.README_BEGIN in out and run_all.README_END in out


def test_sync_readme_requires_markers(tmp_path):
    rows = [{"config": "gpt2_fwd", "metric": "tokens_per_sec",
             "value": 1.0, "platform": "tpu"}]
    results = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(results))
    readme = tmp_path / "README.md"
    readme.write_text("no markers here\n")
    import pytest

    with pytest.raises(SystemExit, match="markers"):
        run_all.sync_readme(results_path=str(results),
                            readme_path=str(readme))
