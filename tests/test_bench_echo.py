"""Round-trip pin between run_all.write_results_md and bench.py's
stale-TPU echo parser: the echo scrapes RESULTS.md, so any format drift
in the writer must break THIS test, not silently return None and ship a
perf-blind round (the exact failure the echo exists to prevent)."""

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


bench = _load("_bench_echo_bench", "bench.py")
run_all = _load("_bench_echo_run_all", os.path.join("benchmarks",
                                                    "run_all.py"))


def test_echo_round_trips_write_results_md(tmp_path):
    rows = [
        {"config": "cifar_cnn_fwd", "metric": "images_per_sec",
         "value": 100.0, "platform": "tpu", "batch": 1024},
        {"config": "gpt2_fwd", "metric": "tokens_per_sec",
         "value": 454770.9, "mfu": 0.614, "platform": "tpu",
         "batch": 8, "seq": 512},
    ]
    path = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(path))

    ref = bench._last_good_tpu_reference(str(path))
    assert ref is not None, "echo parser lost the writer's format"
    assert ref["value"] == 454770.9
    assert ref["mfu"] == 0.614
    assert ref["commit"]  # provenance stamp present
    assert "NOT measured this run" in ref["note"]


def test_echo_refuses_cpu_only_tables(tmp_path):
    """A table whose device section ran on CPU must NOT be echoed as a
    TPU reference."""
    rows = [{"config": "gpt2_fwd", "metric": "tokens_per_sec",
             "value": 1234.5, "platform": "cpu", "batch": 8, "seq": 512}]
    path = tmp_path / "RESULTS.md"
    run_all.write_results_md(rows, str(path))
    assert bench._last_good_tpu_reference(str(path)) is None
