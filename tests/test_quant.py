"""Weight-only int8 quantization (dnn_tpu/quant.py).

Contracts pinned here:
  * per-channel symmetric round trip: |W - q*scale| <= scale/2 elementwise;
  * the int8 linear path in ops.nn equals explicit dequant-then-matmul;
  * quantize-then-stack == stack-then-quantize (scales reduce over the
    contraction dim only, so layer stacking commutes with quantization);
  * a quantized GPT's logits track the f32 model closely (cosine) and the
    quantized tree is the expected fraction of the bytes;
  * the SAME quantized tree drops into every consumer unchanged: full
    forward, KV-cache decode, the continuous-batching server (which must
    stay token-identical to solo decode *under quantized weights*), and
    the stage-sharded SPMD pipeline.

The reference has no quantization (its f32 .pth rides the wire whole,
/root/reference/node.py:294-325); this is a serving capability the rebuild
adds because decode on TPU is HBM-bandwidth-bound.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import quant
from dnn_tpu.models import gpt
from dnn_tpu.ops.nn import linear
from dnn_tpu.parallel.mesh import make_mesh
from dnn_tpu.parallel.pipeline import spmd_pipeline_stacked
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def gpt_setup():
    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    return cfg, params, prepared


def test_quantize_tensor_round_trip_bound():
    w = jax.random.normal(jax.random.PRNGKey(1), (128, 96)) * 0.1
    q, scale = quant.quantize_tensor(w)
    assert q.dtype == jnp.int8 and scale.shape == (1, 96)
    err = jnp.abs(quant.dequantize_tensor(q, scale) - w)
    # round() puts every element within half a quantization step
    assert (err <= scale / 2 + 1e-7).all()


def test_quantize_tensor_zero_column():
    """An all-zero output channel must not divide by zero."""
    w = jnp.zeros((16, 4)).at[:, 1].set(1.0)
    q, scale = quant.quantize_tensor(w)
    assert jnp.isfinite(scale).all()
    np.testing.assert_allclose(quant.dequantize_tensor(q, scale), w, atol=1e-6)


def test_linear_int8_matches_explicit_dequant():
    k1, k2 = jax.random.split(jax.random.PRNGKey(2))
    w = jax.random.normal(k1, (64, 48)) * 0.05
    b = jax.random.normal(k2, (48,)) * 0.01
    x = jax.random.normal(jax.random.PRNGKey(3), (5, 64))
    qp = quant.quantize_linear({"kernel": w, "bias": b})
    got = linear(qp, x)
    want = x @ quant.dequantize_tensor(qp["q"], qp["scale"]) + b
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_quantize_commutes_with_stacking(gpt_setup):
    cfg, params, prepared = gpt_setup
    q_then_stack = gpt.prepare_stacked(quant.quantize_gpt(params), cfg)
    stack_then_q = quant.quantize_gpt(prepared)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        q_then_stack, stack_then_q,
    )


def test_quantized_gpt_logits_close(gpt_setup):
    cfg, _, prepared = gpt_setup
    qtree = quant.quantize_gpt(prepared)
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 24), 0, cfg.vocab_size,
                             dtype=jnp.int32)
    apply_fn = gpt.make_apply_stacked(cfg)
    ref = np.asarray(apply_fn(prepared, ids)).reshape(-1, cfg.vocab_size)
    got = np.asarray(apply_fn(qtree, ids)).reshape(-1, cfg.vocab_size)
    cos = (ref * got).sum(-1) / (
        np.linalg.norm(ref, axis=-1) * np.linalg.norm(got, axis=-1)
    )
    assert (cos > 0.999).all(), f"min cosine {cos.min()}"


def test_quantized_bytes_fraction(gpt_setup):
    cfg, _, prepared = gpt_setup
    qtree = quant.quantize_gpt(prepared)
    ratio = quant.param_bytes(qtree) / quant.param_bytes(prepared)
    # linears drop 4x (plus small scales); embeddings/norms stay f32
    assert ratio < 0.5, f"quantized tree is {ratio:.2f} of original bytes"


def test_quantized_decode_and_serving_parity(gpt_setup):
    """KV-cache decode runs on the quantized tree, and the continuous
    batcher remains token-identical to solo decode under it."""
    cfg, _, prepared = gpt_setup
    qtree = quant.quantize_gpt(prepared)
    prompt = (np.arange(1, 9) * 7) % cfg.vocab_size
    solo = make_generate(cfg, max_new_tokens=10)(
        qtree, jnp.asarray(prompt, jnp.int32)[None, :], jax.random.PRNGKey(9)
    )
    assert np.asarray(solo).shape == (1, 10)
    srv = ContinuousBatcher(cfg, qtree, slots=2, max_len=cfg.block_size,
                            prompt_pad=16)
    rid = srv.submit(prompt, max_new_tokens=10)
    res = srv.drain()
    np.testing.assert_array_equal(res[rid], np.asarray(solo)[0])


def test_quantized_moe_expert_stacks():
    """MoE trees quantize structurally: int8 wi/wo + per-(expert, channel)
    scales, router untouched (routing decisions must not flip), and the
    quantized tree runs both the dense and the expert-parallel paths —
    which must still agree exactly with each other."""
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh as mk
    from dnn_tpu.parallel.moe import init_moe, make_moe_ffn_ep, moe_ffn

    d, e, f = 64, 8, 96
    params = init_moe(jax.random.PRNGKey(0), d, e, f)
    qp = quant.quantize_tree(params)
    assert qp["wi"].dtype == jnp.int8 and qp["wo"].dtype == jnp.int8
    assert qp["wi_scale"].shape == (e, 1, f)
    np.testing.assert_array_equal(  # router stays f32
        np.asarray(qp["router"]["kernel"]), np.asarray(params["router"]["kernel"])
    )

    x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, d))
    dense_f32 = np.asarray(moe_ffn(params, x, top_k=2, groups=8))
    dense_q = np.asarray(moe_ffn(qp, x, top_k=2, groups=8))
    # same routing (f32 router) -> output differs only by weight rounding
    cos = (dense_f32 * dense_q).sum() / (
        np.linalg.norm(dense_f32) * np.linalg.norm(dense_q)
    )
    assert cos > 0.999, f"cosine {cos}"

    mesh = mk({EXPERT_AXIS: 8}, jax.devices()[:8])
    ep = np.asarray(make_moe_ffn_ep(mesh, top_k=2)(qp, x))
    np.testing.assert_allclose(ep, dense_q, atol=1e-5, rtol=1e-5)


def test_router_sized_like_a_linear_is_not_quantized():
    """A wide router ((D, E>=32) kernel, 2D, big enough for the default
    predicate) must still be excluded by path — the routing matmul reads
    params['router']['kernel'] directly."""
    from dnn_tpu.parallel.moe import init_moe, moe_ffn

    params = init_moe(jax.random.PRNGKey(0), 64, 32, 64)
    qp = quant.quantize_tree(params)
    assert "kernel" in qp["router"]
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 4, 64))
    out = moe_ffn(qp, x, top_k=2, groups=4)  # must not KeyError
    assert np.isfinite(np.asarray(out)).all()


def test_quantized_pipeline_stacked(gpt_setup):
    """Int8 stacked block params shard over the stage axis like any other
    leaf; pipeline output equals the single-program quantized forward."""
    cfg, _, prepared = gpt_setup
    qtree = quant.quantize_gpt(prepared)
    mesh = make_mesh({"stage": cfg.n_layer})
    x = jax.random.normal(jax.random.PRNGKey(5), (8, 16, cfg.n_embd))

    y = spmd_pipeline_stacked(
        lambda p, h: gpt.block_apply(p, h, cfg=cfg),
        qtree["blocks"], x, mesh=mesh, num_microbatches=4,
    )
    ref = gpt.blocks_scan(qtree["blocks"], x, cfg=cfg)
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)
