"""Test harness: run every test on a virtual 8-device CPU mesh.

The reference has no tests at all (SURVEY.md §4); its only multi-node story
is "N localhost processes". The TPU-native analog is N virtual host devices:
we force the CPU platform with 8 devices *before* JAX initializes, so the
pipeline/mesh tests (tests/test_pipeline*.py) exercise real
shard_map/ppermute collectives without TPU hardware.
"""

import os

# This environment pre-sets JAX_PLATFORMS=axon (the TPU tunnel), which would
# silently put the whole suite on the one real TPU chip — with bf16-default
# matmul precision and no multi-device mesh. Worse, `import pytest` already
# imports jax via a plugin, so env vars alone are too late for platform
# selection; backend init is lazy though, so jax.config still takes effect.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _cpu_mesh_guard():
    """Fail loudly if the suite ever lands on the TPU backend again."""
    assert jax.default_backend() == "cpu", f"suite must run on CPU, got {jax.default_backend()}"
    assert len(jax.devices()) >= 8, f"expected >=8 virtual devices, got {jax.devices()}"


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches_between_modules():
    """Free each module's compiled executables when it finishes.

    A single pytest process otherwise accumulates every jitted program
    of ~500 tests (plus the device buffers their closures pin); late in
    the run an XLA CPU compile can then die with a hard SIGSEGV inside
    backend_compile_and_load — observed reproducibly at ~85% of the
    suite, while the same test passes in isolation. Clearing BETWEEN
    modules (never within) keeps intra-module contracts intact — e.g.
    the serving tests' jit-cache-size regression checks — at the cost of
    recompiling tiny shared helpers per module."""
    yield
    import gc

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
