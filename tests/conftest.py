"""Test harness: run every test on a virtual 8-device CPU mesh.

The reference has no tests at all (SURVEY.md §4); its only multi-node story
is "N localhost processes". The TPU-native analog is N virtual host devices:
we force the CPU platform with 8 devices *before* JAX initializes, so the
pipeline/mesh tests (tests/test_pipeline*.py) exercise real
shard_map/ppermute collectives without TPU hardware.
"""

import os

# This environment pre-sets JAX_PLATFORMS=axon (the TPU tunnel), which would
# silently put the whole suite on the one real TPU chip — with bf16-default
# matmul precision and no multi-device mesh. Worse, `import pytest` already
# imports jax via a plugin, so env vars alone are too late for platform
# selection; backend init is lazy though, so jax.config still takes effect.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _cpu_mesh_guard():
    """Fail loudly if the suite ever lands on the TPU backend again."""
    assert jax.default_backend() == "cpu", f"suite must run on CPU, got {jax.default_backend()}"
    assert len(jax.devices()) >= 8, f"expected >=8 virtual devices, got {jax.devices()}"


# Measured wall-clock per test module (seconds, full suite on the 2-core
# CI host — regenerate with `pytest --durations=0` and summing per file).
# The tier-1 gate runs under a FIXED TIME BUDGET (ROADMAP.md: 870 s via
# `timeout`), far less than the ~36 min the whole suite takes here, so
# execution ORDER decides how much of the suite the budget certifies.
# Alphabetical order spent the window on a handful of compile-heavy mesh/
# kernel integration modules early in the alphabet; running cheapest
# modules first maximizes tests-verified-per-budget, and truncation then
# falls on the slowest integration tail (which the unbudgeted full run
# still covers). Nothing is deselected — every test remains collected
# and runs when the budget allows.
_MODULE_COST_S = {
    "test_interop_reference": 0.1, "test_config": 0.2, "test_data": 0.2,
    "test_checkpoint": 0.4, "test_bench_echo": 0.5,
    "test_run_all_state": 0.5, "test_flops": 0.6,
    "test_native_loader": 0.7, "test_native": 0.8, "test_hlo_audit": 3.4,
    "test_metrics": 3.7, "test_models_cifar": 4.6, "test_multihost": 4.6,
    "test_comm": 5.7, "test_models_mlp": 7.3, "test_tokenizer": 7.8,
    "test_transport": 14.0,  # ISSUE 7 pluggable transport: wirecodec
    # goldens vs protobuf, negotiation matrix, grpc|shm|device parity on
    # a real 2-stage engine, streamed relay, and one real 2-process shm
    # hop (subprocess) — cheap, certified early in the tier-1 budget
    "test_param_placement": 8.7, "test_qwen3": 9.6,
    "test_torch_export": 11.1, "test_models_gpt": 11.4,
    "test_analysis": 13.7,  # the static-analyzer gate: cheap, CPU-only,
    # and placed early so the tier-1 budget always certifies it
    "test_analysis_shard": 8.5,  # ISSUE 17 sharding-safety analyzer:
    # SHD rule fixture pairs, buggy-program variants through the audit
    # helpers (replicated bill, axis-divergent psum, contract drift,
    # un-aliased sharded donation), the real-program goldens (one
    # module-scoped run_shard_audit), SARIF + CLI exit codes — cheap,
    # certified early in the tier-1 budget next to test_analysis
    "test_analysis_concurrency": 8.0,  # ISSUE 10 concurrency-hazard
    # analyzer: CON rule fixture pairs, the three historical shipped
    # bugs as fixtures, protocol-table goldens, loop-lag sanitizer,
    # CLI --diff/sarif — pure AST + tiny asyncio loops, certified
    # early in the tier-1 budget next to test_analysis
    "test_obs": 28.0,  # the observability layer (spans, /metrics, compile
    # telemetry + the `python -m dnn_tpu.obs trace --selftest` CI smoke):
    # mid-pack cost, certified within the tier-1 budget
    "test_obs_v2": 36.0,  # obs v2 (flight recorder, watchdog, /profilez,
    # memory watermarks): the wedged-probe and crash-dump subprocess legs
    # dominate; placed with test_obs inside the tier-1 budget
    "test_obs_timeline": 12.0,  # ISSUE 11 step-timeline attribution:
    # StepClock phase arithmetic (injected clock), capture-analysis
    # goldens over synthetic Perfetto JSON, one real profiler capture
    # with sidecar-meta alignment, /stepz scrape, CLI smoke — cheap,
    # certified early in the tier-1 budget with the other obs modules
    "test_obs_kvlens": 12.0,  # ISSUE 18 memory-economy observatory:
    # MRC goldens at rate=1 (exact LRU), sampling determinism, thrash
    # arithmetic on an injected clock, /kvz json+prom, CLI smoke, and
    # one real forced-eviction batcher feeding the radix-store seams —
    # the CLI subprocess and batcher compile dominate; placed with the
    # other obs modules inside the tier-1 budget
    "test_obs_caplens": 6.0,  # ISSUE 20 capacity observatory: planner
    # replay goldens + determinism on an injected clock, demand-window
    # and change-point arithmetic, cold-start bucket attribution off
    # the boot gauges, audit-trailed wanted-replicas transitions,
    # /capz json+prom, the /fleetz wanted-rollup max regression, CLI
    # selftest, and the replica-handle lifecycle seams — the CLI
    # subprocess dominates; placed with the other obs modules
    "test_obs_trainlens": 14.0,  # ISSUE 19 training-step observatory:
    # TrainClock phase arithmetic + stall attribution on an injected
    # clock, MFU vs hand arithmetic, GradSentinel NaN/spike/stall
    # episodes, ckpt staleness, /trainz json+prom, CLI selftest, and
    # one real fit() on a tiny GPT feeding every seam — the fit
    # compile dominates; placed with the other obs modules
    "test_obs_fleet": 21.0,  # fleet layer (cross-host stitching, goodput
    # MFU/MBU, SLO burn rates + the `obs fleet --selftest` CLI smoke):
    # cheap HTTP endpoints + one real 2-stage gRPC request, certified
    # inside the tier-1 budget ahead of the obs integration modules
    "test_workloads": 20.0,  # ISSUE 14 SLO observatory: golden arrival
    # schedules, scenario-script determinism, SLO-verdict arithmetic,
    # incident-bundle roundtrip + CLI render, ledger parsing vs the
    # real BENCH_r*.json/RESULTS.md, prefix-cache counters/gauge, one
    # green light scenario + the chaos breach asserted from its bundle
    # — cheap, certified early in the tier-1 budget
    "test_grad_accum": 12.9, "test_train_ckpt": 14.3, "test_remat": 14.6,
    "test_qwen2": 14.7, "test_olmo2": 14.8, "test_tp_generate": 15.6,
    "test_pipeline": 16.5, "test_seq_parallel": 17.0,
    "test_generate": 17.7, "test_eval_distill": 17.8, "test_fsdp": 18.2,
    "test_dp_pp": 18.3, "test_int4": 18.6, "test_prefix_cache": 19.7,
    "test_rope_scaling": 20.4, "test_lm_server_failures": 20.6,
    "test_generate_seq": 20.8, "test_pipeline_dtypes": 22.2,
    "test_phi": 22.3, "test_train_serve_example": 23.1, "test_lora": 23.1,
    "test_qwen2_moe": 23.2, "test_composition": 23.3,
    "test_pipeline_generate": 23.3, "test_ulysses": 24.1,
    "test_quant": 24.3, "test_kvcache": 24.7, "test_lm_streaming": 27.4,
    "test_beam": 28.9, "test_flash_attention": 28.9, "test_moe": 29.3,
    "test_interleaved": 33.5, "test_sampler_extras": 33.6,
    "test_gpt_moe": 34.4, "test_generate_moe": 34.6, "test_train": 35.2,
    "test_constrain": 35.4, "test_engine_cli": 37.0,
    "test_cached_attention": 37.4, "test_serving": 37.6,
    "test_serving_options": 37.6, "test_decode_buckets": 39.9,
    "test_ring_attention": 39.9, "test_gemma": 40.5,
    "test_embeddings": 44.4, "test_audit": 50.6, "test_lm_server": 52.1,
    "test_decode_hotpath": 36.0,  # ISSUE 6 decode hot path: donation/
    # aliasing invariant, kv flag, int4 KV, paged flash-decode kernel,
    # quantized byte accounting — certified inside the tier-1 budget
    "test_spec_buckets": 36.0,  # speculative x bucketed composition
    # parity (greedy + sampled, rung crossings, draft-pool lockstep)
    "test_constrained_hotpath": 56.2,  # ISSUE 16 on-device grammar
    # walk: constrained mixed/overlap token parity vs convoy (dense/
    # paged/bucketed, mid-decode admission, rung crossing, multi-
    # grammar pool, EOS-at-accept), overlap ordering + crow reset,
    # prefix-cache DFA-state adoption, loud spec rejection, transition-
    # pool LRU golden — measured cost (nine parity server builds
    # dominate); sorts with the heavy serving integration modules
    "test_overlap": 50.0,  # ISSUE 12 overlap & fusion: mixed-step token
    # parity vs the convoy path (dense/paged/bucketed/speculative,
    # sampled draw-for-draw, mid-decode admission), double-buffer
    # ordering, fused-sampling logprob agreement, the un-aliased-mixed
    # gate test, int8-weights serving parity + byte pricing — certified
    # inside the tier-1 budget with the serving modules
    "test_control": 55.0,  # ISSUE 13 fleet front door: policy/admission
    # goldens, REPLICA/ROUTER protocol tables + buggy fixtures, KV
    # handoff pack/adopt parity (incl. paged), router e2e over real
    # gRPC (round trip, round-robin spread, dedup affinity join,
    # streaming, disaggregated prefill/decode parity, shed, drain-to-
    # sibling) — in-process replicas; certified inside the tier-1
    # budget with the serving-resilience modules
    "test_kvtier": 46.0,  # ISSUE 15 fleet KV tier: radix trie goldens
    # (insert/lookup/COW/leaf-LRU/refcount protection), block wire
    # codec incl. int4 nibble packing, lease machine + TTL + shm nonce
    # proof + PRO002-both-directions, radix admission parity (COW /
    # full-hit / retire-insert / row-backoff), cross-pool export/adopt
    # parity with block accounting, donor-death fallback with zero
    # divergence and zero leaks, kvput inbox TTL sweep, worker control
    # ops — certified inside the tier-1 budget with the serving modules
    "test_chaos": 42.0,  # ISSUE 8 chaos + self-healing: injection
    # goldens, supervisor restart/backoff/crash-loop (tiny python -c
    # children), requeue token parity, drain-under-load, circuit
    # breaker, corrupted-checkpoint fallback — certified inside the
    # tier-1 budget with the other serving-resilience modules
    "test_serving_spec": 53.1, "test_multilora": 57.9,
    "test_sliding_window": 58.0, "test_tp_pp": 59.9,
    "test_speculative": 62.4, "test_paged": 64.2,
    "test_models_llama": 67.1, "test_mixtral": 79.4, "test_1f1b": 88.0,
    "test_graft_entry": 224.6,
}
_DEFAULT_COST_S = 25.0  # unmeasured/new modules slot in mid-pack


def pytest_collection_modifyitems(config, items):
    """Cheapest-module-first execution order (see _MODULE_COST_S).
    Stable sort keyed per MODULE, so tests within a module stay
    contiguous and in their original relative order (module-scoped
    fixtures and intra-module contracts are untouched)."""
    def key(item):
        # nodeid, not item.module: never forces an import here
        mod = item.nodeid.split("::", 1)[0].rsplit("/", 1)[-1]
        if mod.endswith(".py"):
            mod = mod[:-3]
        return (_MODULE_COST_S.get(mod, _DEFAULT_COST_S), mod)

    items.sort(key=key)


def _rss_gb() -> float:
    """Current resident set of this process, GB. Non-Linux hosts fall
    back to getrusage peak RSS; an unreadable RSS returns inf so the
    gate FAILS CLOSED (clears every module — the old, safe behavior)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / 1e9
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KB on Linux, bytes on macOS
        return ru / 1e9 if sys.platform == "darwin" else ru / 1e6
    except Exception:  # noqa: BLE001 — no RSS signal at all
        return float("inf")


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches_between_modules():
    """Free compiled executables between modules WHEN MEMORY IS HIGH.

    A single pytest process otherwise accumulates every jitted program
    of ~500 tests (plus the device buffers their closures pin); late in
    the run an XLA CPU compile can then die with a hard SIGSEGV inside
    backend_compile_and_load — observed reproducibly at ~85% of the
    suite, while the same test passes in isolation. Clearing BETWEEN
    modules (never within) keeps intra-module contracts intact — e.g.
    the serving tests' jit-cache-size regression checks.

    Gated on actual resident memory (default 3 GB, override with
    DNN_TEST_CLEAR_RSS_GB; 0 = clear every module, the old behavior):
    an unconditional clear forced every module to recompile the shared
    helpers, costing the time-budgeted tier-1 run a large slice of its
    window for protection that is only needed near the memory ceiling."""
    yield
    threshold = float(os.environ.get("DNN_TEST_CLEAR_RSS_GB", "3"))
    if _rss_gb() >= threshold:
        import gc

        jax.clear_caches()
        gc.collect()


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
