"""Decode bucketing (runtime/decode_buckets.py): token-identity parity
between the bucketed cache-view programs and the unbucketed allocation —
solo host-loop decoder AND ContinuousBatcher pool, f32/bf16/int8 caches,
with sequences growing THROUGH a bucket edge mid-decode (the boundary the
masking argument must hold at)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime import generate as gen
from dnn_tpu.runtime.decode_buckets import (
    bucket_for,
    bucket_ladder,
    make_bucketed_generate,
    normalize_ladder,
    pad_cache_to,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.GPTConfig(block_size=256, vocab_size=128, n_layer=2, n_head=2,
                    n_embd=32)


@pytest.fixture(scope="module")
def setup():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    return gpt.prepare_stacked(params, CFG)


def test_ladder_shapes():
    assert bucket_ladder(1536, 64) == (64, 128, 256, 512, 1024, 1536)
    assert bucket_ladder(64, 64) == (64,)
    assert bucket_for((64, 128, 256), 64) == 64
    assert bucket_for((64, 128, 256), 65) == 128
    with pytest.raises(ValueError, match="exceed"):
        bucket_for((64,), 65)
    # explicit ladders: ascending enforced, max_len always the top rung
    assert normalize_ladder((16, 32), 96) == (16, 32, 96)
    assert normalize_ladder((16, 200), 96) == (16, 96)
    with pytest.raises(ValueError, match="ascend"):
        normalize_ladder((32, 16), 96)


def test_pad_cache_grows_position_axis_only():
    cache = gen.init_cache(CFG, 2, 16, "int8")
    grown = pad_cache_to(cache, 48)
    assert grown["k"].shape == cache["k"].shape[:3] + (48,) + \
        cache["k"].shape[4:]
    assert grown["ks"].shape == cache["ks"].shape[:3] + (48,)
    np.testing.assert_array_equal(np.asarray(grown["k"][:, :, :, :16]),
                                  np.asarray(cache["k"]))
    with pytest.raises(ValueError, match="shrink"):
        pad_cache_to(cache, 8)


@pytest.mark.parametrize("kv_dtype", [None, jnp.bfloat16, "int8"],
                         ids=["f32", "bf16", "int8"])
def test_solo_bucketed_greedy_parity_through_edge(setup, kv_dtype):
    """Greedy tokens are identical bucketed vs unbucketed vs the scan
    decoder, with the sequence growing through the 16- and 32-bucket
    edges mid-decode (prompt 10 + 30 new -> live 10..40)."""
    prepared = setup
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0,
                             CFG.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(2)
    kw = dict(max_len=128, max_new_tokens=30, kv_dtype=kv_dtype)
    bucketed = make_bucketed_generate(CFG, buckets=(16, 32, 64), **kw)
    assert bucketed.buckets == (16, 32, 64, 128)
    unbucketed = make_bucketed_generate(CFG, buckets=(128,), **kw)
    got = np.asarray(bucketed(prepared, ids, rng))
    np.testing.assert_array_equal(got,
                                  np.asarray(unbucketed(prepared, ids, rng)))
    # and against the lax.scan decoder (its cache is allocated at
    # prompt+new, a THIRD allocation size — masking makes all three agree)
    scan_fn = gen.make_generate(CFG, max_new_tokens=30, kv_dtype=kv_dtype)
    np.testing.assert_array_equal(got, np.asarray(scan_fn(prepared, ids,
                                                          rng)))


def test_solo_bucketed_sampled_parity(setup):
    """rng discipline matches the scan decoder split-for-split, so even
    SAMPLED streams agree draw-for-draw."""
    prepared = setup
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 6), 0,
                             CFG.vocab_size, dtype=jnp.int32)
    rng = jax.random.PRNGKey(4)
    bucketed = make_bucketed_generate(
        CFG, max_len=128, max_new_tokens=20, buckets=(8, 16, 32),
        temperature=1.0, top_k=8)
    scan_fn = gen.make_generate(CFG, max_new_tokens=20, temperature=1.0,
                                top_k=8)
    np.testing.assert_array_equal(np.asarray(bucketed(prepared, ids, rng)),
                                  np.asarray(scan_fn(prepared, ids, rng)))


def test_solo_rejects_overflow(setup):
    bucketed = make_bucketed_generate(CFG, max_len=32, max_new_tokens=30)
    with pytest.raises(ValueError, match="exceeds"):
        bucketed(setup, jnp.zeros((1, 8), jnp.int32), jax.random.PRNGKey(0))


@pytest.mark.parametrize("kv_dtype", [None, jnp.bfloat16, "int8"],
                         ids=["f32", "bf16", "int8"])
def test_batcher_bucketed_parity(setup, kv_dtype):
    """A bucketed pool (batcher + mixed-length prompts, decode crossing
    the 32- and 64-bucket edges) emits exactly the unbucketed pool's
    tokens."""
    prepared = setup

    def run(decode_buckets):
        srv = ContinuousBatcher(CFG, prepared, slots=3, max_len=96,
                                prompt_pad=16, kv_dtype=kv_dtype,
                                decode_buckets=decode_buckets)
        prompts = [np.arange(1, 12) % CFG.vocab_size,
                   (np.arange(1, 30) * 3) % CFG.vocab_size,
                   np.arange(1, 5)]
        rids = [srv.submit(p, max_new_tokens=24) for p in prompts]
        out = srv.drain()
        return [np.asarray(out[r]) for r in rids], srv

    base, _ = run(False)
    buck, srv = run(True)
    assert srv._buckets == (64, 96)
    # the pool grew past its first bucket (prompt 29 + 24 new -> live 53
    # fits 64; three slots at pos<=52... the long prompt's decode crosses)
    for a, b in zip(base, buck):
        np.testing.assert_array_equal(a, b)


def test_batcher_bucketed_grows_through_edge(setup):
    """Pin the growth mechanics: a pool starting at its smallest bucket
    ends at a larger one after decoding past the edge, and a late-join
    request on the grown pool still matches its solo decode."""
    prepared = setup
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=128,
                            prompt_pad=16, decode_buckets=(32, 48, 128))
    assert srv._cache_len == 32
    r1 = srv.submit(np.arange(1, 20) % CFG.vocab_size, max_new_tokens=20)
    while srv.n_active:
        srv.step()
    assert srv._cache_len == 48  # live ran to 39 -> grew past the 32 edge
    r2 = srv.submit(np.arange(1, 8) % CFG.vocab_size, max_new_tokens=8)
    out = srv.drain()
    solo = ContinuousBatcher(CFG, prepared, slots=2, max_len=128,
                             prompt_pad=16)
    s1 = solo.submit(np.arange(1, 20) % CFG.vocab_size, max_new_tokens=20)
    s2 = solo.submit(np.arange(1, 8) % CFG.vocab_size, max_new_tokens=8)
    sout = solo.drain()
    np.testing.assert_array_equal(out[r1], sout[s1])
    np.testing.assert_array_equal(out[r2], sout[s2])


def test_batcher_bucketed_rejects_paged(setup):
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(CFG, setup, slots=2, max_len=64, prompt_pad=16,
                          paged_blocks=8, block_len=16,
                          decode_buckets=True)
