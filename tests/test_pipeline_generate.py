"""Pipeline-parallel KV-cache generation tests.

Key invariant: decoding through the stage-sharded mesh (per-stage cache
shards, hidden state riding the ppermute ring per token) must be
token-for-token identical to the single-device KV-cache decoder — which is
itself parity-tested against repeated full forwards (test_generate.py).
The reference's GPT pipeline can only emit one stateless forward's logits
(/root/reference/partitions/gpt_model_parts.py:36-50); decode across
stages is capability it lacks entirely.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import STAGE_AXIS
from dnn_tpu.runtime.generate import (
    make_generate,
    make_pipeline_generate,
    prepare_pipeline_stacked,
)

CFG = gpt.PRESETS["gpt2-test"]  # block_size=64, vocab=256, L=4, H=4, C=64
CFG8 = gpt.GPTConfig(block_size=64, vocab_size=128, n_layer=8, n_head=2, n_embd=32)


def _setup(cfg, num_stages, seed=0):
    params = gpt.init(jax.random.PRNGKey(seed), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    mesh = Mesh(np.array(jax.devices()[:num_stages]), (STAGE_AXIS,))
    return prepared, mesh


@pytest.mark.parametrize("cfg,num_stages", [(CFG, 2), (CFG, 4), (CFG8, 8)])
def test_pipeline_decode_matches_single_device_greedy(cfg, num_stages):
    prepared, mesh = _setup(cfg, num_stages)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size,
                             dtype=jnp.int32)
    ref = make_generate(cfg, max_new_tokens=10)(prepared, ids, jax.random.PRNGKey(0))
    sb, aux = prepare_pipeline_stacked(prepared, cfg, mesh)
    got = make_pipeline_generate(cfg, mesh, max_new_tokens=10)(
        sb, aux, ids, jax.random.PRNGKey(0)
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_pipeline_decode_matches_single_device_sampled():
    prepared, mesh = _setup(CFG, 4)
    ids = jnp.zeros((2, 4), jnp.int32)
    kw = dict(max_new_tokens=8, temperature=0.7, top_k=12)
    ref = make_generate(CFG, **kw)(prepared, ids, jax.random.PRNGKey(3))
    sb, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    got = make_pipeline_generate(CFG, mesh, **kw)(sb, aux, ids, jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    assert (np.asarray(got) < CFG.vocab_size).all()


def test_pipeline_cache_shards_stay_per_stage():
    """Each device must hold only its own stage's blocks (the HBM-resident
    per-stage layout) — the stage-block placement the generator consumes."""
    prepared, mesh = _setup(CFG, 4)
    sb, _ = prepare_pipeline_stacked(prepared, CFG, mesh)
    leaf = sb["attn"]["qkv"]["kernel"]  # (S, per_stage, C, 3C)
    assert leaf.shape[0] == 4
    for shard in leaf.addressable_shards:
        assert shard.data.shape[0] == 1  # one stage per device


def test_prepare_pipeline_rejects_indivisible():
    prepared, mesh = _setup(CFG, 3)
    with pytest.raises(ValueError, match="not divisible"):
        prepare_pipeline_stacked(prepared, CFG, mesh)
    with pytest.raises(ValueError, match="not divisible"):
        make_pipeline_generate(CFG, mesh, max_new_tokens=4)


def test_pipeline_generate_rejects_overlong():
    prepared, mesh = _setup(CFG, 2)
    sb, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    gen = make_pipeline_generate(CFG, mesh, max_new_tokens=10)
    with pytest.raises(ValueError, match="block_size"):
        gen(sb, aux, jnp.zeros((1, 60), jnp.int32), jax.random.PRNGKey(0))


def test_engine_generate_pipeline_vs_relay_parity(tmp_path):
    """PipelineEngine.generate must produce the same tokens on the spmd
    (pipeline-parallel) and relay (single-program) runtimes."""
    import json

    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    def build(runtime):
        cfg = TopologyConfig.from_dict({
            "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
            "num_parts": 4,
            "model": "gpt2-test",
            "device_type": "cpu",
            "runtime": runtime,
        })
        return PipelineEngine(cfg, rng_seed=0)

    spmd = build("spmd")
    relay = build("relay")
    ids = np.asarray([[1, 2, 3, 4]], np.int32)
    a = spmd.generate(ids, max_new_tokens=6, rng=jax.random.PRNGKey(0))
    b = relay.generate(ids, max_new_tokens=6, rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # compiled generator is cached per sampling key
    _ = spmd.generate(ids, max_new_tokens=6, rng=jax.random.PRNGKey(1))
    assert len(spmd._generators) == 1


def test_pipeline_generate_int8_cache_matches_solo(devices):
    """Pipeline decode with int8 cache shards == solo decode with the
    int8 cache (same per-row quantization at every write)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import (
        make_generate,
        make_pipeline_generate,
        prepare_pipeline_stacked,
    )

    cfg = gpt.PRESETS["gpt2-test"]
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(31), cfg), cfg)
    mesh = make_mesh({STAGE_AXIS: 2}, devices[:2])
    stage_blocks, aux = prepare_pipeline_stacked(prepared, cfg, mesh)
    ids = jax.random.randint(jax.random.PRNGKey(32), (2, 5), 0, cfg.vocab_size)
    gen = make_pipeline_generate(cfg, mesh, max_new_tokens=5, kv_dtype="int8")
    got = np.asarray(gen(stage_blocks, aux, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate(cfg, max_new_tokens=5, kv_dtype="int8")(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
