"""ISSUE 12 — overlap & fusion: interleaved chunked prefill, the
double-buffered dispatch pipeline, fused on-device admission sampling,
and the int8-weights serving rung.

The load-bearing contracts:

  * mixed-step token parity: a batcher admitting through the MIXED
    program (prefill_chunk_tokens — chunks fold into decode steps, the
    fused finish samples the first token on device) produces token
    streams IDENTICAL to the convoy path, greedy and sampled
    draw-for-draw, across dense/paged/bucketed/speculative pools and
    for requests admitted mid-decode;
  * double-buffer ordering: overlap=True never surfaces step N+1's
    tokens before step N's commit, and drain()/flush_overlap() commit
    the trailing dispatched step;
  * fused-sampling logprob agreement: the fused finish's first-token
    logprobs match the convoy finish's exactly;
  * the analysis gate extends to the mixed-step programs: full
    donation aliasing + zero cache-sized copies on HEAD, and a
    deliberately un-aliased mixed variant FAILS the gate;
  * int8 weight serving (LMServer weights=): token parity within a
    cosine bound, and the MBU byte accounting prices the quantized
    stream (utils/flops.tree_weight_bytes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher
from dnn_tpu.runtime.serving_spec import SpeculativeBatcher


@pytest.fixture(scope="module")
def model():
    cfg = gpt.GPTConfig(block_size=64, vocab_size=64, n_layer=2,
                        n_head=2, n_embd=32)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


def _serve(cfg, prepared, submits, *, spec=None, **kw):
    """Run a submission schedule (list of (prompt, max_new, opts,
    steps_before)) through a batcher; returns {idx: tokens list}."""
    if spec is not None:
        srv = SpeculativeBatcher(cfg, prepared, cfg, spec, spec_k=2,
                                 slots=3, max_len=64, prompt_pad=8, **kw)
    else:
        srv = ContinuousBatcher(cfg, prepared, slots=3, max_len=64,
                                prompt_pad=8, **kw)
    rids = []
    for prompt, max_new, opts, steps_before in submits:
        for _ in range(steps_before):
            srv.step()
        rids.append(srv.submit(np.asarray(prompt, np.int32), max_new,
                               **opts))
    srv.drain()
    return [srv.results[r].tolist() for r in rids], srv


SCHEDULE = [
    (range(1, 10), 12, {"seed": 0}, 0),
    (range(2, 8), 10, {"seed": 1, "temperature": 0.9, "top_k": 5}, 0),
    # admitted mid-decode: three steps in, while others stream
    (range(3, 20), 8, {"seed": 2}, 3),
    # budget-1, admitted once a slot has freed (20 further steps covers
    # the deferred-commit lag of the interleaved path too): retires on
    # its first token without ever decoding
    (range(1, 6), 1, {"seed": 3}, 20),
]


@pytest.mark.parametrize("pool_kw", [
    {},  # dense
    {"kv": "paged", "block_len": 8},
    {"decode_buckets": True},
])
def test_mixed_step_token_parity(model, pool_kw):
    cfg, prepared = model
    base, _ = _serve(cfg, prepared, SCHEDULE, **pool_kw)
    mixed, srv = _serve(cfg, prepared, SCHEDULE,
                        prefill_chunk_tokens=8, **pool_kw)
    assert mixed == base
    both, _ = _serve(cfg, prepared, SCHEDULE, prefill_chunk_tokens=8,
                     overlap=True, **pool_kw)
    assert both == base
    # the interleave actually engaged (pendings flowed through steps)
    assert srv._ilv and srv._mixed is not None


def test_mixed_step_sampled_draw_for_draw(model):
    """Fused on-device admission sampling == the convoy finish,
    draw-for-draw: same per-request rng streams, same filter math."""
    cfg, prepared = model
    sched = [
        (range(1, 12), 9,
         {"seed": 11, "temperature": 0.8, "top_p": 0.9,
          "repetition_penalty": 1.3}, 0),
        (range(4, 9), 7,
         {"seed": 12, "temperature": 1.1, "min_p": 0.05}, 2),
    ]
    base, _ = _serve(cfg, prepared, sched)
    mixed, _ = _serve(cfg, prepared, sched, prefill_chunk_tokens=8)
    assert mixed == base
    both, _ = _serve(cfg, prepared, sched, prefill_chunk_tokens=8,
                     overlap=True)
    assert both == base


def test_multi_chunk_interleaved_prompt(model):
    """A prompt spanning several interleave chunks folds chunk-by-chunk
    across consecutive steps and still matches the convoy stream."""
    cfg, prepared = model
    sched = [(range(1, 30), 10, {"seed": 4}, 0),
             (range(2, 25), 8, {"seed": 5}, 1)]
    base, _ = _serve(cfg, prepared, sched)
    mixed, _ = _serve(cfg, prepared, sched, prefill_chunk_tokens=8)
    assert mixed == base


def test_overlap_ordering_one_step_pipeline(model):
    """The double buffer's contract: step() call N returns step N-1's
    tokens — no step N+1 result is ever consumed before step N's
    commit — and flush_overlap()/drain() commit the trailing step."""
    cfg, prepared = model
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, overlap=True)
    ref = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8)
    r = srv.submit(np.arange(1, 10), 6, seed=0)
    ref.submit(np.arange(1, 10), 6, seed=0)
    out1 = srv.step()      # dispatches step 0, pipeline filling
    assert out1 == {}
    assert srv._inflight is not None
    out2 = srv.step()      # dispatches step 1, commits step 0
    ref1 = ref.step()
    assert out2 == ref1    # exactly step 0's tokens, one call later
    # drain commits everything, including the trailing in-flight step
    srv.drain()
    ref.drain()
    assert srv._inflight is None
    assert srv.results[r].tolist() == ref.results[0].tolist()
    # an idle flush on a drained pool is a no-op
    assert srv.flush_overlap() == {}


def test_overlap_streams_match_and_flush_idempotent(model):
    cfg, prepared = model
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, overlap=True,
                            prefill_chunk_tokens=8)
    r = srv.submit(np.arange(1, 10), 4, seed=0)
    seen = []
    while srv.n_active:
        out = srv.step()
        for t in out.values():
            seen.extend(t if isinstance(t, list) else [t])
    out = srv.flush_overlap()
    for t in out.values():
        seen.extend(t if isinstance(t, list) else [t])
    assert seen == srv.results[r].tolist()


def test_spec_mixed_parity(model):
    cfg, prepared = model
    draft = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(7), cfg),
                                cfg)
    sched = [(range(1, 10), 12, {"seed": 0}, 0),
             (range(3, 14), 9, {"seed": 2}, 2)]
    plain, _ = _serve(cfg, prepared, sched)
    spec_base, _ = _serve(cfg, prepared, sched, spec=draft)
    assert spec_base == plain  # greedy spec == plain batcher (standing)
    spec_ilv, srv = _serve(cfg, prepared, sched, spec=draft,
                           prefill_chunk_tokens=8)
    assert spec_ilv == plain
    assert srv._spec_mixed is not None
    spec_both, _ = _serve(cfg, prepared, sched, spec=draft,
                          prefill_chunk_tokens=8, overlap=True)
    assert spec_both == plain
    # sampled spec: mixed vs convoy draw-for-draw (server-level params)
    s_sched = [(range(1, 10), 8, {"seed": 5}, 0)]
    kw = {"temperature": 0.8, "top_k": 8}
    s_base, _ = _serve(cfg, prepared, s_sched, spec=draft, **kw)
    s_ilv, _ = _serve(cfg, prepared, s_sched, spec=draft,
                      prefill_chunk_tokens=8, overlap=True, **kw)
    assert s_ilv == s_base


def test_spec_bucketed_mixed_parity(model):
    cfg, prepared = model
    draft = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(7), cfg),
                                cfg)
    sched = [(range(1, 10), 26, {"seed": 0}, 0)]
    base, _ = _serve(cfg, prepared, sched, spec=draft,
                     decode_buckets=True)
    mixed, _ = _serve(cfg, prepared, sched, spec=draft,
                      decode_buckets=True, prefill_chunk_tokens=8,
                      overlap=True)
    assert mixed == base


def test_fused_sampling_logprob_agreement(model):
    """The fused finish's first-token logprob record (chosen + top-k)
    agrees exactly with the convoy finish's, and the per-step records
    ride the deferred commit unchanged."""
    cfg, prepared = model

    def lp_run(**kw):
        srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                                prompt_pad=8, logprobs_k=3, **kw)
        r = srv.submit(np.arange(1, 10), 6, seed=0, logprobs=True)
        srv.drain()
        lp = srv.token_logprobs[r]
        return (srv.results[r].tolist(), lp["chosen"].tolist(),
                lp["top_ids"].tolist(), lp["top_logprobs"].tolist())

    base = lp_run()
    assert lp_run(prefill_chunk_tokens=8) == base
    assert lp_run(prefill_chunk_tokens=8, overlap=True) == base


def test_eos_on_deferred_first_token(model):
    """A request whose FIRST token is eos (forced via logit bias)
    retires correctly off the deferred commit, discarding the lagged
    decode token."""
    cfg, prepared = model

    def run(**kw):
        srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                                prompt_pad=8, eos_id=5,
                                allow_logit_bias=True, **kw)
        r = srv.submit(np.arange(1, 10), 8, seed=0,
                       logit_bias={5: 1e9})
        srv.drain()
        return srv.results[r].tolist(), srv.finish_reasons[r]

    base = run()
    assert base[1] == "eos"
    assert run(prefill_chunk_tokens=8) == base
    assert run(prefill_chunk_tokens=8, overlap=True) == base


def test_interleave_validations(model):
    cfg, prepared = model
    with pytest.raises(ValueError, match="allow_constraints"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, prefill_chunk_tokens=8,
                          allow_constraints=True)
    with pytest.raises(ValueError, match="allow_constraints"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, overlap=True,
                          allow_constraints=True)
    with pytest.raises(ValueError, match="prefix cache"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, prefill_chunk_tokens=8,
                          prefix_cache=4)
    with pytest.raises(ValueError, match="block_len"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, kv="paged", block_len=8,
                          prefill_chunk_tokens=12)
    with pytest.raises(ValueError, match="max_len"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, prefill_chunk_tokens=128)


def test_cancel_pending_interleaved_request(model):
    """Cancelling a request whose prefill is still queued frees its
    slot (and paged blocks) without a step ever running it."""
    cfg, prepared = model
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, kv="paged", block_len=8,
                            prefill_chunk_tokens=8)
    used0 = srv._allocator.n_used
    rid = srv.submit(np.arange(1, 10), 6, seed=0)
    assert srv._pending_q
    assert srv.cancel(rid)
    assert not srv._pending_q
    assert srv._allocator.n_used == used0
    assert srv.n_active == 0
    # the pool still serves cleanly afterwards
    r2 = srv.submit(np.arange(1, 10), 4, seed=1)
    srv.drain()
    assert len(srv.results[r2]) == 4


def test_audit_covers_mixed_step_programs():
    """audit_serving_decode extends to the mixed-step programs: every
    donated leaf aliased, zero cache-sized copies, on HEAD."""
    from dnn_tpu.analysis.program import audit_serving_decode

    rep = audit_serving_decode()
    names = set(rep["variants"])
    for want in ("mixed_dense", "mixed_dense_finish", "mixed_paged",
                 "mixed_bucketed", "mixed_speculative",
                 "mixed_speculative_finish"):
        assert want in names, names
        v = rep["variants"][want]
        assert v["aliased"] == v["expected"], (want, v)
        assert v["cache_sized_ops"] == {}, (want, v)
    assert rep["findings"] == []


def test_audit_gate_fails_unaliased_mixed_variant(model):
    """The gate actually gates: the REAL mixed-step program re-jitted
    WITHOUT donation fails the donation-coverage check."""
    from dnn_tpu.analysis.program import check_decode_program

    cfg, prepared = model
    b = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=8, prefill_chunk_tokens=8)
    row = b._ilv_new_row()
    chunk = jnp.zeros((1, 8), jnp.int32)
    args = (b._decode_view, b._decode_view, b.cache, b.pos, b.tok,
            b.active, b.keys, b._temp, b._topk, b._topp, b._minp,
            b._rep, b._seen, b._bias, b._crow, b._ctable,
            row, chunk, jnp.int32(0))
    elems = 2 * cfg.n_head * 64 * (cfg.n_embd // cfg.n_head)
    # HEAD's program passes...
    _, ok_findings = check_decode_program(
        "mixed_ok", b._mixed, args, b._mixed_donate, elems)
    assert ok_findings == []
    # ...the same function jitted with its donations dropped FAILS
    bad = jax.jit(b._mixed.__wrapped__)
    entry, findings = check_decode_program(
        "mixed_unaliased", bad, args, b._mixed_donate, elems)
    assert entry["aliased"] == 0
    assert findings and findings[0].rule == "PRG003"


def test_int8_weights_serving_parity_and_bytes(model):
    """The weight-quant rung: int8 weights through the serving decode
    path stay token-parity-close (cosine-bound logits; identical
    greedy streams at this scale), and the byte accounting prices the
    quantized stream correctly."""
    from dnn_tpu.obs.goodput import model_cost
    from dnn_tpu.quant import quantize_gpt
    from dnn_tpu.utils.flops import tree_weight_bytes

    cfg, prepared = model
    q = quantize_gpt(prepared, bits=8)

    f_bytes = tree_weight_bytes(prepared)
    q_bytes = tree_weight_bytes(q)
    assert q_bytes < 0.55 * f_bytes  # kernels 4x down, embeddings f32
    # goodput's MBU denominator follows the served tree exactly
    assert model_cost(cfg, q).weight_bytes == pytest.approx(q_bytes)
    assert model_cost(cfg, prepared).weight_bytes == \
        pytest.approx(f_bytes)

    # serving parity: same pool, quantized weights — logits cosine
    # bound, greedy token stream identical at this model scale
    def logits_and_tokens(tree):
        srv = ContinuousBatcher(cfg, tree, slots=2, max_len=64,
                                prompt_pad=8, logprobs_k=4)
        r = srv.submit(np.arange(1, 12), 8, seed=0, logprobs=True)
        srv.drain()
        lp = srv.token_logprobs[r]
        return srv.results[r], lp["chosen"]

    toks_f, lp_f = logits_and_tokens(prepared)
    toks_q, lp_q = logits_and_tokens(q)
    assert toks_q.tolist() == toks_f.tolist()
    # chosen-logprob agreement as the scalar parity bound
    assert float(np.max(np.abs(lp_f - lp_q))) < 0.15


def test_int4_packed_weight_pricing():
    """int4 leaves price at the packed half byte + their scale rows —
    the itemsize walk would read 2x."""
    from dnn_tpu.quant import quantize_tensor_int4
    from dnn_tpu.utils.flops import tree_weight_bytes

    w = jnp.ones((64, 32), jnp.float32)
    q, scale = quantize_tensor_int4(w, group=64)
    got = tree_weight_bytes({"q": q, "scale": scale})
    assert got == pytest.approx(64 * 32 * 0.5 + scale.size * 4)


def test_stepclock_mixed_tag_and_overlap_depth(model):
    """StepClock satellites: interleaved steps carry the `mixed` tag
    (records + summary + prom), and the overlap_depth gauge reports
    the producer's pipeline depth."""
    from dnn_tpu import obs
    from dnn_tpu.obs.timeline import StepClock

    cfg, prepared = model
    was = obs.enabled()
    obs.set_enabled(True)
    try:
        srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                                prompt_pad=8, prefill_chunk_tokens=8,
                                overlap=True)
        clock = StepClock()
        srv.step_clock = clock
        srv.submit(np.arange(1, 10), 6, seed=0)
        srv.drain()
        recs = clock.records()
        assert any(r["mixed"] for r in recs)
        assert any(not r["mixed"] for r in recs)
        s = clock.summary()
        assert s["mixed_steps"] >= 1
        assert 0 < s["mixed_frac"] <= 1
        assert s["overlap_depth"] == 1
        prom = clock.render_prom()
        assert "dnn_tpu_step_mixed_steps" in prom
        assert "dnn_tpu_step_overlap_depth 1" in prom
    finally:
        obs.set_enabled(was)


def test_worker_streams_interleaved_and_overlap_tokens(model):
    """The lm_server worker serves interleaved admissions end to end:
    the deferred first token streams through on_token, the future
    resolves with the full budget, and the overlap idle-flush keeps
    the trailing step from dangling."""
    from dnn_tpu.runtime.lm_server import _BatcherWorker

    cfg, prepared = model
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                            prompt_pad=8, prefill_chunk_tokens=8,
                            overlap=True)
    w = _BatcherWorker(srv)
    w.start()
    try:
        streamed = []
        fut = w.submit(np.arange(1, 10, dtype=np.int32), 6, None,
                       on_token=streamed.append)
        out = fut.result(timeout=120)
        assert len(out) == 6
        assert streamed == list(out)
        # idle worker flushed the trailing overlap step
        deadline = 50
        while srv._inflight is not None and deadline:
            import time as _t

            _t.sleep(0.1)
            deadline -= 1
        assert srv._inflight is None
    finally:
        w.stop()
        w.join(timeout=10)
