"""CIFAR CNN family: shapes, partition-vs-full parity, registry.

The key invariant is the one implied (but never tested) by the reference:
composing the split parts must reproduce the full model bit-for-bit
(cifar_model_parts.py:18-26 vs :37-42 + :53-58; SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.models import cifar


@pytest.fixture(scope="module")
def cifar_setup():
    spec = get_model("cifar_cnn")
    params = spec.init(jax.random.PRNGKey(0))
    x = spec.example_input(batch_size=4, rng=jax.random.PRNGKey(1))
    return spec, params, x


def test_full_forward_shape_and_probs(cifar_setup):
    spec, params, x = cifar_setup
    y = spec.apply(params, x)
    assert y.shape == (4, 10)
    # softmax output (reference applies Softmax(dim=1) in-model,
    # cifar_model_parts.py:15,25)
    np.testing.assert_allclose(np.asarray(y).sum(axis=1), np.ones(4), rtol=1e-5)
    assert (np.asarray(y) >= 0).all()


@pytest.mark.parametrize("num_parts", [1, 2, 3, 4])
def test_partition_parity(cifar_setup, num_parts):
    """Composed stages == full model, exactly."""
    spec, params, x = cifar_setup
    stages = spec.partition(num_parts)
    assert len(stages) == num_parts
    h = x
    for stage in stages:
        h = stage.apply(stage.slice_params(params), h)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(spec.apply(params, x)))


def test_two_way_split_boundary(cifar_setup):
    """The 2-way split happens at the flatten boundary with a (B, 4096)
    activation, exactly like the reference (cifar_model_parts.py:41)."""
    spec, params, x = cifar_setup
    s0, s1 = spec.partition(2)
    act = s0.apply(s0.slice_params(params), x)
    assert act.shape == (4, cifar.FLAT_FEATURES)
    assert set(s0.param_keys) == {"conv1", "conv2"}
    assert set(s1.param_keys) == {"fc1", "fc2"}


def test_param_keys_cover_model_exactly(cifar_setup):
    spec, params, _ = cifar_setup
    for n in (1, 2, 3, 4):
        keys = [k for s in spec.partition(n) for k in s.param_keys]
        assert sorted(keys) == sorted(params.keys())
        assert len(set(keys)) == len(keys)  # no param owned by two stages


def test_unsupported_parts_raises(cifar_setup):
    spec, _, _ = cifar_setup
    with pytest.raises(ValueError):
        spec.partition(5)


def test_jit_forward(cifar_setup):
    spec, params, x = cifar_setup
    jy = jax.jit(spec.apply)(params, x)
    np.testing.assert_allclose(np.asarray(jy), np.asarray(spec.apply(params, x)), rtol=1e-6)


def test_bf16_compute_close_to_f32(cifar_setup):
    """make_apply(bf16) — the benchmark configuration — stays close to the
    f32 forward and still emits f32 probabilities."""
    spec, params, x = cifar_setup
    y32 = np.asarray(spec.apply(params, x))
    y16 = np.asarray(jax.jit(cifar.make_apply(jnp.bfloat16))(params, x))
    assert y16.dtype == np.float32
    np.testing.assert_allclose(y16, y32, atol=2e-2)
    assert cifar.make_apply(None) is cifar.apply


def test_torch_numerical_parity():
    """Cross-framework check: our NHWC functional CNN must match a torch
    NCHW model built exactly like the reference's NeuralNetwork
    (cifar_model_parts.py:6-25) when given the converted weights."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    class RefNet(nn.Module):
        # Same architecture as /root/reference/cifar_model_parts.py:6-16
        # (re-typed, not copied: conv-pool-conv-pool-fc-fc-softmax).
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 32, 3, 1, 1)
            self.pool = nn.MaxPool2d(2, 2)
            self.conv2 = nn.Conv2d(32, 64, 3, 1, 1)
            self.fc1 = nn.Linear(64 * 8 * 8, 512)
            self.fc2 = nn.Linear(512, 10)

        def forward(self, x):
            x = self.pool(torch.relu(self.conv1(x)))
            x = self.pool(torch.relu(self.conv2(x)))
            x = x.reshape(-1, 64 * 8 * 8)
            x = torch.relu(self.fc1(x))
            return torch.softmax(self.fc2(x), dim=1)

    from dnn_tpu.io.checkpoint import cifar_params_from_torch_state_dict

    tmodel = RefNet().eval()
    params = cifar_params_from_torch_state_dict(
        {k: v.numpy() for k, v in tmodel.state_dict().items()}
    )
    xt = torch.randn(2, 3, 32, 32)
    with torch.no_grad():
        yt = tmodel(xt).numpy()
    xj = jnp.asarray(xt.numpy().transpose(0, 2, 3, 1))  # NCHW -> NHWC
    yj = np.asarray(get_model("cifar_cnn").apply(params, xj))
    np.testing.assert_allclose(yj, yt, atol=1e-5)
