"""dp×pp composition: pipeline training over a 2D {data, stage} mesh.

The invariant: the same GLOBAL batch produces the same loss and the same
updated params whether it runs data-parallel over 2 columns or on a 1D
stage mesh — dp is a placement choice, the math is the batch mean either
way (fp-reassociation tolerance only)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import DATA_AXIS, STAGE_AXIS, make_mesh
from dnn_tpu.parallel.pipeline import spmd_pipeline_stacked


@pytest.fixture(scope="module")
def setup():
    cfg = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=4,
                        n_embd=32)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    # stage-major stacked layout: (S, per_stage, ...) — one block per stage
    stacks = [gpt.stack_blocks(params, [i]) for i in range(cfg.n_layer)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stacks)
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    return cfg, stacked, aux, tokens


def test_forward_parity(setup):
    """spmd_pipeline_stacked(data_axis=...) == 1D run on the same batch."""
    cfg, stacked, aux, tokens = setup
    x = gpt.embed(aux, tokens, cfg=cfg)

    mesh1 = make_mesh({STAGE_AXIS: 2}, jax.devices()[:2])
    ref = spmd_pipeline_stacked(
        lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg), stacked, x,
        mesh=mesh1, num_microbatches=2,
    )
    mesh2 = make_mesh({DATA_AXIS: 2, STAGE_AXIS: 2}, jax.devices()[:4])
    got = spmd_pipeline_stacked(
        lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg), stacked, x,
        mesh=mesh2, num_microbatches=2, data_axis=DATA_AXIS,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("d", [2, 4])
def test_train_step_parity(setup, d):
    """One dp×pp train step == one 1D pipeline train step: same loss, same
    updated stacked params, on the same global batch."""
    cfg, stacked, aux, tokens = setup
    opt = optax.sgd(1e-2)

    def make(mesh, data_axis):
        return train.make_pipeline_train_step(
            lambda bp, h: gpt.blocks_scan(bp, h, cfg=cfg),
            lambda a, ids: gpt.embed(a, ids, cfg=cfg),
            lambda a, h: gpt.head(a, h.astype(jnp.float32), cfg=cfg),
            opt, mesh, num_microbatches=2, data_axis=data_axis,
        )

    mesh1 = make_mesh({STAGE_AXIS: 2}, jax.devices()[:2])
    st1, aux1, _, loss1 = make(mesh1, None)(
        stacked, aux, (opt.init(stacked), opt.init(aux)), tokens
    )
    mesh2 = make_mesh({DATA_AXIS: d, STAGE_AXIS: 2}, jax.devices()[: 2 * d])
    st2, aux2, _, loss2 = make(mesh2, DATA_AXIS)(
        stacked, aux, (opt.init(stacked), opt.init(aux)), tokens
    )
    np.testing.assert_allclose(float(loss2), float(loss1), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(st1), jax.tree.leaves(st2)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(aux1), jax.tree.leaves(aux2)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_1f1b_rejects_data_axis(setup):
    cfg, stacked, aux, tokens = setup
    mesh = make_mesh({DATA_AXIS: 2, STAGE_AXIS: 2}, jax.devices()[:4])
    with pytest.raises(ValueError, match="gpipe schedule only"):
        train.make_pipeline_train_step(
            lambda bp, h: h, lambda a, i: i, lambda a, h: h,
            optax.sgd(1e-2), mesh, schedule="1f1b", data_axis=DATA_AXIS,
        )


def test_indivisible_batch_raises(setup):
    cfg, stacked, aux, tokens = setup
    mesh = make_mesh({DATA_AXIS: 2, STAGE_AXIS: 2}, jax.devices()[:4])
    x = gpt.embed(aux, tokens[:3], cfg=cfg)  # 3 not divisible by 2
    with pytest.raises(ValueError, match="not divisible by data axis"):
        spmd_pipeline_stacked(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=cfg), stacked, x,
            mesh=mesh, num_microbatches=1, data_axis=DATA_AXIS,
        )