"""Checkpoint subsystem: torch-free .pth parsing, format round-trips,
layout conversion, per-stage slicing."""

import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.io import checkpoint as ckpt


@pytest.fixture(scope="module")
def torch_cifar_sd(tmp_path_factory):
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    m = nn.Sequential()
    m.add_module("conv1", nn.Conv2d(3, 32, 3, 1, 1))
    m.add_module("conv2", nn.Conv2d(32, 64, 3, 1, 1))
    m.add_module("fc1", nn.Linear(64 * 8 * 8, 512))
    m.add_module("fc2", nn.Linear(512, 10))
    path = tmp_path_factory.mktemp("ckpt") / "cifar10_model.pth"
    torch.save(m.state_dict(), str(path))
    return str(path), {k: v.numpy() for k, v in m.state_dict().items()}


def test_pth_reader_matches_torch(torch_cifar_sd):
    """Our zip+pickle reader must reproduce torch.load exactly — this is the
    rebuild of node.py:296 without a torch dependency."""
    path, expect = torch_cifar_sd
    got = ckpt.load_pth_state_dict(path)
    assert sorted(got) == sorted(expect)
    for k in expect:
        np.testing.assert_array_equal(got[k], expect[k])


def test_pth_reader_rejects_code(tmp_path):
    """The restricted unpickler must refuse non-tensor classes."""
    import pickle
    import zipfile

    evil = tmp_path / "evil.pth"
    payload = pickle.dumps(eval)  # builtins.eval reference
    with zipfile.ZipFile(evil, "w") as zf:
        zf.writestr("archive/data.pkl", payload)
    with pytest.raises(Exception):
        ckpt.load_pth_state_dict(str(evil))


def test_npz_roundtrip(tmp_path, torch_cifar_sd):
    _, sd = torch_cifar_sd
    p = tmp_path / "m.npz"
    ckpt.save_npz(str(p), sd)
    got = ckpt.load_checkpoint(str(p))
    for k in sd:
        np.testing.assert_array_equal(got[k], sd[k])


def test_safetensors_load(tmp_path, torch_cifar_sd):
    st = pytest.importorskip("safetensors.numpy")
    _, sd = torch_cifar_sd
    p = tmp_path / "m.safetensors"
    st.save_file(sd, str(p))
    got = ckpt.load_checkpoint(str(p))
    for k in sd:
        np.testing.assert_array_equal(got[k], sd[k])


def test_cifar_conversion_and_stage_slicing(torch_cifar_sd):
    path, _ = torch_cifar_sd
    params = ckpt.cifar_params_from_torch_state_dict(ckpt.load_pth_state_dict(path))
    assert params["conv1"]["kernel"].shape == (3, 3, 3, 32)  # HWIO
    assert params["fc1"]["kernel"].shape == (4096, 512)  # (in, out)

    spec = get_model("cifar_cnn")
    s0, s1 = spec.partition(2)
    p0 = ckpt.slice_params_for_stage(params, s0)
    p1 = ckpt.slice_params_for_stage(params, s1)
    assert set(p0) == {"conv1", "conv2"} and set(p1) == {"fc1", "fc2"}


def test_pth_reader_keeps_scalar_tensors(tmp_path):
    """0-d tensors (step counters, logit scales) must survive the torch-free
    reader, matching torch.load."""
    torch = pytest.importorskip("torch")
    p = tmp_path / "scalars.pth"
    torch.save({"step": torch.tensor(7), "scale": torch.tensor(0.5), "w": torch.ones(3)}, str(p))
    got = ckpt.load_pth_state_dict(str(p))
    assert set(got) == {"step", "scale", "w"}
    assert got["step"].shape == () and int(got["step"]) == 7
    assert float(got["scale"]) == 0.5


def test_pth_reader_rejects_non_torch_zip(tmp_path):
    import zipfile

    p = tmp_path / "notatorch.pth"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("hello.txt", "hi")
    with pytest.raises(ValueError, match="data.pkl"):
        ckpt.load_pth_state_dict(str(p))
