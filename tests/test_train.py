"""Training tests (beyond-parity capability; the reference is inference-only,
readme.md:112). Run on the virtual 8-device CPU mesh from conftest.py.

Invariants:
  * dp x tp sharded step == unsharded step, numerically;
  * pipeline-parallel (ppermute) gradients == sequential gradients;
  * losses actually go down.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import make_mesh, DATA_AXIS, MODEL_AXIS, STAGE_AXIS

CFG = gpt.PRESETS["gpt2-test"]


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    return jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, CFG.vocab_size)


def test_cross_entropy_matches_manual():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 11))
    targets = jax.random.randint(jax.random.PRNGKey(1), (4, 7), 0, 11)
    got = train.cross_entropy(logits, targets)
    logp = jax.nn.log_softmax(logits, -1)
    want = -np.mean(
        [logp[b, t, targets[b, t]] for b in range(4) for t in range(7)]
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((2, 3, 5))
    targets = jnp.array([[1, 2, -1], [-1, -1, 0]])
    got = train.cross_entropy(logits, targets, ignore_index=-1)
    np.testing.assert_allclose(got, np.log(5.0), rtol=1e-6)


def test_generic_step_reduces_loss(params, tokens):
    apply_fn = gpt.make_apply(CFG)
    opt = optax.adam(1e-3)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    step = train.make_train_step(loss_fn, opt)
    opt_state = opt.init(params)
    p = params
    losses = []
    for _ in range(5):
        p, opt_state, l = step(p, opt_state, tokens)
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_sharded_step_matches_unsharded(params, tokens):
    apply_fn = gpt.make_apply(CFG)
    opt = optax.sgd(1e-2)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    # unsharded reference
    step_ref = train.make_train_step(loss_fn, opt)
    p_ref, s_ref, l_ref = step_ref(params, opt.init(params), tokens)

    # dp x tp on a 2x4 mesh
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    specs = train.gpt_tp_specs(params)
    p_sh = train.shard_pytree(params, mesh, specs)
    step_sh = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    p_out, s_out, l_out = step_sh(p_sh, opt.init(p_sh), tokens)

    np.testing.assert_allclose(float(l_out), float(l_ref), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5
        ),
        p_out, p_ref,
    )


def test_zero1_opt_state_sharded_and_parity(params, tokens):
    """ZeRO-1 (zero1=True + init_zero1_opt_state): adam moments live
    1/n-sliced over the data axis — measurably smaller per-device shards —
    while params after N steps match the replicated-state run."""
    import optax as _optax

    apply_fn = gpt.make_apply(CFG)
    opt = _optax.adamw(1e-3)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    specs = train.gpt_tp_specs(params)
    p_sh = train.shard_pytree(params, mesh, specs)

    # replicated-optimizer reference (same mesh, same tp)
    step_ref = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    p_a, s_a = p_sh, opt.init(p_sh)
    for _ in range(3):
        p_a, s_a, l_a = step_ref(p_a, s_a, tokens)

    # ZeRO-1 run
    s_z, opt_specs = train.init_zero1_opt_state(opt, p_sh, specs, mesh)
    step_z = train.make_sharded_train_step(loss_fn, opt, mesh, specs,
                                           zero1=True)
    p_b, s_b = p_sh, s_z
    for _ in range(3):
        p_b, s_b, l_b = step_z(p_b, s_b, tokens)

    np.testing.assert_allclose(float(l_b), float(l_a), rtol=1e-5)
    # atol covers reduction-order drift only: ZeRO-1 slices grads before
    # the adam update while the replicated run updates whole tensors, so
    # the all-reduce/update orders differ; observed worst case 2.4e-5
    # after 3 steps (1 of 4096 elements past the old 2e-5 bound)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5),
        p_b, p_a,
    )

    # the moments really are sharded over "data": an unsharded-by-tp leaf
    # (layer norm scale: tp spec P()) gains the data axis on dim 0...
    from jax.sharding import PartitionSpec as P

    mu = s_b[0].mu  # ScaleByAdamState of adamw's chain
    assert mu["h_0"]["mlp"]["fc"]["kernel"].sharding.spec == P(
        DATA_AXIS, MODEL_AXIS)
    assert mu["wte"]["embedding"].sharding.spec == P(MODEL_AXIS, DATA_AXIS)
    # ...and each device holds 1/2 of what the replicated run holds
    leaf = mu["h_0"]["mlp"]["fc"]["kernel"]
    full = s_a[0].mu["h_0"]["mlp"]["fc"]["kernel"]
    assert (leaf.addressable_shards[0].data.size
            == full.addressable_shards[0].data.size // 2)


def test_tp_specs_shard_expected_leaves(params):
    specs = train.gpt_tp_specs(params)
    from jax.sharding import PartitionSpec as P

    assert specs["h_0"]["attn"]["qkv"]["kernel"] == P(None, MODEL_AXIS)
    assert specs["h_0"]["attn"]["proj"]["kernel"] == P(MODEL_AXIS, None)
    assert specs["h_0"]["mlp"]["fc"]["kernel"] == P(None, MODEL_AXIS)
    assert specs["h_0"]["mlp"]["proj"]["kernel"] == P(MODEL_AXIS, None)
    assert specs["wte"]["embedding"] == P(MODEL_AXIS, None)
    assert specs["lm_head"]["kernel"] == P(None, MODEL_AXIS)
    assert specs["h_0"]["ln_1"]["scale"] == P()


def test_init_sharded_places_params():
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 4})
    p, specs = train.init_sharded(
        lambda rng: gpt.init(rng, CFG), jax.random.PRNGKey(0), mesh
    )
    qkv = p["h_0"]["attn"]["qkv"]["kernel"]
    assert qkv.sharding.spec == specs["h_0"]["attn"]["qkv"]["kernel"]
    # matches a plain init numerically
    ref = gpt.init(jax.random.PRNGKey(0), CFG)
    np.testing.assert_allclose(
        np.asarray(qkv), np.asarray(ref["h_0"]["attn"]["qkv"]["kernel"]), atol=1e-6
    )


def test_pipeline_train_matches_sequential(params, tokens):
    """pp gradients through ppermute == sequential single-device gradients."""
    num_parts = 4
    mesh = make_mesh({STAGE_AXIS: num_parts})
    per_stage = CFG.n_layer // num_parts
    opt = optax.sgd(1e-2)

    stacks = [
        gpt.stack_blocks(params, range(s * per_stage, (s + 1) * per_stage))
        for s in range(num_parts)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stacks)
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}

    def block_fn(stage_blocks, h):
        return gpt.blocks_scan(stage_blocks, h, cfg=CFG)

    def embed_fn(aux_p, ids):
        return gpt.embed(aux_p, ids, cfg=CFG)

    def head_fn(aux_p, h):
        return gpt.head(aux_p, h.astype(jnp.float32), cfg=CFG)

    step = train.make_pipeline_train_step(
        block_fn, embed_fn, head_fn, opt, mesh, num_microbatches=2
    )
    opt_states = (opt.init(stacked), opt.init(aux))
    st1, aux1, _, l_pp = step(stacked, aux, opt_states, tokens)

    # sequential reference
    apply_fn = gpt.make_apply(CFG)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    step_ref = train.make_train_step(loss_fn, opt)
    p_ref, _, l_ref = step_ref(params, opt.init(params), tokens)

    np.testing.assert_allclose(float(l_pp), float(l_ref), rtol=1e-5)
    # compare one early and one late block's updated weights
    np.testing.assert_allclose(
        np.asarray(st1["attn"]["qkv"]["kernel"][0, 0]),
        np.asarray(p_ref["h_0"]["attn"]["qkv"]["kernel"]),
        atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(st1["attn"]["qkv"]["kernel"][-1, -1]),
        np.asarray(p_ref[f"h_{CFG.n_layer - 1}"]["attn"]["qkv"]["kernel"]),
        atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(aux1["lm_head"]["kernel"]),
        np.asarray(p_ref["lm_head"]["kernel"]),
        atol=2e-5,
    )
