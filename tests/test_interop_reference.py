"""True wire interop against the REAL reference node.

Launches /root/reference/node.py (unmodified, as a subprocess) as node 1 of
a 2-part CIFAR pipeline, feeding it a `.pth` this framework exported; our
edge client runs stage 0 and completes the pipeline over localhost gRPC.
This upgrades the wire-compat claim (dnn_tpu/comm/wire.proto vs
node_service.proto:26-42) from assertion to measured result, and re-supplies
the reference's stripped weights blob (.MISSING_LARGE_BLOBS:
cifar10_model.pth) with weights its own loader accepts.

The reference env lacks torchvision (its node.py imports it at module
level, node.py:12, but only the node-0 client path ever *uses* it); a
minimal stub package on PYTHONPATH satisfies the import for the stage-1
server role we exercise.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

REFERENCE_DIR = "/root/reference"

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(REFERENCE_DIR, "node.py")),
    reason="reference tree not present",
)
torch = pytest.importorskip("torch")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_torchvision_stub(root):
    """Just enough for `import torchvision.transforms as transforms`
    (node.py:12) to succeed; the stage-server path never calls it."""
    pkg = os.path.join(root, "torchvision")
    os.makedirs(pkg, exist_ok=True)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("from . import transforms\n")
    with open(os.path.join(pkg, "transforms.py"), "w") as f:
        f.write(
            "class _Unavailable:\n"
            "    def __init__(self, *a, **k):\n"
            "        raise RuntimeError('torchvision stub: transforms unavailable')\n"
            "Compose = Resize = ToTensor = Normalize = _Unavailable\n"
        )
    return root


@pytest.mark.timeout(180)
def test_pipeline_with_real_reference_node(tmp_path):
    from dnn_tpu.comm.client import NodeClient, pipeline_budget
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.io.torch_export import cifar_state_dict_from_params, save_pth
    from dnn_tpu.models import cifar
    from dnn_tpu.runtime.engine import PipelineEngine

    # --- export trained-here weights in the reference's own format ---
    params = cifar.init(jax.random.PRNGKey(11))
    pth_path = str(tmp_path / "cifar10_model.pth")
    save_pth(pth_path, cifar_state_dict_from_params(params))

    port0, port1 = _free_port(), _free_port()
    cfg_dict = {
        "nodes": [
            {"id": "node0", "address": f"127.0.0.1:{port0}", "part_index": 0},
            {"id": "node1", "address": f"127.0.0.1:{port1}", "part_index": 1},
        ],
        "model_weights": pth_path,
        "num_parts": 2,
        "return_to_node_id": "node0",
        "device_type": "cpu",
    }
    cfg_path = str(tmp_path / "config.json")
    with open(cfg_path, "w") as f:
        json.dump(cfg_dict, f)

    stub_root = _write_torchvision_stub(str(tmp_path / "stubs"))
    env = dict(os.environ)
    env["PYTHONPATH"] = stub_root + os.pathsep + env.get("PYTHONPATH", "")

    proc = subprocess.Popen(
        [sys.executable, "node.py", "--node_id", "node1", "--config", cfg_path],
        cwd=REFERENCE_DIR,
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    client = None
    try:
        client = NodeClient(f"127.0.0.1:{port1}")
        assert client.wait_healthy(deadline=60), (
            "reference node never became healthy; output:\n"
            + (proc.stdout.read() if proc.poll() is not None else "<still running>")
        )

        # our stage 0 (convs+flatten) on the same weights the reference loaded
        engine = PipelineEngine(
            TopologyConfig.from_dict(cfg_dict), params=params, role="stage"
        )
        x = np.asarray(cifar.example_input(batch_size=1, rng=jax.random.PRNGKey(5)))
        y0 = np.asarray(engine.run_stage(0, x))
        assert y0.shape == (1, 4096)

        status, result = client.send_tensor(
            y0, request_id="interop_001", timeout=pipeline_budget(2)
        )
        assert result is not None, f"no result tensor from reference node: {status}"
        assert "Prediction" in status or "complete" in status.lower(), status

        ours = np.asarray(cifar.apply(params, x))
        # fp32 torch (oneDNN) vs XLA: tiny elementwise differences only
        np.testing.assert_allclose(result, ours, atol=1e-5, rtol=1e-4)
        assert int(np.argmax(result)) == int(np.argmax(ours))
    finally:
        if client is not None:
            client.close()
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
